"""nn long tail: remaining reference losses, pools, shuffles, wrappers.

Reference surface: the python/paddle/nn/__init__.py exports not covered by
the core passes — loss layers (loss.py), unpool/LP/fractional pools
(pooling.py), pixel/channel shuffles (vision.py), pad/unflatten containers,
and the qkv-packed flash attention entry points
(nn/functional/flash_attention.py:700).
"""

from __future__ import annotations

import math
from typing import Optional, Sequence

import numpy as np

from ..core.dispatch import apply_op, unwrap
from .layer import Layer

__all__ = [
    # functional
    "gaussian_nll_loss", "poisson_nll_loss", "multi_margin_loss",
    "soft_margin_loss", "triplet_margin_with_distance_loss",
    "multi_label_soft_margin_loss", "npair_loss", "hsigmoid_loss",
    "max_unpool1d", "max_unpool2d", "max_unpool3d", "lp_pool1d", "lp_pool2d",
    "adaptive_max_pool3d", "fractional_max_pool2d", "fractional_max_pool3d",
    "feature_alpha_dropout", "gather_tree", "margin_cross_entropy",
    "class_center_sample", "flash_attn_qkvpacked",
    "flash_attn_varlen_qkvpacked", "flashmask_attention", "sparse_attention",
    "rnnt_loss", "adaptive_log_softmax_with_loss",
    # layers
    "CTCLoss", "PairwiseDistance", "GaussianNLLLoss", "PoissonNLLLoss",
    "MultiMarginLoss", "SoftMarginLoss", "TripletMarginWithDistanceLoss",
    "MultiLabelSoftMarginLoss", "HSigmoidLoss", "RNNTLoss",
    "AdaptiveLogSoftmaxWithLoss", "ZeroPad1D", "ZeroPad2D", "ZeroPad3D", "Unflatten",
    "ParameterDict", "PixelUnshuffle", "ChannelShuffle", "Fold",
    "MaxUnPool1D", "MaxUnPool2D", "MaxUnPool3D", "LPPool1D", "LPPool2D",
    "FractionalMaxPool2D", "FractionalMaxPool3D", "Softmax2D",
    "FeatureAlphaDropout", "UpsamplingBilinear2D", "UpsamplingNearest2D",
    "AvgPool3D", "MaxPool3D", "AdaptiveAvgPool3D", "AdaptiveMaxPool1D",
    "AdaptiveMaxPool3D", "Conv1DTranspose", "Conv3DTranspose",
    "BeamSearchDecoder", "dynamic_decode",
]


def _reduce(v, reduction):
    import jax.numpy as jnp

    if reduction == "mean":
        return jnp.mean(v)
    if reduction == "sum":
        return jnp.sum(v)
    return v


# ---------------------------------------------------------------------------
# losses (reference python/paddle/nn/functional/loss.py)
# ---------------------------------------------------------------------------


def gaussian_nll_loss(input, label, variance, full=False, epsilon=1e-6,
                      reduction="mean", name=None):
    import jax.numpy as jnp

    def f(mu, y, var):
        var = jnp.maximum(var, epsilon)
        loss = 0.5 * (jnp.log(var) + (y - mu) ** 2 / var)
        if full:
            loss = loss + 0.5 * math.log(2 * math.pi)
        return _reduce(loss, reduction)

    return apply_op(f, input, label, variance, op_name="gaussian_nll_loss")


def poisson_nll_loss(input, label, log_input=True, full=False, epsilon=1e-8,
                     reduction="mean", name=None):
    import jax.numpy as jnp

    def f(x, y):
        if log_input:
            loss = jnp.exp(x) - y * x
        else:
            loss = x - y * jnp.log(x + epsilon)
        if full:  # Stirling approximation for the y! term
            stirling = y * jnp.log(y + 1e-30) - y + 0.5 * jnp.log(
                2 * math.pi * jnp.maximum(y, 1.0))
            loss = loss + jnp.where(y > 1, stirling, 0.0)
        return _reduce(loss, reduction)

    return apply_op(f, input, label, op_name="poisson_nll_loss")


def soft_margin_loss(input, label, reduction="mean", name=None):
    import jax.numpy as jnp

    return apply_op(
        lambda x, y: _reduce(jnp.log1p(jnp.exp(-y * x)), reduction),
        input, label, op_name="soft_margin_loss")


def multi_margin_loss(input, label, p=1, margin=1.0, weight=None,
                      reduction="mean", name=None):
    import jax.numpy as jnp

    def f(x, y):
        n, c = x.shape
        correct = jnp.take_along_axis(x, y[:, None].astype(jnp.int32), 1)
        m = jnp.maximum(margin - correct + x, 0.0) ** p
        m = m * (1 - jax_one_hot(y, c, x.dtype))
        return _reduce(m.sum(-1) / c, reduction)

    def jax_one_hot(y, c, dt):
        import jax

        return jax.nn.one_hot(y.astype(jnp.int32), c, dtype=dt)

    return apply_op(f, input, label, op_name="multi_margin_loss")


def multi_label_soft_margin_loss(input, label, weight=None, reduction="mean",
                                 name=None):
    import jax
    import jax.numpy as jnp

    def f(x, y):
        loss = -(y * jax.nn.log_sigmoid(x)
                 + (1 - y) * jax.nn.log_sigmoid(-x))
        return _reduce(loss.mean(-1), reduction)

    return apply_op(f, input, label, op_name="multi_label_soft_margin_loss")


def triplet_margin_with_distance_loss(input, positive, negative,
                                      distance_function=None, margin=1.0,
                                      swap=False, reduction="mean",
                                      name=None):
    import jax.numpy as jnp

    from . import functional as F

    dist = distance_function or (
        lambda a, b: F.pairwise_distance(a, b))
    d_ap = unwrap(dist(input, positive))
    d_an = unwrap(dist(input, negative))
    if swap:
        d_pn = unwrap(dist(positive, negative))
        d_an = jnp.minimum(d_an, d_pn)
    return apply_op(
        lambda ap, an: _reduce(jnp.maximum(ap - an + margin, 0.0), reduction),
        d_ap, d_an, op_name="triplet_margin_with_distance_loss")


def npair_loss(anchor, positive, labels, l2_reg=0.002, name=None):
    import jax.numpy as jnp

    def f(a, p, y):
        sim = a @ p.T                                # [n, n]
        same = (y[:, None] == y[None, :]).astype(a.dtype)
        same = same / same.sum(-1, keepdims=True)
        xent = (jax_logsumexp(sim) - (sim * same).sum(-1)).mean()
        reg = l2_reg * ((a * a).sum(-1) + (p * p).sum(-1)).mean() * 0.25
        return xent + reg

    def jax_logsumexp(s):
        import jax

        return jax.scipy.special.logsumexp(s, axis=-1)

    return apply_op(f, anchor, positive, labels, op_name="npair_loss")


def hsigmoid_loss(input, label, num_classes, weight, bias=None,
                  path_table=None, path_code=None, is_sparse=False,
                  name=None):
    """Hierarchical sigmoid over a complete binary tree (reference
    loss.py hsigmoid_loss default-tree mode)."""
    import jax
    import jax.numpy as jnp

    depth = max(1, math.ceil(math.log2(max(num_classes, 2))))

    def f(x, y, w, b=None):
        y = y.reshape(-1).astype(jnp.int32)
        # default complete-tree paths: node ids and left/right codes per level
        codes = []
        nodes = []
        cur = y + num_classes  # leaf index in a heap layout
        for _ in range(depth):
            codes.append((cur % 2).astype(x.dtype))   # right-child bit
            cur = cur // 2
            nodes.append(cur - 1)                     # internal node id
        loss = 0.0
        for lvl in range(depth):
            idx = jnp.clip(nodes[lvl], 0, w.shape[0] - 1)
            logit = (x * w[idx]).sum(-1)
            if b is not None:
                logit = logit + b.reshape(-1)[idx]
            sign = 1.0 - 2.0 * codes[lvl]             # code 0 -> +1
            loss = loss - jax.nn.log_sigmoid(sign * logit)
        return loss.mean()

    if bias is None:
        return apply_op(f, input, label, weight, op_name="hsigmoid_loss")
    return apply_op(f, input, label, weight, bias, op_name="hsigmoid_loss")


def rnnt_loss(input, label, input_lengths, label_lengths, blank=0,
              fastemit_lambda=0.0, reduction="mean", name=None):
    """RNN-T loss: log-space alpha recursion over the (T, U) lattice as a
    lax.scan over anti-diagonals (reference loss.py rnnt_loss / warprnnt).
    FastEmit regularization is not implemented — nonzero fastemit_lambda
    raises rather than silently training without the latency term."""
    import jax
    import jax.numpy as jnp

    if fastemit_lambda:
        raise NotImplementedError(
            "fastemit_lambda != 0 (FastEmit gradient scaling) is not "
            "implemented; pass fastemit_lambda=0")

    def f(logits, labels, ilen, llen):
        logp = jax.nn.log_softmax(logits.astype(jnp.float32), axis=-1)
        B, T, U1, _ = logp.shape
        labels = labels.astype(jnp.int32)
        blank_lp = logp[..., blank]                       # [B, T, U1]
        lab_lp = jnp.take_along_axis(
            logp[:, :, :-1, :], labels[:, None, :, None].astype(jnp.int32),
            axis=-1)[..., 0]                              # [B, T, U]
        NEG = -1e30

        alpha0 = jnp.full((B, U1), NEG).at[:, 0].set(0.0)

        def t_step(alpha, t):
            # emit along u (within the same t): sequential scan over U
            def u_step(a, u):
                val = jnp.where(u > 0, a[:, u - 1] + lab_lp[:, t, u - 1], NEG)
                new = jnp.logaddexp(a[:, u], val)
                # only the emit path updates within this t; the blank path
                # was already folded in from t-1
                return a.at[:, u].set(jnp.where(u > 0, new, a[:, u])), None

            alpha, _ = jax.lax.scan(u_step, alpha, jnp.arange(U1))
            # advance time with a blank from every (t, u)
            nxt = alpha + blank_lp[:, t, :]
            keep = (t + 1 < ilen)[:, None]
            return jnp.where(keep, nxt, alpha), alpha

        alpha_final, alphas = jax.lax.scan(t_step, alpha0, jnp.arange(T))
        # total log prob: alpha at (ilen-1, llen) + blank there
        t_idx = jnp.clip(ilen - 1, 0, T - 1)
        u_idx = jnp.clip(llen, 0, U1 - 1)
        a_end = alphas[t_idx, jnp.arange(B), u_idx]
        lp_end = blank_lp[jnp.arange(B), t_idx, u_idx]
        loss = -(a_end + lp_end)
        return _reduce(loss, reduction)

    return apply_op(f, input, label, input_lengths, label_lengths,
                    op_name="rnnt_loss")


def adaptive_log_softmax_with_loss(input, label, head_weight, tail_weights,
                                   cutoffs, head_bias=None, name=None):
    """Adaptive softmax (reference loss.py): frequent classes in the head,
    rare clusters through projected tails."""
    import jax
    import jax.numpy as jnp

    n_clusters = len(tail_weights)
    head_size = cutoffs[0] + n_clusters
    hw_cols = unwrap(head_weight).shape[-1]
    if hw_cols != head_size:
        raise ValueError(
            f"head_weight trailing dim {hw_cols} != cutoff[0] + n_clusters "
            f"= {head_size}")

    hw = unwrap(head_weight)
    hb = unwrap(head_bias) if head_bias is not None else None
    tws = [tuple(unwrap(w) for w in tw) for tw in tail_weights]

    def f(x, y):
        y = y.reshape(-1).astype(jnp.int32)
        head_logits = x @ hw
        if hb is not None:
            head_logits = head_logits + hb
        head_lp = jax.nn.log_softmax(head_logits, axis=-1)
        out = jnp.zeros(y.shape, x.dtype)
        in_head = y < cutoffs[0]
        out = jnp.where(in_head,
                        jnp.take_along_axis(
                            head_lp, jnp.clip(y, 0, cutoffs[0] - 1)[:, None],
                            1)[:, 0],
                        out)
        for c in range(n_clusters):
            lo, hi = cutoffs[c], cutoffs[c + 1]
            proj, wout = tws[c]
            tail_lp = jax.nn.log_softmax((x @ proj) @ wout, axis=-1)
            rel = jnp.clip(y - lo, 0, hi - lo - 1)
            val = (head_lp[:, cutoffs[0] + c]
                   + jnp.take_along_axis(tail_lp, rel[:, None], 1)[:, 0])
            out = jnp.where((y >= lo) & (y < hi), val, out)
        return out, -out.mean()

    return apply_op(f, input, label, op_name="adaptive_log_softmax_with_loss")


# ---------------------------------------------------------------------------
# pooling extras (reference nn/functional/pooling.py)
# ---------------------------------------------------------------------------


def _unpool(x, indices, spatial_shape):
    """Scatter pooled values back to their argmax positions."""
    import jax.numpy as jnp

    def f(a, idx):
        n, c = a.shape[0], a.shape[1]
        flat_len = int(np.prod(spatial_shape))
        av = a.reshape(n, c, -1)
        iv = idx.reshape(n, c, -1).astype(jnp.int32)
        out = jnp.zeros((n, c, flat_len), a.dtype)
        out = out.at[jnp.arange(n)[:, None, None],
                     jnp.arange(c)[None, :, None], iv].set(av)
        return out.reshape((n, c) + tuple(spatial_shape))

    return apply_op(f, x, indices, op_name="max_unpool")


def _unpool_out_shape(in_spatial, kernel_size, stride, padding, output_size,
                      nd):
    if output_size is not None:
        out = list(output_size)[-nd:]
        return out
    ks = kernel_size if isinstance(kernel_size, (list, tuple)) else [kernel_size] * nd
    st = stride if isinstance(stride, (list, tuple)) else [stride or ks[0]] * nd
    pd = padding if isinstance(padding, (list, tuple)) else [padding] * nd
    return [(i - 1) * s - 2 * p + k
            for i, k, s, p in zip(in_spatial, ks, st, pd)]


def max_unpool1d(x, indices, kernel_size, stride=None, padding=0,
                 data_format="NCL", output_size=None, name=None):
    spatial = _unpool_out_shape(unwrap(x).shape[2:], kernel_size, stride,
                                padding, output_size, 1)
    return _unpool(x, indices, spatial)


def max_unpool2d(x, indices, kernel_size, stride=None, padding=0,
                 data_format="NCHW", output_size=None, name=None):
    spatial = _unpool_out_shape(unwrap(x).shape[2:], kernel_size, stride,
                                padding, output_size, 2)
    return _unpool(x, indices, spatial)


def max_unpool3d(x, indices, kernel_size, stride=None, padding=0,
                 data_format="NCDHW", output_size=None, name=None):
    spatial = _unpool_out_shape(unwrap(x).shape[2:], kernel_size, stride,
                                padding, output_size, 3)
    return _unpool(x, indices, spatial)


def lp_pool1d(x, norm_type, kernel_size, stride=None, padding=0,
              ceil_mode=False, data_format="NCL", name=None):
    from . import functional as F

    import jax.numpy as jnp

    p = float(norm_type)
    powed = apply_op(lambda a: jnp.abs(a) ** p, x, op_name="lp_pow")
    avg = F.avg_pool1d(powed, kernel_size, stride=stride, padding=padding,
                       ceil_mode=ceil_mode)
    k = kernel_size if isinstance(kernel_size, int) else int(np.prod(kernel_size))
    return apply_op(lambda a: (a * k) ** (1.0 / p), avg, op_name="lp_root")


def lp_pool2d(x, norm_type, kernel_size, stride=None, padding=0,
              ceil_mode=False, data_format="NCHW", name=None):
    from . import functional as F

    import jax.numpy as jnp

    p = float(norm_type)
    powed = apply_op(lambda a: jnp.abs(a) ** p, x, op_name="lp_pow")
    avg = F.avg_pool2d(powed, kernel_size, stride=stride, padding=padding,
                       ceil_mode=ceil_mode)
    ks = kernel_size if isinstance(kernel_size, (list, tuple)) else (
        kernel_size, kernel_size)
    k = int(np.prod(ks))
    return apply_op(lambda a: (a * k) ** (1.0 / p), avg, op_name="lp_root")


def adaptive_max_pool3d(x, output_size, return_mask=False, name=None):
    import jax.numpy as jnp

    out = (output_size if isinstance(output_size, (list, tuple))
           else [output_size] * 3)

    def f(a):
        n, c, d, h, w = a.shape

        def pool_axis(arr, axis, size):
            length = arr.shape[axis]
            starts = [(i * length) // size for i in range(size)]
            ends = [-(-((i + 1) * length) // size) for i in range(size)]
            return jnp.stack([jnp.take(arr, jnp.arange(st, en), axis=axis
                                       ).max(axis=axis)
                              for st, en in zip(starts, ends)], axis=axis)

        a = pool_axis(a, 2, out[0])
        a = pool_axis(a, 3, out[1])
        return pool_axis(a, 4, out[2])

    if return_mask:
        raise NotImplementedError("adaptive_max_pool3d(return_mask=True)")
    return apply_op(f, x, op_name="adaptive_max_pool3d")


def _fractional_pool(x, output_size, nd, random_u=None):
    """Deterministic fractional max pooling (reference uses pseudo-random
    sequences seeded by random_u; the region boundaries here follow the same
    alpha-scan construction)."""
    import jax.numpy as jnp

    out = (output_size if isinstance(output_size, (list, tuple))
           else [output_size] * nd)

    def f(a):
        def pool_axis(arr, axis, size):
            length = arr.shape[axis]
            alpha = length / size
            u = random_u if random_u is not None else 0.5
            starts = [min(int((i + u) * alpha) - int(u * alpha), length - 1)
                      for i in range(size)]
            ends = [min(int((i + 1 + u) * alpha) - int(u * alpha), length)
                    for i in range(size)]
            ends = [max(e, s + 1) for s, e in zip(starts, ends)]
            return jnp.stack([jnp.take(arr, jnp.arange(st, en), axis=axis
                                       ).max(axis=axis)
                              for st, en in zip(starts, ends)], axis=axis)

        for d in range(nd):
            a = pool_axis(a, 2 + d, out[d])
        return a

    return apply_op(f, x, op_name="fractional_max_pool")


def fractional_max_pool2d(x, output_size, kernel_size=None, random_u=None,
                          return_mask=False, name=None):
    if return_mask:
        raise NotImplementedError("fractional_max_pool2d(return_mask=True)")
    return _fractional_pool(x, output_size, 2, random_u)


def fractional_max_pool3d(x, output_size, kernel_size=None, random_u=None,
                          return_mask=False, name=None):
    if return_mask:
        raise NotImplementedError("fractional_max_pool3d(return_mask=True)")
    return _fractional_pool(x, output_size, 3, random_u)


def feature_alpha_dropout(x, p=0.5, training=True, name=None):
    """Channel-wise alpha dropout (SELU-preserving; reference functional)."""
    import jax.numpy as jnp

    if not training or p == 0.0:
        return apply_op(lambda a: a, x)
    alpha_p = -1.7580993408473766

    def f(a, key):
        shape = (a.shape[0], a.shape[1]) + (1,) * (a.ndim - 2)
        import jax

        keep = jax.random.bernoulli(key, 1.0 - p, shape)
        A = (1.0 / math.sqrt((1 - p) * (1 + p * alpha_p ** 2)))
        B = -A * p * alpha_p
        return (jnp.where(keep, a, alpha_p) * A + B).astype(a.dtype)

    from ..core import random as prandom

    return apply_op(f, x, prandom.next_key(), op_name="feature_alpha_dropout")


# ---------------------------------------------------------------------------
# decode / misc functional
# ---------------------------------------------------------------------------


def gather_tree(ids, parents):
    """Trace beam-search parents back to full sequences (reference
    nn/decode gather_tree): ids/parents [T, B, beam]."""
    import jax.numpy as jnp

    def g(i, p):
        T = i.shape[0]
        beams = jnp.broadcast_to(jnp.arange(i.shape[2]), i.shape[1:])
        rows = []
        for t in range(T - 1, -1, -1):
            rows.append(jnp.take_along_axis(i[t], beams, axis=-1))
            beams = jnp.take_along_axis(p[t], beams, axis=-1)
        return jnp.stack(rows[::-1], axis=0)

    return apply_op(g, ids, parents, op_name="gather_tree")


def margin_cross_entropy(logits, label, margin1=1.0, margin2=0.5,
                         margin3=0.0, scale=64.0, group=None,
                         return_softmax=False, reduction="mean"):
    """ArcFace-style margin softmax (reference loss.py margin_cross_entropy):
    cos(m1*theta + m2) - m3 on the target logit, then scaled CE."""
    import jax
    import jax.numpy as jnp

    def f(x, y):
        x = jnp.asarray(x)
        y = jnp.asarray(y).reshape(-1).astype(jnp.int32)
        cos_t = jnp.clip(jnp.take_along_axis(x, y[:, None], 1)[:, 0], -1, 1)
        theta = jnp.arccos(cos_t)
        target = jnp.cos(margin1 * theta + margin2) - margin3
        adj = x.at[jnp.arange(x.shape[0]), y].set(target) * scale
        lp = jax.nn.log_softmax(adj, axis=-1)
        loss = -jnp.take_along_axis(lp, y[:, None], 1)[:, 0]
        loss = _reduce(loss, reduction)
        if return_softmax:
            return loss, jnp.exp(lp)
        return loss

    return apply_op(f, logits, label, op_name="margin_cross_entropy")


def class_center_sample(label, num_classes, num_samples, group=None):
    """Sample negative class centers + the positives (reference
    loss.py class_center_sample). Deterministic: positives first, then the
    lowest-id negatives to fill num_samples."""
    import jax.numpy as jnp

    lab = np.asarray(unwrap(label)).reshape(-1)
    pos = np.unique(lab)
    neg = np.setdiff1d(np.arange(num_classes), pos)
    take = max(0, num_samples - len(pos))
    sampled = np.concatenate([pos, neg[:take]])
    remap = -np.ones((num_classes,), np.int64)
    remap[sampled] = np.arange(len(sampled))
    from ..core.dispatch import wrap

    return (wrap(jnp.asarray(remap[lab])), wrap(jnp.asarray(sampled)))


# ---------------------------------------------------------------------------
# packed flash-attention entry points (reference flash_attention.py:700)
# ---------------------------------------------------------------------------


def flash_attn_qkvpacked(qkv, dropout=0.0, causal=False, return_softmax=False,
                         fixed_seed_offset=None, rng_name="", training=True,
                         name=None):
    """qkv: [b, s, heads+2*kv_heads? — reference packs [b, s, 3, h, d]]."""
    from . import functional as F

    q = qkv[:, :, 0]
    k = qkv[:, :, 1]
    v = qkv[:, :, 2]
    out = F.scaled_dot_product_attention(q, k, v, dropout_p=dropout,
                                         is_causal=causal, training=training)
    return out, None


def flash_attn_varlen_qkvpacked(qkv, cu_seqlens_q, cu_seqlens_k, max_seqlen_q,
                                max_seqlen_k, scale=None, dropout=0.0,
                                causal=False, return_softmax=False,
                                varlen_padded=True, training=True, name=None):
    from . import functional as F

    q, k, v = qkv[:, 0], qkv[:, 1], qkv[:, 2]
    return F.flash_attn_unpadded(q, k, v, cu_seqlens_q, cu_seqlens_k,
                                 max_seqlen_q, max_seqlen_k, scale=scale,
                                 dropout=dropout, causal=causal,
                                 training=training)


def flashmask_attention(query, key, value, startend_row_indices=None,
                        dropout=0.0, causal=False, window_size=None,
                        return_softmax_lse=False, name=None):
    """FlashMask (sparse row-range masks): lowered to a dense mask here —
    startend_row_indices [b, kv_heads, sk, 1] marks, per key column, the
    first query row that may NOT attend (causal LT mode)."""
    import jax.numpy as jnp

    from . import functional as F

    if startend_row_indices is None:
        return F.scaled_dot_product_attention(query, key, value,
                                              dropout_p=dropout,
                                              is_causal=causal)
    sq = unwrap(query).shape[1]
    sk = unwrap(key).shape[1]

    def build_mask(rows):
        # rows [b, h_kv, sk, 1] -> bool [b, 1, sq, sk] (True = visible)
        start = rows[..., 0]                       # [b, hkv, sk]
        q_pos = jnp.arange(sq)[None, None, :, None]
        vis = q_pos < start[:, :, None, :]
        if causal:
            vis = vis & (q_pos >= jnp.arange(sk)[None, None, None, :])
        return vis

    mask = apply_op(build_mask, startend_row_indices, op_name="flashmask")
    out = F.scaled_dot_product_attention(query, key, value, attn_mask=mask,
                                         dropout_p=dropout, is_causal=False)
    if return_softmax_lse:
        return out, None
    return out


def sparse_attention(query, key, value, sparse_csr_offset, sparse_csr_columns,
                     key_padding_mask=None, attn_mask=None, name=None):
    """Block-sparse attention at the reference API (functional
    sparse_attention); computed via a dense mask built from the CSR pattern."""
    import jax.numpy as jnp

    def f(q, k, v, offs, cols):
        # q/k/v: [b, h, s, d]; offs [b, h, s+1]; cols [b, h, nnz]
        b, h, s, d = q.shape
        logits = jnp.einsum("bhqd,bhkd->bhqk", q, k) / math.sqrt(d)
        # one vectorized scatter: precompute (b, h, row, col) index arrays
        offs_np = np.asarray(offs).astype(np.int64)
        cols_np = np.asarray(cols).astype(np.int64)
        nnz = cols_np.shape[-1]
        rows_np = np.empty((b, h, nnz), np.int64)
        for bi in range(b):
            for hi in range(h):
                rows_np[bi, hi] = np.repeat(np.arange(s),
                                            np.diff(offs_np[bi, hi]))
        bi_idx = np.arange(b)[:, None, None]
        hi_idx = np.arange(h)[None, :, None]
        mask = jnp.zeros((b, h, s, s), bool).at[
            bi_idx, hi_idx, rows_np, cols_np].set(True)
        logits = jnp.where(mask, logits, -1e30)
        import jax

        probs = jax.nn.softmax(logits, axis=-1)
        return jnp.einsum("bhqk,bhkd->bhqd", probs, v)

    return apply_op(f, query, key, value, sparse_csr_offset,
                    sparse_csr_columns, op_name="sparse_attention")


# ---------------------------------------------------------------------------
# layers
# ---------------------------------------------------------------------------


def _loss_layer(fn, **defaults):
    class _L(Layer):
        def __init__(self, **kw):
            super().__init__()
            self.kw = {**defaults, **kw}

        def forward(self, *args):
            return fn(*args, **self.kw)

    return _L


class CTCLoss(Layer):
    def __init__(self, blank=0, reduction="mean"):
        super().__init__()
        self.blank, self.reduction = blank, reduction

    def forward(self, log_probs, labels, input_lengths, label_lengths,
                norm_by_times=False):
        from . import functional as F

        return F.ctc_loss(log_probs, labels, input_lengths, label_lengths,
                          blank=self.blank, reduction=self.reduction,
                          norm_by_times=norm_by_times)


class PairwiseDistance(Layer):
    def __init__(self, p=2.0, epsilon=1e-6, keepdim=False, name=None):
        super().__init__()
        self.p, self.epsilon, self.keepdim = p, epsilon, keepdim

    def forward(self, x, y):
        from . import functional as F

        return F.pairwise_distance(x, y, p=self.p, epsilon=self.epsilon,
                                   keepdim=self.keepdim)


GaussianNLLLoss = _loss_layer(gaussian_nll_loss)
PoissonNLLLoss = _loss_layer(poisson_nll_loss)
MultiMarginLoss = _loss_layer(multi_margin_loss)
SoftMarginLoss = _loss_layer(soft_margin_loss)
TripletMarginWithDistanceLoss = _loss_layer(triplet_margin_with_distance_loss)
MultiLabelSoftMarginLoss = _loss_layer(multi_label_soft_margin_loss)
RNNTLoss = _loss_layer(rnnt_loss)


class HSigmoidLoss(Layer):
    def __init__(self, feature_size, num_classes, weight_attr=None,
                 bias_attr=None, is_custom=False, is_sparse=False, name=None):
        super().__init__()
        self.num_classes = num_classes
        self.weight = self.create_parameter([num_classes - 1, feature_size])
        self.bias = self.create_parameter([num_classes - 1], is_bias=True)

    def forward(self, input, label, path_table=None, path_code=None):
        return hsigmoid_loss(input, label, self.num_classes, self.weight,
                             self.bias)


class AdaptiveLogSoftmaxWithLoss(Layer):
    def __init__(self, in_features, n_classes, cutoffs, div_value=4.0,
                 head_bias=False, name=None):
        super().__init__()
        self.cutoffs = list(cutoffs) + [n_classes]
        self.head_weight = self.create_parameter(
            [in_features, self.cutoffs[0] + len(self.cutoffs) - 1])
        self.head_bias = (self.create_parameter(
            [self.cutoffs[0] + len(self.cutoffs) - 1], is_bias=True)
            if head_bias else None)
        self.tails = []
        for c in range(len(self.cutoffs) - 1):
            proj_dim = max(1, int(in_features / (div_value ** (c + 1))))
            proj = self.create_parameter([in_features, proj_dim])
            wout = self.create_parameter(
                [proj_dim, self.cutoffs[c + 1] - self.cutoffs[c]])
            self.tails.append((proj, wout))

    def forward(self, input, label):
        return adaptive_log_softmax_with_loss(
            input, label, self.head_weight, self.tails, self.cutoffs,
            head_bias=self.head_bias)


class ZeroPad1D(Layer):
    def __init__(self, padding, data_format="NCL", name=None):
        super().__init__()
        self.padding = (padding if isinstance(padding, (list, tuple))
                        else [padding, padding])

    def forward(self, x):
        from . import functional as F

        return F.pad(x, list(self.padding), mode="constant", value=0.0,
                     data_format="NCL")


class ZeroPad2D(Layer):
    def __init__(self, padding, data_format="NCHW", name=None):
        super().__init__()
        self.padding = (list(padding) if isinstance(padding, (list, tuple))
                        else [padding] * 4)

    def forward(self, x):
        from . import functional as F

        return F.zeropad2d(x, self.padding)


class ZeroPad3D(Layer):
    def __init__(self, padding, data_format="NCDHW", name=None):
        super().__init__()
        self.padding = (list(padding) if isinstance(padding, (list, tuple))
                        else [padding] * 6)

    def forward(self, x):
        from . import functional as F

        return F.pad(x, self.padding, mode="constant", value=0.0,
                     data_format="NCDHW")


class Unflatten(Layer):
    def __init__(self, axis, shape, name=None):
        super().__init__()
        self.axis, self.shape = axis, list(shape)

    def forward(self, x):
        import jax.numpy as jnp

        def f(a):
            s = list(a.shape)
            return a.reshape(s[: self.axis] + self.shape
                             + s[self.axis + 1:])

        return apply_op(f, x, op_name="unflatten")


class ParameterDict(Layer):
    """dict-style parameter container (reference container.py)."""

    def __init__(self, parameters=None):
        super().__init__()
        self._keys = []
        if parameters:
            for k, v in (parameters.items()
                         if hasattr(parameters, "items") else parameters):
                self[k] = v

    def __setitem__(self, key, param):
        if key not in self._keys:  # overwrite must not duplicate the key
            self._keys.append(key)
        setattr(self, key, param)

    def __getitem__(self, key):
        return getattr(self, key)

    def __len__(self):
        return len(self._keys)

    def __iter__(self):
        return iter(self._keys)

    def keys(self):
        return list(self._keys)

    def values(self):
        return [self[k] for k in self._keys]

    def items(self):
        return [(k, self[k]) for k in self._keys]


class PixelUnshuffle(Layer):
    def __init__(self, downscale_factor, data_format="NCHW", name=None):
        super().__init__()
        self.r = downscale_factor

    def forward(self, x):
        import jax.numpy as jnp

        r = self.r

        def f(a):
            n, c, h, w = a.shape
            a = a.reshape(n, c, h // r, r, w // r, r)
            return a.transpose(0, 1, 3, 5, 2, 4).reshape(
                n, c * r * r, h // r, w // r)

        return apply_op(f, x, op_name="pixel_unshuffle")


class ChannelShuffle(Layer):
    def __init__(self, groups, data_format="NCHW", name=None):
        super().__init__()
        self.groups = groups

    def forward(self, x):
        from . import functional as F

        return F.channel_shuffle(x, self.groups)


class Fold(Layer):
    def __init__(self, output_sizes, kernel_sizes, strides=1, paddings=0,
                 dilations=1, name=None):
        super().__init__()
        self.kw = dict(output_sizes=output_sizes, kernel_sizes=kernel_sizes,
                       strides=strides, paddings=paddings,
                       dilations=dilations)

    def forward(self, x):
        from . import functional as F

        return F.fold(x, **self.kw)


def _pool_layer(fn_name, **fixed):
    class _P(Layer):
        def __init__(self, *a, **kw):
            super().__init__()
            self.a, self.kw = a, {**fixed, **kw}

        def forward(self, x):
            from . import functional as F

            return getattr(F, fn_name)(x, *self.a, **self.kw)

    return _P


MaxUnPool1D = _pool_layer("max_unpool1d")
MaxUnPool2D = _pool_layer("max_unpool2d")
MaxUnPool3D = _pool_layer("max_unpool3d")
AvgPool3D = _pool_layer("avg_pool3d")
MaxPool3D = _pool_layer("max_pool3d")
AdaptiveAvgPool3D = _pool_layer("adaptive_avg_pool3d")
AdaptiveMaxPool1D = _pool_layer("adaptive_max_pool1d")
AdaptiveMaxPool3D = _pool_layer("adaptive_max_pool3d")
FractionalMaxPool2D = _pool_layer("fractional_max_pool2d")
FractionalMaxPool3D = _pool_layer("fractional_max_pool3d")


class LPPool1D(Layer):
    def __init__(self, norm_type, kernel_size, stride=None, padding=0,
                 ceil_mode=False, data_format="NCL", name=None):
        super().__init__()
        self.a = (norm_type, kernel_size)
        self.kw = dict(stride=stride, padding=padding, ceil_mode=ceil_mode)

    def forward(self, x):
        return lp_pool1d(x, *self.a, **self.kw)


class LPPool2D(LPPool1D):
    def forward(self, x):
        return lp_pool2d(x, *self.a, **self.kw)


class Softmax2D(Layer):
    def forward(self, x):
        from . import functional as F

        return F.softmax(x, axis=-3)


class FeatureAlphaDropout(Layer):
    def __init__(self, p=0.5, name=None):
        super().__init__()
        self.p = p

    def forward(self, x):
        return feature_alpha_dropout(x, p=self.p, training=self.training)


class UpsamplingNearest2D(Layer):
    def __init__(self, size=None, scale_factor=None, data_format="NCHW",
                 name=None):
        super().__init__()
        self.size, self.scale = size, scale_factor

    def forward(self, x):
        from . import functional as F

        return F.interpolate(x, size=self.size, scale_factor=self.scale,
                             mode="nearest")


class UpsamplingBilinear2D(UpsamplingNearest2D):
    def forward(self, x):
        from . import functional as F

        return F.interpolate(x, size=self.size, scale_factor=self.scale,
                             mode="bilinear", align_corners=True)


class _ConvTransposeNd(Layer):
    """Shared transpose-conv layer over the functional lowering (paddle
    weight layout [in, out/groups, *kernel], like nn/conv.Conv2DTranspose)."""

    ND = 1
    FN = "conv1d_transpose"

    def __init__(self, in_channels, out_channels, kernel_size, stride=1,
                 padding=0, output_padding=0, groups=1, dilation=1,
                 weight_attr=None, bias_attr=None, data_format=None):
        super().__init__()

        def ntuple(v):
            return (list(v) if isinstance(v, (list, tuple))
                    else [v] * self.ND)

        self._stride = ntuple(stride)
        self._padding = padding
        self._output_padding = output_padding
        self._dilation = ntuple(dilation)
        self._groups = groups
        kernel = ntuple(kernel_size)
        self.weight = self.create_parameter(
            [in_channels, out_channels // groups] + kernel, attr=weight_attr)
        self.bias = (self.create_parameter([out_channels], attr=bias_attr,
                                           is_bias=True)
                     if bias_attr is not False else None)

    def forward(self, x, output_size=None):
        from . import functional as F

        fn = getattr(F, self.FN)
        return fn(x, self.weight, self.bias, self._stride, self._padding,
                  self._output_padding, self._groups, self._dilation,
                  output_size=output_size)


class Conv1DTranspose(_ConvTransposeNd):
    ND = 1
    FN = "conv1d_transpose"


class Conv3DTranspose(_ConvTransposeNd):
    ND = 3
    FN = "conv3d_transpose"


# ---------------------------------------------------------------------------
# beam search (reference nn/decode.py BeamSearchDecoder + dynamic_decode)
# ---------------------------------------------------------------------------


class BeamSearchDecoder:
    """Greedy-expansion beam search over an RNN cell (reference decode.py).

    The cell maps (token_embedding, states) -> (logits, states) through
    ``cell(step_input, states)`` + an output layer.
    """

    def __init__(self, cell, start_token, end_token, beam_size,
                 embedding_fn=None, output_fn=None):
        self.cell = cell
        self.start_token = start_token
        self.end_token = end_token
        self.beam_size = beam_size
        self.embedding_fn = embedding_fn
        self.output_fn = output_fn

    def step(self, tokens, states):
        import jax.numpy as jnp

        inp = (self.embedding_fn(tokens) if self.embedding_fn is not None
               else tokens)
        out, new_states = self.cell(inp, states)
        if self.output_fn is not None:
            out = self.output_fn(out)
        return out, new_states


def dynamic_decode(decoder: BeamSearchDecoder, inits=None, max_step_num=32,
                   **kwargs):
    """Beam search loop (reference decode.py dynamic_decode). Returns
    (token_ids [b, beam, T], log_probs [b, beam])."""
    import jax
    import jax.numpy as jnp

    from ..core.dispatch import wrap

    beam = decoder.beam_size

    # bootstrap: run the start token once to find batch size and vocab
    start = decoder.start_token
    states = inits
    tokens = None
    seqs = None
    scores = None
    for t in range(max_step_num):
        if tokens is None:
            logits, states = decoder.step(start, states)
            lp = np.asarray(unwrap(jax.nn.log_softmax(
                unwrap(logits).astype(np.float32))))
            b, vocab = lp.shape
            top = np.argsort(-lp, axis=-1)[:, :beam]            # [b, beam]
            scores = np.take_along_axis(lp, top, -1)            # [b, beam]
            seqs = top[..., None]                               # [b, beam, 1]
            tokens = top
            states = _tile_states(states, beam)
        else:
            flat_tokens = wrap(np.asarray(tokens.reshape(-1)))
            logits, states = decoder.step(flat_tokens, states)
            lp = np.asarray(unwrap(jax.nn.log_softmax(
                unwrap(logits).astype(np.float32))))            # [b*beam, V]
            b = seqs.shape[0]
            vocab = lp.shape[-1]
            total = scores[..., None] + lp.reshape(b, beam, vocab)
            finished = tokens == decoder.end_token
            total = np.where(finished[..., None],
                             np.where(np.arange(vocab)[None, None, :]
                                      == decoder.end_token,
                                      scores[..., None], -1e30), total)
            flat = total.reshape(b, -1)
            top = np.argsort(-flat, -1)[:, :beam]
            scores = np.take_along_axis(flat, top, -1)
            parent = top // vocab
            tok = top % vocab
            seqs = np.concatenate(
                [np.take_along_axis(seqs, parent[..., None], 1),
                 tok[..., None]], axis=-1)
            tokens = tok
            states = _reorder_states(states, parent, beam)
        if np.all(tokens == decoder.end_token):
            break
    return wrap(np.asarray(seqs)), wrap(np.asarray(scores))


def _tile_states(states, beam):
    import jax.numpy as jnp

    def tile(s):
        v = unwrap(s)
        from ..core.dispatch import wrap

        return wrap(jnp.repeat(v, beam, axis=0))

    if states is None:
        return None
    if isinstance(states, tuple):
        return tuple(tile(s) for s in states)
    return tile(states)


def _reorder_states(states, parent, beam):
    import jax.numpy as jnp

    from ..core.dispatch import wrap

    b = parent.shape[0]
    flat_idx = (np.arange(b)[:, None] * beam + parent).reshape(-1)

    def pick(s):
        v = unwrap(s)
        return wrap(jnp.asarray(np.asarray(v)[flat_idx]))

    if states is None:
        return None
    if isinstance(states, tuple):
        return tuple(pick(s) for s in states)
    return pick(states)
