"""``nn`` — layers and functional ops (reference: python/paddle/nn/)."""

from . import functional  # noqa: F401
from . import initializer  # noqa: F401
from .activation import (  # noqa: F401
    CELU,
    ELU,
    GELU,
    GLU,
    SELU,
    Hardshrink,
    Hardsigmoid,
    Hardswish,
    Hardtanh,
    LeakyReLU,
    LogSigmoid,
    LogSoftmax,
    Maxout,
    Mish,
    PReLU,
    ReLU,
    ReLU6,
    RReLU,
    Sigmoid,
    Silu,
    Softmax,
    Softplus,
    Softshrink,
    Softsign,
    Swish,
    Tanh,
    Tanhshrink,
    ThresholdedReLU,
)
from .clip import (  # noqa: F401
    ClipGradByGlobalNorm,
    ClipGradByNorm,
    ClipGradByValue,
    GradientClipByGlobalNorm,
    GradientClipByNorm,
    GradientClipByValue,
)
from .common import (  # noqa: F401
    AlphaDropout,
    Bilinear,
    CosineSimilarity,
    Dropout,
    Dropout2D,
    Dropout3D,
    Embedding,
    Flatten,
    Identity,
    Linear,
    Pad1D,
    Pad2D,
    Pad3D,
    PixelShuffle,
    Unfold,
    Upsample,
)
from .container import LayerDict, LayerList, ParameterList, Sequential  # noqa: F401
from .conv import Conv1D, Conv2D, Conv2DTranspose, Conv3D  # noqa: F401
from .initializer import ParamAttr  # noqa: F401
from .layer import Layer  # noqa: F401
from .loss import (  # noqa: F401
    BCELoss,
    BCEWithLogitsLoss,
    CosineEmbeddingLoss,
    CrossEntropyLoss,
    HingeEmbeddingLoss,
    KLDivLoss,
    L1Loss,
    MarginRankingLoss,
    MSELoss,
    NLLLoss,
    SmoothL1Loss,
    TripletMarginLoss,
)
from .norm import (  # noqa: F401
    BatchNorm,
    BatchNorm1D,
    BatchNorm2D,
    BatchNorm3D,
    GroupNorm,
    InstanceNorm1D,
    InstanceNorm2D,
    InstanceNorm3D,
    LayerNorm,
    LocalResponseNorm,
    RMSNorm,
    SyncBatchNorm,
)
from .pooling import (  # noqa: F401
    AdaptiveAvgPool1D,
    AdaptiveAvgPool2D,
    AdaptiveMaxPool2D,
    AvgPool1D,
    AvgPool2D,
    MaxPool1D,
    MaxPool2D,
)
from .transformer import (  # noqa: F401
    MultiHeadAttention,
    Transformer,
    TransformerDecoder,
    TransformerDecoderLayer,
    TransformerEncoder,
    TransformerEncoderLayer,
)
