"""``Layer`` — the module base class.

Reference surface: python/paddle/nn/layer/layers.py:354 (parameters/buffers/
sublayers registries, hooks, state_dict, ``to()``, ``apply``, train/eval).

TPU-native addition: a functional bridge (``functional_state`` /
``bind_state``) that temporarily rebinds every parameter/buffer payload to a
provided pytree. This is what lets the same define-by-run ``forward`` be
traced by ``jax.jit``/``jax.grad`` into one XLA program with parameters as
real inputs (donatable, shardable) instead of baked constants — the analogue
of the reference's dy2static ProgramTranslator, with XLA in place of PIR.
"""

from __future__ import annotations

import threading
from collections import OrderedDict
from contextlib import contextmanager
from typing import Iterator, Optional, Tuple

import jax.numpy as jnp
import numpy as np

from ..core import dtype as dtypes
from ..core.tensor import Parameter, Tensor
from .initializer import Constant, XavierNormal, _resolve_initializer

# serializes bind_state swaps across threads (see Layer.bind_state)
_BIND_LOCK = threading.RLock()


class Layer:
    def __init__(self, name_scope=None, dtype="float32"):
        self.training = True
        self._dtype = dtypes.convert_dtype(dtype)
        self._parameters = OrderedDict()
        self._buffers = OrderedDict()
        self._non_persistable_buffer_names = set()
        self._sub_layers = OrderedDict()
        self._forward_pre_hooks = OrderedDict()
        self._forward_post_hooks = OrderedDict()
        self._name_scope = name_scope or self.__class__.__name__.lower()

    # -- registration ------------------------------------------------------
    def __setattr__(self, name, value):
        params = self.__dict__.get("_parameters")
        layers = self.__dict__.get("_sub_layers")
        buffers = self.__dict__.get("_buffers")
        if isinstance(value, Parameter):
            if params is None:
                raise RuntimeError("call super().__init__() before assigning parameters")
            params[name] = value
            self.__dict__.pop(name, None)
        elif isinstance(value, Layer):
            if layers is None:
                raise RuntimeError("call super().__init__() before assigning sublayers")
            layers[name] = value
            self.__dict__.pop(name, None)
        else:
            if params is not None and name in params:
                if value is None:
                    del params[name]
                else:
                    raise TypeError(f"cannot assign non-Parameter to parameter {name}")
            if layers is not None and name in layers and value is None:
                del layers[name]
                return
            if buffers is not None and name in buffers:
                if value is None or isinstance(value, Tensor):
                    buffers[name] = value
                    return
            object.__setattr__(self, name, value)

    def __getattr__(self, name):
        for registry in ("_parameters", "_buffers", "_sub_layers"):
            d = self.__dict__.get(registry)
            if d is not None and name in d:
                return d[name]
        raise AttributeError(
            f"'{type(self).__name__}' object has no attribute '{name}'"
        )

    def __delattr__(self, name):
        for registry in ("_parameters", "_buffers", "_sub_layers"):
            d = self.__dict__.get(registry)
            if d is not None and name in d:
                del d[name]
                return
        object.__delattr__(self, name)

    def add_parameter(self, name, parameter):
        if parameter is not None and not isinstance(parameter, Parameter):
            raise TypeError("add_parameter expects a Parameter")
        self._parameters[name] = parameter
        return parameter

    def add_sublayer(self, name, sublayer):
        self._sub_layers[str(name)] = sublayer
        return sublayer

    def register_buffer(self, name, tensor, persistable=True):
        self._buffers[name] = tensor
        if not persistable:
            self._non_persistable_buffer_names.add(name)
        return tensor

    def create_parameter(
        self,
        shape,
        attr=None,
        dtype=None,
        is_bias=False,
        default_initializer=None,
    ) -> Parameter:
        from .initializer import ParamAttr

        dtype = dtypes.convert_dtype(dtype) if dtype is not None else self._dtype
        init = None
        name = None
        trainable = True
        if isinstance(attr, ParamAttr):
            init = attr.initializer
            name = attr.name
            trainable = attr.trainable
        elif attr is not None and attr is not True:
            init = _resolve_initializer(attr)
        if init is None:
            # priority (reference base/initializer.py set_global_initializer):
            # ParamAttr init > global init > the layer's default init
            from . import initializer as _ini

            init = (_ini._global_bias_init if is_bias
                    else _ini._global_weight_init)
        if init is None:
            init = default_initializer or (Constant(0.0) if is_bias else XavierNormal())
        data = init(tuple(shape), dtype)
        p = Parameter(data, trainable=trainable, name=name)
        return p

    # -- traversal ---------------------------------------------------------
    def children(self) -> Iterator["Layer"]:
        for l in self._sub_layers.values():
            if l is not None:
                yield l

    def named_children(self):
        for n, l in self._sub_layers.items():
            if l is not None:
                yield n, l

    def sublayers(self, include_self=False):
        out = []
        if include_self:
            out.append(self)
        for l in self.children():
            out.extend(l.sublayers(include_self=True))
        return out

    def named_sublayers(self, prefix="", include_self=False, layers_set=None):
        if include_self:
            yield prefix, self
        for n, l in self.named_children():
            p = f"{prefix}.{n}" if prefix else n
            yield from l.named_sublayers(prefix=p, include_self=True)

    def parameters(self, include_sublayers=True):
        return [p for _, p in self.named_parameters(include_sublayers=include_sublayers)]

    def named_parameters(self, prefix="", include_sublayers=True):
        seen = set()
        for name, p in self._parameters.items():
            if p is not None and id(p) not in seen:
                seen.add(id(p))
                yield (f"{prefix}.{name}" if prefix else name), p
        if include_sublayers:
            for lname, l in self.named_children():
                sub_prefix = f"{prefix}.{lname}" if prefix else lname
                for n, p in l.named_parameters(prefix=sub_prefix):
                    if id(p) not in seen:
                        seen.add(id(p))
                        yield n, p

    def buffers(self, include_sublayers=True):
        return [b for _, b in self.named_buffers(include_sublayers=include_sublayers)]

    def named_buffers(self, prefix="", include_sublayers=True):
        for name, b in self._buffers.items():
            if b is not None:
                yield (f"{prefix}.{name}" if prefix else name), b
        if include_sublayers:
            for lname, l in self.named_children():
                sub_prefix = f"{prefix}.{lname}" if prefix else lname
                yield from l.named_buffers(prefix=sub_prefix)

    def apply(self, fn):
        for l in self.children():
            l.apply(fn)
        fn(self)
        return self

    # -- modes -------------------------------------------------------------
    def train(self):
        self.training = True
        for l in self.children():
            l.train()
        return self

    def eval(self):
        self.training = False
        for l in self.children():
            l.eval()
        return self

    # -- state dict ---------------------------------------------------------
    def state_dict(self, destination=None, include_sublayers=True, structured_name_prefix="", use_hook=True):
        dest = destination if destination is not None else OrderedDict()
        for name, p in self._parameters.items():
            if p is not None:
                dest[structured_name_prefix + name] = p
        for name, b in self._buffers.items():
            if b is not None and name not in self._non_persistable_buffer_names:
                dest[structured_name_prefix + name] = b
        if include_sublayers:
            for lname, l in self.named_children():
                l.state_dict(dest, True, structured_name_prefix + lname + ".")
        return dest

    def set_state_dict(self, state_dict, use_structured_name=True):
        own = self.state_dict()
        missing, unexpected = [], []
        for k, v in state_dict.items():
            if k not in own:
                unexpected.append(k)
                continue
            tgt = own[k]
            data = v._data if isinstance(v, Tensor) else jnp.asarray(np.asarray(v))
            if tuple(data.shape) != tuple(tgt._data.shape):
                raise ValueError(
                    f"shape mismatch for {k}: {data.shape} vs {tgt._data.shape}"
                )
            tgt._replace_data(data.astype(tgt._data.dtype))
        for k in own:
            if k not in state_dict:
                missing.append(k)
        return missing, unexpected

    set_dict = set_state_dict
    load_dict = set_state_dict

    def to(self, device=None, dtype=None, blocking=None):
        from ..core.device import to_device

        def convert(t):
            data = t._data
            if dtype is not None and dtypes.is_floating_point(data.dtype):
                data = data.astype(dtypes.convert_dtype(dtype))
            if device is not None:
                data = to_device(data, device if isinstance(device, str) else "cpu")
            t._replace_data(data)

        for _, p in self.named_parameters():
            convert(p)
        for _, b in self.named_buffers():
            convert(b)
        if dtype is not None:
            self._dtype = dtypes.convert_dtype(dtype)
        return self

    def astype(self, dtype):
        return self.to(dtype=dtype)

    def float(self):
        return self.to(dtype="float32")

    def bfloat16(self):
        return self.to(dtype="bfloat16")

    # -- hooks ---------------------------------------------------------------
    def register_forward_pre_hook(self, hook):
        key = len(self._forward_pre_hooks)
        self._forward_pre_hooks[key] = hook
        return _HookHandle(self._forward_pre_hooks, key)

    def register_forward_post_hook(self, hook):
        key = len(self._forward_post_hooks)
        self._forward_post_hooks[key] = hook
        return _HookHandle(self._forward_post_hooks, key)

    # -- call ----------------------------------------------------------------
    def __call__(self, *inputs, **kwargs):
        for hook in self._forward_pre_hooks.values():
            out = hook(self, inputs)
            if out is not None:
                inputs = out if isinstance(out, tuple) else (out,)
        outputs = self.forward(*inputs, **kwargs)
        for hook in self._forward_post_hooks.values():
            res = hook(self, inputs, outputs)
            if res is not None:
                outputs = res
        return outputs

    def forward(self, *inputs, **kwargs):
        raise NotImplementedError

    def extra_repr(self):
        return ""

    def __repr__(self):
        extra = self.extra_repr()
        lines = []
        for name, child in self.named_children():
            child_repr = repr(child).split("\n")
            child_repr = [child_repr[0]] + ["  " + l for l in child_repr[1:]]
            lines.append(f"  ({name}): " + "\n".join(child_repr))
        main = f"{type(self).__name__}({extra}"
        if lines:
            return main + "\n" + "\n".join(lines) + "\n)"
        return main + ")"

    def full_name(self):
        return self._name_scope

    def clear_gradients(self):
        for p in self.parameters():
            p.clear_grad()

    # -- functional bridge (jit/grad/pjit path) ------------------------------
    def functional_state(self, trainable_only=False):
        """Pytree {name: jax.Array} of parameters (+buffers unless trainable_only)."""
        tree = {n: p._data for n, p in self.named_parameters()
                if (not trainable_only) or p.trainable}
        if not trainable_only:
            tree.update({n: b._data for n, b in self.named_buffers()})
        return tree

    def raw_state(self):
        """{name: Tensor} over params+buffers (handles, not copies)."""
        d = dict(self.named_parameters())
        d.update(dict(self.named_buffers()))
        return d

    @contextmanager
    def bind_state(self, tree):
        """Temporarily rebind parameter/buffer payloads to ``tree`` values.

        Values may be jax.Arrays or tracers; forward run inside this context
        traces against them, enabling jax.jit/grad/vmap over the layer.

        Serialized by a global re-entrant lock: the swap mutates the SHARED
        Tensor handles, so two threads tracing the same (or overlapping)
        layers concurrently would interleave save/restore and leak tracers
        into each other's graphs (seen with serving-engine decode tracing
        racing a client thread's generate_cached). The lock is held only
        while tracing — compiled executions never re-enter here — so
        steady-state concurrency is unaffected.
        """
        with _BIND_LOCK:
            handles = self.raw_state()
            saved = {}
            try:
                for name, val in tree.items():
                    t = handles.get(name)
                    if t is None:
                        continue
                    saved[name] = t._data
                    t._data = val._data if isinstance(val, Tensor) else val
                yield self
            finally:
                for name, val in saved.items():
                    handles[name]._data = val


class _HookHandle:
    def __init__(self, registry, key):
        self._registry = registry
        self._key = key

    def remove(self):
        self._registry.pop(self._key, None)
