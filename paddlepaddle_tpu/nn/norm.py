"""Normalization layers (reference: python/paddle/nn/layer/norm.py)."""

from __future__ import annotations

from ..core.tensor import Tensor
from . import functional as F
from .initializer import Constant
from .layer import Layer


class LayerNorm(Layer):
    def __init__(self, normalized_shape, epsilon=1e-5, weight_attr=None, bias_attr=None, name=None):
        super().__init__()
        if isinstance(normalized_shape, int):
            normalized_shape = [normalized_shape]
        self.normalized_shape = list(normalized_shape)
        self.epsilon = epsilon
        self.weight = (
            self.create_parameter(self.normalized_shape, attr=weight_attr,
                                  default_initializer=Constant(1.0))
            if weight_attr is not False else None
        )
        self.bias = (
            self.create_parameter(self.normalized_shape, attr=bias_attr, is_bias=True)
            if bias_attr is not False else None
        )

    def forward(self, x):
        return F.layer_norm(x, self.normalized_shape, self.weight, self.bias, self.epsilon)

    def extra_repr(self):
        return f"normalized_shape={self.normalized_shape}, epsilon={self.epsilon}"


class RMSNorm(Layer):
    """TPU-native fused rms_norm (reference: incubate fused_rms_norm + PaddleNLP RMSNorm)."""

    def __init__(self, normalized_shape, epsilon=1e-6, weight_attr=None, bias_attr=False, name=None):
        super().__init__()
        if isinstance(normalized_shape, int):
            normalized_shape = [normalized_shape]
        self.normalized_shape = list(normalized_shape)
        self.epsilon = epsilon
        self.weight = self.create_parameter(
            self.normalized_shape, attr=weight_attr, default_initializer=Constant(1.0))
        self.bias = (
            self.create_parameter(self.normalized_shape, attr=bias_attr, is_bias=True)
            if bias_attr not in (False, None) else None
        )

    def forward(self, x):
        return F.rms_norm(x, self.weight, self.bias, self.epsilon)


class _BatchNormBase(Layer):
    def __init__(self, num_features, momentum=0.9, epsilon=1e-5, weight_attr=None,
                 bias_attr=None, data_format="NCHW", use_global_stats=None, name=None):
        super().__init__()
        self._num_features = num_features
        self._momentum = momentum
        self._epsilon = epsilon
        self._data_format = data_format
        self._use_global_stats = use_global_stats
        self.weight = (
            self.create_parameter([num_features], attr=weight_attr,
                                  default_initializer=Constant(1.0))
            if weight_attr is not False else None
        )
        self.bias = (
            self.create_parameter([num_features], attr=bias_attr, is_bias=True)
            if bias_attr is not False else None
        )
        from ..ops.creation import ones, zeros

        self.register_buffer("_mean", zeros([num_features], "float32"))
        self.register_buffer("_variance", ones([num_features], "float32"))

    def forward(self, x):
        return F.batch_norm(
            x, self._mean, self._variance, self.weight, self.bias,
            training=self.training, momentum=self._momentum, epsilon=self._epsilon,
            data_format=self._data_format, use_global_stats=self._use_global_stats,
        )

    def extra_repr(self):
        return f"num_features={self._num_features}, momentum={self._momentum}, epsilon={self._epsilon}"


class BatchNorm(_BatchNormBase):
    pass


class BatchNorm1D(_BatchNormBase):
    def __init__(self, num_features, momentum=0.9, epsilon=1e-5, weight_attr=None,
                 bias_attr=None, data_format="NCL", use_global_stats=None, name=None):
        super().__init__(num_features, momentum, epsilon, weight_attr, bias_attr,
                         "NCHW" if data_format == "NCL" else data_format, use_global_stats)


class BatchNorm2D(_BatchNormBase):
    pass


class BatchNorm3D(_BatchNormBase):
    def __init__(self, num_features, momentum=0.9, epsilon=1e-5, weight_attr=None,
                 bias_attr=None, data_format="NCDHW", use_global_stats=None, name=None):
        super().__init__(num_features, momentum, epsilon, weight_attr, bias_attr,
                         data_format, use_global_stats)


class SyncBatchNorm(_BatchNormBase):
    """On TPU, batch statistics are computed over the global (sharded) batch by
    XLA when the input is sharded over the data axis — sync is free under
    GSPMD; this class exists for API parity (reference:
    python/paddle/nn/layer/norm.py SyncBatchNorm)."""

    @classmethod
    def convert_sync_batchnorm(cls, layer):
        if isinstance(layer, _BatchNormBase) and not isinstance(layer, SyncBatchNorm):
            new = SyncBatchNorm(layer._num_features, layer._momentum, layer._epsilon,
                                data_format=layer._data_format)
            new.weight, new.bias = layer.weight, layer.bias
            new._buffers = layer._buffers
            return new
        for name, sub in list(layer.named_children()):
            layer._sub_layers[name] = cls.convert_sync_batchnorm(sub)
        return layer


class GroupNorm(Layer):
    def __init__(self, num_groups, num_channels, epsilon=1e-5, weight_attr=None,
                 bias_attr=None, data_format="NCHW", name=None):
        super().__init__()
        self._num_groups = num_groups
        self._epsilon = epsilon
        self._data_format = data_format
        self.weight = (
            self.create_parameter([num_channels], attr=weight_attr,
                                  default_initializer=Constant(1.0))
            if weight_attr is not False else None
        )
        self.bias = (
            self.create_parameter([num_channels], attr=bias_attr, is_bias=True)
            if bias_attr is not False else None
        )

    def forward(self, x):
        return F.group_norm(x, self._num_groups, self.weight, self.bias,
                            self._epsilon, self._data_format)


class InstanceNorm1D(Layer):
    def __init__(self, num_features, epsilon=1e-5, momentum=0.9, weight_attr=None,
                 bias_attr=None, data_format="NCL", name=None):
        super().__init__()
        self._epsilon = epsilon
        self.weight = (
            self.create_parameter([num_features], attr=weight_attr,
                                  default_initializer=Constant(1.0))
            if weight_attr is not False else None
        )
        self.bias = (
            self.create_parameter([num_features], attr=bias_attr, is_bias=True)
            if bias_attr is not False else None
        )

    def forward(self, x):
        return F.instance_norm(x, weight=self.weight, bias=self.bias, eps=self._epsilon)


class InstanceNorm2D(InstanceNorm1D):
    def __init__(self, num_features, epsilon=1e-5, momentum=0.9, weight_attr=None,
                 bias_attr=None, data_format="NCHW", name=None):
        super().__init__(num_features, epsilon, momentum, weight_attr, bias_attr)


class InstanceNorm3D(InstanceNorm1D):
    def __init__(self, num_features, epsilon=1e-5, momentum=0.9, weight_attr=None,
                 bias_attr=None, data_format="NCDHW", name=None):
        super().__init__(num_features, epsilon, momentum, weight_attr, bias_attr)


class LocalResponseNorm(Layer):
    def __init__(self, size, alpha=1e-4, beta=0.75, k=1.0, data_format="NCHW", name=None):
        super().__init__()
        self.args = (size, alpha, beta, k, data_format)

    def forward(self, x):
        return F.local_response_norm(x, *self.args)


class SpectralNorm(Layer):
    """Spectral normalization: weight / sigma_max(weight), sigma estimated by
    power iteration (reference: python/paddle/nn/layer/norm.py SpectralNorm —
    forward(weight) returns the normalized weight; u/v are persistent
    buffers updated without gradient each call)."""

    def __init__(self, weight_shape, dim=0, power_iters=1, eps=1e-12,
                 dtype="float32", name=None):
        super().__init__()
        import numpy as _np

        self.dim = int(dim)
        self.power_iters = int(power_iters)
        self.eps = float(eps)
        self._shape = list(weight_shape)
        h = self._shape[self.dim]
        w = 1
        for i, s in enumerate(self._shape):
            if i != self.dim:
                w *= s
        rng = _np.random.default_rng(0)
        from ..core.tensor import Tensor as _T

        self.register_buffer("weight_u", _T(
            (rng.standard_normal(h) / _np.sqrt(h)).astype(dtype)))
        self.register_buffer("weight_v", _T(
            (rng.standard_normal(w) / _np.sqrt(w)).astype(dtype)))

    def forward(self, weight):
        import jax
        import jax.numpy as jnp

        from ..core.dispatch import apply_op

        dim, iters, eps = self.dim, self.power_iters, self.eps

        def f(w, u, v):
            perm = [dim] + [i for i in range(w.ndim) if i != dim]
            mat = jnp.transpose(w, perm).reshape(w.shape[dim], -1)  # [h, m]

            def norm(x):
                return x / (jnp.linalg.norm(x) + eps)

            for _ in range(max(iters, 1)):
                v = norm(jax.lax.stop_gradient(mat).T @ u)
                u = norm(jax.lax.stop_gradient(mat) @ v)
            sigma = u @ mat @ v
            return w / sigma, u, v

        out, u_new, v_new = apply_op(f, weight, self.weight_u, self.weight_v,
                                     op_name="spectral_norm")
        # buffer update (no grad): the reference's power-iteration state
        self.weight_u._replace_data(jax.lax.stop_gradient(u_new._data))
        self.weight_v._replace_data(jax.lax.stop_gradient(v_new._data))
        return out
