"""Activation layers (reference: python/paddle/nn/layer/activation.py)."""

from __future__ import annotations

from . import functional as F
from .layer import Layer


def _layer(fn_name, **fixed):
    class _Act(Layer):
        def __init__(self, *args, name=None, **kwargs):
            super().__init__()
            self._args = args
            self._kwargs = kwargs

        def forward(self, x):
            return getattr(F, fn_name)(x, *self._args, **self._kwargs)

    _Act.__name__ = "".join(p.capitalize() for p in fn_name.split("_"))
    return _Act


ReLU = _layer("relu")
ReLU6 = _layer("relu6")
Sigmoid = _layer("sigmoid")
Tanh = _layer("tanh")
Silu = _layer("silu")
Swish = _layer("swish")
Mish = _layer("mish")
GELU = _layer("gelu")
LeakyReLU = _layer("leaky_relu")
ELU = _layer("elu")
CELU = _layer("celu")
SELU = _layer("selu")
Hardtanh = _layer("hardtanh")
Hardsigmoid = _layer("hardsigmoid")
Hardswish = _layer("hardswish")
Hardshrink = _layer("hardshrink")
Softshrink = _layer("softshrink")
Softplus = _layer("softplus")
Softsign = _layer("softsign")
Tanhshrink = _layer("tanhshrink")
ThresholdedReLU = _layer("thresholded_relu")
LogSigmoid = _layer("log_sigmoid")
Maxout = _layer("maxout")
GLU = _layer("glu")
RReLU = _layer("rrelu")


class Softmax(Layer):
    def __init__(self, axis=-1, name=None):
        super().__init__()
        self.axis = axis

    def forward(self, x):
        return F.softmax(x, axis=self.axis)


class LogSoftmax(Layer):
    def __init__(self, axis=-1, name=None):
        super().__init__()
        self.axis = axis

    def forward(self, x):
        return F.log_softmax(x, axis=self.axis)


class PReLU(Layer):
    def __init__(self, num_parameters=1, init=0.25, weight_attr=None,
                 data_format="NCHW", name=None):
        super().__init__()
        from .initializer import Constant

        self.data_format = data_format
        self.weight = self.create_parameter(
            [num_parameters], attr=weight_attr, default_initializer=Constant(init))

    def forward(self, x):
        return F.prelu(x, self.weight, self.data_format)
