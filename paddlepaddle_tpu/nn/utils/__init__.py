"""paddle.nn.utils (reference: python/paddle/nn/utils/): weight_norm /
spectral_norm reparameterizations, grad clipping, parameter flattening."""

from __future__ import annotations

from typing import Optional

import jax.numpy as jnp
import numpy as np

from ...core.dispatch import apply_op
from ...core.tensor import Tensor
from ..clip import clip_grad_norm_  # noqa: F401
from ..layer import Layer


def parameters_to_vector(parameters, name=None) -> Tensor:
    """Concatenate parameters into one flat vector (reference
    transform_parameters.py)."""
    vals = [jnp.ravel(p._data) for p in parameters]
    return Tensor._from_data(jnp.concatenate(vals))


def vector_to_parameters(vec: Tensor, parameters, name=None) -> None:
    data = vec._data if isinstance(vec, Tensor) else jnp.asarray(vec)
    off = 0
    for p in parameters:
        n = int(np.prod(p.shape))
        p._replace_data(data[off:off + n].reshape(p.shape).astype(p._data.dtype))
        off += n


def _norm_except_dim(v, dim):
    axes = tuple(i for i in range(v.ndim) if i != dim)
    return jnp.sqrt(jnp.sum(v.astype(jnp.float32) ** 2, axis=axes,
                            keepdims=True))


def weight_norm(layer: Layer, name: str = "weight", dim: int = 0) -> Layer:
    """Reparameterize ``layer.<name>`` as g * v/||v|| (reference
    weight_norm_hook.py): adds <name>_g and <name>_v parameters and
    recomputes the weight in a forward pre-hook."""
    from ...core.tensor import Parameter

    w = getattr(layer, name)
    if dim is None:
        dim = -1  # whole-tensor norm
    v0 = jnp.asarray(w._data)
    if dim == -1:
        g0 = jnp.sqrt(jnp.sum(v0.astype(jnp.float32) ** 2)).reshape(1)
    else:
        g0 = _norm_except_dim(v0, dim).reshape(-1)
    layer.add_parameter(name + "_g", Parameter(g0.astype(v0.dtype)))
    layer.add_parameter(name + "_v", Parameter(v0))
    del layer._parameters[name]

    def _compute(lyr):
        g = lyr._parameters[name + "_g"]
        v = lyr._parameters[name + "_v"]

        def f(gv, vv):
            if dim == -1:
                nrm = jnp.sqrt(jnp.sum(vv.astype(jnp.float32) ** 2))
                return (vv / nrm * gv.reshape(())).astype(vv.dtype)
            nrm = _norm_except_dim(vv, dim)
            sh = [1] * vv.ndim
            sh[dim] = -1
            return (vv / nrm * gv.reshape(sh)).astype(vv.dtype)

        return apply_op(f, g, v)

    # expose the computed weight under the original attribute — a PURE
    # function of (g, v), so computing on access (once per forward: the
    # layer reads self.<name> exactly once) needs no pre-hook or cache
    cls = type(layer)

    class _WN(cls):
        pass

    def _get(self):
        return _compute(self)

    setattr(_WN, name, property(_get))
    _WN.__name__ = cls.__name__
    layer.__class__ = _WN
    return layer


def remove_weight_norm(layer: Layer, name: str = "weight") -> Layer:
    """Materialize the current weight and drop the reparameterization."""
    from ...core.tensor import Parameter

    w = getattr(layer, name)           # computed via the property
    for suffix in ("_g", "_v"):
        layer._parameters.pop(name + suffix, None)
    layer.__class__ = type(layer).__mro__[1]   # undo the property subclass
    layer.add_parameter(name, Parameter(w._data if isinstance(w, Tensor)
                                        else jnp.asarray(w)))
    return layer


def spectral_norm(layer: Layer, name: str = "weight", n_power_iterations: int = 1,
                  eps: float = 1e-12, dim: Optional[int] = None) -> Layer:
    """Functional spectral_norm (reference utils/spectral_norm_hook.py):
    wraps the layer's weight with power-iteration normalization on each
    forward via the SpectralNorm module's math."""
    from ..norm import SpectralNorm

    w = getattr(layer, name)
    dim = 0 if dim is None else dim
    sn = SpectralNorm(list(w.shape), dim=dim, power_iters=n_power_iterations,
                      eps=eps)

    def _apply(lyr, inputs):
        object.__setattr__(lyr, "_sn_" + name, sn(lyr._parameters[name + "_orig"]))
        return None

    from ...core.tensor import Parameter

    layer.add_parameter(name + "_orig", Parameter(jnp.asarray(w._data)))
    del layer._parameters[name]
    layer.register_forward_pre_hook(_apply)
    cls = type(layer)

    class _SN(cls):
        pass

    def _get(self):
        cached = self.__dict__.get("_sn_" + name)
        if cached is None:     # lazy first compute; afterwards the pre-hook
            _apply(self, ())   # is the only power-iteration advance, so
            cached = self.__dict__.get("_sn_" + name)  # reads don't mutate
        return cached

    setattr(_SN, name, property(_get))
    _SN.__name__ = cls.__name__
    layer.__class__ = _SN
    return layer

