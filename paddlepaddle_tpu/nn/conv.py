"""Conv layers (reference: python/paddle/nn/layer/conv.py). Weight layout
[out_channels, in_channels/groups, *kernel] (paddle OIHW convention); lowering
is one XLA conv_general_dilated which the TPU compiler maps to the MXU."""

from __future__ import annotations

import numpy as np

from . import functional as F
from .initializer import KaimingUniform, Uniform
from .layer import Layer


def _ntuple(v, n):
    return tuple(v) if isinstance(v, (list, tuple)) else (v,) * n


class _ConvNd(Layer):
    def __init__(self, in_channels, out_channels, kernel_size, nd, stride=1, padding=0,
                 dilation=1, groups=1, padding_mode="zeros", weight_attr=None,
                 bias_attr=None, data_format="NCHW"):
        super().__init__()
        self._in_channels = in_channels
        self._out_channels = out_channels
        self._kernel_size = _ntuple(kernel_size, nd)
        self._stride = _ntuple(stride, nd)
        self._padding = padding
        self._dilation = _ntuple(dilation, nd)
        self._groups = groups
        self._data_format = data_format
        self._nd = nd
        filter_shape = [out_channels, in_channels // groups] + list(self._kernel_size)
        fan_in = in_channels * int(np.prod(self._kernel_size)) // groups
        self.weight = self.create_parameter(
            filter_shape, attr=weight_attr, default_initializer=KaimingUniform(fan_in=fan_in))
        bound = 1.0 / np.sqrt(fan_in)
        self.bias = (
            self.create_parameter([out_channels], attr=bias_attr, is_bias=True,
                                  default_initializer=Uniform(-bound, bound))
            if bias_attr is not False else None
        )

    def extra_repr(self):
        return (f"{self._in_channels}, {self._out_channels}, kernel_size={self._kernel_size}, "
                f"stride={self._stride}, padding={self._padding}")


class Conv1D(_ConvNd):
    def __init__(self, in_channels, out_channels, kernel_size, stride=1, padding=0,
                 dilation=1, groups=1, padding_mode="zeros", weight_attr=None,
                 bias_attr=None, data_format="NCL"):
        super().__init__(in_channels, out_channels, kernel_size, 1, stride, padding,
                         dilation, groups, padding_mode, weight_attr, bias_attr, data_format)

    def forward(self, x):
        return F.conv1d(x, self.weight, self.bias, self._stride, self._padding,
                        self._dilation, self._groups, self._data_format)


class Conv2D(_ConvNd):
    def __init__(self, in_channels, out_channels, kernel_size, stride=1, padding=0,
                 dilation=1, groups=1, padding_mode="zeros", weight_attr=None,
                 bias_attr=None, data_format="NCHW"):
        super().__init__(in_channels, out_channels, kernel_size, 2, stride, padding,
                         dilation, groups, padding_mode, weight_attr, bias_attr, data_format)

    def forward(self, x):
        return F.conv2d(x, self.weight, self.bias, self._stride, self._padding,
                        self._dilation, self._groups, self._data_format)


class Conv3D(_ConvNd):
    def __init__(self, in_channels, out_channels, kernel_size, stride=1, padding=0,
                 dilation=1, groups=1, padding_mode="zeros", weight_attr=None,
                 bias_attr=None, data_format="NCDHW"):
        super().__init__(in_channels, out_channels, kernel_size, 3, stride, padding,
                         dilation, groups, padding_mode, weight_attr, bias_attr, data_format)

    def forward(self, x):
        return F.conv3d(x, self.weight, self.bias, self._stride, self._padding,
                        self._dilation, self._groups, self._data_format)


class Conv2DTranspose(Layer):
    def __init__(self, in_channels, out_channels, kernel_size, stride=1, padding=0,
                 output_padding=0, dilation=1, groups=1, weight_attr=None,
                 bias_attr=None, data_format="NCHW"):
        super().__init__()
        self._stride = _ntuple(stride, 2)
        self._padding = padding
        self._output_padding = output_padding
        self._dilation = _ntuple(dilation, 2)
        self._groups = groups
        kernel = _ntuple(kernel_size, 2)
        # paddle layout for transpose conv: [in, out/groups, kh, kw]
        self.weight = self.create_parameter(
            [in_channels, out_channels // groups] + list(kernel), attr=weight_attr)
        self.bias = (self.create_parameter([out_channels], attr=bias_attr, is_bias=True)
                     if bias_attr is not False else None)

    def forward(self, x, output_size=None):
        return F.conv2d_transpose(x, self.weight, self.bias, self._stride, self._padding,
                                  self._output_padding, self._groups, self._dilation,
                                  output_size=output_size)
