"""Gradient clipping (reference: python/paddle/nn/clip.py).

ClipGradByGlobalNorm's cross-group norm aggregation under hybrid parallel
(reference hybrid_parallel_optimizer.py:266) is handled in
paddlepaddle_tpu.distributed: on a GSPMD mesh the global norm over sharded
grads is computed by XLA collectives automatically when grads are sharded."""

from __future__ import annotations

import jax.numpy as jnp

from ..core.dispatch import unwrap, wrap


class ClipGradBase:
    def __call__(self, params_grads):
        raise NotImplementedError


class ClipGradByValue(ClipGradBase):
    def __init__(self, max, min=None):
        self.max = max
        self.min = min if min is not None else -max

    def __call__(self, params_grads):
        out = []
        for p, g in params_grads:
            if g is None or not getattr(p, "need_clip", True):
                out.append((p, g))
                continue
            out.append((p, wrap(jnp.clip(unwrap(g), self.min, self.max))))
        return out


class ClipGradByNorm(ClipGradBase):
    def __init__(self, clip_norm):
        self.clip_norm = clip_norm

    def __call__(self, params_grads):
        out = []
        for p, g in params_grads:
            if g is None or not getattr(p, "need_clip", True):
                out.append((p, g))
                continue
            gd = unwrap(g)
            norm = jnp.sqrt(jnp.sum(jnp.square(gd.astype(jnp.float32))))
            scale = jnp.minimum(self.clip_norm / jnp.maximum(norm, 1e-12), 1.0)
            out.append((p, wrap((gd * scale).astype(gd.dtype))))
        return out


class ClipGradByGlobalNorm(ClipGradBase):
    def __init__(self, clip_norm, group_name="default_group", auto_skip_clip=False):
        self.clip_norm = clip_norm
        self.group_name = group_name

    def __call__(self, params_grads):
        sq = []
        for p, g in params_grads:
            if g is None or not getattr(p, "need_clip", True):
                continue
            gd = unwrap(g).astype(jnp.float32)
            sq.append(jnp.sum(jnp.square(gd)))
        if not sq:
            return params_grads
        global_norm = jnp.sqrt(sum(sq))
        scale = jnp.minimum(self.clip_norm / jnp.maximum(global_norm, 1e-12), 1.0)
        out = []
        for p, g in params_grads:
            if g is None or not getattr(p, "need_clip", True):
                out.append((p, g))
                continue
            gd = unwrap(g)
            out.append((p, wrap((gd.astype(jnp.float32) * scale).astype(gd.dtype))))
        return out

    def clip_tree(self, grads_tree):
        """Functional form for jitted train steps: clip a pytree of jnp grads."""
        import jax

        leaves = jax.tree_util.tree_leaves(grads_tree)
        global_norm = jnp.sqrt(sum(jnp.sum(jnp.square(g.astype(jnp.float32))) for g in leaves))
        scale = jnp.minimum(self.clip_norm / jnp.maximum(global_norm, 1e-12), 1.0)
        return jax.tree_util.tree_map(lambda g: (g.astype(jnp.float32) * scale).astype(g.dtype), grads_tree)


GradientClipByValue = ClipGradByValue
GradientClipByNorm = ClipGradByNorm
GradientClipByGlobalNorm = ClipGradByGlobalNorm


def clip_grad_norm_(parameters, max_norm, norm_type=2.0, error_if_nonfinite=False):
    if not isinstance(parameters, (list, tuple)):
        parameters = [parameters]
    grads = [p._grad for p in parameters if p._grad is not None]
    if not grads:
        return wrap(jnp.asarray(0.0))
    if norm_type == float("inf"):
        total = jnp.max(jnp.stack([jnp.max(jnp.abs(g)) for g in grads]))
    else:
        total = jnp.sum(jnp.stack([jnp.sum(jnp.abs(g.astype(jnp.float32)) ** norm_type) for g in grads])) ** (
            1.0 / norm_type
        )
    scale = jnp.minimum(max_norm / jnp.maximum(total, 1e-12), 1.0)
    for p in parameters:
        if p._grad is not None:
            p._grad = (p._grad.astype(jnp.float32) * scale).astype(p._grad.dtype)
    return wrap(total)
