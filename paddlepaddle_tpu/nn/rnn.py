"""Recurrent layers — cells, RNN/BiRNN drivers, SimpleRNN/LSTM/GRU stacks.

Reference surface: python/paddle/nn/layer/rnn.py (RNNCellBase:~, SimpleRNNCell,
LSTMCell:190 forward with [i,f,g,o] gate chunks, GRUCell with [r,z,c] and the
reset gate applied to the hidden projection, RNN/BiRNN drivers, and the
multi-layer SimpleRNN/LSTM/GRU with forward/bidirect directions).

TPU notes: the time loop is a python loop over unstacked steps — under
``jit``/``TrainStep`` XLA unrolls and fuses it (static seq lens); gate
matmuls are batched [B, 4H] GEMMs on the MXU. Weight layout and gate order
match the reference (and torch): ``weight_ih [G*H, in]``, applied as
``x @ W.T``.
"""

from __future__ import annotations

import math
from typing import Optional, Sequence

import numpy as np

from ..core.dispatch import apply_op
from .initializer import Uniform
from .layer import Layer

__all__ = [
    "RNNCellBase", "SimpleRNNCell", "LSTMCell", "GRUCell", "RNN", "BiRNN",
    "SimpleRNN", "LSTM", "GRU",
]


class RNNCellBase(Layer):
    def get_initial_states(self, batch_ref, shape=None, dtype=None,
                           init_value=0.0, batch_dim_idx=0):
        import jax.numpy as jnp

        batch = batch_ref.shape[batch_dim_idx]

        def make(_):
            return apply_op(
                lambda: jnp.full((batch, self.hidden_size), init_value,
                                 jnp.float32), op_name="rnn_init_state")

        n = len(self.state_shape) if isinstance(self.state_shape, tuple) else 1
        states = tuple(make(i) for i in range(n))
        return states if n > 1 else states[0]


def _init_cell_params(layer, in_size, hidden, gates):
    k = 1.0 / math.sqrt(hidden) if hidden > 0 else 0.0
    u = Uniform(-k, k)
    layer.weight_ih = layer.create_parameter([gates * hidden, in_size],
                                             default_initializer=u)
    layer.weight_hh = layer.create_parameter([gates * hidden, hidden],
                                             default_initializer=u)
    layer.bias_ih = layer.create_parameter([gates * hidden], is_bias=True,
                                           default_initializer=u)
    layer.bias_hh = layer.create_parameter([gates * hidden], is_bias=True,
                                           default_initializer=u)


class SimpleRNNCell(RNNCellBase):
    def __init__(self, input_size, hidden_size, activation="tanh",
                 weight_ih_attr=None, weight_hh_attr=None, bias_ih_attr=None,
                 bias_hh_attr=None, name=None):
        super().__init__()
        self.input_size = input_size
        self.hidden_size = hidden_size
        self.activation = activation
        _init_cell_params(self, input_size, hidden_size, 1)

    @property
    def state_shape(self):
        return (self.hidden_size,)

    def forward(self, inputs, states=None):
        import jax.numpy as jnp

        if states is None:
            states = self.get_initial_states(inputs)
        act = jnp.tanh if self.activation == "tanh" else (
            lambda v: jnp.maximum(v, 0))

        def f(x, h, wi, wh, bi, bh):
            return act(x @ wi.T + bi + h @ wh.T + bh)

        h = apply_op(f, inputs, states, self.weight_ih, self.weight_hh,
                     self.bias_ih, self.bias_hh, op_name="simple_rnn_cell")
        return h, h


class LSTMCell(RNNCellBase):
    """Gate chunks [i, f, g, o] (reference rnn.py:201-207)."""

    def __init__(self, input_size, hidden_size, weight_ih_attr=None,
                 weight_hh_attr=None, bias_ih_attr=None, bias_hh_attr=None,
                 proj_size=0, name=None):
        super().__init__()
        self.input_size = input_size
        self.hidden_size = hidden_size
        _init_cell_params(self, input_size, hidden_size, 4)

    @property
    def state_shape(self):
        return ((self.hidden_size,), (self.hidden_size,))

    def forward(self, inputs, states=None):
        import jax
        import jax.numpy as jnp

        if states is None:
            states = self.get_initial_states(inputs)
        h0, c0 = states

        def f2(x, h, c, wi, wh, bi, bh):
            gates = x @ wi.T + bi + h @ wh.T + bh
            i, fg, g, o = jnp.split(gates, 4, axis=-1)
            c_new = (jax.nn.sigmoid(fg) * c
                     + jax.nn.sigmoid(i) * jnp.tanh(g))
            h_new = jax.nn.sigmoid(o) * jnp.tanh(c_new)
            return h_new, c_new

        h, c = apply_op(f2, inputs, h0, c0, self.weight_ih, self.weight_hh,
                        self.bias_ih, self.bias_hh, op_name="lstm_cell")
        return h, (h, c)


class GRUCell(RNNCellBase):
    """Gate chunks [r, z, c]; reset gate scales the hidden candidate
    projection (reference rnn.py:1158)."""

    def __init__(self, input_size, hidden_size, weight_ih_attr=None,
                 weight_hh_attr=None, bias_ih_attr=None, bias_hh_attr=None,
                 name=None):
        super().__init__()
        self.input_size = input_size
        self.hidden_size = hidden_size
        _init_cell_params(self, input_size, hidden_size, 3)

    @property
    def state_shape(self):
        return (self.hidden_size,)

    def forward(self, inputs, states=None):
        import jax
        import jax.numpy as jnp

        if states is None:
            states = self.get_initial_states(inputs)

        def f(x, h, wi, wh, bi, bh):
            xg = x @ wi.T + bi
            hg = h @ wh.T + bh
            xr, xz, xc = jnp.split(xg, 3, axis=-1)
            hr, hz, hc = jnp.split(hg, 3, axis=-1)
            r = jax.nn.sigmoid(xr + hr)
            z = jax.nn.sigmoid(xz + hz)
            cand = jnp.tanh(xc + r * hc)
            return (1.0 - z) * cand + z * h

        h = apply_op(f, inputs, states, self.weight_ih, self.weight_hh,
                     self.bias_ih, self.bias_hh, op_name="gru_cell")
        return h, h


class RNN(Layer):
    """Drive a cell over the time dim (reference rnn.py RNN)."""

    def __init__(self, cell, is_reverse=False, time_major=False):
        super().__init__()
        self.cell = cell
        self.is_reverse = is_reverse
        self.time_major = time_major

    def forward(self, inputs, initial_states=None, sequence_length=None):
        from ..ops.manipulation import stack as t_stack

        x = inputs if self.time_major else inputs.transpose([1, 0, 2])
        T = x.shape[0]
        order = range(T - 1, -1, -1) if self.is_reverse else range(T)
        states = initial_states
        outs = [None] * T
        for t in order:
            out, new_states = self.cell(x[t], states)
            if sequence_length is not None:
                out, new_states = _mask_step(t, sequence_length, out,
                                             new_states, states)
            states = new_states
            outs[t] = out
        y = t_stack(outs, axis=0)
        if not self.time_major:
            y = y.transpose([1, 0, 2])
        return y, states


def _mask_step(t, seq_lens, out, new_states, old_states):
    """Freeze finished sequences (t >= their length)."""
    import jax.numpy as jnp

    def pick(n, o):
        return apply_op(
            lambda nv, ov, sl: jnp.where((t < sl)[:, None], nv, ov),
            n, o, seq_lens, op_name="rnn_mask")

    if old_states is None:
        return out, new_states
    if isinstance(new_states, tuple):
        masked = tuple(pick(n, o) for n, o in zip(new_states, old_states))
        return pick(out, old_states[0]), masked
    m = pick(new_states, old_states)
    return m, m


class BiRNN(Layer):
    def __init__(self, cell_fw, cell_bw, time_major=False):
        super().__init__()
        self.rnn_fw = RNN(cell_fw, is_reverse=False, time_major=time_major)
        self.rnn_bw = RNN(cell_bw, is_reverse=True, time_major=time_major)

    def forward(self, inputs, initial_states=None, sequence_length=None):
        from ..ops.manipulation import concat

        s_fw, s_bw = (initial_states if initial_states is not None
                      else (None, None))
        y_fw, st_fw = self.rnn_fw(inputs, s_fw, sequence_length)
        y_bw, st_bw = self.rnn_bw(inputs, s_bw, sequence_length)
        return concat([y_fw, y_bw], axis=-1), (st_fw, st_bw)


class _RNNBase(Layer):
    """Multi-layer / bidirectional stack (reference SimpleRNN/LSTM/GRU)."""

    CELL = None
    STATES = 1

    def __init__(self, input_size, hidden_size, num_layers=1,
                 direction="forward", time_major=False, dropout=0.0,
                 activation=None, weight_ih_attr=None, weight_hh_attr=None,
                 bias_ih_attr=None, bias_hh_attr=None, name=None):
        super().__init__()
        if direction not in ("forward", "bidirect", "bidirectional"):
            raise ValueError(f"unknown direction {direction!r}")
        self.bidirectional = direction != "forward"
        self.num_layers = num_layers
        self.hidden_size = hidden_size
        self.time_major = time_major
        self.dropout = dropout
        ndir = 2 if self.bidirectional else 1
        self._layers_fw = []
        self._layers_bw = []
        for l in range(num_layers):
            in_size = input_size if l == 0 else hidden_size * ndir
            kw = {"activation": activation} if (
                activation and self.CELL is SimpleRNNCell) else {}
            fw = self.CELL(in_size, hidden_size, **kw)
            self.add_sublayer(f"cell_fw_l{l}", fw)
            self._layers_fw.append(RNN(fw, time_major=True))
            if self.bidirectional:
                bw = self.CELL(in_size, hidden_size, **kw)
                self.add_sublayer(f"cell_bw_l{l}", bw)
                self._layers_bw.append(RNN(bw, is_reverse=True,
                                           time_major=True))

    def forward(self, inputs, initial_states=None, sequence_length=None):
        from ..nn import functional as F
        from ..ops.manipulation import concat, stack

        x = inputs if self.time_major else inputs.transpose([1, 0, 2])
        finals = []
        for l in range(self.num_layers):
            init_fw = init_bw = None
            if initial_states is not None:
                init_fw, init_bw = self._layer_init(initial_states, l)
            y_fw, st_fw = self._layers_fw[l](x, init_fw, sequence_length)
            if self.bidirectional:
                y_bw, st_bw = self._layers_bw[l](x, init_bw, sequence_length)
                x = concat([y_fw, y_bw], axis=-1)
                finals.extend([st_fw, st_bw])
            else:
                x = y_fw
                finals.append(st_fw)
            if self.dropout and l < self.num_layers - 1:
                x = F.dropout(x, p=self.dropout, training=self.training)
        y = x if self.time_major else x.transpose([1, 0, 2])
        if self.STATES == 1:
            states = stack(finals, axis=0)  # [L*D, B, H]
        else:
            states = tuple(
                stack([f[i] for f in finals], axis=0)
                for i in range(self.STATES))
        return y, states

    def _layer_init(self, initial_states, l):
        ndir = 2 if self.bidirectional else 1

        def slot(i):
            if self.STATES == 1:
                return initial_states[l * ndir + i]
            return tuple(s[l * ndir + i] for s in initial_states)

        return slot(0), (slot(1) if self.bidirectional else None)


class SimpleRNN(_RNNBase):
    CELL = SimpleRNNCell
    STATES = 1


class LSTM(_RNNBase):
    CELL = LSTMCell
    STATES = 2


class GRU(_RNNBase):
    CELL = GRUCell
    STATES = 1
