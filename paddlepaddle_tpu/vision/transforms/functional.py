"""paddle.vision.transforms functional ops (reference:
python/paddle/vision/transforms/functional{,_cv2,_pil,_tensor}.py).

Host-side numpy implementations over HWC arrays (uint8 or float), the
backend-neutral subset of the reference's cv2/PIL/tensor triple backends:
geometry (resize/crop/flip/pad/affine/rotate/perspective) samples through
one inverse-warp helper; photometry (brightness/contrast/saturation/hue)
follows the blend formulas the reference's tensor backend uses."""

from __future__ import annotations

import numbers

import numpy as np

__all__ = [
    "to_tensor", "hflip", "vflip", "resize", "pad", "crop", "center_crop",
    "affine", "rotate", "perspective", "to_grayscale", "normalize",
    "adjust_brightness", "adjust_contrast", "adjust_saturation",
    "adjust_hue", "erase",
]


def _hwc(img):
    a = np.asarray(img)
    if a.ndim == 2:
        a = a[:, :, None]
    return a


def _restore_dtype(out, ref):
    if np.asarray(ref).dtype == np.uint8:
        return np.clip(np.round(out), 0, 255).astype(np.uint8)
    return out.astype(np.asarray(ref).dtype)


def to_tensor(pic, data_format="CHW"):
    """HWC image -> CHW float Tensor in [0,1] (reference functional
    to_tensor)."""
    from ...core.tensor import Tensor

    a = _hwc(pic)
    if a.dtype == np.uint8:
        a = a.astype(np.float32) / 255.0
    else:
        a = a.astype(np.float32)
    if data_format == "CHW":
        a = np.transpose(a, (2, 0, 1))
    import jax.numpy as jnp

    return Tensor._from_data(jnp.asarray(a))


def hflip(img):
    return np.ascontiguousarray(np.asarray(img)[:, ::-1])


def vflip(img):
    return np.ascontiguousarray(np.asarray(img)[::-1])


def resize(img, size, interpolation="bilinear"):
    from . import Resize

    return Resize(size, interpolation)._apply_image(np.asarray(img))


def crop(img, top, left, height, width):
    return np.asarray(img)[top:top + height, left:left + width]


def center_crop(img, output_size):
    if isinstance(output_size, numbers.Number):
        output_size = (int(output_size), int(output_size))
    a = np.asarray(img)
    h, w = a.shape[:2]
    th, tw = output_size
    return crop(a, max(0, (h - th) // 2), max(0, (w - tw) // 2), th, tw)


def pad(img, padding, fill=0, padding_mode="constant"):
    """padding: int (all sides) | (lr, tb) | (left, top, right, bottom);
    modes constant/edge/reflect/symmetric (reference functional pad)."""
    a = _hwc(img)
    if isinstance(padding, numbers.Number):
        l = t = r = b = int(padding)
    elif len(padding) == 2:
        l, t = int(padding[0]), int(padding[1])
        r, b = l, t
    else:
        l, t, r, b = (int(p) for p in padding)
    spec = [(t, b), (l, r), (0, 0)]
    if padding_mode != "constant":
        out = np.pad(a, spec, mode=padding_mode)
    elif isinstance(fill, (list, tuple)):
        # per-channel fill (reference: a length-3 tuple fills R, G, B)
        out = np.stack([np.pad(a[..., c], spec[:2], constant_values=fv)
                        for c, fv in enumerate(fill)], -1)
    else:
        out = np.pad(a, spec, constant_values=fill)
    return out if np.asarray(img).ndim == 3 else out[:, :, 0]


def normalize(img, mean, std, data_format="CHW", to_rgb=False):
    a = np.asarray(img, np.float32)
    mean = np.asarray(mean, np.float32)
    std = np.asarray(std, np.float32)
    if data_format == "CHW":
        return (a - mean.reshape(-1, 1, 1)) / std.reshape(-1, 1, 1)
    if to_rgb:
        a = a[..., ::-1]
    return (a - mean) / std


def to_grayscale(img, num_output_channels=1):
    """ITU-R 601-2 luma (reference functional to_grayscale)."""
    a = _hwc(img)
    if a.shape[2] == 1:
        gray = a[:, :, 0].astype(np.float32)
    else:
        gray = (0.299 * a[..., 0] + 0.587 * a[..., 1]
                + 0.114 * a[..., 2]).astype(np.float32)
    out = np.repeat(gray[:, :, None], num_output_channels, axis=2)
    return _restore_dtype(out, img)


# ---- photometric adjustments ----------------------------------------------


def _blend(img1, img2, ratio):
    out = ratio * img1.astype(np.float32) + (1.0 - ratio) * img2
    return out


def adjust_brightness(img, brightness_factor):
    a = _hwc(img).astype(np.float32)
    return _restore_dtype(
        _blend(a, np.zeros_like(a), brightness_factor), img)


def adjust_contrast(img, contrast_factor):
    a = _hwc(img)
    g = to_grayscale(a.astype(np.float32))
    mean = float(np.round(g[..., 0].mean())) if np.asarray(img).dtype == \
        np.uint8 else float(g[..., 0].mean())
    return _restore_dtype(
        _blend(a.astype(np.float32), mean, contrast_factor), img)


def adjust_saturation(img, saturation_factor):
    a = _hwc(img).astype(np.float32)
    g = to_grayscale(a)
    return _restore_dtype(_blend(a, g.astype(np.float32),
                                 saturation_factor), img)


def adjust_hue(img, hue_factor):
    """Shift hue by ``hue_factor`` in [-0.5, 0.5] of a full cycle
    (reference functional adjust_hue, HSV round-trip)."""
    if not -0.5 <= hue_factor <= 0.5:
        raise ValueError(f"hue_factor {hue_factor} is not in [-0.5, 0.5]")
    a = _hwc(img)
    if a.shape[2] < 3:
        # grayscale has no hue — the reference returns it unchanged
        return np.asarray(img)
    f = a.astype(np.float32) / (255.0 if a.dtype == np.uint8 else 1.0)
    r, g, b = f[..., 0], f[..., 1], f[..., 2]
    mx, mn = f.max(-1), f.min(-1)
    d = mx - mn
    safe = np.where(d == 0, 1.0, d)
    h = np.select(
        [mx == r, mx == g],
        [((g - b) / safe) % 6.0, (b - r) / safe + 2.0],
        (r - g) / safe + 4.0) / 6.0
    h = np.where(d == 0, 0.0, h)
    s = np.where(mx == 0, 0.0, d / np.where(mx == 0, 1.0, mx))
    h = (h + hue_factor) % 1.0
    # HSV -> RGB
    i = np.floor(h * 6.0)
    fr = h * 6.0 - i
    p = mx * (1 - s)
    q = mx * (1 - s * fr)
    t = mx * (1 - s * (1 - fr))
    i = i.astype(np.int32) % 6
    r2 = np.choose(i, [mx, q, p, p, t, mx])
    g2 = np.choose(i, [t, mx, mx, q, p, p])
    b2 = np.choose(i, [p, p, t, mx, mx, q])
    out = np.stack([r2, g2, b2], -1)
    if a.dtype == np.uint8:
        out = out * 255.0
    return _restore_dtype(out, img)


def erase(img, i, j, h, w, v, inplace=False):
    """Set region [i:i+h, j:j+w] to v (reference functional erase).
    Accepts HWC/CHW ndarrays or Tensors (CHW, the post-ToTensor case)."""
    from ...core.tensor import Tensor

    if isinstance(img, Tensor):
        import jax.numpy as jnp

        data = img._data
        val = jnp.asarray(v, data.dtype)
        if val.ndim == 1:                               # per-channel (CHW)
            val = val.reshape(-1, 1, 1)
        new = data.at[..., i:i + h, j:j + w].set(
            jnp.broadcast_to(val, data[..., i:i + h, j:j + w].shape))
        if inplace:
            img._replace_data(new)
            return img
        return Tensor._from_data(new)
    a = np.asarray(img)
    out = a if inplace else a.copy()
    v = np.asarray(v, a.dtype)
    if a.ndim == 3 and a.shape[0] in (1, 3) and a.shape[0] <= a.shape[2]:
        if v.ndim == 1:                                 # per-channel value
            v = v.reshape(-1, 1, 1)
        out[:, i:i + h, j:j + w] = v                    # CHW
    else:
        if v.ndim == 1 and a.ndim == 3:
            v = v.reshape(1, 1, -1)
        out[i:i + h, j:j + w] = v                       # HW(C)
    return out


# ---- geometric warps -------------------------------------------------------


def _warp(img, inv, out_h, out_w, interpolation="nearest", fill=0):
    """Sample output pixel centers through the inverse transform ``inv``
    (3x3), zero-/fill-padded outside, nearest or bilinear."""
    a = _hwc(img).astype(np.float32)
    h, w, c = a.shape
    ys, xs = np.meshgrid(np.arange(out_h, dtype=np.float64),
                         np.arange(out_w, dtype=np.float64), indexing="ij")
    ones = np.ones_like(xs)
    src = inv @ np.stack([xs.ravel(), ys.ravel(), ones.ravel()])
    sx = (src[0] / src[2]).reshape(out_h, out_w)
    sy = (src[1] / src[2]).reshape(out_h, out_w)

    fill_v = np.broadcast_to(np.asarray(fill, np.float32), (c,))
    if interpolation == "nearest":
        xi = np.round(sx).astype(np.int64)
        yi = np.round(sy).astype(np.int64)
        ok = (xi >= 0) & (xi < w) & (yi >= 0) & (yi < h)
        out = a[yi.clip(0, h - 1), xi.clip(0, w - 1)]
        out = np.where(ok[..., None], out, fill_v)
    else:
        x0 = np.floor(sx).astype(np.int64)
        y0 = np.floor(sy).astype(np.int64)
        out = np.zeros((out_h, out_w, c), np.float32)
        wsum = np.zeros((out_h, out_w, 1), np.float32)
        for dy in (0, 1):
            for dx in (0, 1):
                xi, yi = x0 + dx, y0 + dy
                wgt = ((1 - np.abs(sx - xi)) * (1 - np.abs(sy - yi)))
                ok = (xi >= 0) & (xi < w) & (yi >= 0) & (yi < h)
                wgt = np.where(ok, wgt, 0.0)[..., None].astype(np.float32)
                out += wgt * a[yi.clip(0, h - 1), xi.clip(0, w - 1)]
                wsum += wgt
        out = out + (1.0 - wsum) * fill_v
    out = _restore_dtype(out, img)
    return out if np.asarray(img).ndim == 3 else out[:, :, 0]


def _affine_matrix(center, angle, translate, scale, shear):
    """Forward affine about ``center``: translate . C . R(angle) .
    Shear . Scale . C^-1 (the reference/torchvision composition; angles
    in degrees)."""
    rot = np.deg2rad(angle)
    sx, sy = (np.deg2rad(s) for s in shear)
    cx, cy = center
    tx, ty = translate
    # R * Shear^-1 convention of the reference: build RSS directly
    a = np.cos(rot - sy) / np.cos(sy)
    b = -np.cos(rot - sy) * np.tan(sx) / np.cos(sy) - np.sin(rot)
    c = np.sin(rot - sy) / np.cos(sy)
    d = -np.sin(rot - sy) * np.tan(sx) / np.cos(sy) + np.cos(rot)
    m = np.array([[a * scale, b * scale, 0.0],
                  [c * scale, d * scale, 0.0],
                  [0.0, 0.0, 1.0]])
    pre = np.array([[1, 0, cx + tx], [0, 1, cy + ty], [0, 0, 1.0]])
    post = np.array([[1, 0, -cx], [0, 1, -cy], [0, 0, 1.0]])
    return pre @ m @ post


def affine(img, angle, translate, scale, shear, interpolation="nearest",
           fill=0, center=None):
    """Affine warp (reference functional affine): rotation ``angle`` deg,
    pixel ``translate``, isotropic ``scale``, (sx, sy) ``shear`` deg."""
    a = _hwc(img)
    h, w = a.shape[:2]
    if isinstance(shear, numbers.Number):
        shear = (shear, 0.0)
    if center is None:
        center = ((w - 1) * 0.5, (h - 1) * 0.5)
    fwd = _affine_matrix(center, angle, translate, scale, tuple(shear))
    return _warp(img, np.linalg.inv(fwd), h, w, interpolation, fill)


def rotate(img, angle, interpolation="nearest", expand=False, center=None,
           fill=0):
    """Rotate ``angle`` degrees counter-clockwise (reference functional
    rotate); ``expand`` grows the canvas to hold the whole rotation
    (ignoring any explicit center, as upstream)."""
    a = _hwc(img)
    h, w = a.shape[:2]
    if center is None or expand:
        center = ((w - 1) * 0.5, (h - 1) * 0.5)
    fwd = _affine_matrix(center, -angle, (0, 0), 1.0, (0.0, 0.0))
    out_h, out_w = h, w
    if expand:
        corners = np.array([[0, 0, 1], [w - 1, 0, 1],
                            [0, h - 1, 1], [w - 1, h - 1, 1]]).T
        mapped = fwd @ corners
        xs, ys = mapped[0], mapped[1]
        out_w = int(np.ceil(xs.max() - xs.min())) + 1
        out_h = int(np.ceil(ys.max() - ys.min())) + 1
        shift = np.array([[1, 0, -xs.min()], [0, 1, -ys.min()],
                          [0, 0, 1.0]])
        fwd = shift @ fwd
    return _warp(img, np.linalg.inv(fwd), out_h, out_w, interpolation, fill)


def perspective(img, startpoints, endpoints, interpolation="nearest",
                fill=0):
    """Projective warp mapping 4 ``startpoints`` to ``endpoints``
    (reference functional perspective); points are (x, y)."""
    a = _hwc(img)
    h, w = a.shape[:2]
    A, rhs = [], []
    for (sx, sy), (ex, ey) in zip(startpoints, endpoints):
        A.append([sx, sy, 1, 0, 0, 0, -ex * sx, -ex * sy])
        A.append([0, 0, 0, sx, sy, 1, -ey * sx, -ey * sy])
        rhs += [ex, ey]
    coef = np.linalg.lstsq(np.asarray(A, np.float64),
                           np.asarray(rhs, np.float64), rcond=None)[0]
    fwd = np.append(coef, 1.0).reshape(3, 3)
    return _warp(img, np.linalg.inv(fwd), h, w, interpolation, fill)
