"""Vision transforms (reference: python/paddle/vision/transforms/transforms.py).

Numpy/host-side preprocessing (HWC uint8/float in, CHW float out) — the data
pipeline stays on host, the device sees ready batches.
"""

from __future__ import annotations

import numbers
import random
from typing import Sequence

import numpy as np


class Compose:
    def __init__(self, transforms):
        self.transforms = list(transforms)

    def __call__(self, data):
        for t in self.transforms:
            data = t(data)
        return data


class BaseTransform:
    def __call__(self, img):
        return self._apply_image(np.asarray(img))

    def _apply_image(self, img):
        raise NotImplementedError


class ToTensor(BaseTransform):
    """HWC [0,255] uint8 -> CHW float32 [0,1]."""

    def __init__(self, data_format="CHW"):
        self.data_format = data_format

    def _apply_image(self, img):
        img = np.asarray(img)
        if img.ndim == 2:
            img = img[:, :, None]
        if img.dtype == np.uint8:
            img = img.astype(np.float32) / 255.0
        else:
            img = img.astype(np.float32)
        if self.data_format == "CHW":
            img = np.transpose(img, (2, 0, 1))
        return img


class Normalize(BaseTransform):
    def __init__(self, mean=0.0, std=1.0, data_format="CHW", to_rgb=False):
        if isinstance(mean, numbers.Number):
            mean = [mean] * 3
        if isinstance(std, numbers.Number):
            std = [std] * 3
        self.mean = np.asarray(mean, np.float32)
        self.std = np.asarray(std, np.float32)
        self.data_format = data_format

    def _apply_image(self, img):
        img = np.asarray(img, np.float32)
        if self.data_format == "CHW":
            return (img - self.mean[:, None, None]) / self.std[:, None, None]
        return (img - self.mean) / self.std


class Resize(BaseTransform):
    """Reference semantics: an int size scales the SHORTER edge preserving
    aspect ratio; a (h, w) pair is exact. Bilinear by default."""

    def __init__(self, size, interpolation="bilinear"):
        self.size = size
        self.interpolation = interpolation

    def _target(self, h, w):
        if isinstance(self.size, int):
            if h <= w:
                return self.size, max(1, int(round(w * self.size / h)))
            return max(1, int(round(h * self.size / w))), self.size
        return tuple(self.size)

    def _apply_image(self, img):
        img = np.asarray(img)
        chw = img.ndim == 3 and img.shape[0] in (1, 3) and img.shape[0] < img.shape[-1]
        if chw:
            img = np.transpose(img, (1, 2, 0))
        h, w = img.shape[:2]
        th, tw = self._target(h, w)
        if self.interpolation == "nearest":
            ys = (np.arange(th) * (h / th)).astype(np.int64).clip(0, h - 1)
            xs = (np.arange(tw) * (w / tw)).astype(np.int64).clip(0, w - 1)
            out = img[ys][:, xs]
        else:  # bilinear (align_corners=False convention)
            fy = (np.arange(th) + 0.5) * (h / th) - 0.5
            fx = (np.arange(tw) + 0.5) * (w / tw) - 0.5
            y0 = np.clip(np.floor(fy).astype(np.int64), 0, h - 1)
            x0 = np.clip(np.floor(fx).astype(np.int64), 0, w - 1)
            y1 = np.clip(y0 + 1, 0, h - 1)
            x1 = np.clip(x0 + 1, 0, w - 1)
            wy = np.clip(fy - y0, 0.0, 1.0)[:, None]
            wx = np.clip(fx - x0, 0.0, 1.0)[None, :]
            if img.ndim == 3:
                wy = wy[..., None]
                wx = wx[..., None]
            f = img.astype(np.float32)
            top = f[y0][:, x0] * (1 - wx) + f[y0][:, x1] * wx
            bot = f[y1][:, x0] * (1 - wx) + f[y1][:, x1] * wx
            out = top * (1 - wy) + bot * wy
            if img.dtype == np.uint8:
                out = np.clip(np.round(out), 0, 255).astype(np.uint8)
            else:
                out = out.astype(img.dtype)
        if chw:
            out = np.transpose(out, (2, 0, 1))
        return out


class CenterCrop(BaseTransform):
    def __init__(self, size):
        self.size = (size, size) if isinstance(size, int) else tuple(size)

    def _apply_image(self, img):
        img = np.asarray(img)
        h, w = img.shape[:2]
        th, tw = self.size
        i = max(0, (h - th) // 2)
        j = max(0, (w - tw) // 2)
        return img[i:i + th, j:j + tw]


class RandomCrop(BaseTransform):
    def __init__(self, size, padding=0):
        self.size = (size, size) if isinstance(size, int) else tuple(size)
        self.padding = padding

    def _apply_image(self, img):
        img = np.asarray(img)
        if self.padding:
            pad = [(self.padding, self.padding), (self.padding, self.padding)]
            if img.ndim == 3:
                pad.append((0, 0))
            img = np.pad(img, pad)
        h, w = img.shape[:2]
        th, tw = self.size
        i = random.randint(0, max(0, h - th))
        j = random.randint(0, max(0, w - tw))
        return img[i:i + th, j:j + tw]


class RandomHorizontalFlip(BaseTransform):
    def __init__(self, prob=0.5):
        self.prob = prob

    def _apply_image(self, img):
        if random.random() < self.prob:
            return np.asarray(img)[:, ::-1].copy()
        return np.asarray(img)


class Transpose(BaseTransform):
    def __init__(self, order=(2, 0, 1)):
        self.order = order

    def _apply_image(self, img):
        return np.transpose(np.asarray(img), self.order)
