"""Vision transforms (reference: python/paddle/vision/transforms/transforms.py).

Numpy/host-side preprocessing (HWC uint8/float in, CHW float out) — the data
pipeline stays on host, the device sees ready batches.
"""

from __future__ import annotations

import numbers
import random
from typing import Sequence

import numpy as np


class Compose:
    def __init__(self, transforms):
        self.transforms = list(transforms)

    def __call__(self, data):
        for t in self.transforms:
            data = t(data)
        return data


class BaseTransform:
    def __call__(self, img):
        return self._apply_image(np.asarray(img))

    def _apply_image(self, img):
        raise NotImplementedError


class ToTensor(BaseTransform):
    """HWC [0,255] uint8 -> CHW float32 [0,1]."""

    def __init__(self, data_format="CHW"):
        self.data_format = data_format

    def _apply_image(self, img):
        img = np.asarray(img)
        if img.ndim == 2:
            img = img[:, :, None]
        if img.dtype == np.uint8:
            img = img.astype(np.float32) / 255.0
        else:
            img = img.astype(np.float32)
        if self.data_format == "CHW":
            img = np.transpose(img, (2, 0, 1))
        return img


class Normalize(BaseTransform):
    def __init__(self, mean=0.0, std=1.0, data_format="CHW", to_rgb=False):
        if isinstance(mean, numbers.Number):
            mean = [mean] * 3
        if isinstance(std, numbers.Number):
            std = [std] * 3
        self.mean = np.asarray(mean, np.float32)
        self.std = np.asarray(std, np.float32)
        self.data_format = data_format

    def _apply_image(self, img):
        img = np.asarray(img, np.float32)
        if self.data_format == "CHW":
            return (img - self.mean[:, None, None]) / self.std[:, None, None]
        return (img - self.mean) / self.std


class Resize(BaseTransform):
    """Reference semantics: an int size scales the SHORTER edge preserving
    aspect ratio; a (h, w) pair is exact. Bilinear by default."""

    def __init__(self, size, interpolation="bilinear"):
        self.size = size
        self.interpolation = interpolation

    def _target(self, h, w):
        if isinstance(self.size, int):
            if h <= w:
                return self.size, max(1, int(round(w * self.size / h)))
            return max(1, int(round(h * self.size / w))), self.size
        return tuple(self.size)

    def _apply_image(self, img):
        img = np.asarray(img)
        chw = img.ndim == 3 and img.shape[0] in (1, 3) and img.shape[0] < img.shape[-1]
        if chw:
            img = np.transpose(img, (1, 2, 0))
        h, w = img.shape[:2]
        th, tw = self._target(h, w)
        if self.interpolation == "nearest":
            ys = (np.arange(th) * (h / th)).astype(np.int64).clip(0, h - 1)
            xs = (np.arange(tw) * (w / tw)).astype(np.int64).clip(0, w - 1)
            out = img[ys][:, xs]
        else:  # bilinear (align_corners=False convention)
            fy = (np.arange(th) + 0.5) * (h / th) - 0.5
            fx = (np.arange(tw) + 0.5) * (w / tw) - 0.5
            y0 = np.clip(np.floor(fy).astype(np.int64), 0, h - 1)
            x0 = np.clip(np.floor(fx).astype(np.int64), 0, w - 1)
            y1 = np.clip(y0 + 1, 0, h - 1)
            x1 = np.clip(x0 + 1, 0, w - 1)
            wy = np.clip(fy - y0, 0.0, 1.0)[:, None]
            wx = np.clip(fx - x0, 0.0, 1.0)[None, :]
            if img.ndim == 3:
                wy = wy[..., None]
                wx = wx[..., None]
            f = img.astype(np.float32)
            top = f[y0][:, x0] * (1 - wx) + f[y0][:, x1] * wx
            bot = f[y1][:, x0] * (1 - wx) + f[y1][:, x1] * wx
            out = top * (1 - wy) + bot * wy
            if img.dtype == np.uint8:
                out = np.clip(np.round(out), 0, 255).astype(np.uint8)
            else:
                out = out.astype(img.dtype)
        if chw:
            out = np.transpose(out, (2, 0, 1))
        return out


class CenterCrop(BaseTransform):
    def __init__(self, size):
        self.size = (size, size) if isinstance(size, int) else tuple(size)

    def _apply_image(self, img):
        img = np.asarray(img)
        h, w = img.shape[:2]
        th, tw = self.size
        i = max(0, (h - th) // 2)
        j = max(0, (w - tw) // 2)
        return img[i:i + th, j:j + tw]


class RandomCrop(BaseTransform):
    def __init__(self, size, padding=0):
        self.size = (size, size) if isinstance(size, int) else tuple(size)
        self.padding = padding

    def _apply_image(self, img):
        img = np.asarray(img)
        if self.padding:
            pad = [(self.padding, self.padding), (self.padding, self.padding)]
            if img.ndim == 3:
                pad.append((0, 0))
            img = np.pad(img, pad)
        h, w = img.shape[:2]
        th, tw = self.size
        i = random.randint(0, max(0, h - th))
        j = random.randint(0, max(0, w - tw))
        return img[i:i + th, j:j + tw]


class RandomHorizontalFlip(BaseTransform):
    def __init__(self, prob=0.5):
        self.prob = prob

    def _apply_image(self, img):
        if random.random() < self.prob:
            return np.asarray(img)[:, ::-1].copy()
        return np.asarray(img)


class Transpose(BaseTransform):
    def __init__(self, order=(2, 0, 1)):
        self.order = order

    def _apply_image(self, img):
        return np.transpose(np.asarray(img), self.order)


from . import functional  # noqa: E402,F401
from .functional import (  # noqa: E402,F401
    adjust_brightness,
    adjust_contrast,
    adjust_hue,
    adjust_saturation,
    affine,
    center_crop,
    crop,
    erase,
    hflip,
    normalize,
    pad,
    perspective,
    resize,
    rotate,
    to_grayscale,
    to_tensor,
    vflip,
)


class RandomVerticalFlip(BaseTransform):
    def __init__(self, prob=0.5):
        self.prob = prob

    def _apply_image(self, img):
        if random.random() < self.prob:
            return functional.vflip(img)
        return np.asarray(img)


class Pad(BaseTransform):
    def __init__(self, padding, fill=0, padding_mode="constant"):
        self.padding = padding
        self.fill = fill
        self.padding_mode = padding_mode

    def _apply_image(self, img):
        return functional.pad(img, self.padding, self.fill,
                              self.padding_mode)


class Grayscale(BaseTransform):
    def __init__(self, num_output_channels=1):
        self.num_output_channels = num_output_channels

    def _apply_image(self, img):
        return functional.to_grayscale(img, self.num_output_channels)


class RandomResizedCrop(BaseTransform):
    """Random area/aspect crop then resize (reference transforms.py
    RandomResizedCrop): 10 sampling attempts, center-crop fallback."""

    def __init__(self, size, scale=(0.08, 1.0), ratio=(3.0 / 4, 4.0 / 3),
                 interpolation="bilinear"):
        self.size = (size, size) if isinstance(size, int) else tuple(size)
        self.scale = scale
        self.ratio = ratio
        self.interpolation = interpolation

    def _get_param(self, img):
        h, w = np.asarray(img).shape[:2]
        area = h * w
        for _ in range(10):
            target = random.uniform(*self.scale) * area
            log_ratio = (np.log(self.ratio[0]), np.log(self.ratio[1]))
            ar = np.exp(random.uniform(*log_ratio))
            tw = int(round(np.sqrt(target * ar)))
            th = int(round(np.sqrt(target / ar)))
            if 0 < tw <= w and 0 < th <= h:
                i = random.randint(0, h - th)
                j = random.randint(0, w - tw)
                return i, j, th, tw
        # fallback: largest center crop at a bound ratio
        in_ratio = w / h
        if in_ratio < self.ratio[0]:
            tw, th = w, int(round(w / self.ratio[0]))
        elif in_ratio > self.ratio[1]:
            th, tw = h, int(round(h * self.ratio[1]))
        else:
            tw, th = w, h
        return (h - th) // 2, (w - tw) // 2, th, tw

    def _apply_image(self, img):
        i, j, th, tw = self._get_param(img)
        return functional.resize(functional.crop(img, i, j, th, tw),
                                 self.size, self.interpolation)


def _jitter_range(value, name, center=1.0, bound=None, clip_zero=True):
    """Reference _check_input: a number v means [center-v, center+v]
    (clipped at 0), a (min, max) pair is taken as-is."""
    if isinstance(value, numbers.Number):
        if value < 0:
            raise ValueError(f"{name} value should be non-negative")
        lo, hi = center - value, center + value
        if clip_zero:
            lo = max(0.0, lo)
    else:
        lo, hi = (float(value[0]), float(value[1]))
    if bound is not None and not (bound[0] <= lo <= hi <= bound[1]):
        raise ValueError(f"{name} values should be between {bound}")
    return (lo, hi)


class BrightnessTransform(BaseTransform):
    def __init__(self, value):
        self._range = _jitter_range(value, type(self).__name__)
        self.value = value

    def _is_identity(self):
        return self._range == (1.0, 1.0)

    def _factor(self):
        return random.uniform(*self._range)

    def _apply_image(self, img):
        if self._is_identity():
            return np.asarray(img)
        return functional.adjust_brightness(img, self._factor())


class ContrastTransform(BrightnessTransform):
    def _apply_image(self, img):
        if self._is_identity():
            return np.asarray(img)
        return functional.adjust_contrast(img, self._factor())


class SaturationTransform(BrightnessTransform):
    def _apply_image(self, img):
        if self._is_identity():
            return np.asarray(img)
        return functional.adjust_saturation(img, self._factor())


class HueTransform(BaseTransform):
    def __init__(self, value):
        self._range = _jitter_range(value, "hue", center=0.0,
                                    bound=(-0.5, 0.5), clip_zero=False)
        if isinstance(value, numbers.Number) and not 0 <= value <= 0.5:
            raise ValueError("hue value should be in [0, 0.5]")
        self.value = value

    def _apply_image(self, img):
        if self._range == (0.0, 0.0):
            return np.asarray(img)
        return functional.adjust_hue(img, random.uniform(*self._range))


class ColorJitter(BaseTransform):
    """Random brightness/contrast/saturation/hue in a random order
    (reference transforms.py ColorJitter)."""

    def __init__(self, brightness=0, contrast=0, saturation=0, hue=0):
        self.transforms = [BrightnessTransform(brightness),
                           ContrastTransform(contrast),
                           SaturationTransform(saturation),
                           HueTransform(hue)]

    def _apply_image(self, img):
        order = list(range(4))
        random.shuffle(order)
        for idx in order:
            img = self.transforms[idx]._apply_image(img)
        return img


class RandomRotation(BaseTransform):
    def __init__(self, degrees, interpolation="nearest", expand=False,
                 center=None, fill=0):
        if isinstance(degrees, numbers.Number):
            if degrees < 0:
                raise ValueError("degrees must be non-negative")
            degrees = (-degrees, degrees)
        self.degrees = tuple(degrees)
        self.interpolation = interpolation
        self.expand = expand
        self.center = center
        self.fill = fill

    def _apply_image(self, img):
        angle = random.uniform(*self.degrees)
        return functional.rotate(img, angle, self.interpolation,
                                 self.expand, self.center, self.fill)


class RandomAffine(BaseTransform):
    """Random rotation/translation/scale/shear in one warp (reference
    transforms.py RandomAffine)."""

    def __init__(self, degrees, translate=None, scale=None, shear=None,
                 interpolation="nearest", fill=0, center=None):
        if isinstance(degrees, numbers.Number):
            degrees = (-abs(degrees), abs(degrees))
        self.degrees = tuple(degrees)
        self.translate = translate
        self.scale = scale
        self.shear = shear
        self.interpolation = interpolation
        self.fill = fill
        self.center = center

    def _apply_image(self, img):
        h, w = np.asarray(img).shape[:2]
        angle = random.uniform(*self.degrees)
        tx = ty = 0.0
        if self.translate is not None:
            tx = random.uniform(-self.translate[0], self.translate[0]) * w
            ty = random.uniform(-self.translate[1], self.translate[1]) * h
        sc = random.uniform(*self.scale) if self.scale is not None else 1.0
        sh = (0.0, 0.0)
        if self.shear is not None:
            shear = self.shear
            if isinstance(shear, numbers.Number):
                sh = (random.uniform(-shear, shear), 0.0)
            elif len(shear) == 2:
                sh = (random.uniform(shear[0], shear[1]), 0.0)
            else:
                sh = (random.uniform(shear[0], shear[1]),
                      random.uniform(shear[2], shear[3]))
        return functional.affine(img, angle, (tx, ty), sc, sh,
                                 self.interpolation, self.fill, self.center)


class RandomPerspective(BaseTransform):
    def __init__(self, prob=0.5, distortion_scale=0.5,
                 interpolation="nearest", fill=0):
        self.prob = prob
        self.distortion_scale = distortion_scale
        self.interpolation = interpolation
        self.fill = fill

    def _apply_image(self, img):
        if random.random() >= self.prob:
            return np.asarray(img)
        h, w = np.asarray(img).shape[:2]
        d = self.distortion_scale
        dx, dy = int(d * w / 2), int(d * h / 2)
        start = [(0, 0), (w - 1, 0), (w - 1, h - 1), (0, h - 1)]
        end = [(random.randint(0, dx), random.randint(0, dy)),
               (w - 1 - random.randint(0, dx), random.randint(0, dy)),
               (w - 1 - random.randint(0, dx), h - 1 - random.randint(0, dy)),
               (random.randint(0, dx), h - 1 - random.randint(0, dy))]
        return functional.perspective(img, start, end, self.interpolation,
                                      self.fill)


class RandomErasing(BaseTransform):
    """Random rectangle erase on a CHW tensor/array (reference
    transforms.py RandomErasing; applied after ToTensor)."""

    def __init__(self, prob=0.5, scale=(0.02, 0.33), ratio=(0.3, 3.3),
                 value=0, inplace=False):
        self.prob = prob
        self.scale = scale
        self.ratio = ratio
        self.value = value
        self.inplace = inplace

    def __call__(self, img):          # operates on tensors, skip asarray
        return self._apply_image(img)

    def _apply_image(self, img):
        if random.random() >= self.prob:
            return img
        shape = img.shape
        ch_first = len(shape) == 3 and shape[0] in (1, 3)
        h, w = (shape[1], shape[2]) if ch_first else (shape[0], shape[1])
        area = h * w
        for _ in range(10):
            target = random.uniform(*self.scale) * area
            log_ratio = (np.log(self.ratio[0]), np.log(self.ratio[1]))
            ar = np.exp(random.uniform(*log_ratio))
            eh = int(round(np.sqrt(target / ar)))
            ew = int(round(np.sqrt(target * ar)))
            if eh < h and ew < w:
                i = random.randint(0, h - eh)
                j = random.randint(0, w - ew)
                if isinstance(self.value, str):         # 'random': noise
                    # per-pixel normal noise like the reference (scaled
                    # to the uint8 range for integer images)
                    shape = ((shape[0], eh, ew) if ch_first
                             else (eh, ew) + tuple(shape[2:]))
                    v = np.random.normal(size=shape).astype(np.float32)
                    if getattr(img, "dtype", None) == np.uint8:
                        v = np.clip(v * 255, 0, 255).astype(np.uint8)
                else:
                    v = self.value
                return functional.erase(img, i, j, eh, ew, v, self.inplace)
        return img
