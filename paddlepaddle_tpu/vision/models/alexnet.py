"""AlexNet (reference: python/paddle/vision/models/alexnet.py)."""

from ...nn.activation import ReLU
from ...nn.common import Dropout, Linear
from ...nn.container import Sequential
from ...nn.conv import Conv2D
from ...nn.layer import Layer
from ...nn.pooling import AdaptiveAvgPool2D, MaxPool2D


class AlexNet(Layer):
    def __init__(self, num_classes=1000):
        super().__init__()
        self.num_classes = num_classes
        self.features = Sequential(
            Conv2D(3, 64, 11, stride=4, padding=2), ReLU(), MaxPool2D(3, 2),
            Conv2D(64, 192, 5, padding=2), ReLU(), MaxPool2D(3, 2),
            Conv2D(192, 384, 3, padding=1), ReLU(),
            Conv2D(384, 256, 3, padding=1), ReLU(),
            Conv2D(256, 256, 3, padding=1), ReLU(), MaxPool2D(3, 2),
        )
        self.avgpool = AdaptiveAvgPool2D((6, 6))
        if num_classes > 0:
            self.classifier = Sequential(
                Dropout(), Linear(256 * 6 * 6, 4096), ReLU(),
                Dropout(), Linear(4096, 4096), ReLU(),
                Linear(4096, num_classes),
            )

    def forward(self, x):
        x = self.avgpool(self.features(x))
        if self.num_classes > 0:
            x = self.classifier(x.flatten(1))
        return x


def alexnet(**kwargs):
    return AlexNet(**kwargs)
