"""GoogLeNet / Inception v1 (reference: python/paddle/vision/models/googlenet.py).

Same topology and aux-classifier contract as the reference: forward returns
(main, aux1, aux2) — aux heads run only in train mode, zeros-shaped outputs
otherwise are NOT emulated; like the reference we always return the tuple
and let the caller pick."""

import jax.numpy as jnp

from ...core.dispatch import apply_op
from ...nn.activation import ReLU
from ...nn.common import Dropout, Linear
from ...nn.container import Sequential
from ...nn.conv import Conv2D
from ...nn.layer import Layer
from ...nn.pooling import AdaptiveAvgPool2D, AvgPool2D, MaxPool2D


def _cat(*xs):
    return apply_op(lambda *a: jnp.concatenate(a, axis=1), *xs)


class _ConvBlock(Layer):
    def __init__(self, cin, cout, k, stride=1, padding=0):
        super().__init__()
        self.conv = Conv2D(cin, cout, k, stride=stride, padding=padding)
        self.relu = ReLU()

    def forward(self, x):
        return self.relu(self.conv(x))


class _Inception(Layer):
    """The four-branch inception block (1x1 / 3x3 / 5x5 / pool-proj)."""

    def __init__(self, cin, c1, c3r, c3, c5r, c5, proj):
        super().__init__()
        self.b1 = _ConvBlock(cin, c1, 1)
        self.b2 = Sequential(_ConvBlock(cin, c3r, 1), _ConvBlock(c3r, c3, 3, padding=1))
        self.b3 = Sequential(_ConvBlock(cin, c5r, 1), _ConvBlock(c5r, c5, 5, padding=2))
        self.b4 = Sequential(MaxPool2D(3, 1, padding=1), _ConvBlock(cin, proj, 1))

    def forward(self, x):
        return _cat(self.b1(x), self.b2(x), self.b3(x), self.b4(x))


class _AuxHead(Layer):
    def __init__(self, cin, num_classes):
        super().__init__()
        self.pool = AvgPool2D(5, 3)
        self.conv = _ConvBlock(cin, 128, 1)
        self.fc1 = Linear(2048, 1024)
        self.relu = ReLU()
        self.drop = Dropout(0.7)
        self.fc2 = Linear(1024, num_classes)

    def forward(self, x):
        x = self.conv(self.pool(x))
        x = apply_op(lambda a: a.reshape(a.shape[0], -1), x)
        return self.fc2(self.drop(self.relu(self.fc1(x))))


class GoogLeNet(Layer):
    def __init__(self, num_classes=1000, with_pool=True):
        super().__init__()
        self.num_classes = num_classes
        self.with_pool = with_pool
        self.stem = Sequential(
            _ConvBlock(3, 64, 7, stride=2, padding=3), MaxPool2D(3, 2, padding=1),
            _ConvBlock(64, 64, 1), _ConvBlock(64, 192, 3, padding=1),
            MaxPool2D(3, 2, padding=1))
        self.i3a = _Inception(192, 64, 96, 128, 16, 32, 32)
        self.i3b = _Inception(256, 128, 128, 192, 32, 96, 64)
        self.pool3 = MaxPool2D(3, 2, padding=1)
        self.i4a = _Inception(480, 192, 96, 208, 16, 48, 64)
        self.i4b = _Inception(512, 160, 112, 224, 24, 64, 64)
        self.i4c = _Inception(512, 128, 128, 256, 24, 64, 64)
        self.i4d = _Inception(512, 112, 144, 288, 32, 64, 64)
        self.i4e = _Inception(528, 256, 160, 320, 32, 128, 128)
        self.pool4 = MaxPool2D(3, 2, padding=1)
        self.i5a = _Inception(832, 256, 160, 320, 32, 128, 128)
        self.i5b = _Inception(832, 384, 192, 384, 48, 128, 128)
        if with_pool:
            self.avgpool = AdaptiveAvgPool2D((1, 1))
        if num_classes > 0:
            self.drop = Dropout(0.4)
            self.fc = Linear(1024, num_classes)
            self.aux1 = _AuxHead(512, num_classes)
            self.aux2 = _AuxHead(528, num_classes)

    def forward(self, x):
        x = self.stem(x)
        x = self.pool3(self.i3b(self.i3a(x)))
        x = self.i4a(x)
        aux1 = self.aux1(x) if self.num_classes > 0 and self.training else None
        x = self.i4d(self.i4c(self.i4b(x)))
        aux2 = self.aux2(x) if self.num_classes > 0 and self.training else None
        x = self.pool4(self.i4e(x))
        x = self.i5b(self.i5a(x))
        if self.with_pool:
            x = self.avgpool(x)
        if self.num_classes > 0:
            x = apply_op(lambda a: a.reshape(a.shape[0], -1), x)
            x = self.fc(self.drop(x))
        return x, aux1, aux2


def googlenet(pretrained=False, **kwargs):
    if pretrained:
        raise NotImplementedError(
            "pretrained weights are not bundled; load a state_dict instead")
    return GoogLeNet(**kwargs)
