"""ShuffleNetV2 (reference: python/paddle/vision/models/shufflenetv2.py)."""

import jax.numpy as jnp

from ...core.dispatch import apply_op
from ...nn.activation import ReLU, Swish
from ...nn.common import Linear
from ...nn.container import Sequential
from ...nn.conv import Conv2D
from ...nn.layer import Layer
from ...nn.norm import BatchNorm2D
from ...nn.pooling import AdaptiveAvgPool2D, MaxPool2D


def _channel_shuffle(x, groups):
    def f(a):
        b, c, h, w = a.shape
        a = a.reshape(b, groups, c // groups, h, w)
        a = jnp.swapaxes(a, 1, 2)
        return a.reshape(b, c, h, w)

    return apply_op(f, x, op_name="channel_shuffle")


def _split2(x):
    def f(a):
        half = a.shape[1] // 2
        return a[:, :half], a[:, half:]

    return apply_op(f, x)


def _cat(a, b):
    return apply_op(lambda u, v: jnp.concatenate([u, v], axis=1), a, b)


def _act(name):
    return Swish() if name == "swish" else ReLU()


class _InvertedResidual(Layer):
    def __init__(self, inp, oup, stride, act="relu"):
        super().__init__()
        self.stride = stride
        branch_c = oup // 2
        if stride > 1:
            self.branch1 = Sequential(
                Conv2D(inp, inp, 3, stride=stride, padding=1, groups=inp, bias_attr=False),
                BatchNorm2D(inp),
                Conv2D(inp, branch_c, 1, bias_attr=False), BatchNorm2D(branch_c), _act(act))
            b2_in = inp
        else:
            self.branch1 = None
            b2_in = inp // 2
        self.branch2 = Sequential(
            Conv2D(b2_in, branch_c, 1, bias_attr=False), BatchNorm2D(branch_c), _act(act),
            Conv2D(branch_c, branch_c, 3, stride=stride, padding=1, groups=branch_c, bias_attr=False),
            BatchNorm2D(branch_c),
            Conv2D(branch_c, branch_c, 1, bias_attr=False), BatchNorm2D(branch_c), _act(act))

    def forward(self, x):
        if self.stride == 1:
            x1, x2 = _split2(x)
            out = _cat(x1, self.branch2(x2))
        else:
            out = _cat(self.branch1(x), self.branch2(x))
        return _channel_shuffle(out, 2)


class ShuffleNetV2(Layer):
    _stage_repeats = [4, 8, 4]
    _out_channels = {
        0.25: [24, 24, 48, 96, 512],
        0.33: [24, 32, 64, 128, 512],
        0.5: [24, 48, 96, 192, 1024],
        1.0: [24, 116, 232, 464, 1024],
        1.5: [24, 176, 352, 704, 1024],
        2.0: [24, 224, 488, 976, 2048],
    }

    def __init__(self, scale=1.0, act="relu", num_classes=1000, with_pool=True):
        super().__init__()
        chans = self._out_channels[scale]
        self.conv1 = Sequential(
            Conv2D(3, chans[0], 3, stride=2, padding=1, bias_attr=False),
            BatchNorm2D(chans[0]), _act(act))
        self.maxpool = MaxPool2D(3, 2, padding=1)
        stages = []
        inp = chans[0]
        for i, reps in enumerate(self._stage_repeats):
            oup = chans[i + 1]
            blocks = [_InvertedResidual(inp, oup, 2, act)]
            for _ in range(reps - 1):
                blocks.append(_InvertedResidual(oup, oup, 1, act))
            stages.append(Sequential(*blocks))
            inp = oup
        self.stages = Sequential(*stages)
        self.conv5 = Sequential(
            Conv2D(inp, chans[-1], 1, bias_attr=False), BatchNorm2D(chans[-1]), _act(act))
        self.num_classes = num_classes
        self.with_pool = with_pool
        if with_pool:
            self.pool = AdaptiveAvgPool2D((1, 1))
        if num_classes > 0:
            self.fc = Linear(chans[-1], num_classes)

    def forward(self, x):
        x = self.conv5(self.stages(self.maxpool(self.conv1(x))))
        if self.with_pool:
            x = self.pool(x)
        if self.num_classes > 0:
            x = self.fc(x.flatten(1))
        return x


def shufflenet_v2_x0_25(**kwargs):
    return ShuffleNetV2(scale=0.25, **kwargs)


def shufflenet_v2_x0_33(**kwargs):
    return ShuffleNetV2(scale=0.33, **kwargs)


def shufflenet_v2_x0_5(**kwargs):
    return ShuffleNetV2(scale=0.5, **kwargs)


def shufflenet_v2_x1_0(**kwargs):
    return ShuffleNetV2(scale=1.0, **kwargs)


def shufflenet_v2_x1_5(**kwargs):
    return ShuffleNetV2(scale=1.5, **kwargs)


def shufflenet_v2_x2_0(**kwargs):
    return ShuffleNetV2(scale=2.0, **kwargs)


def shufflenet_v2_swish(**kwargs):
    return ShuffleNetV2(scale=1.0, act="swish", **kwargs)
