"""MobileNetV2 (reference: python/paddle/vision/models/mobilenetv2.py)."""

from ...nn.activation import ReLU6
from ...nn.common import Dropout, Linear
from ...nn.container import Sequential
from ...nn.conv import Conv2D
from ...nn.layer import Layer
from ...nn.norm import BatchNorm2D
from ...nn.pooling import AdaptiveAvgPool2D


def _make_divisible(v, divisor=8, min_value=None):
    min_value = min_value or divisor
    new_v = max(min_value, int(v + divisor / 2) // divisor * divisor)
    if new_v < 0.9 * v:
        new_v += divisor
    return new_v


def _conv_bn(inp, oup, kernel, stride, groups=1):
    pad = (kernel - 1) // 2
    return Sequential(
        Conv2D(inp, oup, kernel, stride=stride, padding=pad, groups=groups, bias_attr=False),
        BatchNorm2D(oup),
        ReLU6(),
    )


class InvertedResidual(Layer):
    def __init__(self, inp, oup, stride, expand_ratio):
        super().__init__()
        self.stride = stride
        hidden = int(round(inp * expand_ratio))
        self.use_res = stride == 1 and inp == oup
        layers = []
        if expand_ratio != 1:
            layers.append(_conv_bn(inp, hidden, 1, 1))
        layers.extend([
            _conv_bn(hidden, hidden, 3, stride, groups=hidden),
            Conv2D(hidden, oup, 1, bias_attr=False),
            BatchNorm2D(oup),
        ])
        self.conv = Sequential(*layers)

    def forward(self, x):
        out = self.conv(x)
        return x + out if self.use_res else out


class MobileNetV2(Layer):
    def __init__(self, scale=1.0, num_classes=1000, with_pool=True):
        super().__init__()
        self.num_classes = num_classes
        self.with_pool = with_pool
        cfg = [
            (1, 16, 1, 1), (6, 24, 2, 2), (6, 32, 3, 2), (6, 64, 4, 2),
            (6, 96, 3, 1), (6, 160, 3, 2), (6, 320, 1, 1),
        ]
        in_c = _make_divisible(32 * scale)
        last_c = _make_divisible(1280 * max(1.0, scale))
        features = [_conv_bn(3, in_c, 3, 2)]
        for t, c, n, s in cfg:
            out_c = _make_divisible(c * scale)
            for i in range(n):
                features.append(InvertedResidual(in_c, out_c, s if i == 0 else 1, t))
                in_c = out_c
        features.append(_conv_bn(in_c, last_c, 1, 1))
        self.features = Sequential(*features)
        if with_pool:
            self.pool = AdaptiveAvgPool2D((1, 1))
        if num_classes > 0:
            self.classifier = Sequential(Dropout(0.2), Linear(last_c, num_classes))
        self._last_c = last_c

    def forward(self, x):
        x = self.features(x)
        if self.with_pool:
            x = self.pool(x)
        if self.num_classes > 0:
            x = self.classifier(x.flatten(1))
        return x


def mobilenet_v2(scale=1.0, **kwargs):
    return MobileNetV2(scale=scale, **kwargs)


# ---------------------------------------------------------------------------
# MobileNetV1 (reference: python/paddle/vision/models/mobilenetv1.py —
# depthwise-separable stacks) and MobileNetV3 small/large (mobilenetv3.py —
# inverted residuals with squeeze-excite and hardswish).
# ---------------------------------------------------------------------------

from ...core.dispatch import apply_op as _apply_op
from ...nn.activation import Hardsigmoid, Hardswish, ReLU


def _dw_sep(inp, oup, stride):
    """depthwise 3x3 + pointwise 1x1, each conv-bn-relu."""
    return Sequential(
        Conv2D(inp, inp, 3, stride=stride, padding=1, groups=inp,
               bias_attr=False),
        BatchNorm2D(inp), ReLU(),
        Conv2D(inp, oup, 1, bias_attr=False),
        BatchNorm2D(oup), ReLU(),
    )


class MobileNetV1(Layer):
    def __init__(self, scale=1.0, num_classes=1000, with_pool=True):
        super().__init__()
        self.num_classes = num_classes
        self.with_pool = with_pool

        def c(ch):
            return max(8, int(ch * scale))

        cfg = [(64, 1), (128, 2), (128, 1), (256, 2), (256, 1), (512, 2),
               (512, 1), (512, 1), (512, 1), (512, 1), (512, 1), (1024, 2),
               (1024, 1)]
        layers = [Sequential(Conv2D(3, c(32), 3, stride=2, padding=1,
                                    bias_attr=False),
                             BatchNorm2D(c(32)), ReLU())]
        inp = c(32)
        for ch, s in cfg:
            layers.append(_dw_sep(inp, c(ch), s))
            inp = c(ch)
        self.features = Sequential(*layers)
        if with_pool:
            self.pool = AdaptiveAvgPool2D((1, 1))
        if num_classes > 0:
            self.fc = Linear(inp, num_classes)
        self._out_c = inp

    def forward(self, x):
        x = self.features(x)
        if self.with_pool:
            x = self.pool(x)
        if self.num_classes > 0:
            x = self.fc(x.flatten(1))
        return x


def mobilenet_v1(scale=1.0, **kwargs):
    return MobileNetV1(scale=scale, **kwargs)


class _SqueezeExcite(Layer):
    def __init__(self, ch, squeeze=4):
        super().__init__()
        mid = _make_divisible(ch // squeeze)
        self.pool = AdaptiveAvgPool2D((1, 1))
        self.fc1 = Conv2D(ch, mid, 1)
        self.relu = ReLU()
        self.fc2 = Conv2D(mid, ch, 1)
        self.hsig = Hardsigmoid()

    def forward(self, x):
        s = self.hsig(self.fc2(self.relu(self.fc1(self.pool(x)))))
        return _apply_op(lambda a, b: a * b, x, s)


class _V3Block(Layer):
    def __init__(self, inp, mid, oup, kernel, stride, use_se, act):
        super().__init__()
        self.use_res = stride == 1 and inp == oup
        Act = Hardswish if act == "hs" else ReLU
        layers = []
        if mid != inp:
            layers += [Conv2D(inp, mid, 1, bias_attr=False),
                       BatchNorm2D(mid), Act()]
        layers += [Conv2D(mid, mid, kernel, stride=stride,
                          padding=kernel // 2, groups=mid, bias_attr=False),
                   BatchNorm2D(mid), Act()]
        if use_se:
            layers.append(_SqueezeExcite(mid))
        layers += [Conv2D(mid, oup, 1, bias_attr=False), BatchNorm2D(oup)]
        self.conv = Sequential(*layers)

    def forward(self, x):
        out = self.conv(x)
        return x + out if self.use_res else out


_V3_SMALL = [  # kernel, mid, out, se, act, stride
    (3, 16, 16, True, "re", 2), (3, 72, 24, False, "re", 2),
    (3, 88, 24, False, "re", 1), (5, 96, 40, True, "hs", 2),
    (5, 240, 40, True, "hs", 1), (5, 240, 40, True, "hs", 1),
    (5, 120, 48, True, "hs", 1), (5, 144, 48, True, "hs", 1),
    (5, 288, 96, True, "hs", 2), (5, 576, 96, True, "hs", 1),
    (5, 576, 96, True, "hs", 1),
]
_V3_LARGE = [
    (3, 16, 16, False, "re", 1), (3, 64, 24, False, "re", 2),
    (3, 72, 24, False, "re", 1), (5, 72, 40, True, "re", 2),
    (5, 120, 40, True, "re", 1), (5, 120, 40, True, "re", 1),
    (3, 240, 80, False, "hs", 2), (3, 200, 80, False, "hs", 1),
    (3, 184, 80, False, "hs", 1), (3, 184, 80, False, "hs", 1),
    (3, 480, 112, True, "hs", 1), (3, 672, 112, True, "hs", 1),
    (5, 672, 160, True, "hs", 2), (5, 960, 160, True, "hs", 1),
    (5, 960, 160, True, "hs", 1),
]


class MobileNetV3(Layer):
    def __init__(self, cfg, last_c, scale=1.0, num_classes=1000,
                 with_pool=True):
        super().__init__()
        self.num_classes = num_classes
        self.with_pool = with_pool

        def c(ch):
            return _make_divisible(ch * scale)

        inp = c(16)
        layers = [Sequential(Conv2D(3, inp, 3, stride=2, padding=1,
                                    bias_attr=False),
                             BatchNorm2D(inp), Hardswish())]
        for k, mid, out, se, act, s in cfg:
            layers.append(_V3Block(inp, c(mid), c(out), k, s, se, act))
            inp = c(out)
        head_c = c(cfg[-1][1])
        layers.append(Sequential(Conv2D(inp, head_c, 1, bias_attr=False),
                                 BatchNorm2D(head_c), Hardswish()))
        self.features = Sequential(*layers)
        if with_pool:
            self.pool = AdaptiveAvgPool2D((1, 1))
        if num_classes > 0:
            self.classifier = Sequential(
                Linear(head_c, last_c), Hardswish(), Dropout(0.2),
                Linear(last_c, num_classes))

    def forward(self, x):
        x = self.features(x)
        if self.with_pool:
            x = self.pool(x)
        if self.num_classes > 0:
            x = self.classifier(x.flatten(1))
        return x


class MobileNetV3Small(MobileNetV3):
    def __init__(self, scale=1.0, **kw):
        super().__init__(_V3_SMALL, 1024, scale=scale, **kw)


class MobileNetV3Large(MobileNetV3):
    def __init__(self, scale=1.0, **kw):
        super().__init__(_V3_LARGE, 1280, scale=scale, **kw)


def mobilenet_v3_small(scale=1.0, **kwargs):
    return MobileNetV3Small(scale=scale, **kwargs)


def mobilenet_v3_large(scale=1.0, **kwargs):
    return MobileNetV3Large(scale=scale, **kwargs)
