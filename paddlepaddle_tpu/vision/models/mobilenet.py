"""MobileNetV2 (reference: python/paddle/vision/models/mobilenetv2.py)."""

from ...nn.activation import ReLU6
from ...nn.common import Dropout, Linear
from ...nn.container import Sequential
from ...nn.conv import Conv2D
from ...nn.layer import Layer
from ...nn.norm import BatchNorm2D
from ...nn.pooling import AdaptiveAvgPool2D


def _make_divisible(v, divisor=8, min_value=None):
    min_value = min_value or divisor
    new_v = max(min_value, int(v + divisor / 2) // divisor * divisor)
    if new_v < 0.9 * v:
        new_v += divisor
    return new_v


def _conv_bn(inp, oup, kernel, stride, groups=1):
    pad = (kernel - 1) // 2
    return Sequential(
        Conv2D(inp, oup, kernel, stride=stride, padding=pad, groups=groups, bias_attr=False),
        BatchNorm2D(oup),
        ReLU6(),
    )


class InvertedResidual(Layer):
    def __init__(self, inp, oup, stride, expand_ratio):
        super().__init__()
        self.stride = stride
        hidden = int(round(inp * expand_ratio))
        self.use_res = stride == 1 and inp == oup
        layers = []
        if expand_ratio != 1:
            layers.append(_conv_bn(inp, hidden, 1, 1))
        layers.extend([
            _conv_bn(hidden, hidden, 3, stride, groups=hidden),
            Conv2D(hidden, oup, 1, bias_attr=False),
            BatchNorm2D(oup),
        ])
        self.conv = Sequential(*layers)

    def forward(self, x):
        out = self.conv(x)
        return x + out if self.use_res else out


class MobileNetV2(Layer):
    def __init__(self, scale=1.0, num_classes=1000, with_pool=True):
        super().__init__()
        self.num_classes = num_classes
        self.with_pool = with_pool
        cfg = [
            (1, 16, 1, 1), (6, 24, 2, 2), (6, 32, 3, 2), (6, 64, 4, 2),
            (6, 96, 3, 1), (6, 160, 3, 2), (6, 320, 1, 1),
        ]
        in_c = _make_divisible(32 * scale)
        last_c = _make_divisible(1280 * max(1.0, scale))
        features = [_conv_bn(3, in_c, 3, 2)]
        for t, c, n, s in cfg:
            out_c = _make_divisible(c * scale)
            for i in range(n):
                features.append(InvertedResidual(in_c, out_c, s if i == 0 else 1, t))
                in_c = out_c
        features.append(_conv_bn(in_c, last_c, 1, 1))
        self.features = Sequential(*features)
        if with_pool:
            self.pool = AdaptiveAvgPool2D((1, 1))
        if num_classes > 0:
            self.classifier = Sequential(Dropout(0.2), Linear(last_c, num_classes))
        self._last_c = last_c

    def forward(self, x):
        x = self.features(x)
        if self.with_pool:
            x = self.pool(x)
        if self.num_classes > 0:
            x = self.classifier(x.flatten(1))
        return x


def mobilenet_v2(scale=1.0, **kwargs):
    return MobileNetV2(scale=scale, **kwargs)
