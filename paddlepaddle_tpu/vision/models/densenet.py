"""DenseNet (reference: python/paddle/vision/models/densenet.py)."""

from ...core.dispatch import apply_op
from ...nn.activation import ReLU
from ...nn.common import Dropout, Linear
from ...nn.container import Sequential
from ...nn.conv import Conv2D
from ...nn.layer import Layer
from ...nn.norm import BatchNorm2D
from ...nn.pooling import AdaptiveAvgPool2D, AvgPool2D, MaxPool2D

import jax.numpy as jnp


def _concat(xs):
    return apply_op(lambda *a: jnp.concatenate(a, axis=1), *xs)


class _DenseLayer(Layer):
    def __init__(self, num_input_features, growth_rate, bn_size, drop_rate):
        super().__init__()
        self.norm1 = BatchNorm2D(num_input_features)
        self.relu = ReLU()
        self.conv1 = Conv2D(num_input_features, bn_size * growth_rate, 1, bias_attr=False)
        self.norm2 = BatchNorm2D(bn_size * growth_rate)
        self.conv2 = Conv2D(bn_size * growth_rate, growth_rate, 3, padding=1, bias_attr=False)
        self.drop_rate = drop_rate
        self.dropout = Dropout(drop_rate) if drop_rate > 0 else None

    def forward(self, x):
        out = self.conv1(self.relu(self.norm1(x)))
        out = self.conv2(self.relu(self.norm2(out)))
        if self.dropout is not None:
            out = self.dropout(out)
        return _concat([x, out])


class _DenseBlock(Layer):
    def __init__(self, num_layers, num_input_features, bn_size, growth_rate, drop_rate):
        super().__init__()
        layers = []
        for i in range(num_layers):
            layers.append(_DenseLayer(num_input_features + i * growth_rate,
                                      growth_rate, bn_size, drop_rate))
        self.block = Sequential(*layers)

    def forward(self, x):
        return self.block(x)


class _Transition(Layer):
    def __init__(self, num_input_features, num_output_features):
        super().__init__()
        self.norm = BatchNorm2D(num_input_features)
        self.relu = ReLU()
        self.conv = Conv2D(num_input_features, num_output_features, 1, bias_attr=False)
        self.pool = AvgPool2D(2, 2)

    def forward(self, x):
        return self.pool(self.conv(self.relu(self.norm(x))))


class DenseNet(Layer):
    # layers -> (num_init_features, growth_rate, block_config); densenet161
    # is the wide variant (96, 48) — reference vision/models/densenet.py:296
    _cfgs = {121: (64, 32, (6, 12, 24, 16)), 161: (96, 48, (6, 12, 36, 24)),
             169: (64, 32, (6, 12, 32, 32)), 201: (64, 32, (6, 12, 48, 32)),
             264: (64, 32, (6, 12, 64, 48))}

    def __init__(self, layers=121, growth_rate=None, num_init_features=None,
                 bn_size=4, dropout=0.0, num_classes=1000, with_pool=True):
        super().__init__()
        cfg_init, cfg_growth, block_config = self._cfgs[layers]
        growth_rate = cfg_growth if growth_rate is None else growth_rate
        num_init_features = (cfg_init if num_init_features is None
                             else num_init_features)
        self.features_head = Sequential(
            Conv2D(3, num_init_features, 7, stride=2, padding=3, bias_attr=False),
            BatchNorm2D(num_init_features), ReLU(), MaxPool2D(3, 2, padding=1))
        num_features = num_init_features
        blocks = []
        for i, num_layers in enumerate(block_config):
            blocks.append(_DenseBlock(num_layers, num_features, bn_size, growth_rate, dropout))
            num_features += num_layers * growth_rate
            if i != len(block_config) - 1:
                blocks.append(_Transition(num_features, num_features // 2))
                num_features //= 2
        self.blocks = Sequential(*blocks)
        self.norm5 = BatchNorm2D(num_features)
        self.relu = ReLU()
        self.with_pool = with_pool
        self.num_classes = num_classes
        if with_pool:
            self.pool = AdaptiveAvgPool2D((1, 1))
        if num_classes > 0:
            self.classifier = Linear(num_features, num_classes)

    def forward(self, x):
        x = self.relu(self.norm5(self.blocks(self.features_head(x))))
        if self.with_pool:
            x = self.pool(x)
        if self.num_classes > 0:
            x = self.classifier(x.flatten(1))
        return x


def densenet121(**kwargs):
    return DenseNet(121, **kwargs)


def densenet161(**kwargs):
    return DenseNet(161, **kwargs)


def densenet169(**kwargs):
    return DenseNet(169, **kwargs)


def densenet201(**kwargs):
    return DenseNet(201, **kwargs)


def densenet264(**kwargs):
    return DenseNet(264, **kwargs)
