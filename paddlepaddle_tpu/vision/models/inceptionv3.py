"""Inception v3 (reference: python/paddle/vision/models/inceptionv3.py).

The five reference block families (InceptionA..E) with the same channel
plans and BN-convs; 299x299 inputs. Aux head omitted at inference like the
reference default (aux_logits exists only for training builds there too)."""

import jax.numpy as jnp

from ...core.dispatch import apply_op
from ...nn.activation import ReLU
from ...nn.common import Dropout, Linear
from ...nn.container import Sequential
from ...nn.conv import Conv2D
from ...nn.layer import Layer
from ...nn.norm import BatchNorm2D
from ...nn.pooling import AdaptiveAvgPool2D, AvgPool2D, MaxPool2D


def _cat(*xs):
    return apply_op(lambda *a: jnp.concatenate(a, axis=1), *xs)


class _ConvBN(Layer):
    def __init__(self, cin, cout, k, stride=1, padding=0):
        super().__init__()
        self.conv = Conv2D(cin, cout, k, stride=stride, padding=padding,
                           bias_attr=False)
        self.bn = BatchNorm2D(cout)
        self.relu = ReLU()

    def forward(self, x):
        return self.relu(self.bn(self.conv(x)))


class _InceptionA(Layer):
    def __init__(self, cin, pool_features):
        super().__init__()
        self.b1 = _ConvBN(cin, 64, 1)
        self.b5 = Sequential(_ConvBN(cin, 48, 1), _ConvBN(48, 64, 5, padding=2))
        self.b3 = Sequential(_ConvBN(cin, 64, 1), _ConvBN(64, 96, 3, padding=1),
                             _ConvBN(96, 96, 3, padding=1))
        self.bp = Sequential(AvgPool2D(3, 1, padding=1),
                             _ConvBN(cin, pool_features, 1))

    def forward(self, x):
        return _cat(self.b1(x), self.b5(x), self.b3(x), self.bp(x))


class _InceptionB(Layer):
    def __init__(self, cin):
        super().__init__()
        self.b3 = _ConvBN(cin, 384, 3, stride=2)
        self.b3d = Sequential(_ConvBN(cin, 64, 1), _ConvBN(64, 96, 3, padding=1),
                              _ConvBN(96, 96, 3, stride=2))
        self.pool = MaxPool2D(3, 2)

    def forward(self, x):
        return _cat(self.b3(x), self.b3d(x), self.pool(x))


class _InceptionC(Layer):
    def __init__(self, cin, c7):
        super().__init__()
        self.b1 = _ConvBN(cin, 192, 1)
        self.b7 = Sequential(_ConvBN(cin, c7, 1),
                             _ConvBN(c7, c7, (1, 7), padding=(0, 3)),
                             _ConvBN(c7, 192, (7, 1), padding=(3, 0)))
        self.b7d = Sequential(_ConvBN(cin, c7, 1),
                              _ConvBN(c7, c7, (7, 1), padding=(3, 0)),
                              _ConvBN(c7, c7, (1, 7), padding=(0, 3)),
                              _ConvBN(c7, c7, (7, 1), padding=(3, 0)),
                              _ConvBN(c7, 192, (1, 7), padding=(0, 3)))
        self.bp = Sequential(AvgPool2D(3, 1, padding=1), _ConvBN(cin, 192, 1))

    def forward(self, x):
        return _cat(self.b1(x), self.b7(x), self.b7d(x), self.bp(x))


class _InceptionD(Layer):
    def __init__(self, cin):
        super().__init__()
        self.b3 = Sequential(_ConvBN(cin, 192, 1), _ConvBN(192, 320, 3, stride=2))
        self.b7 = Sequential(_ConvBN(cin, 192, 1),
                             _ConvBN(192, 192, (1, 7), padding=(0, 3)),
                             _ConvBN(192, 192, (7, 1), padding=(3, 0)),
                             _ConvBN(192, 192, 3, stride=2))
        self.pool = MaxPool2D(3, 2)

    def forward(self, x):
        return _cat(self.b3(x), self.b7(x), self.pool(x))


class _InceptionE(Layer):
    def __init__(self, cin):
        super().__init__()
        self.b1 = _ConvBN(cin, 320, 1)
        self.b3_1 = _ConvBN(cin, 384, 1)
        self.b3_2a = _ConvBN(384, 384, (1, 3), padding=(0, 1))
        self.b3_2b = _ConvBN(384, 384, (3, 1), padding=(1, 0))
        self.bd_1 = Sequential(_ConvBN(cin, 448, 1), _ConvBN(448, 384, 3, padding=1))
        self.bd_2a = _ConvBN(384, 384, (1, 3), padding=(0, 1))
        self.bd_2b = _ConvBN(384, 384, (3, 1), padding=(1, 0))
        self.bp = Sequential(AvgPool2D(3, 1, padding=1), _ConvBN(cin, 192, 1))

    def forward(self, x):
        a = self.b3_1(x)
        d = self.bd_1(x)
        return _cat(self.b1(x), self.b3_2a(a), self.b3_2b(a),
                    self.bd_2a(d), self.bd_2b(d), self.bp(x))


class InceptionV3(Layer):
    def __init__(self, num_classes=1000, with_pool=True):
        super().__init__()
        self.num_classes = num_classes
        self.with_pool = with_pool
        self.stem = Sequential(
            _ConvBN(3, 32, 3, stride=2), _ConvBN(32, 32, 3),
            _ConvBN(32, 64, 3, padding=1), MaxPool2D(3, 2),
            _ConvBN(64, 80, 1), _ConvBN(80, 192, 3), MaxPool2D(3, 2))
        self.blocks = Sequential(
            _InceptionA(192, 32), _InceptionA(256, 64), _InceptionA(288, 64),
            _InceptionB(288),
            _InceptionC(768, 128), _InceptionC(768, 160),
            _InceptionC(768, 160), _InceptionC(768, 192),
            _InceptionD(768),
            _InceptionE(1280), _InceptionE(2048))
        if with_pool:
            self.avgpool = AdaptiveAvgPool2D((1, 1))
        if num_classes > 0:
            self.dropout = Dropout(0.5)
            self.fc = Linear(2048, num_classes)

    def forward(self, x):
        x = self.blocks(self.stem(x))
        if self.with_pool:
            x = self.avgpool(x)
        if self.num_classes > 0:
            x = apply_op(lambda a: a.reshape(a.shape[0], -1), x)
            x = self.fc(self.dropout(x))
        return x


def inception_v3(pretrained=False, **kwargs):
    if pretrained:
        raise NotImplementedError(
            "pretrained weights are not bundled; load a state_dict instead")
    return InceptionV3(**kwargs)
