"""SqueezeNet (reference: python/paddle/vision/models/squeezenet.py)."""

import jax.numpy as jnp

from ...core.dispatch import apply_op
from ...nn.activation import ReLU
from ...nn.common import Dropout
from ...nn.container import Sequential
from ...nn.conv import Conv2D
from ...nn.layer import Layer
from ...nn.pooling import AdaptiveAvgPool2D, MaxPool2D


class _Fire(Layer):
    def __init__(self, inplanes, squeeze_planes, expand1x1_planes, expand3x3_planes):
        super().__init__()
        self.squeeze = Conv2D(inplanes, squeeze_planes, 1)
        self.relu = ReLU()
        self.expand1x1 = Conv2D(squeeze_planes, expand1x1_planes, 1)
        self.expand3x3 = Conv2D(squeeze_planes, expand3x3_planes, 3, padding=1)

    def forward(self, x):
        x = self.relu(self.squeeze(x))
        a = self.relu(self.expand1x1(x))
        b = self.relu(self.expand3x3(x))
        return apply_op(lambda u, v: jnp.concatenate([u, v], axis=1), a, b)


class SqueezeNet(Layer):
    def __init__(self, version="1.1", num_classes=1000, with_pool=True):
        super().__init__()
        self.num_classes = num_classes
        self.with_pool = with_pool
        if version == "1.0":
            self.features = Sequential(
                Conv2D(3, 96, 7, stride=2), ReLU(), MaxPool2D(3, 2),
                _Fire(96, 16, 64, 64), _Fire(128, 16, 64, 64), _Fire(128, 32, 128, 128),
                MaxPool2D(3, 2),
                _Fire(256, 32, 128, 128), _Fire(256, 48, 192, 192),
                _Fire(384, 48, 192, 192), _Fire(384, 64, 256, 256),
                MaxPool2D(3, 2), _Fire(512, 64, 256, 256))
        else:
            self.features = Sequential(
                Conv2D(3, 64, 3, stride=2), ReLU(), MaxPool2D(3, 2),
                _Fire(64, 16, 64, 64), _Fire(128, 16, 64, 64),
                MaxPool2D(3, 2),
                _Fire(128, 32, 128, 128), _Fire(256, 32, 128, 128),
                MaxPool2D(3, 2),
                _Fire(256, 48, 192, 192), _Fire(384, 48, 192, 192),
                _Fire(384, 64, 256, 256), _Fire(512, 64, 256, 256))
        if num_classes > 0:
            self.classifier = Sequential(
                Dropout(0.5), Conv2D(512, num_classes, 1), ReLU())
        if with_pool:
            self.pool = AdaptiveAvgPool2D((1, 1))

    def forward(self, x):
        x = self.features(x)
        if self.num_classes > 0:
            x = self.classifier(x)
        if self.with_pool:
            x = self.pool(x)
        return x.flatten(1)


def squeezenet1_0(**kwargs):
    return SqueezeNet("1.0", **kwargs)


def squeezenet1_1(**kwargs):
    return SqueezeNet("1.1", **kwargs)
