"""paddle.vision.ops — detection operators.

Reference surface: python/paddle/vision/ops.py (nms, roi_align, roi_pool,
box_coder, deform_conv2d, yolo ops, ...). TPU-native surface: nms, matrix_nms,
roi_align/roi_pool/psroi_pool (+ layer forms), box_coder, prior_box,
generate_proposals, FPN distribution, file IO, deform_conv2d (bilinear
gather + grouped GEMM), and the yolo decode/loss pair — all with static
shapes.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

from ..core.dispatch import apply_op


def nms(boxes, iou_threshold=0.3, scores=None, category_idxs=None,
        categories=None, top_k=None, name=None):
    """Greedy hard-NMS (reference vision/ops.py nms): keeps box indices in
    descending score order, suppressing IoU > threshold. With
    ``category_idxs`` the suppression is per category (boxes of different
    categories never suppress each other). Returns kept indices, score-
    sorted. Static shapes: the scan visits every box; suppressed slots are
    masked out of the result."""

    def f(bx, sc, cat):
        n = bx.shape[0]
        sc_ = jnp.arange(n, 0, -1, dtype=jnp.float32) if sc is None else \
            sc.astype(jnp.float32)
        order = jnp.argsort(-sc_)
        b = bx[order].astype(jnp.float32)
        c = (jnp.zeros((n,), jnp.int32) if cat is None
             else cat[order].astype(jnp.int32))
        x1, y1, x2, y2 = b[:, 0], b[:, 1], b[:, 2], b[:, 3]
        area = jnp.maximum(x2 - x1, 0) * jnp.maximum(y2 - y1, 0)
        ix1 = jnp.maximum(x1[:, None], x1[None, :])
        iy1 = jnp.maximum(y1[:, None], y1[None, :])
        ix2 = jnp.minimum(x2[:, None], x2[None, :])
        iy2 = jnp.minimum(y2[:, None], y2[None, :])
        inter = jnp.maximum(ix2 - ix1, 0) * jnp.maximum(iy2 - iy1, 0)
        iou = inter / jnp.maximum(area[:, None] + area[None, :] - inter, 1e-10)
        same_cat = c[:, None] == c[None, :]
        sup = (iou > iou_threshold) & same_cat

        def body(keep, i):
            # i survives unless an earlier KEPT box suppresses it
            earlier = jnp.arange(n) < i
            killed = jnp.any(sup[:, i] & keep & earlier)
            return keep.at[i].set(~killed), None

        keep, _ = jax.lax.scan(body, jnp.ones((n,), bool), jnp.arange(n))
        kept_sorted = jnp.where(keep, jnp.arange(n), n)
        sel = jnp.sort(kept_sorted)          # kept positions in score order
        return order[jnp.clip(sel, 0, n - 1)], keep.sum()

    idx, cnt = apply_op(f, boxes, scores, category_idxs, op_name="nms")
    import numpy as np

    k = int(np.asarray(cnt.numpy()))
    out = idx[:k]
    if top_k is not None:
        out = out[: int(top_k)]
    return out


def _roi_sample(feat, rois, output_size, spatial_scale, mode,
                sampling_ratio=1, aligned=True):
    """feat [C, H, W]; rois [K, 4] (x1, y1, x2, y2) -> [K, C, oh, ow]."""
    C, H, W = feat.shape
    oh, ow = output_size
    # aligned=True: continuous coordinates get the half-pixel correction
    # (the modern convention); aligned=False keeps the legacy offset
    off = 0.5 if aligned else 0.0
    x1 = rois[:, 0] * spatial_scale - off
    y1 = rois[:, 1] * spatial_scale - off
    x2 = rois[:, 2] * spatial_scale - off
    y2 = rois[:, 3] * spatial_scale - off
    if mode == "align":
        # S x S bilinear samples per bin, averaged (sampling_ratio<=0
        # collapses to the 1-sample bin center)
        S = int(sampling_ratio) if sampling_ratio and sampling_ratio > 0 else 1
        bw = (x2 - x1) / ow
        bh = (y2 - y1) / oh
        jj = (jnp.arange(ow * S) + 0.5) / S                       # [ow*S]
        ii = (jnp.arange(oh * S) + 0.5) / S                       # [oh*S]
        cx = x1[:, None] + jj * bw[:, None]                       # [K, ow*S]
        cy = y1[:, None] + ii * bh[:, None]                       # [K, oh*S]
        x0 = jnp.floor(cx - 0.5)
        y0 = jnp.floor(cy - 0.5)
        lx = (cx - 0.5) - x0
        ly = (cy - 0.5) - y0

        def gather(yy, xx):
            yy = jnp.clip(yy.astype(jnp.int32), 0, H - 1)
            xx = jnp.clip(xx.astype(jnp.int32), 0, W - 1)
            # feat[:, yy[k, i], xx[k, j]] -> [K, C, oh*S, ow*S]
            return feat[:, yy[:, :, None], xx[:, None, :]].transpose(1, 0, 2, 3)

        v00 = gather(y0, x0)
        v01 = gather(y0, x0 + 1)
        v10 = gather(y0 + 1, x0)
        v11 = gather(y0 + 1, x0 + 1)
        wx = lx[:, None, None, :]
        wy = ly[:, None, :, None]
        out = (v00 * (1 - wy) * (1 - wx) + v01 * (1 - wy) * wx
               + v10 * wy * (1 - wx) + v11 * wy * wx)
        K = rois.shape[0]
        return out.reshape(K, C, oh, S, ow, S).mean(axis=(3, 5))
    # pool: max over an evenly-strided sample grid per bin (4x4 samples)
    S = 4
    bw = (x2 - x1) / ow
    bh = (y2 - y1) / oh
    gx = x1[:, None, None] + (jnp.arange(ow)[None, :, None] +
                              (jnp.arange(S) + 0.5)[None, None, :] / S) \
        * bw[:, None, None]                                     # [K, ow, S]
    gy = y1[:, None, None] + (jnp.arange(oh)[None, :, None] +
                              (jnp.arange(S) + 0.5)[None, None, :] / S) \
        * bh[:, None, None]
    xi = jnp.clip(gx.astype(jnp.int32), 0, W - 1).reshape(gx.shape[0], -1)
    yi = jnp.clip(gy.astype(jnp.int32), 0, H - 1).reshape(gy.shape[0], -1)
    vals = feat[:, yi[:, :, None], xi[:, None, :]]   # [C, K, oh*S, ow*S]
    vals = vals.transpose(1, 0, 2, 3).reshape(
        gx.shape[0], C, oh, S, ow, S)
    return vals.max(axis=(3, 5))


def _gather_roi_images(feat, bx, bn):
    """Per-roi image gather: batch index from the boxes_num prefix sums —
    the one shared roi->image mapping (rois_op modes and psroi_pool)."""
    csum = jnp.cumsum(bn)
    roi_batch = jnp.searchsorted(csum, jnp.arange(bx.shape[0]), side="right")
    return feat[roi_batch]


def _rois_op(x, boxes, boxes_num, output_size, spatial_scale, mode,
             sampling_ratio=1, aligned=True):
    if isinstance(output_size, int):
        output_size = (output_size, output_size)

    def f(feat, bx, bn):
        feats = _gather_roi_images(feat, bx, bn)    # [K, C, H, W]
        return jax.vmap(lambda fm, rb: _roi_sample(
            fm, rb[None], output_size, spatial_scale, mode,
            sampling_ratio, aligned)[0])(feats, bx)

    return apply_op(f, x, boxes, boxes_num, op_name=f"roi_{mode}")


def roi_align(x, boxes, boxes_num, output_size, spatial_scale=1.0,
              sampling_ratio=-1, aligned=True, name=None):
    """Reference vision/ops.py roi_align: x [N,C,H,W], boxes [K,4]
    (x1,y1,x2,y2), boxes_num [N] rois per image -> [K, C, oh, ow].
    S x S bilinear samples per bin averaged (sampling_ratio<=0 uses the
    single bin-center sample); ``aligned`` selects the half-pixel vs
    legacy coordinate convention."""
    return _rois_op(x, boxes, boxes_num, output_size, spatial_scale,
                    "align", sampling_ratio, aligned)


def roi_pool(x, boxes, boxes_num, output_size, spatial_scale=1.0, name=None):
    """Reference vision/ops.py roi_pool: max-pool each roi bin (legacy
    coordinates, like the reference)."""
    return _rois_op(x, boxes, boxes_num, output_size, spatial_scale,
                    "pool", aligned=False)


def box_coder(prior_box, prior_box_var, target_box,
              code_type="encode_center_size", box_normalized=True,
              axis=0, name=None):
    """Reference vision/ops.py box_coder: encode/decode between corner
    boxes and center-size offsets."""

    def f(pb, pbv, tb):
        norm = 0.0 if box_normalized else 1.0
        pw = pb[:, 2] - pb[:, 0] + norm
        ph = pb[:, 3] - pb[:, 1] + norm
        px = pb[:, 0] + pw * 0.5
        py = pb[:, 1] + ph * 0.5
        if pbv is None:
            var = jnp.ones((1, 4), jnp.float32)
        elif pbv.ndim == 1:
            var = pbv[None, :]
        else:
            var = pbv
        if code_type == "encode_center_size":
            tw = tb[:, 2] - tb[:, 0] + norm
            th = tb[:, 3] - tb[:, 1] + norm
            tx = tb[:, 0] + tw * 0.5
            ty = tb[:, 1] + th * 0.5
            out = jnp.stack([(tx[:, None] - px[None, :]) / pw[None, :],
                             (ty[:, None] - py[None, :]) / ph[None, :],
                             jnp.log(tw[:, None] / pw[None, :]),
                             jnp.log(th[:, None] / ph[None, :])], -1)
            return out / var[None, :, :]
        # decode_center_size (axis=0: priors broadcast over row dim)
        d = tb * var[None, :, :] if tb.ndim == 3 else (tb * var)[:, None, :]
        dx, dy, dw, dh = d[..., 0], d[..., 1], d[..., 2], d[..., 3]
        # axis: which target dim the priors align with (reference box_coder
        # axis semantics) — dim 1 is the layout the encode above produces
        if axis == 1:
            bw_, bh_, bx_, by_ = pw[None, :], ph[None, :], px[None, :], py[None, :]
        elif axis == 0:
            bw_, bh_, bx_, by_ = pw[:, None], ph[:, None], px[:, None], py[:, None]
        else:
            raise ValueError(f"box_coder axis must be 0 or 1, got {axis}")
        ox = dx * bw_ + bx_
        oy = dy * bh_ + by_
        ow_ = jnp.exp(dw) * bw_
        oh_ = jnp.exp(dh) * bh_
        return jnp.stack([ox - ow_ * 0.5, oy - oh_ * 0.5,
                          ox + ow_ * 0.5 - norm, oy + oh_ * 0.5 - norm], -1)

    return apply_op(f, prior_box, prior_box_var, target_box,
                    op_name="box_coder")


def _bilinear_gather(x_g, h_im, w_im, H, W):
    """Bilinear sample with per-corner zero padding (reference
    funcs::DmcnIm2colBilinear, deformable_conv_functor.h:23): corners
    outside [0, H-1]x[0, W-1] contribute zero.

    x_g:  [n, dg, cpg, H*W] flattened group-split image.
    h_im, w_im: [n, dg, T] fractional sample coordinates.
    Returns [n, dg, cpg, T].
    """
    h_low = jnp.floor(h_im)
    w_low = jnp.floor(w_im)
    lh = h_im - h_low
    lw = w_im - w_low
    hl = h_low.astype(jnp.int32)
    wl = w_low.astype(jnp.int32)

    out = 0.0
    for dh, dw, cw in ((0, 0, (1 - lh) * (1 - lw)), (0, 1, (1 - lh) * lw),
                       (1, 0, lh * (1 - lw)), (1, 1, lh * lw)):
        hh = hl + dh
        ww = wl + dw
        ok = (hh >= 0) & (hh <= H - 1) & (ww >= 0) & (ww <= W - 1)
        idx = jnp.clip(hh, 0, H - 1) * W + jnp.clip(ww, 0, W - 1)
        v = jnp.take_along_axis(x_g, idx[:, :, None, :], axis=-1)
        out = out + jnp.where(ok, cw, 0.0)[:, :, None, :] * v
    return out


def deform_conv2d(x, offset, weight, bias=None, stride=1, padding=0,
                  dilation=1, deformable_groups=1, groups=1, mask=None,
                  name=None):
    """Deformable convolution v1 (``mask=None``) / v2 (reference
    vision/ops.py deform_conv2d, kernel semantics from
    phi/kernels/funcs/deformable_conv_functor.cc:22): each kernel tap
    samples the input at ``p + p_k + Δp_k`` by bilinear interpolation
    (zero outside), optionally modulated by ``Δm_k``, then a grouped
    GEMM applies the filter — im2col-with-offsets as one vectorized
    XLA gather feeding a dot_general on the MXU.

    offset: [N, 2*dg*kh*kw, Ho, Wo], channel pairs (dy, dx) per tap;
    mask:   [N, dg*kh*kw, Ho, Wo].
    """
    sh, sw = (stride, stride) if isinstance(stride, int) else tuple(stride)
    ph, pw = (padding, padding) if isinstance(padding, int) else tuple(padding)
    dh_, dw_ = (dilation, dilation) if isinstance(dilation, int) \
        else tuple(dilation)
    dg = deformable_groups

    def f(x, offset, mask, weight, bias):
        n, cin, H, W = x.shape
        cout, cpg_w, kh, kw = weight.shape
        if cin % groups or cin % dg or cpg_w != cin // groups:
            raise ValueError(
                f"deform_conv2d: in_channels {cin} incompatible with "
                f"groups={groups}/deformable_groups={dg}/weight {weight.shape}")
        Ho = (H + 2 * ph - (dh_ * (kh - 1) + 1)) // sh + 1
        Wo = (W + 2 * pw - (dw_ * (kw - 1) + 1)) // sw + 1
        dt = jnp.result_type(x.dtype, jnp.float32)
        xf = x.astype(dt)
        off = offset.astype(dt).reshape(n, dg, kh * kw, 2, Ho, Wo)

        ti, tj = jnp.meshgrid(jnp.arange(kh), jnp.arange(kw), indexing="ij")
        base_h = (jnp.arange(Ho) * sh - ph)[:, None] \
            + (ti.reshape(-1) * dh_)[None, :]            # [Ho, taps]
        base_w = (jnp.arange(Wo) * sw - pw)[:, None] \
            + (tj.reshape(-1) * dw_)[None, :]            # [Wo, taps]
        # sample coords [n, dg, taps, Ho, Wo]
        h_im = base_h.T[None, None, :, :, None] + off[:, :, :, 0]
        w_im = base_w.T[None, None, :, None, :] + off[:, :, :, 1]
        # reference gate: the whole tap is zero unless -1 < p < size
        ok = (h_im > -1) & (h_im < H) & (w_im > -1) & (w_im < W)

        T = kh * kw * Ho * Wo
        x_g = xf.reshape(n, dg, cin // dg, H * W)
        cols = _bilinear_gather(x_g, h_im.reshape(n, dg, T),
                                w_im.reshape(n, dg, T), H, W)
        cols = cols * ok.reshape(n, dg, 1, T)
        if mask is not None:
            m = mask.astype(dt).reshape(n, dg, 1, T)
            cols = cols * m
        # [n, dg, cpg_dg, taps, Ho*Wo] -> [n, cin, taps, Ho*Wo], channel-major
        cols = cols.reshape(n, dg, cin // dg, kh * kw, Ho * Wo)
        cols = cols.reshape(n, cin, kh * kw, Ho * Wo)
        cols = cols.reshape(n, groups, (cin // groups) * kh * kw, Ho * Wo)
        wg = weight.astype(dt).reshape(
            groups, cout // groups, (cin // groups) * kh * kw)
        out = jnp.einsum("gok,ngkp->ngop", wg, cols,
                         preferred_element_type=dt)
        out = out.reshape(n, cout, Ho, Wo)
        if bias is not None:
            out = out + bias.astype(dt)[None, :, None, None]
        return out.astype(x.dtype)

    return apply_op(f, x, offset, mask, weight, bias,
                    op_name="deform_conv2d")


def yolo_box(x, img_size, anchors, class_num, conf_thresh, downsample_ratio,
             clip_bbox=True, name=None, scale_x_y=1.0, iou_aware=False,
             iou_aware_factor=0.5):
    """YOLOv3 box decode (reference phi/kernels/cpu/yolo_box_kernel.cc:25,
    funcs/yolo_box_util.h:26): grid-offset sigmoid xy, anchor-scaled exp
    wh, boxes rescaled to image size as xyxy; entries whose (iou-aware)
    confidence is below ``conf_thresh`` output zero boxes and scores.

    Returns (boxes [N, an*H*W, 4], scores [N, an*H*W, class_num]).
    """
    an = jnp.asarray(anchors, jnp.float32).reshape(-1, 2)
    an_num = an.shape[0]
    scale = float(scale_x_y)
    bias = -0.5 * (scale - 1.0)

    def f(x, img_size):
        n, c, h, w = x.shape
        xf = x.astype(jnp.float32)
        if iou_aware:
            iou_t = xf[:, :an_num].reshape(n, an_num, h, w)
            box_t = xf[:, an_num:].reshape(n, an_num, 5 + class_num, h, w)
        else:
            box_t = xf.reshape(n, an_num, 5 + class_num, h, w)
        img_h = img_size[:, 0].astype(jnp.float32)[:, None, None, None]
        img_w = img_size[:, 1].astype(jnp.float32)[:, None, None, None]
        gx = jnp.arange(w, dtype=jnp.float32)[None, None, None, :]
        gy = jnp.arange(h, dtype=jnp.float32)[None, None, :, None]
        sig = jax.nn.sigmoid
        bx = (gx + sig(box_t[:, :, 0]) * scale + bias) * img_w / w
        by = (gy + sig(box_t[:, :, 1]) * scale + bias) * img_h / h
        bw = jnp.exp(box_t[:, :, 2]) * an[None, :, 0, None, None] * img_w \
            / (downsample_ratio * w)
        bh = jnp.exp(box_t[:, :, 3]) * an[None, :, 1, None, None] * img_h \
            / (downsample_ratio * h)
        conf = sig(box_t[:, :, 4])
        if iou_aware:
            conf = conf ** (1.0 - iou_aware_factor) \
                * sig(iou_t) ** iou_aware_factor
        keep = conf >= conf_thresh

        x1, y1 = bx - bw * 0.5, by - bh * 0.5
        x2, y2 = bx + bw * 0.5, by + bh * 0.5
        if clip_bbox:
            x1, y1 = jnp.maximum(x1, 0.0), jnp.maximum(y1, 0.0)
            x2 = jnp.minimum(x2, img_w - 1.0)
            y2 = jnp.minimum(y2, img_h - 1.0)
        boxes = jnp.stack([x1, y1, x2, y2], -1) * keep[..., None]
        scores = (conf[..., None] * sig(
            jnp.moveaxis(box_t[:, :, 5:], 2, -1))) * keep[..., None]
        return (boxes.reshape(n, an_num * h * w, 4),
                scores.reshape(n, an_num * h * w, class_num))

    return apply_op(f, x, img_size, op_name="yolo_box")


def _cxcywh_iou(b1, b2):
    """IoU of center-size boxes, broadcasting (reference CalcBoxIoU,
    cpu/yolo_loss_kernel.cc:83 — no epsilon in the union)."""
    ov_w = jnp.minimum(b1[..., 0] + b1[..., 2] / 2, b2[..., 0] + b2[..., 2] / 2) \
        - jnp.maximum(b1[..., 0] - b1[..., 2] / 2, b2[..., 0] - b2[..., 2] / 2)
    ov_h = jnp.minimum(b1[..., 1] + b1[..., 3] / 2, b2[..., 1] + b2[..., 3] / 2) \
        - jnp.maximum(b1[..., 1] - b1[..., 3] / 2, b2[..., 1] - b2[..., 3] / 2)
    inter = jnp.where((ov_w < 0) | (ov_h < 0), 0.0, ov_w * ov_h)
    union = b1[..., 2] * b1[..., 3] + b2[..., 2] * b2[..., 3] - inter
    return inter / union


def yolo_loss(x, gt_box, gt_label, anchors, anchor_mask, class_num,
              ignore_thresh, downsample_ratio, gt_score=None,
              use_label_smooth=True, name=None, scale_x_y=1.0):
    """YOLOv3 loss (reference phi/kernels/cpu/yolo_loss_kernel.cc:181):
    sigmoid-CE xy + L1 wh box loss scaled by (2 - w*h)*score at each
    gt's best-anchor cell, label-smoothed class CE, and objectness CE
    where predictions overlapping any gt above ``ignore_thresh`` are
    ignored. Fully vectorized except the per-gt objectness scatter,
    which keeps the kernel's last-writer-wins order via a trace-time
    loop over the (static) max-box dimension. Returns loss [N]."""
    an = jnp.asarray(anchors, jnp.float32).reshape(-1, 2)
    an_num = an.shape[0]
    mask_list = list(anchor_mask)
    mask_num = len(mask_list)
    # an_idx -> first position in anchor_mask, or -1 (GetMaskIndex)
    lut = [-1] * an_num
    for pos, v in enumerate(mask_list):
        if lut[v] == -1:
            lut[v] = pos
    lut = jnp.asarray(lut, jnp.int32)
    scale = float(scale_x_y)
    bias = -0.5 * (scale - 1.0)

    if use_label_smooth:
        sw = min(1.0 / class_num, 1.0 / 40)
        label_pos, label_neg = 1.0 - sw, sw
    else:
        label_pos, label_neg = 1.0, 0.0

    def sce(logit, label):
        return jnp.maximum(logit, 0.0) - logit * label \
            + jnp.log1p(jnp.exp(-jnp.abs(logit)))

    def f(x, gt_box, gt_label, gt_score):
        n, c, h, w = x.shape
        if h != w:
            # the reference kernel mixes grid_size=h with gi=gt.x*w and is
            # only well-defined on square maps (its docstring requires H==W)
            raise ValueError(f"yolo_loss requires a square feature map, "
                             f"got H={h}, W={w}")
        b = gt_box.shape[1]
        input_size = downsample_ratio * h
        xr = x.astype(jnp.float32).reshape(n, mask_num, 5 + class_num, h, w)
        gt = gt_box.astype(jnp.float32)
        score = (jnp.ones((n, b), jnp.float32) if gt_score is None
                 else gt_score.astype(jnp.float32))
        valid = (gt[..., 2] >= 1e-6) & (gt[..., 3] >= 1e-6)

        # --- ignore mask: best pred-vs-gt IoU > ignore_thresh ---
        gx = jnp.arange(w, dtype=jnp.float32)[None, None, None, :]
        gy = jnp.arange(h, dtype=jnp.float32)[None, None, :, None]
        sig = jax.nn.sigmoid
        m_an = an[jnp.asarray(mask_list, jnp.int32)]     # [mask_num, 2]
        px = (gx + sig(xr[:, :, 0]) * scale + bias) / w
        py = (gy + sig(xr[:, :, 1]) * scale + bias) / h
        pw = jnp.exp(xr[:, :, 2]) * m_an[None, :, 0, None, None] / input_size
        ph = jnp.exp(xr[:, :, 3]) * m_an[None, :, 1, None, None] / input_size
        pred = jnp.stack([px, py, pw, ph], -1)           # [n,mask,h,w,4]
        iou = _cxcywh_iou(pred[:, :, :, :, None, :],
                          gt[:, None, None, None, :, :])  # [n,mask,h,w,b]
        iou = jnp.where(valid[:, None, None, None, :], iou, 0.0)
        best_iou = jnp.max(iou, -1) if b else jnp.zeros_like(px)
        obj_mask = jnp.where(best_iou > ignore_thresh, -1.0, 0.0)

        # --- per-gt best anchor over ALL anchors (shifted-box IoU) ---
        inter = jnp.minimum(an[None, None, :, 0] / input_size, gt[..., None, 2]) \
            * jnp.minimum(an[None, None, :, 1] / input_size, gt[..., None, 3])
        a_area = (an[:, 0] * an[:, 1] / (input_size * input_size))[None, None]
        union = a_area + gt[..., None, 2] * gt[..., None, 3] - inter
        best_n = jnp.argmax(inter / union, -1)           # [n, b]
        mask_idx = lut[best_n]
        matched = valid & (mask_idx >= 0)

        gi = jnp.clip((gt[..., 0] * w).astype(jnp.int32), 0, w - 1)
        gj = jnp.clip((gt[..., 1] * h).astype(jnp.int32), 0, h - 1)

        # gather predictions at each gt cell: [n, b, 5+class]
        ii = jnp.arange(n)[:, None]
        mi = jnp.maximum(mask_idx, 0)
        pv = jnp.moveaxis(xr, 2, -1)[ii, mi, gj, gi]

        tx = gt[..., 0] * w - gi
        ty = gt[..., 1] * h - gj
        tw = jnp.log(gt[..., 2] * input_size
                     / jnp.maximum(an[best_n, 0], 1e-10))
        th = jnp.log(gt[..., 3] * input_size
                     / jnp.maximum(an[best_n, 1], 1e-10))
        box_w = (2.0 - gt[..., 2] * gt[..., 3]) * score
        loc = (sce(pv[..., 0], tx) + sce(pv[..., 1], ty)
               + jnp.abs(pv[..., 2] - tw) + jnp.abs(pv[..., 3] - th)) * box_w

        cls_t = jnp.where(
            jnp.arange(class_num)[None, None] == gt_label[..., None],
            label_pos, label_neg)
        cls = jnp.sum(sce(pv[..., 5:], cls_t), -1) * score
        loss = jnp.sum(jnp.where(matched, loc + cls, 0.0), -1)   # [n]

        # --- objectness target: sequential writes keep C-kernel order ---
        mi_w = jnp.where(matched, mask_idx, mask_num)    # OOB -> dropped
        ib = jnp.arange(n)
        for t in range(b):
            obj_mask = obj_mask.at[ib, mi_w[:, t], gj[:, t], gi[:, t]].set(
                score[:, t], mode="drop")
        tobj = xr[:, :, 4]
        pos = obj_mask > 1e-5
        neg = (~pos) & (obj_mask > -0.5)
        obj_loss = jnp.sum(
            jnp.where(pos, sce(tobj, 1.0) * obj_mask, 0.0)
            + jnp.where(neg, sce(tobj, 0.0), 0.0), (1, 2, 3))
        return loss + obj_loss

    return apply_op(f, x, gt_box, gt_label, gt_score, op_name="yolo_loss")



class RoIAlign:
    """Layer form of roi_align (reference vision/ops.py RoIAlign)."""

    def __init__(self, output_size, spatial_scale=1.0):
        self.output_size = output_size
        self.spatial_scale = spatial_scale

    def __call__(self, x, boxes, boxes_num):
        return roi_align(x, boxes, boxes_num, self.output_size,
                         self.spatial_scale)


class RoIPool:
    """Layer form of roi_pool (reference vision/ops.py RoIPool)."""

    def __init__(self, output_size, spatial_scale=1.0):
        self.output_size = output_size
        self.spatial_scale = spatial_scale

    def __call__(self, x, boxes, boxes_num):
        return roi_pool(x, boxes, boxes_num, self.output_size,
                        self.spatial_scale)


def distribute_fpn_proposals(fpn_rois, min_level, max_level, refer_level,
                             refer_scale, pixel_offset=False, rois_num=None,
                             name=None):
    """Reference vision/ops.py distribute_fpn_proposals: assign each roi to
    an FPN level by sqrt(area) (FPN paper eq. 1), returning per-level roi
    lists + the restore index."""
    import numpy as np

    from ..core.tensor import Tensor

    rois = np.asarray(fpn_rois.numpy() if isinstance(fpn_rois, Tensor)
                      else fpn_rois)
    off = 1.0 if pixel_offset else 0.0
    w = rois[:, 2] - rois[:, 0] + off
    h = rois[:, 3] - rois[:, 1] + off
    scale = np.sqrt(np.maximum(w * h, 1e-12))
    lvl = np.floor(np.log2(scale / refer_scale + 1e-9)) + refer_level
    lvl = np.clip(lvl, min_level, max_level).astype(np.int64)
    outs, nums, order = [], [], []
    for L in range(min_level, max_level + 1):
        idx = np.nonzero(lvl == L)[0]
        order.append(idx)
        outs.append(Tensor._from_data(jnp.asarray(rois[idx])))
        nums.append(Tensor._from_data(jnp.asarray(
            np.asarray([len(idx)], np.int32))))
    restore = np.argsort(np.concatenate(order)) if order else np.zeros(0)
    return outs, Tensor._from_data(jnp.asarray(restore.astype(np.int32))), nums


def read_file(filename, name=None):
    """Reference vision/ops.py read_file: raw bytes as a uint8 tensor."""
    import numpy as np

    from ..core.tensor import Tensor

    with open(filename, "rb") as f:
        data = f.read()
    return Tensor._from_data(jnp.asarray(np.frombuffer(data, np.uint8)))


def decode_jpeg(x, mode="unchanged", name=None):
    """Reference vision/ops.py decode_jpeg (nvjpeg there): decoded via PIL
    when available — CHW uint8 like the reference."""
    import io as _io

    import numpy as np

    from ..core.tensor import Tensor

    try:
        from PIL import Image
    except ImportError:
        raise NotImplementedError(
            "decode_jpeg needs Pillow (the reference needs nvjpeg); install "
            "pillow or decode outside the framework") from None
    raw = np.asarray(x.numpy() if isinstance(x, Tensor) else x,
                     np.uint8).tobytes()
    img = Image.open(_io.BytesIO(raw))
    if mode == "gray":
        img = img.convert("L")
    elif mode == "rgb":
        img = img.convert("RGB")
    # mode == "unchanged": keep the file's native channel count
    arr = np.asarray(img)
    if arr.ndim == 2:
        arr = arr[None]
    else:
        arr = arr.transpose(2, 0, 1)
    return Tensor._from_data(jnp.asarray(arr))


def prior_box(input, image, min_sizes, max_sizes=None, aspect_ratios=(1.0,),
              variance=(0.1, 0.1, 0.2, 0.2), flip=False, clip=False,
              steps=(0.0, 0.0), offset=0.5, min_max_aspect_ratios_order=False,
              name=None):
    """Reference vision/ops.py prior_box (SSD anchors): one (box, variance)
    pair per feature-map cell x anchor shape."""
    import numpy as np

    from ..core.tensor import Tensor

    H, W = int(input.shape[2]), int(input.shape[3])
    img_h, img_w = int(image.shape[2]), int(image.shape[3])
    step_w = steps[0] or img_w / W
    step_h = steps[1] or img_h / H
    ars = [1.0]
    for ar in aspect_ratios:
        if ar != 1.0:
            ars.append(float(ar))
            if flip:
                ars.append(1.0 / float(ar))
    boxes = []
    for y in range(H):
        for x_ in range(W):
            cx = (x_ + offset) * step_w
            cy = (y + offset) * step_h
            cell = []
            for k, ms in enumerate(min_sizes):
                def _box(half_w, half_h):
                    return [(cx - half_w) / img_w, (cy - half_h) / img_h,
                            (cx + half_w) / img_w, (cy + half_h) / img_h]

                cell.append(_box(ms / 2, ms / 2))        # ar = 1 min box
                max_box = None
                if max_sizes:
                    s = np.sqrt(ms * max_sizes[k]) / 2
                    max_box = _box(s, s)
                if min_max_aspect_ratios_order and max_box is not None:
                    cell.append(max_box)                 # reference order A
                for ar in ars:
                    if ar == 1.0:
                        continue
                    cell.append(_box(ms * np.sqrt(ar) / 2,
                                     ms / np.sqrt(ar) / 2))
                if not min_max_aspect_ratios_order and max_box is not None:
                    cell.append(max_box)                 # reference order B
            boxes.append(cell)
    out = np.asarray(boxes, np.float32).reshape(H, W, -1, 4)
    if clip:
        out = np.clip(out, 0.0, 1.0)
    var = np.broadcast_to(np.asarray(variance, np.float32),
                          out.shape).copy()
    return Tensor._from_data(jnp.asarray(out)), Tensor._from_data(
        jnp.asarray(var))


def matrix_nms(bboxes, scores, score_threshold, post_threshold=0.0,
               nms_top_k=400, keep_top_k=200, use_gaussian=False,
               gaussian_sigma=2.0, background_label=0, normalized=True,
               return_index=False, return_rois_num=True, name=None):
    """Matrix NMS (reference vision/ops.py matrix_nms, SOLOv2): instead of
    hard suppression, every box's score decays by the most-suppressive
    higher-scored box of its class — one IoU matrix, no sequential loop.
    bboxes [N, M, 4], scores [N, C, M]. Returns (out [K, 6] rows of
    (label, score, x1, y1, x2, y2), rois_num, index?) like the reference."""
    import numpy as np

    from ..core.tensor import Tensor

    bx = np.asarray(bboxes.numpy() if isinstance(bboxes, Tensor) else bboxes,
                    np.float32)
    sc = np.asarray(scores.numpy() if isinstance(scores, Tensor) else scores,
                    np.float32)
    norm = 0.0 if normalized else 1.0
    outs, idxs, nums = [], [], []
    for n in range(bx.shape[0]):
        rows = []
        ridx = []
        for c in range(sc.shape[1]):
            if c == background_label:
                continue
            s = sc[n, c]
            keep = np.nonzero(s > score_threshold)[0]
            if keep.size == 0:
                continue
            order = keep[np.argsort(-s[keep])]
            if nms_top_k > -1:              # -1 = keep all (reference)
                order = order[:nms_top_k]
            b = bx[n, order]
            ss = s[order]
            x1, y1, x2, y2 = b[:, 0], b[:, 1], b[:, 2], b[:, 3]
            area = np.maximum(x2 - x1 + norm, 0) * np.maximum(y2 - y1 + norm, 0)
            ix1 = np.maximum(x1[:, None], x1[None, :])
            iy1 = np.maximum(y1[:, None], y1[None, :])
            ix2 = np.minimum(x2[:, None], x2[None, :])
            iy2 = np.minimum(y2[:, None], y2[None, :])
            inter = (np.maximum(ix2 - ix1 + norm, 0)
                     * np.maximum(iy2 - iy1 + norm, 0))
            iou = inter / np.maximum(area[:, None] + area[None, :] - inter,
                                     1e-10)
            iou = np.triu(iou, k=1)         # iou[i, j], i higher-scored
            # comp_i: how suppressed the SUPPRESSOR i itself is (its max IoU
            # with any higher-scored box) — the matrix-NMS compensation term
            comp = iou.max(axis=0)
            if use_gaussian:
                # reference kernel: exp((comp^2 - iou^2) * sigma)
                decay = np.exp((comp[:, None] ** 2 - iou ** 2)
                               * gaussian_sigma)
            else:
                decay = (1 - iou) / np.maximum(1 - comp[:, None], 1e-10)
            decay = np.where(np.triu(np.ones_like(iou), k=1) > 0, decay, 1.0)
            decay = decay.min(axis=0)
            ds = ss * decay
            ok = ds > post_threshold
            for j in np.nonzero(ok)[0]:
                rows.append([float(c), float(ds[j]), *b[j].tolist()])
                ridx.append(int(order[j]))
        if rows:
            arr = np.asarray(rows, np.float32)
            top = np.argsort(-arr[:, 1])
            if keep_top_k > -1:             # -1 = keep all (reference)
                top = top[:keep_top_k]
            arr = arr[top]
            ridx = np.asarray(ridx, np.int64)[top]
        else:
            arr = np.zeros((0, 6), np.float32)
            ridx = np.zeros((0,), np.int64)
        outs.append(arr)
        idxs.append(ridx + n * bx.shape[1])
        nums.append(len(arr))
    out = Tensor._from_data(jnp.asarray(np.concatenate(outs, 0)))
    rois = Tensor._from_data(jnp.asarray(np.asarray(nums, np.int32))) \
        if return_rois_num else None
    index = Tensor._from_data(jnp.asarray(np.concatenate(idxs))) \
        if return_index else None
    return out, rois, index     # always a 3-tuple, like the reference


def generate_proposals(scores, bbox_deltas, img_size, anchors, variances,
                       pre_nms_top_n=6000, post_nms_top_n=1000,
                       nms_thresh=0.5, min_size=0.1, eta=1.0,
                       pixel_offset=False, return_rois_num=True, name=None):
    """RPN proposal generation (reference vision/ops.py generate_proposals):
    decode anchor deltas (box_coder math), clip to the image, drop tiny
    boxes, top-k -> NMS -> top-k. scores [N, A, H, W],
    bbox_deltas [N, 4A, H, W], anchors/variances [H, W, A, 4]."""
    import numpy as np

    from ..core.tensor import Tensor

    sc = np.asarray(scores.numpy() if isinstance(scores, Tensor) else scores,
                    np.float32)
    dl = np.asarray(bbox_deltas.numpy() if isinstance(bbox_deltas, Tensor)
                    else bbox_deltas, np.float32)
    an = np.asarray(anchors.numpy() if isinstance(anchors, Tensor)
                    else anchors, np.float32).reshape(-1, 4)
    va = np.asarray(variances.numpy() if isinstance(variances, Tensor)
                    else variances, np.float32).reshape(-1, 4)
    imgs = np.asarray(img_size.numpy() if isinstance(img_size, Tensor)
                      else img_size, np.float32)
    off = 1.0 if pixel_offset else 0.0
    N, A = sc.shape[0], sc.shape[1]
    outs, probs, nums = [], [], []
    for n in range(N):
        s = sc[n].transpose(1, 2, 0).reshape(-1)              # [H*W*A]
        d = dl[n].reshape(A, 4, *dl.shape[2:]).transpose(2, 3, 0, 1)             .reshape(-1, 4)
        order = np.argsort(-s)[:pre_nms_top_n]
        s, d, a, v = s[order], d[order], an[order], va[order]
        aw = a[:, 2] - a[:, 0] + off
        ah = a[:, 3] - a[:, 1] + off
        ax = a[:, 0] + aw * 0.5
        ay = a[:, 1] + ah * 0.5
        cx = v[:, 0] * d[:, 0] * aw + ax
        cy = v[:, 1] * d[:, 1] * ah + ay
        w = np.exp(np.minimum(v[:, 2] * d[:, 2], 10.0)) * aw
        h = np.exp(np.minimum(v[:, 3] * d[:, 3], 10.0)) * ah
        boxes = np.stack([cx - w * 0.5, cy - h * 0.5,
                          cx + w * 0.5 - off, cy + h * 0.5 - off], -1)
        H, W = imgs[n, 0], imgs[n, 1]
        boxes[:, 0::2] = np.clip(boxes[:, 0::2], 0, W - off)
        boxes[:, 1::2] = np.clip(boxes[:, 1::2], 0, H - off)
        ms = max(float(min_size), 1.0)   # reference FilterBoxes clamp
        bw_ = boxes[:, 2] - boxes[:, 0] + off
        bh_ = boxes[:, 3] - boxes[:, 1] + off
        keep = (bw_ >= ms) & (bh_ >= ms)
        if pixel_offset:
            cx_ = boxes[:, 0] + bw_ * 0.5
            cy_ = boxes[:, 1] + bh_ * 0.5
            keep &= (cx_ <= W) & (cy_ <= H)  # center inside the image
        boxes, s = boxes[keep], s[keep]
        if len(boxes):
            kept = nms(Tensor._from_data(jnp.asarray(boxes)), nms_thresh,
                       Tensor._from_data(jnp.asarray(s))).numpy()
            kept = kept[:post_nms_top_n]
            boxes, s = boxes[kept], s[kept]
        outs.append(boxes)
        probs.append(s)
        nums.append(len(boxes))
    rois = Tensor._from_data(jnp.asarray(
        np.concatenate(outs, 0) if outs else np.zeros((0, 4), np.float32)))
    roi_probs = Tensor._from_data(jnp.asarray(
        (np.concatenate(probs, 0) if probs
         else np.zeros((0,), np.float32)).reshape(-1, 1)))  # [K, 1] like ref
    nums_t = Tensor._from_data(jnp.asarray(np.asarray(nums, np.int32)))
    if return_rois_num:
        return rois, roi_probs, nums_t
    return rois, roi_probs
def psroi_pool(x, boxes, boxes_num, output_size, spatial_scale=1.0,
               name=None):
    """Position-sensitive RoI pooling (reference vision/ops.py psroi_pool,
    R-FCN): input channels C = output_channels * oh * ow; output channel c
    of bin (i, j) AVERAGE-pools input channel c*oh*ow + i*ow + j over that
    bin. Reference window semantics: the roi is rounded to
    [round(x1)*scale, round(x2 + 1)*scale) and EMPTY bins (integer window
    collapses) yield exactly 0; within non-empty bins a fixed 4x4 sample
    grid approximates the integer-window average (static shapes)."""
    if isinstance(output_size, int):
        output_size = (output_size, output_size)
    oh, ow = output_size
    C = int(x.shape[1])
    if C % (oh * ow):
        raise ValueError(
            f"psroi_pool needs channels ({C}) divisible by "
            f"output_size^2 ({oh}*{ow})")
    out_c = C // (oh * ow)

    def f(feat, bx, bn):
        feats = _gather_roi_images(feat, bx, bn)     # [K, C, H, W]
        K = bx.shape[0]
        H, W = feat.shape[2], feat.shape[3]
        S = 4
        # reference window: rounded starts, end + 1 before scaling
        x1 = jnp.round(bx[:, 0]) * spatial_scale
        y1 = jnp.round(bx[:, 1]) * spatial_scale
        x2 = jnp.round(bx[:, 2] + 1.0) * spatial_scale
        y2 = jnp.round(bx[:, 3] + 1.0) * spatial_scale
        bw = (x2 - x1) / ow
        bh = (y2 - y1) / oh
        jj = (jnp.arange(ow * S) + 0.5) / S
        ii = (jnp.arange(oh * S) + 0.5) / S
        gx = x1[:, None] + jj[None, :] * bw[:, None]         # [K, ow*S]
        gy = y1[:, None] + ii[None, :] * bh[:, None]
        xi = jnp.clip(gx.astype(jnp.int32), 0, W - 1)
        yi = jnp.clip(gy.astype(jnp.int32), 0, H - 1)
        # ONE gather of every sample, then the position-sensitive diagonal
        vals = jax.vmap(lambda fm, yy, xx: fm[:, yy[:, None], xx[None, :]])(
            feats, yi, xi)                                   # [K, C, ohS, owS]
        vals = vals.reshape(K, out_c, oh, ow, oh, S, ow, S)
        # put the four bin axes adjacent so the advanced-index diagonal
        # lands in place (separated advanced indices would jump to axis 0)
        vals = vals.transpose(0, 1, 5, 7, 2, 3, 4, 6)   # [K,outc,S,S,ohc,owc,ohs,ows]
        I = jnp.arange(oh)[:, None]
        J = jnp.arange(ow)[None, :]
        diag = vals[:, :, :, :, I, J, I, J]             # [K, outc, S, S, oh, ow]
        out = diag.mean(axis=(2, 3))
        # empty-bin mask (reference: floor(start) >= ceil(end) after image
        # clipping -> write 0)
        ys = jnp.clip(y1[:, None] + jnp.arange(oh)[None, :] * bh[:, None],
                      0, H)
        ye = jnp.clip(y1[:, None] + (jnp.arange(oh)[None, :] + 1)
                      * bh[:, None], 0, H)
        xs = jnp.clip(x1[:, None] + jnp.arange(ow)[None, :] * bw[:, None],
                      0, W)
        xe = jnp.clip(x1[:, None] + (jnp.arange(ow)[None, :] + 1)
                      * bw[:, None], 0, W)
        empty = (jnp.floor(ys)[:, :, None] >= jnp.ceil(ye)[:, :, None]
                 - 0) | (jnp.floor(xs)[:, None, :] >= jnp.ceil(xe)[:, None, :])
        empty = (jnp.floor(ys[:, :, None]) >= jnp.ceil(ye[:, :, None])) |                 (jnp.floor(xs[:, None, :]) >= jnp.ceil(xe[:, None, :]))
        return jnp.where(empty[:, None, :, :], 0.0, out)

    return apply_op(f, x, boxes, boxes_num, op_name="psroi_pool")


class PSRoIPool:
    """Layer form of psroi_pool (reference vision/ops.py PSRoIPool)."""

    def __init__(self, output_size, spatial_scale=1.0):
        self.output_size = output_size
        self.spatial_scale = spatial_scale

    def __call__(self, x, boxes, boxes_num):
        return psroi_pool(x, boxes, boxes_num, self.output_size,
                          self.spatial_scale)


from ..nn.conv import _ConvNd  # noqa: E402  (after the function surface)


class DeformConv2D(_ConvNd):
    """Layer form of deform_conv2d (reference vision/ops.py DeformConv2D):
    holds the filter/bias; offset (and optional v2 mask) are forward
    inputs produced by a sibling conv branch."""

    def __init__(self, in_channels, out_channels, kernel_size, stride=1,
                 padding=0, dilation=1, deformable_groups=1, groups=1,
                 weight_attr=None, bias_attr=None):
        super().__init__(in_channels, out_channels, kernel_size, 2, stride,
                         padding, dilation, groups, "zeros", weight_attr,
                         bias_attr, "NCHW")
        self._deformable_groups = deformable_groups

    def forward(self, x, offset, mask=None):
        return deform_conv2d(
            x, offset, self.weight, self.bias, self._stride, self._padding,
            self._dilation, self._deformable_groups, self._groups, mask)
