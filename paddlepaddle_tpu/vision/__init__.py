"""paddle.vision — models, transforms, datasets (reference: python/paddle/vision/)."""

from . import datasets, models, transforms  # noqa: F401


# image backend helpers (reference python/paddle/vision/image.py)
_image_backend = "pil"


def set_image_backend(backend):
    global _image_backend
    if backend not in ("pil", "cv2", "tensor"):
        raise ValueError(
            f"backend must be 'pil', 'cv2' or 'tensor', got {backend!r}")
    _image_backend = backend


def get_image_backend():
    return _image_backend


def image_load(path, backend=None):
    """Load an image with the selected backend (reference image_load)."""
    b = backend or _image_backend
    if b == "cv2":
        try:
            import cv2

            return cv2.imread(path)
        except ImportError as e:
            raise NotImplementedError("cv2 is not installed") from e
    from PIL import Image

    img = Image.open(path)
    if b == "tensor":
        import numpy as _np

        from ..core.dispatch import wrap
        import jax.numpy as _jnp

        return wrap(_jnp.asarray(_np.asarray(img)))
    return img

from . import ops  # noqa: F401
