"""Vision datasets (reference: python/paddle/vision/datasets/).

Zero-egress environment: MNIST/Cifar accept a local ``data_file`` path
instead of downloading; FakeData generates synthetic samples for smoke runs
(the role of the reference's tests' fake inputs).
"""

from __future__ import annotations

import gzip
import os
import pickle
import struct

import numpy as np

from ...io.dataset import Dataset


class FakeData(Dataset):
    """Synthetic image classification dataset."""

    def __init__(self, num_samples=100, image_shape=(3, 32, 32), num_classes=10,
                 transform=None, seed=0):
        self.num_samples = num_samples
        self.image_shape = tuple(image_shape)
        self.num_classes = num_classes
        self.transform = transform
        self._rng = np.random.default_rng(seed)
        self._images = self._rng.standard_normal(
            (num_samples,) + self.image_shape).astype(np.float32)
        self._labels = self._rng.integers(0, num_classes, (num_samples,)).astype(np.int64)

    def __getitem__(self, idx):
        img = self._images[idx]
        if self.transform is not None:
            img = self.transform(img)
        return img, self._labels[idx]

    def __len__(self):
        return self.num_samples


class MNIST(Dataset):
    """MNIST from local idx/gz files (image_path/label_path as the reference's
    data_file args; no download in this environment)."""

    def __init__(self, image_path=None, label_path=None, mode="train",
                 transform=None, download=False, backend=None):
        if download and (image_path is None or label_path is None):
            raise ValueError("downloads are disabled; pass image_path/label_path")
        self.transform = transform
        self.images, self.labels = self._load(image_path, label_path)

    @staticmethod
    def _load(image_path, label_path):
        opener = gzip.open if str(image_path).endswith(".gz") else open
        with opener(image_path, "rb") as f:
            magic, n, rows, cols = struct.unpack(">IIII", f.read(16))
            images = np.frombuffer(f.read(), np.uint8).reshape(n, rows, cols)
        opener = gzip.open if str(label_path).endswith(".gz") else open
        with opener(label_path, "rb") as f:
            struct.unpack(">II", f.read(8))
            labels = np.frombuffer(f.read(), np.uint8).astype(np.int64)
        return images, labels

    def __getitem__(self, idx):
        img = self.images[idx]
        if self.transform is not None:
            img = self.transform(img)
        return img, self.labels[idx]

    def __len__(self):
        return len(self.images)


class Cifar10(Dataset):
    """CIFAR-10 from a local python-pickle batch directory."""

    def __init__(self, data_file=None, mode="train", transform=None,
                 download=False, backend=None):
        if download and data_file is None:
            raise ValueError("downloads are disabled; pass data_file")
        self.transform = transform
        files = (["data_batch_%d" % i for i in range(1, 6)]
                 if mode == "train" else ["test_batch"])
        xs, ys = [], []
        for fn in files:
            with open(os.path.join(data_file, fn), "rb") as f:
                d = pickle.load(f, encoding="bytes")
            xs.append(np.asarray(d[b"data"]).reshape(-1, 3, 32, 32))
            ys.extend(d[b"labels"])
        self.images = np.concatenate(xs)
        self.labels = np.asarray(ys, np.int64)

    def __getitem__(self, idx):
        img = self.images[idx]
        if self.transform is not None:
            img = self.transform(img)
        return img, self.labels[idx]

    def __len__(self):
        return len(self.images)
