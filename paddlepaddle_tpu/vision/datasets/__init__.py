"""Vision datasets (reference: python/paddle/vision/datasets/).

Zero-egress environment: MNIST/Cifar accept a local ``data_file`` path
instead of downloading; FakeData generates synthetic samples for smoke runs
(the role of the reference's tests' fake inputs).
"""

from __future__ import annotations

import gzip
import os
import pickle
import struct

import numpy as np

from ...io.dataset import Dataset


class FakeData(Dataset):
    """Synthetic image classification dataset."""

    def __init__(self, num_samples=100, image_shape=(3, 32, 32), num_classes=10,
                 transform=None, seed=0):
        self.num_samples = num_samples
        self.image_shape = tuple(image_shape)
        self.num_classes = num_classes
        self.transform = transform
        self._rng = np.random.default_rng(seed)
        self._images = self._rng.standard_normal(
            (num_samples,) + self.image_shape).astype(np.float32)
        self._labels = self._rng.integers(0, num_classes, (num_samples,)).astype(np.int64)

    def __getitem__(self, idx):
        img = self._images[idx]
        if self.transform is not None:
            img = self.transform(img)
        return img, self._labels[idx]

    def __len__(self):
        return self.num_samples


class MNIST(Dataset):
    """MNIST from local idx/gz files (image_path/label_path as the reference's
    data_file args; no download in this environment)."""

    def __init__(self, image_path=None, label_path=None, mode="train",
                 transform=None, download=False, backend=None):
        if download and (image_path is None or label_path is None):
            raise ValueError("downloads are disabled; pass image_path/label_path")
        self.transform = transform
        self.images, self.labels = self._load(image_path, label_path)

    @staticmethod
    def _load(image_path, label_path):
        opener = gzip.open if str(image_path).endswith(".gz") else open
        with opener(image_path, "rb") as f:
            magic, n, rows, cols = struct.unpack(">IIII", f.read(16))
            images = np.frombuffer(f.read(), np.uint8).reshape(n, rows, cols)
        opener = gzip.open if str(label_path).endswith(".gz") else open
        with opener(label_path, "rb") as f:
            struct.unpack(">II", f.read(8))
            labels = np.frombuffer(f.read(), np.uint8).astype(np.int64)
        return images, labels

    def __getitem__(self, idx):
        img = self.images[idx]
        if self.transform is not None:
            img = self.transform(img)
        return img, self.labels[idx]

    def __len__(self):
        return len(self.images)


class Cifar10(Dataset):
    """CIFAR-10 from a local python-pickle batch directory."""

    def __init__(self, data_file=None, mode="train", transform=None,
                 download=False, backend=None):
        if download and data_file is None:
            raise ValueError("downloads are disabled; pass data_file")
        self.transform = transform
        files = (["data_batch_%d" % i for i in range(1, 6)]
                 if mode == "train" else ["test_batch"])
        xs, ys = [], []
        for fn in files:
            with open(os.path.join(data_file, fn), "rb") as f:
                d = pickle.load(f, encoding="bytes")
            xs.append(np.asarray(d[b"data"]).reshape(-1, 3, 32, 32))
            ys.extend(d[b"labels"])
        self.images = np.concatenate(xs)
        self.labels = np.asarray(ys, np.int64)

    def __getitem__(self, idx):
        img = self.images[idx]
        if self.transform is not None:
            img = self.transform(img)
        return img, self.labels[idx]

    def __len__(self):
        return len(self.images)


IMG_EXTENSIONS = (".jpg", ".jpeg", ".png", ".ppm", ".bmp", ".pgm", ".tif",
                  ".tiff", ".webp")


def _default_loader(path):
    try:
        from PIL import Image

        with Image.open(path) as img:
            return np.asarray(img.convert("RGB"))
    except ImportError:
        return np.fromfile(path, np.uint8)


class DatasetFolder(Dataset):
    """class-per-subdirectory image dataset (reference
    vision/datasets/folder.py DatasetFolder): samples are (path-loaded
    image, class index); classes are the sorted subdirectory names."""

    def __init__(self, root, loader=None, extensions=None, transform=None,
                 is_valid_file=None):
        self.root = root
        self.loader = loader or _default_loader
        self.transform = transform
        extensions = tuple(extensions or IMG_EXTENSIONS)
        classes = sorted(d for d in os.listdir(root)
                         if os.path.isdir(os.path.join(root, d)))
        if not classes:
            raise RuntimeError(f"Found 0 directories in {root}")
        self.classes = classes
        self.class_to_idx = {c: i for i, c in enumerate(classes)}
        valid = is_valid_file or (
            lambda p: p.lower().endswith(extensions))
        self.samples = []
        for c in classes:
            cdir = os.path.join(root, c)
            for base, _, fnames in sorted(os.walk(cdir)):
                for fn in sorted(fnames):
                    p = os.path.join(base, fn)
                    if valid(p):
                        self.samples.append((p, self.class_to_idx[c]))
        if not self.samples:
            raise RuntimeError(
                f"Found 0 files in subfolders of {root} with extensions "
                f"{','.join(extensions)}")

    def __getitem__(self, idx):
        path, target = self.samples[idx]
        img = self.loader(path)
        if self.transform is not None:
            img = self.transform(img)
        return img, target

    def __len__(self):
        return len(self.samples)


class ImageFolder(Dataset):
    """flat (unlabeled) image folder (reference folder.py ImageFolder):
    yields [image] per sample."""

    def __init__(self, root, loader=None, extensions=None, transform=None,
                 is_valid_file=None):
        self.root = root
        self.loader = loader or _default_loader
        self.transform = transform
        extensions = tuple(extensions or IMG_EXTENSIONS)
        valid = is_valid_file or (
            lambda p: p.lower().endswith(extensions))
        self.samples = []
        for base, _, fnames in sorted(os.walk(root)):
            for fn in sorted(fnames):
                p = os.path.join(base, fn)
                if valid(p):
                    self.samples.append(p)
        if not self.samples:
            raise RuntimeError(
                f"Found 0 files in {root} with extensions "
                f"{','.join(extensions)}")

    def __getitem__(self, idx):
        img = self.loader(self.samples[idx])
        if self.transform is not None:
            img = self.transform(img)
        return [img]

    def __len__(self):
        return len(self.samples)


class FashionMNIST(MNIST):
    """Same idx format as MNIST (reference vision/datasets/mnist.py
    FashionMNIST subclass) from local files."""


class Cifar100(Dataset):
    """CIFAR-100 from the local python-pickle directory (reference
    vision/datasets/cifar.py Cifar100: fine labels)."""

    def __init__(self, data_file=None, mode="train", transform=None,
                 download=False, backend=None):
        if data_file is None:
            raise ValueError("downloads are disabled; pass data_file "
                             "(the cifar-100-python directory)")
        self.transform = transform
        with open(os.path.join(data_file,
                               "train" if mode == "train" else "test"),
                  "rb") as f:
            d = pickle.load(f, encoding="bytes")
        self.images = np.asarray(d[b"data"]).reshape(-1, 3, 32, 32)
        self.labels = np.asarray(d[b"fine_labels"], np.int64)

    def __getitem__(self, idx):
        img = self.images[idx]
        if self.transform is not None:
            img = self.transform(img)
        return img, self.labels[idx]

    def __len__(self):
        return len(self.images)


class Flowers(Dataset):
    """Oxford 102 Flowers (reference vision/datasets/flowers.py) from
    local files: an image directory plus the official .mat label/setid
    files (scipy parses them)."""

    def __init__(self, data_file=None, label_file=None, setid_file=None,
                 mode="train", transform=None, download=False, backend=None):
        if data_file is None or label_file is None or setid_file is None:
            raise ValueError(
                "downloads are disabled; pass data_file (jpg dir), "
                "label_file (imagelabels.mat), setid_file (setid.mat)")
        import scipy.io

        self.transform = transform
        labels = scipy.io.loadmat(label_file)["labels"].ravel()
        setid = scipy.io.loadmat(setid_file)
        key = {"train": "trnid", "valid": "valid", "test": "tstid"}[mode]
        self.indexes = setid[key].ravel()
        self.data_file = data_file
        self.labels = labels

    def __getitem__(self, idx):
        flower_id = int(self.indexes[idx])
        img = _default_loader(
            os.path.join(self.data_file, f"image_{flower_id:05d}.jpg"))
        if self.transform is not None:
            img = self.transform(img)
        # labels are 1-based in the official .mat
        return img, np.int64(self.labels[flower_id - 1] - 1)

    def __len__(self):
        return len(self.indexes)


class VOC2012(Dataset):
    """Pascal VOC2012 segmentation pairs (reference
    vision/datasets/voc2012.py) from a local VOCdevkit/VOC2012 tree."""

    def __init__(self, data_file=None, mode="train", transform=None,
                 download=False, backend=None):
        if data_file is None:
            raise ValueError("downloads are disabled; pass data_file "
                             "(the VOCdevkit/VOC2012 directory)")
        self.transform = transform
        name = {"train": "train", "valid": "val", "test": "val",
                "val": "val"}[mode]
        lst = os.path.join(data_file, "ImageSets", "Segmentation",
                           f"{name}.txt")
        with open(lst) as f:
            ids = [line.strip() for line in f if line.strip()]
        self.pairs = [
            (os.path.join(data_file, "JPEGImages", f"{i}.jpg"),
             os.path.join(data_file, "SegmentationClass", f"{i}.png"))
            for i in ids]

    def __getitem__(self, idx):
        img = _default_loader(self.pairs[idx][0])
        label = _default_loader(self.pairs[idx][1])
        if self.transform is not None:
            img = self.transform(img)
        return img, label

    def __len__(self):
        return len(self.pairs)
