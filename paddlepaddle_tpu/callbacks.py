"""paddle.callbacks namespace (reference: python/paddle/callbacks.py is a
re-export of hapi.callbacks)."""

from .hapi.callbacks import (  # noqa: F401
    Callback,
    EarlyStopping,
    LRScheduler,
    ModelCheckpoint,
    ProgBarLogger,
    ReduceLROnPlateau,
    VisualDL,
    WandbCallback,
)
