"""paddle.profiler (reference: python/paddle/profiler/profiler.py:358 —
Profiler with scheduler/on_trace_ready, RecordEvent annotations,
chrome-tracing export; C++ host tracer + CUPTI device tracer).

TPU-native: jax.profiler is the device tracer (XPlane/TensorBoard +
Perfetto); host spans ride the framework-wide observability recorder
(observability/recorder.py) — ONE event pipeline, so ``RecordEvent``
regions, dispatch op spans and collective spans all land in the same ring
buffer, chrome-trace export, and ``observability.summary()`` table. Each
span also opens a ``jax.profiler.TraceAnnotation`` so it interleaves with
XLA device activity in TensorBoard/Perfetto. Summary statistics here are
the recorder's per-name aggregates for the "record_event" category.
"""

from __future__ import annotations

import os
import time
from typing import Callable, Iterable, Optional

import jax

from ..observability import get_recorder

_RECORD_EVENT_CAT = "record_event"


class ProfilerTarget:
    CPU = 0
    GPU = 1
    CUSTOM_DEVICE = 3
    TPU = 4


class ProfilerState:
    CLOSED = 0
    READY = 1
    RECORD = 2
    RECORD_AND_RETURN = 3


def make_scheduler(*, closed: int, ready: int, record: int, repeat: int = 0,
                   skip_first: int = 0) -> Callable[[int], int]:
    """Reference profiler.make_scheduler: step -> ProfilerState."""
    cycle = closed + ready + record

    def sched(step: int) -> int:
        if step < skip_first:
            return ProfilerState.CLOSED
        s = step - skip_first
        if repeat and s >= cycle * repeat:
            return ProfilerState.CLOSED
        pos = s % cycle
        if pos < closed:
            return ProfilerState.CLOSED
        if pos < closed + ready:
            return ProfilerState.READY
        if pos == cycle - 1:
            return ProfilerState.RECORD_AND_RETURN
        return ProfilerState.RECORD

    return sched


class RecordEvent:
    """Host annotation (reference: paddle/phi/api/profiler/event_tracing.h:32).

    A thin wrapper over the observability recorder's explicit span path:
    always records (no ``PADDLE_OBS_*`` flags needed), opens a
    ``jax.profiler.TraceAnnotation`` (device-timeline interleaving), and
    registers in the comm-task registry so a watchdog timeout names the
    active region (CommTaskManager-style attribution)."""

    def __init__(self, name: str, event_type=None):
        self.name = name
        self._t0 = None
        self._ann = None
        self._task = None

    def begin(self):
        from ..distributed import comm_task as _ct

        if self._t0 is not None:
            return
        self._task = _ct.begin_task(self.name, group="region")
        self._ann = jax.profiler.TraceAnnotation(self.name)
        self._ann.__enter__()
        self._t0 = time.perf_counter()

    def end(self):
        from ..distributed import comm_task as _ct

        if self._t0 is None:
            return
        # per-instance timing (not the recorder's thread-local stack):
        # begin/end are user-driven, so pairs may overlap without nesting
        # or span threads — record_complete handles both
        dur = time.perf_counter() - self._t0
        self._t0 = None
        if self._ann is not None:
            self._ann.__exit__(None, None, None)
            self._ann = None
        get_recorder().record_complete(self.name, _RECORD_EVENT_CAT, dur)
        if self._task is not None:
            _ct.end_task(self._task)
            self._task = None

    def __enter__(self):
        self.begin()
        return self

    def __exit__(self, *exc):
        self.end()
        return False


def export_chrome_tracing(dir_name: str, worker_name: Optional[str] = None):
    def handler(prof):
        os.makedirs(dir_name, exist_ok=True)
        prof.export(os.path.join(
            dir_name, f"{worker_name or 'host'}_trace.json"))

    return handler


class Profiler:
    def __init__(self, *, targets: Optional[Iterable] = None, scheduler=None,
                 on_trace_ready=None, timer_only: bool = False,
                 record_shapes: bool = False, profile_memory: bool = False,
                 with_flops: bool = False):
        self._scheduler = (make_scheduler(closed=0, ready=0, record=scheduler[1] - scheduler[0],
                                          skip_first=scheduler[0])
                           if isinstance(scheduler, (tuple, list)) else scheduler)
        self._on_trace_ready = on_trace_ready
        self._timer_only = timer_only
        self._step = 0
        self._dir = None
        self._active = False

    def start(self):
        self._dir = os.environ.get("PADDLE_PROFILER_LOGDIR", "/tmp/paddlepaddle_tpu_prof")
        if not self._timer_only:
            os.makedirs(self._dir, exist_ok=True)
            jax.profiler.start_trace(self._dir)
            self._active = True
        self._t0 = time.perf_counter()
        return self

    def stop(self):
        if self._active:
            jax.profiler.stop_trace()
            self._active = False
        if self._on_trace_ready is not None:
            self._on_trace_ready(self)
        return self

    def step(self, num_samples: Optional[int] = None):
        self._step += 1

    def step_info(self, unit=None):
        dt = time.perf_counter() - self._t0
        self._t0 = time.perf_counter()
        return f"step {self._step}: {dt * 1000:.2f} ms"

    def export(self, path: str, format: str = "json"):
        """Write the host span ring buffer as chrome trace-event JSON at
        ``path`` (device XPlane traces are already in the logdir from
        stop_trace; this adds the host timeline Perfetto can overlay)."""
        if path and format == "json":
            # export_chrome_tracing handlers pass the trace DIRECTORY —
            # drop the host timeline in a file alongside the device XPlanes
            if path.endswith(os.sep) or os.path.isdir(path):
                os.makedirs(path, exist_ok=True)
                path = os.path.join(path, "host_trace.json")
            return get_recorder().export_chrome_trace(path)
        return self._dir

    def summary(self, sorted_by=None, op_detail=True, thread_sep=False,
                time_unit="ms"):
        lines = [f"{'Event':<40}{'Calls':<8}{'Total(ms)':<12}{'Avg(ms)':<10}"]
        stats = get_recorder().stats(_RECORD_EVENT_CAT)
        for name, (cnt, total, _mn, _mx) in sorted(stats.items(),
                                                   key=lambda kv: -kv[1][1]):
            lines.append(f"{name:<40}{cnt:<8}{total * 1e3:<12.3f}{total / max(cnt, 1) * 1e3:<10.3f}")
        out = "\n".join(lines)
        print(out)
        return out

    def __enter__(self):
        return self.start()

    def __exit__(self, *exc):
        self.stop()
        return False


def load_profiler_result(path):
    raise NotImplementedError("load XPlane traces with TensorBoard")
