"""paddle.profiler (reference: python/paddle/profiler/profiler.py:358 —
Profiler with scheduler/on_trace_ready, RecordEvent annotations,
chrome-tracing export; C++ host tracer + CUPTI device tracer).

TPU-native: jax.profiler is the device tracer (XPlane/TensorBoard +
Perfetto); RecordEvent maps to jax.profiler.TraceAnnotation so host
annotations land in the same timeline. Summary statistics are host-side
wall-time aggregates per RecordEvent name.
"""

from __future__ import annotations

import os
import time
from collections import defaultdict
from typing import Callable, Iterable, Optional

import jax


class ProfilerTarget:
    CPU = 0
    GPU = 1
    CUSTOM_DEVICE = 3
    TPU = 4


class ProfilerState:
    CLOSED = 0
    READY = 1
    RECORD = 2
    RECORD_AND_RETURN = 3


def make_scheduler(*, closed: int, ready: int, record: int, repeat: int = 0,
                   skip_first: int = 0) -> Callable[[int], int]:
    """Reference profiler.make_scheduler: step -> ProfilerState."""
    cycle = closed + ready + record

    def sched(step: int) -> int:
        if step < skip_first:
            return ProfilerState.CLOSED
        s = step - skip_first
        if repeat and s >= cycle * repeat:
            return ProfilerState.CLOSED
        pos = s % cycle
        if pos < closed:
            return ProfilerState.CLOSED
        if pos < closed + ready:
            return ProfilerState.READY
        if pos == cycle - 1:
            return ProfilerState.RECORD_AND_RETURN
        return ProfilerState.RECORD

    return sched


_event_stats = defaultdict(lambda: [0, 0.0])  # name -> [count, total_s]


class RecordEvent:
    """Host annotation (reference: paddle/phi/api/profiler/event_tracing.h:32);
    shows up in the jax trace via TraceAnnotation and in summary()."""

    def __init__(self, name: str, event_type=None):
        self.name = name
        self._ann = None
        self._t0 = None

    def begin(self):
        from ..distributed import comm_task as _ct

        self._ann = jax.profiler.TraceAnnotation(self.name)
        self._ann.__enter__()
        self._t0 = time.perf_counter()
        # registered in the comm-task registry so a watchdog timeout names
        # the active region (CommTaskManager-style attribution)
        self._task = _ct.begin_task(self.name, group="region")

    def end(self):
        from ..distributed import comm_task as _ct

        if getattr(self, "_task", None) is not None:
            _ct.end_task(self._task)
            self._task = None
        if self._t0 is not None:
            stats = _event_stats[self.name]
            stats[0] += 1
            stats[1] += time.perf_counter() - self._t0
        if self._ann is not None:
            self._ann.__exit__(None, None, None)
            self._ann = None

    def __enter__(self):
        self.begin()
        return self

    def __exit__(self, *exc):
        self.end()
        return False


def export_chrome_tracing(dir_name: str, worker_name: Optional[str] = None):
    def handler(prof):
        prof.export(dir_name)

    return handler


class Profiler:
    def __init__(self, *, targets: Optional[Iterable] = None, scheduler=None,
                 on_trace_ready=None, timer_only: bool = False,
                 record_shapes: bool = False, profile_memory: bool = False,
                 with_flops: bool = False):
        self._scheduler = (make_scheduler(closed=0, ready=0, record=scheduler[1] - scheduler[0],
                                          skip_first=scheduler[0])
                           if isinstance(scheduler, (tuple, list)) else scheduler)
        self._on_trace_ready = on_trace_ready
        self._timer_only = timer_only
        self._step = 0
        self._dir = None
        self._active = False

    def start(self):
        self._dir = os.environ.get("PADDLE_PROFILER_LOGDIR", "/tmp/paddlepaddle_tpu_prof")
        if not self._timer_only:
            os.makedirs(self._dir, exist_ok=True)
            jax.profiler.start_trace(self._dir)
            self._active = True
        self._t0 = time.perf_counter()
        return self

    def stop(self):
        if self._active:
            jax.profiler.stop_trace()
            self._active = False
        if self._on_trace_ready is not None:
            self._on_trace_ready(self)
        return self

    def step(self, num_samples: Optional[int] = None):
        self._step += 1

    def step_info(self, unit=None):
        dt = time.perf_counter() - self._t0
        self._t0 = time.perf_counter()
        return f"step {self._step}: {dt * 1000:.2f} ms"

    def export(self, path: str, format: str = "json"):
        # device trace already written to self._dir by stop_trace
        return self._dir

    def summary(self, sorted_by=None, op_detail=True, thread_sep=False,
                time_unit="ms"):
        lines = [f"{'Event':<40}{'Calls':<8}{'Total(ms)':<12}{'Avg(ms)':<10}"]
        for name, (cnt, total) in sorted(_event_stats.items(),
                                         key=lambda kv: -kv[1][1]):
            lines.append(f"{name:<40}{cnt:<8}{total * 1e3:<12.3f}{total / max(cnt, 1) * 1e3:<10.3f}")
        out = "\n".join(lines)
        print(out)
        return out

    def __enter__(self):
        return self.start()

    def __exit__(self, *exc):
        self.stop()
        return False


def load_profiler_result(path):
    raise NotImplementedError("load XPlane traces with TensorBoard")
