"""paddle.regularizer (reference: python/paddle/regularizer.py): weight
decay config objects optimizers accept via ``weight_decay=``. The base
optimizer folds a float coefficient into the gradient (coupled L2) —
these classes carry the coefficient plus the L1/L2 flavor."""

from __future__ import annotations


class WeightDecayRegularizer:
    def __init__(self, coeff: float = 0.0):
        self._coeff = float(coeff)

    def __float__(self):
        return self._coeff

    def __repr__(self):
        return f"{type(self).__name__}(coeff={self._coeff})"


class L1Decay(WeightDecayRegularizer):
    """|w| penalty: grad += coeff * sign(w)."""


class L2Decay(WeightDecayRegularizer):
    """0.5*coeff*||w||^2 penalty: grad += coeff * w (the optimizer default)."""
