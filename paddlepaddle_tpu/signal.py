"""paddle.signal namespace (reference: python/paddle/signal.py — stft/istft
live both at paddle.signal.* and paddle.*)."""

from .ops.longtail import istft, stft  # noqa: F401

__all__ = ["stft", "istft"]
