"""``paddle.linalg`` namespace (reference: python/paddle/linalg.py)."""

from .ops.linalg import (  # noqa: F401
    cholesky,
    cholesky_solve,
    corrcoef,
    cov,
    det,
    eig,
    eigh,
    eigvals,
    eigvalsh,
    householder_product,
    inv,
    lstsq,
    lu,
    matmul,
    matrix_norm,
    matrix_power,
    matrix_rank,
    multi_dot,
    norm,
    pinv,
    qr,
    slogdet,
    solve,
    svd,
    svdvals,
    triangular_solve,
    vector_norm,
)

from .ops.linalg import (  # noqa: F401,E402
    fp8_fp8_half_gemm_fused,
    matrix_exp,
)
from .ops.longtail import cholesky_inverse, cond  # noqa: F401,E402

# names the reference linalg namespace shares with the top level
import paddlepaddle_tpu as _p  # noqa: E402

cross = _p.cross
vecdot = _p.vecdot
matrix_transpose = _p.matrix_transpose
pca_lowrank = _p.pca_lowrank
svd_lowrank = _p.svd_lowrank
lu_unpack = _p.lu_unpack
ormqr = _p.ormqr
del _p
