"""True multi-process fleet: socket-backed replica client + OS-process
supervisor.

Reference surface: the reference fleet executor runs replicas as real
processes by construction (paddle/fluid distributed serving); here the
same boundary lands on the seams PRs 3–16 left ready:

* :class:`RemoteReplicaClient` implements the exact 4-method
  :class:`~.router.ReplicaClient` surface (submit/health/drain/restart,
  plus start/stop/warmup/kill) over the C-API frame protocol against a
  :mod:`~.replica_main` process. Typed errors rehydrate through
  :func:`~.robustness.error_from_wire`, so the router's failover,
  breaker, and backoff semantics are byte-identical to in-process; a
  request journey (:mod:`~..observability.reqtrace`) rides the submit
  frame as ``{trace_id, req_id}`` and the replica's spans come back in
  the terminal frame, re-anchored onto the client's clock — one stitched
  waterfall across the process hop.
* :class:`ReplicaSupervisor` spawns/monitors/restarts the engine process
  from a bundle path: readiness via the ``REPLICA_READY`` line,
  crash-loop exponential backoff with jitter on unexpected exits
  (:func:`~..resilience.retry.compute_delay`), last-exit capture (code +
  final output lines) for the health block,
  ``paddle_replica_{spawns,crashes,crash_loop_backoffs}_total``
  counters. restart = SIGTERM → drain (PR 3 hook) → respawn; kill =
  SIGKILL — the chaos seam is a real process death.
* :class:`ProcessReplicaFactory` slots both into
  :class:`~.fleet.FleetController`'s versioned replica factory
  (``makes_clients`` marker), so autoscaling, canary deploys, and
  rolling restarts manage OS processes, each loading its serving bundle
  in a fresh interpreter — which deletes the in-process "Symbols not
  found" bundle caveat instead of documenting it.
"""

from __future__ import annotations

import json
import os
import signal
import socket
import struct
import subprocess
import sys
import tempfile
import threading
import time
import uuid
import zlib
from collections import deque
from typing import Callable, Dict, Optional, Sequence, Tuple

import numpy as np

from ..resilience.retry import RetryPolicy, call_with_retry, compute_delay
from .c_api_server import (
    _HB_INTERVAL_S,
    _MAGIC,
    _OP_DRAIN,
    _OP_HEALTH,
    _OP_RESTART,
    _OP_SUBMIT,
    _ST_CHUNK,
    _ST_CRC_FLAG,
    _ST_OK,
    _ST_TYPED,
    _Cursor,
    _pack_tensor,
    _unpack_tensor,
)
from .robustness import ReplicaStalledError, WireCorruptionError, \
    error_from_wire
from .robustness import safe_inc as _safe_inc
from .router import ReplicaClient
from .serving import _REQ_IDS, GenerationResult

__all__ = ["RemoteReplicaClient", "ReplicaSupervisor",
           "ProcessReplicaFactory"]

_KEEP = object()      # restart(): "keep the current bundle" sentinel


# ---------------------------------------------------------------------------
# wire plumbing
# ---------------------------------------------------------------------------

def _recv_exact(sock: socket.socket, n: int) -> bytes:
    buf = b""
    while len(buf) < n:
        chunk = sock.recv(n - len(buf))
        if not chunk:
            raise ConnectionError("replica closed the connection")
        buf += chunk
    return buf


def _recv_frame(sock: socket.socket) -> bytes:
    (length,) = struct.unpack("<Q", _recv_exact(sock, 8))
    return _recv_exact(sock, length)


def _send_frame(sock: socket.socket, payload: bytes) -> None:
    sock.sendall(struct.pack("<Q", len(payload)) + payload)


def _parse_reply(frame: bytes) -> Tuple[int, _Cursor]:
    c = _Cursor(frame)
    if c.take("I") != _MAGIC:
        raise ConnectionError("bad reply magic from replica")
    status = c.take("B")
    if status & _ST_CRC_FLAG:
        # CRC-armed frame (this stream asked for it): verify before ANY
        # byte of the payload is interpreted — corruption must surface as
        # a typed infra failure, never as wrong tokens
        want = c.take("I")
        rest = c.b[c.o:]
        if zlib.crc32(rest) != want:
            raise WireCorruptionError(
                f"frame payload failed CRC32 ({len(rest)} bytes, "
                f"status {status & 0x7F})")
        status &= 0x7F
    return status, c


def _json_body(c: _Cursor) -> dict:
    return json.loads(c.raw(c.take("I")).decode() or "{}")


def _stitch_journey(parent, wire: Optional[dict], replica: str) -> None:
    """Append the replica process's spans onto the client-side journey,
    re-anchored by the wall-clock offset between the two processes'
    journey births (perf_counter and wall clocks advance in lockstep on
    one host, so the wall delta IS the perf_counter delta)."""
    if parent is None or not wire:
        return
    try:
        delta = float(wire.get("t0_wall") or 0.0) - parent.t0_wall
        for s in wire.get("spans") or []:
            if len(parent.spans) >= parent.max_spans:
                parent.dropped += 1
                continue
            s2 = dict(s)
            s2["t"] = round(float(s.get("t", 0.0)) + delta, 6)
            s2.setdefault("replica", replica)
            parent.spans.append(s2)
        parent.dropped += int(wire.get("dropped") or 0)
    except Exception:
        pass        # observability must never break request delivery


# ---------------------------------------------------------------------------
# the socket-backed ReplicaClient
# ---------------------------------------------------------------------------

class RemoteReplicaClient(ReplicaClient):
    """The :class:`~.router.ReplicaClient` surface over a replica
    PROCESS (a subclass so the router's isinstance wrapping passes
    clients through; every method is overridden — there is no in-process
    engine). ``address`` is a UDS path (str) or a TCP port (int,
    loopback) — or pass ``supervisor=`` and the address (and the process
    behind it) is the supervisor's, re-resolved per connection so a
    respawned replica on a fresh ephemeral port is found again.

    Transport failures surface as ``ConnectionError``/``TimeoutError`` —
    untyped, which the router classifies as retryable infra failure:
    a dead process reads exactly like :meth:`ReplicaClient.kill` did
    in-process. Typed serving errors cross the wire as JSON and
    rehydrate into the same classes (same retryability, same
    ``retry_after_s`` hints).

    Wire hardening (all client-negotiated, legacy servers unaffected):

    * **stall watchdog** — the submit stream expects SOME frame (chunk,
      heartbeat, terminal) within ``heartbeat_timeout_s``; silence means
      the wire black-holed, and the typed retryable
      :class:`~.robustness.ReplicaStalledError` fails the request over
      in ~2 s instead of pinning it for ``read_timeout_s``.
    * **frame CRC** — ``crc=True`` (default) asks the server to CRC32
      its reply payloads; a mismatch raises the typed retryable
      :class:`~.robustness.WireCorruptionError` and abandons the
      connection.
    * **idempotent submit** — every submit carries a ``req_uid``; a
      resubmit of the same uid after an ambiguous failure replays the
      server's cached terminal instead of decoding twice.

    Set ``PADDLE_NETCHAOS`` and every connection routes through a
    :class:`~..resilience.netchaos.NetChaosProxy` injecting the spec'd
    faults — the deterministic chaos drill for all three paths."""

    supports_req_uid = True

    def __init__(self, address=None, name: str = "replica",
                 supervisor: Optional["ReplicaSupervisor"] = None,
                 connect_timeout_s: float = 5.0,
                 read_timeout_s: float = 30.0,
                 heartbeat_timeout_s: float = 2.0,
                 crc: bool = True,
                 connect_policy: Optional[RetryPolicy] = None):
        if address is None and supervisor is None:
            raise ValueError("RemoteReplicaClient needs address= or "
                             "supervisor=")
        self.name = name
        self.supervisor = supervisor
        self._address = address
        self.connect_timeout_s = float(connect_timeout_s)
        self.read_timeout_s = float(read_timeout_s)
        self.heartbeat_timeout_s = float(heartbeat_timeout_s)
        self.crc = bool(crc)
        self._nc_proxy = None       # None = not checked, False = disabled
        if min(self.heartbeat_timeout_s, self.read_timeout_s) \
                <= _HB_INTERVAL_S:
            # config cross-check: a watchdog at or below the server's
            # heartbeat interval reads EVERY long decode as a stall —
            # guaranteed spurious failovers and breaker evictions. Warn
            # loudly; do not silently "fix" the caller's number
            _safe_inc("paddle_replica_timeout_misconfig_total",
                      "clients built with stall/read timeouts at or "
                      "below the server heartbeat interval",
                      replica=name)
            sys.stderr.write(
                f"[remote-replica] {name}: heartbeat_timeout_s="
                f"{self.heartbeat_timeout_s:g}s / read_timeout_s="
                f"{self.read_timeout_s:g}s is at or below the server "
                f"heartbeat interval ({_HB_INTERVAL_S:g}s) — every "
                f"quiet-but-healthy decode will trip the stall watchdog "
                f"and cause spurious failovers\n")
        # bounded reconnect with jittered backoff for SUBMIT connects: a
        # replica mid-respawn (supervisor restart window) is a transient,
        # not a failover — health probes stay single-attempt so the
        # router's 0.25 s prober is never wedged behind a backoff sleep
        self.connect_policy = connect_policy or RetryPolicy(
            max_attempts=3, base_delay=0.05, max_delay=0.5, jitter=0.25)
        self.generation = 0
        self._killed = False

    # -- transport -----------------------------------------------------------
    def address(self):
        if self.supervisor is not None:
            return self.supervisor.address()
        return self._address

    def _netchaos(self):
        """PADDLE_NETCHAOS auto-wrap: lazily start ONE proxy per client
        targeting :meth:`address` (re-resolved per connection, so a
        supervisor respawn is chased through the proxy too). Disabled =
        one getenv on the first connect, then a cached False."""
        if self._nc_proxy is False:
            return None
        if self._nc_proxy is None:
            from ..resilience import netchaos as _nc

            spec = _nc.env_spec()
            if not spec:
                self._nc_proxy = False
                return None
            self._nc_proxy = _nc.NetChaosProxy(
                self.address, specs=spec,
                name=f"netchaos:{self.name}").start()
        return self._nc_proxy

    def _connect_once(self) -> socket.socket:
        proxy = self._netchaos()
        addr = proxy.address() if proxy is not None else self.address()
        if addr is None:
            raise ConnectionError(
                f"replica {self.name} has no address (process not ready)")
        if isinstance(addr, int):
            s = socket.create_connection(("127.0.0.1", addr),
                                         timeout=self.connect_timeout_s)
        else:
            s = socket.socket(socket.AF_UNIX, socket.SOCK_STREAM)
            s.settimeout(self.connect_timeout_s)
            try:
                s.connect(str(addr))
            except OSError:
                s.close()
                raise
        s.settimeout(self.read_timeout_s)
        return s

    def _connect(self, retry: bool = True) -> socket.socket:
        if not retry:
            return self._connect_once()
        return call_with_retry(self._connect_once,
                               policy=self.connect_policy,
                               name=f"replica_connect:{self.name}")

    def _rpc(self, payload: bytes, retry: bool = False) -> Tuple[int, _Cursor]:
        s = self._connect(retry=retry)
        try:
            _send_frame(s, payload)
            return _parse_reply(_recv_frame(s))
        finally:
            s.close()

    # -- ReplicaClient surface -----------------------------------------------
    def start(self) -> "RemoteReplicaClient":
        if self._killed:
            raise ConnectionError(f"replica {self.name} is dead")
        if self.supervisor is not None:
            self.supervisor.start()
        self.health()         # reachable or raise — start() must be honest
        return self

    def submit(self, prompt_ids, max_new_tokens: int = 32,
               temperature: float = 0.0, top_k: int = 0,
               eos_token_id=None, deadline_s: Optional[float] = None,
               prefix_len: Optional[int] = None,
               req_uid: Optional[str] = None,
               trace=None) -> GenerationResult:
        if self._killed:
            raise ConnectionError(f"replica {self.name} is dead")
        fut = GenerationResult()
        fut._req_id = next(_REQ_IDS)
        fut._trace = trace            # carried, never closed: the caller
        #   (router wrapper or direct user) owns the journey
        # mint a uid when the caller (router hedging passes its own)
        # didn't: an ambiguous terminal-frame loss must be resubmittable
        # without a second decode
        fut._req_uid = req_uid or uuid.uuid4().hex
        hdr = {"max_new_tokens": int(max_new_tokens),
               "temperature": float(temperature), "top_k": int(top_k),
               "eos_token_id": eos_token_id, "deadline_s": deadline_s,
               "prefix_len": prefix_len,
               "req_uid": fut._req_uid}
        if self.crc:
            hdr["crc"] = True
        if trace is not None:
            hdr["trace"] = {"trace_id": getattr(trace, "trace_id", None),
                            "req_id": getattr(trace, "req_id", None)}
        blob = json.dumps(hdr, default=str).encode()
        prompt = np.ascontiguousarray(
            np.asarray(prompt_ids, np.int32).reshape(-1))
        payload = (struct.pack("<IB", _MAGIC, _OP_SUBMIT)
                   + struct.pack("<I", len(blob)) + blob
                   + _pack_tensor("prompt", prompt))
        s = self._connect()
        try:
            _send_frame(s, payload)
            # the stream-progress watchdog starts NOW: the server's
            # accepted frame (and after it, at least a heartbeat every
            # _HB_INTERVAL_S) must land within heartbeat_timeout_s, or
            # the wire black-holed — fail over in ~2 s, not
            # read_timeout_s
            s.settimeout(self.heartbeat_timeout_s)
            status, c = _parse_reply(_recv_frame(s))
        except socket.timeout:
            s.close()
            raise self._stall_error()
        except Exception:
            s.close()
            raise
        if status == _ST_TYPED:
            # admission refusal: raise the SAME typed error the
            # in-process engine would have raised from submit()
            s.close()
            raise error_from_wire(_json_body(c))
        if status != _ST_CHUNK:
            s.close()
            raise ConnectionError(
                f"replica {self.name}: unexpected first frame "
                f"status {status}")
        # accepted: the stream is live — hand it to a reader thread
        t = threading.Thread(target=self._read_stream, args=(s, fut, trace),
                             daemon=True,
                             name=f"remote-replica-read:{self.name}")
        t.start()
        # a client cancel must reach the replica: closing the socket trips
        # the server's disconnect probe, which cancels the remote request
        # and releases its decode slot + KV pages
        fut._add_done_callback(
            lambda f, _s=s: (_close_quietly(_s) if f.cancelled() else None))
        return fut

    def _stall_error(self) -> ReplicaStalledError:
        _safe_inc("paddle_replica_stalls_total",
                  "stream-progress watchdog trips (no frame within "
                  "heartbeat_timeout_s)", replica=self.name)
        try:
            from ..observability import flight

            flight.record("stall", self.name,
                          timeout_s=self.heartbeat_timeout_s)
        except Exception:
            pass
        return ReplicaStalledError(
            f"replica {self.name}: no stream frame (chunk or heartbeat) "
            f"within {self.heartbeat_timeout_s:g}s — wire black-holed or "
            f"replica wedged", stalled_after_s=self.heartbeat_timeout_s)

    def _read_stream(self, s: socket.socket, fut: GenerationResult,
                     trace) -> None:
        try:
            while not fut.done():
                status, c = _parse_reply(_recv_frame(s))
                if status == _ST_CHUNK:
                    ev = _json_body(c)
                    kind = ev.get("ev")
                    if kind == "admit" and fut._t_admit is None:
                        fut._t_admit = time.perf_counter()
                    elif kind == "first" and fut._t_first is None:
                        fut._t_first = time.perf_counter()
                        fut._n_at_first = int(ev.get("n") or 1)
                        fut._n_new = max(fut._n_new, fut._n_at_first)
                    elif kind == "progress":
                        fut._n_new = int(ev.get("n") or fut._n_new)
                    continue
                if status == _ST_OK:
                    head = _json_body(c)
                    _, out = _unpack_tensor(c)
                    fut._n_new = int(head.get("n_new") or 0)
                    fut._n_at_first = int(head.get("n_at_first") or 1)
                    fut._streaming = bool(head.get("streaming", True))
                    if fut._t_admit is None \
                            and head.get("admit_rel") is not None:
                        fut._t_admit = (fut._t_submit
                                        + float(head["admit_rel"]))
                    if fut._t_first is None \
                            and head.get("first_rel") is not None:
                        # no first-token chunk arrived in time (a fast
                        # request finishing inside one poll tick): fall
                        # back to the replica-relative stamp so TTFT is
                        # the engine's, never fabricated-now
                        fut._t_first = (fut._t_submit
                                        + float(head["first_rel"]))
                    _stitch_journey(trace, head.get("journey"), self.name)
                    fut._set(output=out)
                    return
                if status == _ST_TYPED:
                    doc = _json_body(c)
                    _stitch_journey(trace, doc.get("journey"), self.name)
                    fut._set(error=error_from_wire(doc))
                    return
                fut._set(error=ConnectionError(
                    f"replica {self.name}: unexpected stream frame "
                    f"status {status}"))
                return
        except socket.timeout:
            # the watchdog tripped mid-stream: close the socket (the
            # server's disconnect probe then cancels the request and
            # releases its decode slot) and surface the typed stall
            fut._set(error=self._stall_error())
        except WireCorruptionError as e:
            _safe_inc("paddle_wire_corruption_total",
                      "reply frames abandoned on CRC32 mismatch",
                      replica=self.name)
            fut._set(error=e)
        except Exception as e:
            # SIGKILL mid-stream lands here: EOF/reset → an UNTYPED
            # connection error, which the router fails over — the exact
            # in-process kill() contract
            fut._set(error=ConnectionError(
                f"replica {self.name} connection lost mid-stream "
                f"({type(e).__name__}: {e})"))
        finally:
            _close_quietly(s)

    def health(self) -> Dict[str, object]:
        if self._killed:
            raise ConnectionError(f"replica {self.name} is dead")
        status, c = self._rpc(struct.pack("<IB", _MAGIC, _OP_HEALTH))
        if status != _ST_OK:
            raise ConnectionError(
                f"replica {self.name} health probe failed: "
                f"{c.raw(c.take('I')).decode(errors='replace')}")
        snap = _json_body(c)
        if self.supervisor is not None:
            snap["supervisor"] = self.supervisor.info()
        return snap

    def warmup(self) -> Dict[str, object]:
        """Remote replicas warm at boot (bundle load / --warmup inside
        :mod:`~.replica_main`) — the pre-admission warmup the router
        calls is a no-op, exactly the duck-typed contract
        :meth:`ReplicaClient.warmup` documents for remote forms."""
        if self._killed:
            raise ConnectionError(f"replica {self.name} is dead")
        return {"programs": 0, "compiled": 0, "remote": True}

    def drain(self, timeout: Optional[float] = None,
              reason: Optional[str] = None) -> Dict[str, object]:
        if self._killed:
            raise ConnectionError(f"replica {self.name} is dead")
        blob = json.dumps({"timeout": timeout,
                           "reason": reason or "drain"}).encode()
        status, c = self._rpc(struct.pack("<IB", _MAGIC, _OP_DRAIN)
                              + struct.pack("<I", len(blob)) + blob)
        doc = _json_body(c)
        if status == _ST_TYPED:
            raise error_from_wire(doc)
        if status != _ST_OK:
            raise ConnectionError(f"replica {self.name} drain failed")
        return doc

    def stop(self) -> None:
        # drain FIRST, tear the chaos proxy down LAST: the drain RPC goes
        # through _connect, which would lazily re-arm a fresh proxy from
        # the env after a premature stop (and leak its accept thread)
        try:
            if self.supervisor is not None:
                self.supervisor.stop()
            else:
                try:
                    self.drain(0.0, reason="stop")
                except Exception:
                    pass
        finally:
            if self._nc_proxy:
                self._nc_proxy.stop()
                self._nc_proxy = None

    def restart(self, drain_timeout: Optional[float] = None,
                factory: Optional[Callable] = None) -> None:
        """SIGTERM → drain (the replica's preemption hook) → respawn.
        ``factory`` keeps the deploy pipeline's version-switch seam: the
        fleet controller's factories carry a ``version`` attribute (the
        candidate/rollback bundle path), which becomes the respawned
        process's ``--bundle``. Without a supervisor this falls back to
        the wire ``_OP_RESTART`` (drain + in-place engine restart)."""
        bundle = getattr(factory, "version", _KEEP)
        if self.supervisor is not None:
            self.supervisor.restart(drain_timeout=drain_timeout,
                                    bundle=bundle)
        else:
            blob = json.dumps({"timeout": drain_timeout}).encode()
            status, c = self._rpc(struct.pack("<IB", _MAGIC, _OP_RESTART)
                                  + struct.pack("<I", len(blob)) + blob,
                                  retry=True)
            if status == _ST_TYPED:
                raise error_from_wire(_json_body(c))
            if status != _ST_OK:
                raise ConnectionError(
                    f"replica {self.name} restart failed")
        self.generation += 1
        self._killed = False

    def kill(self) -> None:
        """Chaos seam, now REAL: SIGKILL the replica process. In-flight
        streams see EOF and fail untyped (router failover); submits and
        probes refuse until :meth:`restart` respawns it."""
        self._killed = True
        if self.supervisor is not None:
            self.supervisor.kill()


def _close_quietly(s: socket.socket) -> None:
    try:
        s.close()
    except OSError:
        pass


# ---------------------------------------------------------------------------
# the process supervisor
# ---------------------------------------------------------------------------

class ReplicaSupervisor:
    """Owns ONE replica process: spawn from a bundle path, watch for
    readiness (``REPLICA_READY`` line) and for death, respawn crashed
    processes under exponential jittered crash-loop backoff, capture the
    last exit (code + final output lines) for the health block.

    ``auto_respawn`` (default on) covers UNEXPECTED exits only —
    deliberate :meth:`stop`/:meth:`restart`/:meth:`kill` set the
    expected flag first, so chaos kills stay dead until the router's
    recovery path restarts them, exactly like the in-process seam."""

    def __init__(self, bundle: Optional[str] = None,
                 socket_path: Optional[str] = None,
                 port: Optional[int] = None,
                 preset: str = "tiny",
                 model_json: Optional[str] = None,
                 engine_json: Optional[str] = None,
                 server_json: Optional[str] = None,
                 warmup: str = "auto",
                 metrics_port: Optional[int] = None,
                 allow_bundle_fallback: bool = False,
                 ready_timeout_s: float = 180.0,
                 term_grace_s: float = 10.0,
                 auto_respawn: bool = True,
                 max_respawns: int = 8,
                 backoff: Optional[RetryPolicy] = None,
                 name: str = "replica",
                 python: Optional[str] = None,
                 extra_args: Sequence[str] = (),
                 env: Optional[Dict[str, str]] = None):
        self.bundle = bundle
        if socket_path is None and port is None:
            # short, stable path: respawns keep the address (UDS paths
            # have a ~107-char limit — never derive from a test tmpdir)
            socket_path = os.path.join(
                tempfile.gettempdir(),
                f"pdr-{os.getpid()}-{id(self) & 0xFFFF:x}-{name}.sock")
        self.socket_path = socket_path
        self.port = port
        self.preset = preset
        self.model_json = model_json
        self.engine_json = engine_json
        self.server_json = server_json
        self.warmup = warmup
        self.metrics_port = metrics_port
        self.allow_bundle_fallback = bool(allow_bundle_fallback)
        self.ready_timeout_s = float(ready_timeout_s)
        self.term_grace_s = float(term_grace_s)
        self.auto_respawn = bool(auto_respawn)
        self.max_respawns = int(max_respawns)
        self.backoff = backoff or RetryPolicy(
            max_attempts=max(2, self.max_respawns), base_delay=0.25,
            max_delay=8.0, multiplier=2.0, jitter=0.25)
        self.name = name
        self.python = python or sys.executable
        self.extra_args = list(extra_args)
        self.env = env
        self._proc: Optional[subprocess.Popen] = None
        self._ready = threading.Event()
        self.ready_info: Dict[str, object] = {}
        self._ring: deque = deque(maxlen=40)   # last output lines
        self._lock = threading.RLock()
        self._expected_exit = False
        self._consecutive_crashes = 0
        self.state = "idle"
        self.stats = {"spawns": 0, "restarts": 0, "crashes": 0,
                      "crash_loop_backoffs": 0}
        self.last_exit: Optional[Dict[str, object]] = None

    # -- address / info ------------------------------------------------------
    def address(self):
        if self.socket_path is not None:
            return self.socket_path
        info = self.ready_info
        return info.get("port") if info else None

    def pid(self) -> Optional[int]:
        p = self._proc
        return p.pid if p is not None and p.poll() is None else None

    def info(self) -> Dict[str, object]:
        """The supervisor health block ``obsctl fleet``/``top`` render:
        pid, spawn/restart/crash counters, last exit (code + why)."""
        return {"pid": self.pid(), "state": self.state,
                "bundle": self.bundle, **self.stats,
                "last_exit": self.last_exit}

    # -- lifecycle -----------------------------------------------------------
    def _cmd(self):
        cmd = [self.python, "-m",
               "paddlepaddle_tpu.inference.replica_main",
               "--preset", self.preset, "--warmup", self.warmup]
        if self.socket_path is not None:
            cmd += ["--socket", self.socket_path]
        else:
            cmd += ["--port", str(self.port or 0)]
        if self.bundle:
            cmd += ["--bundle", str(self.bundle)]
        if self.allow_bundle_fallback:
            cmd += ["--allow-bundle-fallback"]
        if self.model_json:
            cmd += ["--model-json", self.model_json]
        if self.engine_json:
            cmd += ["--engine-json", self.engine_json]
        if self.server_json:
            cmd += ["--server-json", self.server_json]
        if self.metrics_port is not None:
            cmd += ["--metrics-port", str(self.metrics_port)]
        return cmd + self.extra_args

    def _spawn(self) -> None:
        # lock held by caller
        self._ready.clear()
        self.ready_info = {}
        self.state = "starting"
        env = dict(os.environ)
        env.setdefault("JAX_PLATFORMS", "cpu")
        if self.env:
            env.update(self.env)
        self._proc = subprocess.Popen(
            self._cmd(), stdout=subprocess.PIPE,
            stderr=subprocess.STDOUT, text=True, env=env,
            cwd=os.path.dirname(os.path.dirname(os.path.dirname(
                os.path.abspath(__file__)))))
        self.stats["spawns"] += 1
        _safe_inc("paddle_replica_spawns_total",
                  "replica processes spawned by the supervisor",
                  replica=self.name)
        threading.Thread(target=self._pump, args=(self._proc,),
                         daemon=True,
                         name=f"replica-pump:{self.name}").start()
        threading.Thread(target=self._watch, args=(self._proc,),
                         daemon=True,
                         name=f"replica-watch:{self.name}").start()

    def _pump(self, proc: subprocess.Popen) -> None:
        try:
            for line in proc.stdout:
                line = line.rstrip("\n")
                self._ring.append(line)
                if line.startswith("REPLICA_READY "):
                    try:
                        self.ready_info = json.loads(
                            line[len("REPLICA_READY "):])
                    except Exception:
                        self.ready_info = {}
                    if proc is self._proc:
                        self.state = "serving"
                        self._ready.set()
        except Exception:
            pass

    def _watch(self, proc: subprocess.Popen) -> None:
        code = proc.wait()
        with self._lock:
            if proc is not self._proc:
                return          # an old generation's watcher: stale
            tail = [ln for ln in list(self._ring)[-5:] if ln.strip()]
            self.last_exit = {"code": code, "wall": time.time(),
                              "reason": (tail[-1] if tail else None)}
            self._ready.clear()
            if self._expected_exit:
                if self.state != "dead":    # kill() already branded it
                    self.state = "stopped"
                return
            # UNEXPECTED death: a crash (or an external SIGKILL)
            self.stats["crashes"] += 1
            self._consecutive_crashes += 1
            _safe_inc("paddle_replica_crashes_total",
                      "replica processes that died unexpectedly",
                      replica=self.name)
            if not self.auto_respawn \
                    or self._consecutive_crashes > self.max_respawns:
                self.state = "dead"
                return
            self.state = "backoff"
            delay = compute_delay(self.backoff,
                                  min(self._consecutive_crashes, 8))
            self.stats["crash_loop_backoffs"] += 1
            _safe_inc("paddle_replica_crash_loop_backoffs_total",
                      "crash-loop backoff sleeps before a respawn",
                      replica=self.name)
            sys.stderr.write(
                f"[replica-supervisor] {self.name} exited {code} "
                f"unexpectedly (crash #{self._consecutive_crashes}); "
                f"respawn in {delay:.2f}s\n")
        # sleep OUTSIDE the lock — stop()/restart() must not block on a
        # backoff window
        time.sleep(delay)
        with self._lock:
            if proc is not self._proc or self._expected_exit:
                return
            self._spawn()

    def start(self) -> "ReplicaSupervisor":
        with self._lock:
            if self.pid() is not None:
                return self
            self._expected_exit = False
            self._consecutive_crashes = 0
            self._spawn()
        # poll-wait so a crash-looped-to-dead replica fails fast instead
        # of sitting out the whole ready timeout
        deadline = time.monotonic() + self.ready_timeout_s
        while not self._ready.wait(0.2):
            if self.state == "dead" or time.monotonic() > deadline:
                proc = self._proc
                code = proc.poll() if proc is not None else None
                tail = "; ".join(list(self._ring)[-3:])
                raise RuntimeError(
                    f"replica {self.name} never became ready "
                    f"(state={self.state}, exit={code}, "
                    f"last output: {tail!r})")
        # a replica that stays up resets the crash-loop streak: backoff
        # punishes LOOPS, not one transient failure a week apart
        with self._lock:
            self._consecutive_crashes = 0
        return self

    def _terminate(self, sig: int, wait_s: float) -> None:
        # lock held by caller
        proc = self._proc
        if proc is None or proc.poll() is not None:
            return
        try:
            proc.send_signal(sig)
        except (ProcessLookupError, OSError):
            return
        try:
            proc.wait(wait_s)
        except subprocess.TimeoutExpired:
            try:
                proc.kill()
                proc.wait(5.0)
            except (ProcessLookupError, OSError,
                    subprocess.TimeoutExpired):
                pass

    def stop(self, drain_timeout: Optional[float] = None) -> None:
        """Graceful: SIGTERM (the replica drains via its preemption hook
        and exits 143), escalate to SIGKILL past the grace window."""
        with self._lock:
            self._expected_exit = True
            grace = (drain_timeout if drain_timeout is not None
                     else self.term_grace_s) + 5.0
            self._terminate(signal.SIGTERM, grace)
            self.state = "stopped"

    def restart(self, drain_timeout: Optional[float] = None,
                bundle=_KEEP) -> "ReplicaSupervisor":
        """SIGTERM → wait → respawn (optionally onto a new bundle — the
        deploy pipeline's version switch)."""
        self.stop(drain_timeout)
        with self._lock:
            if bundle is not _KEEP:
                self.bundle = bundle
            self.stats["restarts"] += 1
        return self.start()

    def kill(self) -> None:
        """Chaos: SIGKILL, no drain, no respawn — a dead replica stays
        dead until something deliberately restarts it."""
        with self._lock:
            self._expected_exit = True
            self._terminate(signal.SIGKILL, 5.0)
            self.state = "dead"

    def __enter__(self):
        return self.start()

    def __exit__(self, *exc):
        self.stop()
        if self.socket_path and os.path.exists(self.socket_path):
            try:
                os.unlink(self.socket_path)
            except OSError:
                pass


# ---------------------------------------------------------------------------
# the fleet factory
# ---------------------------------------------------------------------------

class ProcessReplicaFactory:
    """Versioned replica factory producing :class:`RemoteReplicaClient`s
    (one supervised OS process each) — hand it to
    :class:`~.fleet.FleetController` and autoscaling/canary/rolling
    restarts manage processes. The ``makes_clients`` marker tells the
    controller the factory returns ready clients, not engines; the
    VERSION it is called with (a serving-bundle path, or None before any
    deploy) becomes the spawned process's ``--bundle``."""

    makes_clients = True

    def __init__(self, preset: str = "tiny",
                 engine_json: Optional[str] = None,
                 model_json: Optional[str] = None,
                 warmup: str = "auto",
                 default_bundle: Optional[str] = None,
                 supervisor_kw: Optional[dict] = None,
                 client_kw: Optional[dict] = None):
        self.preset = preset
        self.engine_json = engine_json
        self.model_json = model_json
        self.warmup = warmup
        self.default_bundle = default_bundle
        self.supervisor_kw = dict(supervisor_kw or {})
        self.client_kw = dict(client_kw or {})

    def __call__(self, version: Optional[str] = None,
                 name: str = "replica") -> RemoteReplicaClient:
        sup = ReplicaSupervisor(
            bundle=version or self.default_bundle, preset=self.preset,
            model_json=self.model_json, engine_json=self.engine_json,
            warmup=self.warmup, name=name, **self.supervisor_kw)
        return RemoteReplicaClient(supervisor=sup, name=name,
                                   **self.client_kw)
