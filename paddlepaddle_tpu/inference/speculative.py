"""Speculative decoding — fixed-k draft+verify programs that multiply
tokens per target weight-read.

Decode on the serving card is weight-bandwidth-bound (BASELINE.md pins
~254 MB of bf16 weight reads per token-step against a 650-700 GB/s
effective HBM roofline). Weight-only int8 (docs/quantization.md) halves
that traffic; speculative decoding (Leviathan et al. 2023; Chen et al.
2023) attacks the same roofline from the other side: a small DRAFT model
proposes ``k`` greedy tokens per slot, the TARGET model verifies all
``k+1`` positions in ONE batched forward, and every accepted token
amortizes the target's weight read. At acceptance rate ``a`` a target
step yields ``1 + a*k`` tokens instead of 1.

Static-shape JAX form, three fixed-shape programs per engine config — all
first-class :mod:`~.compile_plan` entries, so they ride warmup, the
persistent compile cache, AOT bundles, and the recompile watchdog's
planned-region exemptions exactly like the decode program:

* ``draft_admit_p<bucket>`` — prefill the prompt through the draft model
  into its slot-contiguous KV cache at admission (the draft always
  prefills the FULL prompt, even on a target prefix-cache hit — the
  draft keeps no prefix cache of its own).
* ``draft_k<K>`` — K greedy draft steps over all slots. The FIRST step
  feeds a fixed 2-token window ``[prev, tokens]`` at positions
  ``lens-1, lens``: after a fully-accepted round the draft cache is
  exactly one position behind the committed stream, and re-writing an
  already-written position produces identical K/V — so one static shape
  repairs every possible deficit.
* ``verify_k<K>`` — ONE target forward over the ``k+1`` tokens
  ``[tokens, d_1..d_k]`` at positions ``lens..lens+k`` (the model's
  ragged cached-attention path handles multi-token steps at per-slot
  positions natively), then accept/reject as masked ops in-graph:
  greedy acceptance ``d_{j+1} == argmax(logits_j)`` on the longest
  matching prefix, plus the target's own token at the first mismatch
  (the "bonus"/correction token) — token-EXACT vs the non-speculative
  engine by construction, for ANY draft model. Sampling-correctness
  (rejection resampling at temperature > 0) is a follow-up seam; the
  engine rejects non-greedy requests at admission.

KV ROLLBACK IS AN INDEX EDIT: the verify forward writes K/V for all
``k+1`` positions, but ``lens`` only advances by the tokens actually
emitted — rejected positions sit beyond the new length, masked out of
every later gather by the ragged causal mask, and are overwritten in
place when decode reaches them. Page-table indirection makes this free:
positions past the slot's reservation land in the null page, positions
past ``max_len`` are explicitly redirected there, and no page is copied
or moved to roll back. The draft cache rolls back the same way (its
writes are position-indexed by the shared ``lens``).

The draft model is itself servable weight-only int8 (``draft_quant``) —
the draft's weight reads are the speculation overhead, so halving them
compounds with the amortization. Draft facts (arch, quant, k) join the
compile-plan fingerprint: a bundle built with one draft can never be
silently served with another.
"""

from __future__ import annotations

import weakref
from typing import Dict, List, Optional

import jax
import jax.numpy as jnp
import numpy as np

from ..core import autograd as _ag
from ..core.dispatch import unwrap

__all__ = ["SpeculativeDecoder", "resolve_draft"]


def _model_forward(model, params, toks, caches, pos):
    """One forward of ``model`` (target or draft): toks [b, s] ->
    (logits [b, s, V], caches') — the draft-parameterized twin of
    ``BatchDecodeEngine._forward``."""
    with _ag.no_grad(), model.bind_state(params):
        hidden, new_caches = model.model(toks, caches=caches, pos=pos)
        if model.lm_head is None:
            logits = unwrap(hidden) @ unwrap(
                model.model.embed_tokens.weight).T
        else:
            logits = unwrap(model.lm_head(hidden))
    return logits, [(unwrap(k), unwrap(v)) for k, v in new_caches]


def resolve_draft(draft, target_cfg, max_len: int, spec_k: int):
    """Normalize the ``draft=`` argument into a live model.

    Accepts a ``LlamaConfig``-shaped config (a draft model is built from
    it, with ``max_position_embeddings`` widened to cover the engine's
    ``max_len + k`` rope positions) or a ready model instance (anything
    exposing ``.config``, ``.model(...)`` and ``.functional_state()``).
    Validates the two facts speculation cannot survive without: a shared
    vocabulary (proposals are target token ids) and rope tables long
    enough for every verify position."""
    import dataclasses

    if hasattr(draft, "functional_state") and hasattr(draft, "config"):
        model = draft
    elif hasattr(draft, "vocab_size"):
        from ..models import LlamaForCausalLM

        cfg = draft
        need = max_len + spec_k
        if cfg.max_position_embeddings < need:
            cfg = dataclasses.replace(cfg, max_position_embeddings=need)
        model = LlamaForCausalLM(cfg)
    else:
        raise ValueError(
            f"draft must be a model config or a LlamaForCausalLM-shaped "
            f"model, got {type(draft).__name__}")
    dcfg = model.config
    if dcfg.vocab_size != target_cfg.vocab_size:
        raise ValueError(
            f"draft vocab_size {dcfg.vocab_size} != target "
            f"{target_cfg.vocab_size} — speculative proposals are target "
            "token ids, the vocabularies must be identical")
    if dcfg.max_position_embeddings < max_len:
        raise ValueError(
            f"draft max_position_embeddings {dcfg.max_position_embeddings} "
            f"< engine max_len {max_len} — the draft must cover every "
            "position it proposes at")
    return model


class SpeculativeDecoder:
    """Draft-model state + the three program implementations, owned by a
    :class:`~.decode_engine.BatchDecodeEngine` with ``spec_k > 0``.

    Host-side accounting (``stats``/``runlen``) is engine-thread-only,
    updated once per spec chunk (never per token); ``info()`` is the
    ``health()["spec"]`` block and is safe to read from probe threads."""

    def __init__(self, engine, draft, spec_k: int,
                 draft_quant: Optional[str] = None):
        if spec_k < 1:
            raise ValueError(f"spec_k must be >= 1, got {spec_k}")
        if engine.kv_layout != "paged":
            raise ValueError(
                "speculative decoding requires kv_layout='paged' — the "
                "page-table indirection IS the KV rollback mechanism")
        self.engine_ref = weakref.ref(engine)
        self.k = int(spec_k)
        self.draft_model = resolve_draft(draft, engine.cfg, engine.L,
                                         self.k)
        dcfg = self.draft_model.config
        self.draft_cfg = dcfg
        self.draft_quant = draft_quant
        self.draft_params = self.draft_model.functional_state()
        self.draft_quant_meta: Dict[str, object] = {}
        if draft_quant is not None:
            from ..nn.quant import quantize_param_tree

            self.draft_params, self.draft_quant_meta = quantize_param_tree(
                self.draft_params, algo=draft_quant)
        if engine.plan is not None:
            # the draft is small by construction: replicate it (params and
            # KV) rather than teaching the sharding plan a second head
            # count — the target's ICI collectives are untouched
            self.draft_params = jax.tree_util.tree_map(
                engine.plan.replicate, self.draft_params)
        dtype = (jnp.bfloat16 if dcfg.dtype == "bfloat16" else jnp.float32)
        S, L = engine.S, engine.L
        kvh, hd = dcfg.num_key_value_heads, dcfg.head_dim
        # slot-contiguous draft KV: the draft is small, so the paged
        # layout's byte savings don't pay for a second page table
        self.draft_caches = [
            (engine._repl(jnp.zeros((S, L, kvh, hd), dtype)),
             engine._repl(jnp.zeros((S, L, kvh, hd), dtype)))
            for _ in range(dcfg.num_hidden_layers)]
        # token at position lens-1 of the committed stream (the draft
        # catch-up window's first element); engine.tokens is the second
        self.prev_tokens = engine._repl(jnp.zeros((S,), jnp.int32))
        self.stats = {"target_steps": 0, "proposed": 0, "accepted": 0,
                      "rollbacks": 0, "emitted": 0}
        self.runlen = [0] * (self.k + 1)   # accepted-run-length histogram
        try:
            from ..observability import flight

            ref = weakref.ref(self)

            def _spec_annotation():
                s = ref()
                return s.info() if s is not None else {"enabled": "released"}

            flight.annotate("serving_spec", _spec_annotation)
        except Exception:
            pass

    # -- facts ---------------------------------------------------------------
    def facts(self) -> Dict[str, object]:
        """The compile-plan fingerprint's spec block: everything that makes
        draft/verify programs exchangeable. A draft-model swap (arch OR
        quant) changes the fingerprint, so a stale bundle falls back
        loudly instead of serving another draft's executables."""
        dcfg = self.draft_cfg
        arch = {k: v for k, v in sorted(vars(dcfg).items())
                if isinstance(v, (int, float, str, bool, type(None)))}
        return {"k": self.k, "draft_model": arch,
                "draft_quant": self.draft_quant or "off"}

    def describe_draft(self) -> Dict[str, object]:
        dcfg = self.draft_cfg
        return {
            "hidden_size": dcfg.hidden_size,
            "num_hidden_layers": dcfg.num_hidden_layers,
            "num_attention_heads": dcfg.num_attention_heads,
            "vocab_size": dcfg.vocab_size,
            "params_m": round(dcfg.num_params() / 1e6, 2),
            "quant": self.draft_quant or "off",
        }

    def info(self) -> Dict[str, object]:
        """``health()["spec"]``: config + live acceptance."""
        st = self.stats
        steps = st["target_steps"]
        return {
            "enabled": True,
            "k": self.k,
            "draft": self.describe_draft(),
            "target_steps": steps,
            "proposed": st["proposed"],
            "accepted": st["accepted"],
            "rollbacks": st["rollbacks"],
            "acceptance_rate": (round(st["accepted"] / st["proposed"], 4)
                                if st["proposed"] else None),
            "tokens_per_target_step": (round(st["emitted"] / steps, 3)
                                       if steps else None),
            "accept_run_p50": self.runlen_pct(0.50),
            "accept_run_p99": self.runlen_pct(0.99),
        }

    def runlen_pct(self, q: float) -> Optional[int]:
        """Percentile of the accepted-run-length histogram (0..k)."""
        total = sum(self.runlen)
        if not total:
            return None
        target = q * (total - 1) + 1
        seen = 0
        for length, n in enumerate(self.runlen):
            seen += n
            if seen >= target:
                return length
        return self.k

    # -- program implementations --------------------------------------------
    def draft_admit_impl(self, dparams, dcaches, prev, ids, plen, slot):
        """Prefill ``ids[1, bucket]`` through the draft model and scatter
        the K/V prefix into draft-cache slot ``slot``; record the last
        prompt token as the slot's catch-up ``prev``. The logits are
        discarded — the target's admission already sampled the first
        token, and speculation must propose from the SAME stream."""
        dcfg = self.draft_cfg
        bucket = ids.shape[1]
        kvh, hd = dcfg.num_key_value_heads, dcfg.head_dim
        dtype = dcaches[0][0].dtype
        scratch = [(jnp.zeros((1, bucket, kvh, hd), dtype),
                    jnp.zeros((1, bucket, kvh, hd), dtype))
                   for _ in range(dcfg.num_hidden_layers)]
        _, scratch = _model_forward(self.draft_model, dparams, ids, scratch,
                                    jnp.int32(0))
        zero = jnp.int32(0)
        out = []
        for (kc, vc), (ks, vs) in zip(dcaches, scratch):
            kc = jax.lax.dynamic_update_slice(kc, ks, (slot, zero, zero,
                                                       zero))
            vc = jax.lax.dynamic_update_slice(vc, vs, (slot, zero, zero,
                                                       zero))
            out.append((kc, vc))
        prev = prev.at[slot].set(ids[0, plen - 1])
        return out, prev

    def draft_program(self, k: int):
        """K greedy draft proposals per slot: one 2-token catch-up step
        (``[prev, tokens]`` at ``lens-1, lens``) then ``k-1`` single-token
        steps via ``lax.scan``. Inactive slots' writes land inside their
        own retired cache rows (re-prefilled at the next admission) and
        their proposals are discarded by the verify emit mask."""
        model = self.draft_model

        def run(dparams, dcaches, prev, tokens, lens, active):
            toks0 = jnp.stack([prev, tokens], axis=1)          # [S, 2]
            logits, dcaches = _model_forward(
                model, dparams, toks0, dcaches,
                jnp.maximum(lens - 1, 0))
            cur = jnp.argmax(logits[:, 1].astype(jnp.float32),
                             axis=-1).astype(jnp.int32)

            def body(carry, i):
                caches, tok = carry
                lg, caches = _model_forward(model, dparams, tok[:, None],
                                            caches, lens + i)
                nxt = jnp.argmax(lg[:, 0].astype(jnp.float32),
                                 axis=-1).astype(jnp.int32)
                return (caches, nxt), nxt

            (dcaches, _), rest = jax.lax.scan(
                body, (dcaches, cur),
                jnp.arange(1, k, dtype=jnp.int32))
            props = jnp.concatenate([cur[:, None], rest.T], axis=1)
            return dcaches, props                              # [S, k]

        return run

    def verify_program(self, k: int):
        """ONE batched target forward over the ``k+1`` positions plus the
        greedy accept/reject as masked in-graph ops.

        Emission semantics are EXACTLY the sequential engine's: a token is
        emitted iff it extends the longest draft/target-greedy matching
        prefix (the bonus token always does), the per-slot budget has room,
        and no earlier token in this run was the slot's eos. ``lens``
        advances by the emitted count — that IS the KV rollback. Returns
        one packed [S, k+3] payload per step (k+1 emitted-token columns,
        -1 padded; the raw accepted-run length, -1 when the slot is
        inactive; the end-of-step active flag) so a chunk of steps syncs
        to the host as a single transfer."""

        def run(params, caches, page_table, lens, tokens, prev, active,
                budgets, eos_ids, proposals):
            eng = self.engine_ref()
            S = eng.S
            rows = jnp.arange(S, dtype=jnp.int32)
            # the k+1-position target forward IS the engine's paged decode
            # forward at W=k+1 — one implementation, so the verify path
            # can never diverge from single-token decode. This includes
            # kv_quant="int8": verify scatters quantized pages and
            # dequantizes in the same kernel (or reference) pass as W=1
            # decode, while the draft keeps its own full-precision
            # contiguous caches above — acceptance compares target
            # greedy tokens, so quantization error shows up as a lower
            # acceptance rate, never as a divergent committed stream
            toks = jnp.concatenate([tokens[:, None], proposals], axis=1)
            logits, caches = eng._forward_paged(
                params, toks, caches, page_table, lens)
            g = jnp.argmax(logits.astype(jnp.float32),
                           axis=-1).astype(jnp.int32)           # [S, k+1]
            match = (proposals == g[:, :k]).astype(jnp.int32)
            acc = jnp.cumprod(match, axis=1).astype(jnp.int32)
            # dtype pinned: under x64 an int32 sum promotes to int64 and
            # the carry would stop matching the compiled avals
            a = jnp.sum(acc, axis=1, dtype=jnp.int32)     # accepted 0..k
            bonus = g[rows, a]
            idx = jnp.arange(k + 1, dtype=jnp.int32)[None, :]
            prop_ext = jnp.concatenate(
                [proposals, jnp.zeros((S, 1), jnp.int32)], axis=1)
            cand = jnp.where(idx < a[:, None], prop_ext, bonus[:, None])
            eos_hit = ((eos_ids[:, None] >= 0)
                       & (cand == eos_ids[:, None])).astype(jnp.int32)
            prior_eos = jnp.cumsum(eos_hit, axis=1, dtype=jnp.int32) \
                - eos_hit
            emit = (active[:, None] & (idx <= a[:, None])
                    & (idx < budgets[:, None]) & (prior_eos == 0))
            m = jnp.sum(emit, axis=1, dtype=jnp.int32)    # [S] emitted
            emitted = jnp.where(emit, cand, -1)
            # committed stream tail: full[0] = the pre-step last token,
            # full[i+1] = cand_i — so the new last/second-to-last tokens
            # are plain gathers at m and m-1
            full = jnp.concatenate([tokens[:, None], cand], axis=1)
            m_pos = jnp.minimum(m, k + 1)
            tokens_new = jnp.where(m > 0, full[rows, m_pos], tokens)
            prev_new = jnp.where(m > 0,
                                 full[rows, jnp.maximum(m_pos - 1, 0)],
                                 prev)
            lens_new = lens + m
            budgets_new = budgets - m
            active_new = (active & (budgets_new > 0)
                          & ~((eos_ids >= 0) & (tokens_new == eos_ids)))
            a_report = jnp.where(active, a, -1)
            payload = jnp.concatenate(
                [emitted, a_report[:, None],
                 active_new[:, None].astype(jnp.int32)], axis=1)
            return (caches, lens_new, tokens_new, prev_new, active_new,
                    budgets_new, payload)

        return run

    # -- host-side accounting -------------------------------------------------
    def round_summary(self, acc_row: np.ndarray) -> Dict[str, int]:
        """One slot's spec-chunk attrs for its request journey
        (observability.reqtrace ``spec.round`` span): verify steps run
        this chunk and draft tokens proposed/accepted at this k — defined
        here, next to the payload format that produces ``acc_row``, so
        the trace schema can never drift from the verify program."""
        live = acc_row[acc_row >= 0]
        return {"k": self.k, "steps": int(live.size),
                "proposed": int(live.size) * self.k,
                "accepted": int(live.sum())}

    def record_chunk(self, acc_matrix: np.ndarray, emitted_count: int
                     ) -> None:
        """Fold one spec chunk's accepted-run lengths (``[S, steps]``, -1
        for inactive slot-steps) into stats + metrics — once per chunk,
        the same cold cadence as the engine's KV gauges."""
        from .robustness import safe_inc as _safe_inc

        live = acc_matrix[acc_matrix >= 0]
        if live.size == 0:
            return
        steps = int(live.size)
        accepted = int(live.sum())
        rollbacks = int((live < self.k).sum())
        st = self.stats
        st["target_steps"] += steps
        st["proposed"] += steps * self.k
        st["accepted"] += accepted
        st["rollbacks"] += rollbacks
        st["emitted"] += int(emitted_count)
        counts = np.bincount(live, minlength=self.k + 1)
        for length, n in enumerate(counts[: self.k + 1]):
            if n:
                self.runlen[length] += int(n)
                _safe_inc("paddle_serving_spec_accept_run_length_total",
                          "accepted-run-length histogram of speculative "
                          "verify steps, by run length", int(n),
                          len=str(length))
        _safe_inc("paddle_serving_spec_proposed_total",
                  "draft tokens proposed to the target verifier",
                  steps * self.k)
        _safe_inc("paddle_serving_spec_accepted_total",
                  "draft tokens accepted by the target verifier", accepted)
        if rollbacks:
            _safe_inc("paddle_serving_spec_rollbacks_total",
                      "verify steps that rejected at least one draft "
                      "token (KV rolled back by index rewind)", rollbacks)
