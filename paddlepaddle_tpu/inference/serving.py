"""Serving engine — request queue + batched KV-cache decode, wrapped in an
overload-and-failure protection layer.

Reference surface: the Predictor/predictor-pool deployment layer
(paddle/fluid/inference/api/paddle_inference_api.h:52,229 — config,
zero-copy handles, a pool of predictors serving concurrent callers) and the
serving-grade batched attention
(paddle/phi/kernels/fusion/gpu/block_multi_head_attention_kernel.cu via
python/paddle/incubate/nn/functional/block_multihead_attention.py).

TPU-native: one engine thread owns the chip; concurrent callers submit
GenerationRequests into a queue; futures deliver per-request results. Two
schedulers:

* ``mode="continuous"`` (default) — slot-based continuous batching over
  the BatchDecodeEngine (decode_engine.py): ragged prompt lengths, mixed
  sampling params and budgets share ONE compiled multi-step decode program
  with per-slot cache positions; finished slots retire and free slots admit
  queued requests mid-flight. KV lives in a PAGED pool by default
  (``kv_layout="paged"``): a device page table gathers each slot's
  logical cache, admission reserves pages for the request's REAL
  prompt+budget (not ``max_len``), and ``submit(prefix_len=…)`` shares
  page-aligned system-prompt prefixes across requests through a
  ref-counted prompt cache. The TPU-native equivalent of the reference's
  paged block_multi_head_attention serving path.
* ``mode="static"`` — groups compatible requests (same prompt-length
  bucket and sampling params) into one batched ``generate_cached`` call;
  simpler, kept for models without the cache-vector-position path.

Robustness layer (robustness.py), all opt-in except the circuit breaker:

* admission control — ``max_queue`` bounds the queue and sheds with a typed
  :class:`~.robustness.ServerOverloadedError` (queue depth + retry-after
  hint); ``max_queue_wait_s`` sheds on estimated wait; prompt/budget are
  validated against ``max_len`` at submit;
* deadlines & cancellation — per-request ``deadline_s`` sheds expired
  requests before they're decoded; ``GenerationResult.cancel()`` frees an
  in-flight slot so a departed client stops burning chip time;
* circuit breaker — N consecutive decode failures open it (submits fail
  fast, slots reset), half-open probe recovery, optional hung-decode
  watchdog (``decode_timeout_s``) that trips it;
* graceful drain — ``drain(timeout)`` stops admission, finishes in-flight
  slots, sheds the rest; ``install_preemption_hook()`` registers the drain
  with :mod:`~..resilience.preemption` so SIGTERM drains before exit 143;
* ``health()`` — readiness snapshot (queue depth, busy slots, breaker
  state, last-decode age), also served as the ``_OP_HEALTH`` frame by
  :class:`~.c_api_server.CApiServer`.

Chaos seams (resilience.chaos): ``serving.admit`` fires inside submit after
admission checks pass; ``serving.decode`` fires before each decode attempt,
so an armed fault storm exercises the breaker exactly like a sick model.
"""

from __future__ import annotations

import itertools
import queue
import sys
import threading
import time
import weakref
from collections import deque
from typing import Dict, List, Optional

import numpy as np

from ..core import flags as _flags
from ..resilience.chaos import chaos_point
from .kv_pool import pages_needed
from .robustness import (
    CircuitBreaker,
    CircuitOpenError,
    DeadlineExceededError,
    EngineDrainingError,
    KVCapacityError,
    QueueWaitEstimator,
    RequestCancelledError,
    RequestValidationError,
    ServerOverloadedError,
)
from .robustness import safe_inc as _rob_safe_inc
from .robustness import safe_set as _rob_safe_set

# observability hook: _obs_srv(event, value) with events "latency" (seconds
# submit-to-result for one completed request), "error"/"cancelled" (a request
# failed / was cancelled), "batch_size" (decode slots / requests active in
# the current batch), "queue_depth" (requests waiting, queue + deferred),
# "batch" (value "ok"|"error": one decode attempt's outcome).
# None when observability is off.
_obs_srv = None

_BREAKER_STATE_NUM = {"closed": 0, "half_open": 1, "open": 2}

# process-wide request ids: the join key across SLO metrics, trace spans
# (request#<id>) and flight-recorder lifecycle events
_REQ_IDS = itertools.count(1)


def _flight_record(kind: str, name: str, **data) -> None:
    """Request-lifecycle feed into the crash flight recorder; one global
    check when the black box is disarmed, never raises."""
    try:
        from ..observability import flight

        flight.record(kind, name, **data)
    except Exception:
        pass


# cold-path metric wrappers shared with decode_engine (robustness.py):
# always record, never raise, cost nothing on the serve path
_safe_inc = _rob_safe_inc
_safe_set = _rob_safe_set


def _goodput_account(kind: str, n: int) -> None:
    """Goodput-ledger attribution for the serving-layer waste paths the
    engine cannot see (a failed decode chunk's partial output, drain/stop
    abandonment, static-batch delivery). Never raises."""
    if n <= 0:
        return
    try:
        from ..observability import goodput

        goodput.account(kind, n)
    except Exception:
        pass


class GenerationResult:
    """Future for one request. Carries the request's lifecycle timestamps
    (submit -> admit -> first token -> finish), stamped by the engine, so
    TTFT / TPOT / queue-wait are measured per request — :meth:`slo`
    returns them, and completed requests feed the
    ``paddle_serving_{ttft,tpot,queue_wait,deadline_margin}_seconds``
    histograms plus a ``request#<id>`` span in the trace."""

    def __init__(self):
        self._event = threading.Event()
        self._lock = threading.Lock()  # one-writer-wins arbitration: the
        #   router adds ROUTINE concurrent writers (client cancel() vs the
        #   winning replica's delivery) — check-then-act alone could tear
        #   the outcome (error=None AND output=None observed by a waiter)
        self._output = None
        self._error: Optional[BaseException] = None
        self._cancelled = False
        self._cancel_kind = "cancel"   # goodput kind a cancel wastes as
        self._callbacks: List = []     # run once, after the outcome is set
        self._obs_emit = True          # False: a wrapper future (router)
        #           whose replica-side inner future already feeds the SLO
        #           histograms + flight ring — one request, one record
        self._t_submit = time.perf_counter()
        self._t_admit: Optional[float] = None     # decode-slot admission
        self._t_first: Optional[float] = None     # first token on host
        self._t_done: Optional[float] = None
        self._n_new = 0                           # tokens generated
        self._n_at_first = 1     # tokens already delivered at _t_first: 1
        #   on the one-token-per-step path (bit-identical TPOT), stamped
        #   higher by multi-token (speculative) engines whose first host
        #   sync lands a burst — TPOT must divide by tokens that arrived
        #   AFTER _t_first, not assume one token per decode chunk
        self._req_id: Optional[int] = None
        self._deadline: Optional[float] = None    # absolute monotonic
        self._streaming = True                    # False: tokens arrive as
        #                       one batch (static mode) — TPOT meaningless
        self._trace = None       # reqtrace Journey riding this request
        self._trace_owner = False  # True on the future whose _set closes
        #   the journey (the router wrapper, or an engine-direct future);
        #   replica-side inner futures carry the journey but never close it
        self._t_dispatch: Optional[float] = None  # winning attempt's own
        #   submit time (router failover): queue wait is measured per
        #   attempt, not from the first submit across every retry

    def done(self) -> bool:
        return self._event.is_set()

    def cancelled(self) -> bool:
        return self._cancelled

    def cancel(self, reason: str = "cancel") -> bool:
        """Cancel the request: the future fails with
        :class:`RequestCancelledError` immediately, a queued request is
        dropped at pop time, and an in-flight decode slot is released on
        the next scheduler cycle (the chip stops spending on it). Returns
        True if the request had not already finished. ``reason`` names
        the goodput kind the abandoned tokens are attributed to (the
        router passes ``"hedge_loser"`` when reaping a hedge's loser);
        it rides the future because the slot sweep that releases the
        decode slot runs later, on the engine thread."""
        self._cancelled = True
        self._cancel_kind = reason
        if self._event.is_set():
            return False
        self._set(error=RequestCancelledError("request cancelled by client"))
        return True

    def result(self, timeout: Optional[float] = None) -> np.ndarray:
        if not self._event.wait(timeout):
            raise TimeoutError("generation did not finish in time")
        if self._error is not None:
            raise self._error
        return self._output

    def _add_done_callback(self, fn) -> None:
        """Run ``fn(self)`` exactly once when the outcome lands (now, if it
        already has). The router's failover path hangs off this — a failed
        replica future re-dispatches without a waiter thread per request.
        Callbacks run on whichever thread sets the outcome (usually the
        engine loop), must not block, and never raise into the engine."""
        self._callbacks.append(fn)
        if self._event.is_set():
            self._drain_callbacks()

    def _drain_callbacks(self) -> None:
        # pop-one-at-a-time: a concurrent _set/_add_done_callback race may
        # drain in parallel, but each callback is popped (and so run) once
        while True:
            try:
                fn = self._callbacks.pop(0)
            except IndexError:
                return
            try:
                fn(self)
            except Exception:
                pass

    def slo(self) -> Dict[str, object]:
        """Per-request SLO numbers (None where the lifecycle point was
        never reached — e.g. a shed request has no TTFT). TPOT is the
        per-output-token average after the first token; in static serving
        mode there is no streaming, so TTFT equals full latency."""
        end = self._t_done
        t_first = self._t_first
        # queue wait is PER ATTEMPT: after a router failover the winning
        # attempt's own submit time (_t_dispatch) is the base — measuring
        # from the first submit would book the failed attempt's decode and
        # the backoff as "queue wait". TTFT/latency stay client-relative.
        t_base = (self._t_dispatch if self._t_dispatch is not None
                  else self._t_submit)
        return {
            "req_id": self._req_id,
            "new_tokens": self._n_new,
            "queue_wait_s": (None if self._t_admit is None
                             else self._t_admit - t_base),
            "ttft_s": (None if t_first is None
                       else t_first - self._t_submit),
            "tpot_s": (None if (t_first is None or end is None
                                or self._n_new <= self._n_at_first
                                or not self._streaming)
                       else (end - t_first)
                       / (self._n_new - self._n_at_first)),
            "latency_s": None if end is None else end - self._t_submit,
        }

    def _set(self, output=None, error=None):
        with self._lock:
            if self._event.is_set():
                return  # first outcome wins: a late writer (a retiring
            #   slot racing stop(), a delivery racing cancel()) must not
            #   flip — or tear — a result
            self._output = output
            self._error = error
            self._t_done = now = time.perf_counter()
            self._event.set()
        obs = _obs_srv if self._obs_emit else None
        outcome = ("ok" if error is None
                   else "cancelled" if isinstance(error, RequestCancelledError)
                   else "error")
        if obs is not None:
            if error is None:
                obs("latency", now - self._t_submit)
                s = self.slo()
                obs("slo", {
                    "id": self._req_id,
                    "latency": s["latency_s"],
                    "ttft": s["ttft_s"],
                    "tpot": s["tpot_s"],
                    "queue_wait": s["queue_wait_s"],
                    "deadline_margin": (None if self._deadline is None
                                        else self._deadline
                                        - time.monotonic()),
                    "tokens": self._n_new,
                })
            elif isinstance(error, RequestCancelledError):
                obs("cancelled", 1)
            else:
                obs("error", 1)
        if self._obs_emit:
            _flight_record(
                "request", str(self._req_id or "?"), phase="finish",
                outcome=outcome, tokens=self._n_new,
                latency_ms=round((now - self._t_submit) * 1e3, 3),
                **({} if self._t_first is None else
                   {"ttft_ms": round((self._t_first - self._t_submit)
                                     * 1e3, 3)}))
        try:
            if (error is None and self._obs_emit
                    and (_flags.flag_value("slo_ttft_ms") > 0
                         or _flags.flag_value("slo_tpot_ms") > 0)):
                from ..observability import reqtrace as _rt

                s = self.slo()
                _rt.slo_observe(s["ttft_s"], s["tpot_s"])
            tr = self._trace
            if tr is not None and self._trace_owner:
                from ..observability import reqtrace as _rt

                _rt.finish_future(tr, self, outcome)
        except Exception:
            pass       # observability must never break request delivery
        self._drain_callbacks()


def slo_summary(results) -> Dict[str, Optional[float]]:
    """TTFT p50/p99, TPOT and queue-wait percentiles over completed
    :class:`GenerationResult` futures — per-request lifecycle timestamps,
    no metrics plane needed. The SLO block ``tools/serving_bench.py`` and
    ``tools/quant_ab.py`` print beside tokens/s, and the numbers the
    continuous-batching work (ROADMAP item 1) must not regress: aggregate
    throughput that costs 10x TTFT is not a win."""
    slos = [r.slo() for r in results]
    ttfts = sorted(s["ttft_s"] for s in slos if s["ttft_s"] is not None)
    tpots = sorted(s["tpot_s"] for s in slos if s["tpot_s"] is not None)
    waits = sorted(s["queue_wait_s"] for s in slos
                   if s["queue_wait_s"] is not None)

    def pct(vals, q):
        if not vals:
            return None
        return vals[min(len(vals) - 1, int(q * (len(vals) - 1) + 0.5))]

    def ms(v):
        return None if v is None else round(v * 1e3, 2)

    return {
        "ttft_p50_ms": ms(pct(ttfts, 0.50)),
        "ttft_p99_ms": ms(pct(ttfts, 0.99)),
        "tpot_ms": ms(pct(tpots, 0.50)),
        "tpot_p99_ms": ms(pct(tpots, 0.99)),
        "queue_wait_p50_ms": ms(pct(waits, 0.50)),
        "queue_wait_p99_ms": ms(pct(waits, 0.99)),
    }


class GenerationRequest:
    def __init__(self, prompt_ids, max_new_tokens, temperature, top_k,
                 eos_token_id, deadline: Optional[float] = None,
                 prefix_len: Optional[int] = None):
        arr = np.asarray(prompt_ids, np.int32)
        if arr.ndim == 2 and arr.shape[0] == 1:
            arr = arr[0]
        if arr.ndim != 1:
            raise ValueError(
                f"submit() takes ONE prompt (1-D ids or [1, L]); got shape "
                f"{arr.shape} — submit a batch as separate requests, the "
                "engine batches compatible ones itself")
        self.prompt_ids = arr.reshape(1, -1)
        self.max_new_tokens = int(max_new_tokens)
        self.temperature = float(temperature)
        self.top_k = int(top_k)
        self.eos_token_id = eos_token_id
        self.deadline = deadline            # absolute time.monotonic(), or None
        # leading prompt tokens forming a SHARED prefix (system prompt) —
        # the paged engine content-hashes its page-aligned head so N
        # requests with one system prompt pay one prefill plus N tails
        self.prefix_len = None if prefix_len is None else int(prefix_len)
        self.id = next(_REQ_IDS)
        self.result = GenerationResult()
        self.result._req_id = self.id
        self.result._deadline = deadline

    def batch_key(self):
        # static-shape batching: same prompt length and sampling config share
        # one compiled decode program
        return (self.prompt_ids.shape[1], self.temperature, self.top_k,
                self.eos_token_id)


def _flag_or(value, flag_name, off_value=0):
    """Constructor default plumbing: explicit argument wins, else the
    FLAGS_serving_* flag. The "off" sentinel (0 / 0.0) maps to None from
    BOTH sources — an explicit ``max_queue=0`` means unbounded exactly like
    the flag's documented default, not a queue that sheds everything."""
    if value is None:
        value = _flags.flag_value(flag_name)
    return None if value == off_value else value


class ServingEngine:
    """Batched generation server over a model exposing ``generate_cached``."""

    def __init__(self, model, max_batch_size: int = 8,
                 max_wait_ms: float = 5.0, mode: str = "continuous",
                 max_len: Optional[int] = None, decode_chunk: int = 16,
                 max_queue: Optional[int] = None,
                 max_queue_wait_s: Optional[float] = None,
                 default_deadline_s: Optional[float] = None,
                 breaker_threshold: Optional[int] = None,
                 breaker_reset_s: Optional[float] = None,
                 decode_timeout_s: Optional[float] = None,
                 drain_timeout_s: Optional[float] = None,
                 drain_on_sigterm: bool = False,
                 quant: Optional[str] = None,
                 quant_group_size: int = -1,
                 kv_layout: str = "paged",
                 kv_page_size: int = 64,
                 kv_num_pages: Optional[int] = None,
                 prefix_cache: bool = True,
                 mesh=None,
                 plan=None,
                 bundle: Optional[str] = None,
                 draft=None,
                 spec_k: int = 0,
                 draft_quant: Optional[str] = None,
                 fused_kernels: Optional[bool] = None,
                 kv_quant: Optional[str] = None,
                 kv_host_bytes: Optional[int] = None):
        if mode not in ("continuous", "static"):
            raise ValueError(f"mode must be 'continuous' or 'static', got {mode!r}")
        if (kv_quant not in (None, "", "off")
                or kv_host_bytes) and mode != "continuous":
            raise ValueError(
                "kv_quant/kv_host_bytes require the continuous engine — "
                "the paged KV pool (int8 pages, host spill tier) lives "
                "there; static mode decodes through generate_cached")
        if (draft is not None or spec_k) and mode != "continuous":
            raise ValueError(
                "speculative decoding (draft=/spec_k=) requires the "
                "continuous engine — static mode decodes through the "
                "model's own generate_cached")
        if bundle is not None and mode != "continuous":
            raise ValueError(
                "bundle= requires the continuous engine (static mode "
                "decodes through the model's own generate_cached; AOT "
                "bundles serialize the decode engine's compiled programs)")
        if quant is not None and mode != "continuous":
            raise ValueError(
                "quant mode requires the continuous engine (static mode "
                "decodes through the model's own generate_cached, whose "
                "bound params are full precision)")
        if (mesh is not None or plan is not None) and mode != "continuous":
            raise ValueError(
                "tensor-parallel serving (mesh=/plan=) requires the "
                "continuous engine — static mode decodes through the "
                "model's own generate_cached, whose bound params are "
                "single-chip")
        self.model = model
        self.mode = mode
        self.max_batch_size = max_batch_size
        self.max_wait = max_wait_ms / 1e3
        self._queue: "queue.Queue[GenerationRequest]" = queue.Queue()
        self._deferred: "deque[GenerationRequest]" = deque()  # FIFO, drained
        # ahead of the queue — a batch-incompatible request parks here and
        # becomes a later leader instead of rotating behind newer arrivals
        self._stop = threading.Event()
        self._draining = threading.Event()
        self._drained = threading.Event()
        self._drain_reason = "drain"   # metric label for drain-shed
        #   requests: "drain" unless the caller marked the drain
        #   deliberate ("scale_down", "sigterm", ...)
        self._thread: Optional[threading.Thread] = None
        self._watchdog_thread: Optional[threading.Thread] = None
        self._stats_lock = threading.Lock()
        self.stats = {"requests": 0, "batches": 0, "batched_requests": 0,
                      "decode_tokens": 0, "batches_failed": 0, "shed": 0,
                      "cancelled": 0, "deadline_expired": 0,
                      "decode_failures": 0}
        # robustness limits: explicit args win, else FLAGS_serving_* (whose
        # 0 default means "off"), so a fleet can arm them by env alone
        self.max_queue = _flag_or(max_queue, "serving_max_queue")
        self.max_queue_wait_s = _flag_or(max_queue_wait_s,
                                         "serving_max_queue_wait_s", 0.0)
        self.default_deadline_s = _flag_or(default_deadline_s,
                                           "serving_default_deadline_s", 0.0)
        self.decode_timeout_s = _flag_or(decode_timeout_s,
                                         "serving_decode_timeout_s", 0.0)
        self.drain_timeout_s = (drain_timeout_s if drain_timeout_s is not None
                                else _flags.flag_value("serving_drain_timeout_s"))
        self._breaker = CircuitBreaker(
            threshold=(breaker_threshold if breaker_threshold is not None
                       else _flags.flag_value("serving_breaker_threshold")),
            reset_s=(breaker_reset_s if breaker_reset_s is not None
                     else _flags.flag_value("serving_breaker_reset_s")),
            on_transition=self._on_breaker_transition)
        self._estimator = QueueWaitEstimator()
        self._static_inflight = 0     # static scheduler's current batch size
        self._decode_started_at: Optional[float] = None
        self._hang_tripped = False
        self._last_decode_ok: Optional[float] = None
        self._drain_on_sigterm = bool(drain_on_sigterm)
        self._limits_armed = (self.max_queue is not None
                              or self.max_queue_wait_s is not None)
        self._engine = None
        self.quant = quant
        if mode == "continuous":
            from .decode_engine import BatchDecodeEngine

            self._engine = BatchDecodeEngine(
                model, max_slots=max_batch_size, max_len=max_len,
                chunk=decode_chunk, quant=quant,
                quant_group_size=quant_group_size, kv_layout=kv_layout,
                page_size=kv_page_size, num_pages=kv_num_pages,
                prefix_cache=prefix_cache, mesh=mesh, plan=plan,
                bundle=bundle, draft=draft, spec_k=spec_k,
                draft_quant=draft_quant, fused_kernels=fused_kernels,
                kv_quant=kv_quant, kv_host_bytes=kv_host_bytes)
            self._spec_enabled = self._engine.spec is not None
            if self._spec_enabled:
                self._announce_spec()
            self._max_len = self._engine.L
            self._top_k_cap = self._engine.TOP_K_CAP
            # page-pool capacity admission facts (None = contiguous): a
            # request needing more pages than the pool HOLDS must be shed
            # at submit, not deadlock at the head of the queue
            self._kv_page_size = (self._engine.page_size
                                  if kv_layout == "paged" else None)
            self._kv_capacity = (self._engine.pool.usable
                                 if kv_layout == "paged" else None)
            try:
                from ..observability import flight

                # CALLABLE annotation (resolved at dump time): a crash
                # dump carries the pool occupancy / prefix-hit state at
                # the moment of death, not at construction. Weakly bound:
                # the module-global annotation dict must not pin a
                # dropped engine's device buffers (params + KV pools)
                # alive for the life of the process
                eng_ref = weakref.ref(self._engine)

                def _kv_annotation():
                    eng = eng_ref()
                    return (eng.kv_stats() if eng is not None
                            else {"layout": "engine-released"})

                flight.annotate("serving_kv", _kv_annotation)
            except Exception:
                pass
            if quant is not None:
                self._announce_quant(self._engine.quant_meta)
            if (self._engine.kv_quant is not None
                    or self._engine.kv_host is not None):
                self._announce_kv_memory()
        else:
            self._max_len = max_len or getattr(
                getattr(model, "config", None), "max_position_embeddings",
                None)
            self._top_k_cap = None
            self._kv_page_size = None
            self._kv_capacity = None
            self._spec_enabled = False

    def _bump(self, key, n=1):
        with self._stats_lock:
            self.stats[key] += n

    def _announce_quant(self, meta: Dict[str, object]) -> None:
        """One-time (construction, cold path) observability for quant mode:
        paddle_serving_quant_* metrics, the flight-recorder header
        annotation, and a stderr line. With quant off NONE of this runs —
        the off path stays zero-overhead (check_serving_overhead.py)."""
        _safe_set("paddle_serving_quant_enabled",
                  "serving weight-only quantization armed (1 = on)", 1,
                  mode=self.quant)
        _safe_set("paddle_serving_quant_weights",
                  "matmul weights quantized by the serving engine",
                  len(meta.get("quantized", ())))
        _safe_set("paddle_serving_quant_bytes_saved",
                  "HBM weight bytes a decode step no longer reads",
                  meta.get("bytes_saved", 0))
        try:
            from ..observability import flight

            flight.annotate("serving_quant", {
                "mode": self.quant,
                "group_size": meta.get("group_size", -1),
                "weights": len(meta.get("quantized", ())),
                "bytes_saved": meta.get("bytes_saved", 0)})
        except Exception:
            pass
        sys.stderr.write(
            f"[serving] weight-only quant armed: {self.quant}, "
            f"{len(meta.get('quantized', ()))} weights, "
            f"{meta.get('bytes_saved', 0) / 1e6:.1f} MB HBM reads saved "
            "per full weight pass\n")

    def _announce_kv_memory(self) -> None:
        """One-time (construction, cold path) observability for the KV
        memory levers (ROADMAP item 4): int8 KV pages and/or the host-RAM
        prefix tier. Off path runs none of this."""
        eng = self._engine
        parts = []
        if eng.kv_quant is not None:
            parts.append(f"kv_quant={eng.kv_quant}")
        if eng.kv_host is not None:
            _safe_set("paddle_serving_kv_host_budget_bytes",
                      "byte budget of the host-RAM prefix spill tier",
                      eng.kv_host.max_bytes)
            parts.append(
                f"host tier {eng.kv_host.max_bytes / 1e6:.1f} MB")
        sys.stderr.write(
            f"[serving] KV memory: {', '.join(parts)} "
            f"({eng.pool.usable} device pages x "
            f"{eng.kv_stats()['page_bytes']} B)\n")

    def _announce_spec(self) -> None:
        """One-time (construction, cold path) observability for
        speculative decoding: gauges + a stderr line. The flight-recorder
        ``serving_spec`` header annotation (draft arch, k, live
        acceptance at dump time) is installed by the decoder itself. With
        speculation off none of this runs — the off path stays
        zero-overhead."""
        spec = self._engine.spec
        draft = spec.describe_draft()
        _safe_set("paddle_serving_spec_enabled",
                  "speculative decoding armed (1 = on)", 1,
                  k=spec.k, draft_quant=spec.draft_quant or "off")
        _safe_set("paddle_serving_spec_k",
                  "draft proposals per speculative target step", spec.k)
        sys.stderr.write(
            f"[serving] speculative decoding armed: k={spec.k}, draft "
            f"{draft['params_m']}M params ({draft['hidden_size']}h x "
            f"{draft['num_hidden_layers']}L, quant {draft['quant']})\n")

    # -- admission control ---------------------------------------------------
    def _on_breaker_transition(self, old: str, new: str) -> None:
        sys.stderr.write(f"[serving] circuit breaker {old} -> {new}\n")
        _safe_inc("paddle_serving_breaker_transitions_total",
                  "serving circuit-breaker state transitions", to=new)
        _safe_set("paddle_serving_breaker_state",
                  "serving breaker state (0 closed, 1 half-open, 2 open)",
                  _BREAKER_STATE_NUM[new])
        try:
            from ..observability import flight

            flight.record("breaker", "serving",
                          **{"from": old, "to": new})
            if new == "open":
                # an opening breaker means the engine is sick; capture the
                # black box while the evidence (recent decode failures, the
                # engine thread's stack) is still in the ring. Deferred to
                # a thread: this callback runs UNDER the breaker lock, and
                # a dump fsync (possibly to network storage) must not
                # freeze every submit's allow() check behind it
                threading.Thread(
                    target=lambda: flight.dump("breaker_open"),
                    daemon=True, name="flight-breaker-dump").start()
        except Exception:
            pass

    def _shed(self, reason: str, exc: BaseException) -> None:
        self._bump("shed")
        _safe_inc("paddle_serving_shed_total",
                  "requests shed by serving admission control, by reason",
                  reason=reason)
        try:
            from ..observability import flight

            flight.record("shed", reason)
        except Exception:
            pass
        raise exc

    def _queue_depth(self) -> int:
        return self._queue.qsize() + len(self._deferred)

    def _check_admission(self, req: GenerationRequest) -> None:
        """Every reason a request may not enter the queue, cheapest first.
        With no limits configured this is a handful of attribute reads
        (breaker state is read lock-free while closed) —
        tools/check_serving_overhead.py holds that path under 5% vs seed."""
        if req.max_new_tokens < 1:
            raise RequestValidationError(
                f"max_new_tokens must be >= 1, got {req.max_new_tokens}")
        ml = self._max_len
        if ml is not None and req.prompt_ids.shape[1] + req.max_new_tokens > ml:
            raise RequestValidationError(
                f"prompt {req.prompt_ids.shape[1]} + {req.max_new_tokens} "
                f"new tokens exceeds engine max_len {ml} (model "
                f"max_position_embeddings caps the KV cache) — shorten the "
                "prompt or lower max_new_tokens")
        if self._top_k_cap is not None and req.top_k > self._top_k_cap:
            raise RequestValidationError(
                f"top_k {req.top_k} exceeds the continuous engine's static "
                f"filter cap {self._top_k_cap} (use the static "
                "serving mode or lower top_k)")
        if self._spec_enabled and req.temperature > 0.0:
            raise RequestValidationError(
                f"temperature {req.temperature:g} with speculative "
                "decoding armed: greedy acceptance is token-exact for "
                "temperature 0 only (sampling-correct rejection "
                "resampling is a planned seam) — send temperature=0 or "
                "serve this engine without spec_k")
        if req.prefix_len is not None and not (
                0 <= req.prefix_len <= req.prompt_ids.shape[1]):
            raise RequestValidationError(
                f"prefix_len {req.prefix_len} must be within the prompt "
                f"(length {req.prompt_ids.shape[1]})")
        if self._kv_capacity is not None:
            # page-pool capacity, not just max_len: a pool sized below
            # slots x max_len can be too small for a request that passes
            # the length check — shed it typed instead of queueing
            # forever. Total need governs even on a prefix hit (the
            # pinned prefix pages occupy capacity too), so this check is
            # EXACT — the engine's own raise can only fire for direct
            # BatchDecodeEngine users
            ps = self._kv_page_size
            need = pages_needed(
                req.prompt_ids.shape[1] + req.max_new_tokens, ps)
            if need > self._kv_capacity:
                self._shed("kv_capacity", KVCapacityError(
                    f"prompt {req.prompt_ids.shape[1]} + "
                    f"{req.max_new_tokens} new tokens needs {need} KV pages "
                    f"(page_size {ps}) but the pool holds only "
                    f"{self._kv_capacity} even when empty — raise "
                    "kv_num_pages or shorten the request",
                    pages_needed=need, pages_capacity=self._kv_capacity))
        if self._draining.is_set():
            self._shed("draining", EngineDrainingError(
                "serving engine is draining; no new requests admitted"))
        breaker = self._breaker
        if breaker._state != "closed" and not breaker.allow():
            self._shed("breaker_open", CircuitOpenError(
                f"decode circuit breaker is open after "
                f"{breaker.consecutive_failures} consecutive "
                "failures; submits fail fast until a half-open probe "
                "succeeds",
                retry_after_s=breaker.retry_after_s()))
        if req.deadline is not None and time.monotonic() >= req.deadline:
            self._bump("deadline_expired")
            self._shed("deadline", DeadlineExceededError(
                "request deadline expired before admission"))
        if self._limits_armed:
            depth = self._queue_depth()
            est = self._estimator.estimate_wait_s(depth, self.max_batch_size)
            if self.max_queue is not None and depth >= self.max_queue:
                self._shed("queue_full", ServerOverloadedError(
                    f"serving queue full ({depth} >= max_queue "
                    f"{self.max_queue})", queue_depth=depth,
                    retry_after_s=max(est, self.max_wait)))
            if (self.max_queue_wait_s is not None
                    and est > self.max_queue_wait_s):
                self._shed("queue_wait", ServerOverloadedError(
                    f"estimated queue wait {est:.2f}s exceeds "
                    f"max_queue_wait_s {self.max_queue_wait_s:g}",
                    queue_depth=depth, retry_after_s=est))
        chaos_point("serving.admit")

    # -- client API ----------------------------------------------------------
    def submit(self, prompt_ids, max_new_tokens=32, temperature=0.0,
               top_k=0, eos_token_id=None,
               deadline_s: Optional[float] = None,
               prefix_len: Optional[int] = None,
               trace=None) -> GenerationResult:
        """Queue one generation request; raises a typed
        :mod:`~.robustness` error instead of queueing when the request
        cannot (validation), or should not (overload, open breaker,
        draining, expired deadline), be served. ``prefix_len`` declares
        the leading shared prefix (system prompt) for the paged engine's
        prompt cache; ignored by the static scheduler and the contiguous
        layout. ``trace`` is a propagated request journey
        (:mod:`~..observability.reqtrace`) — the router passes its
        journey across the replica seam here; with none passed and
        tracing armed, the engine mints one (and this future owns it)."""
        dl = deadline_s if deadline_s is not None else self.default_deadline_s
        req = GenerationRequest(
            prompt_ids, max_new_tokens, temperature, top_k, eos_token_id,
            deadline=None if dl is None else time.monotonic() + dl,
            prefix_len=prefix_len)
        self._check_admission(req)
        tr = trace
        if tr is None:
            try:
                from ..observability import reqtrace as _rt

                if _rt.enabled():
                    tr = _rt.mint(req.id)
                    req.result._trace_owner = tr is not None
            except Exception:
                tr = None
        req.result._trace = tr
        if tr is not None:
            tr.event("engine.submit", prompt=req.prompt_ids.shape[1],
                     budget=req.max_new_tokens,
                     queue_depth=self._queue_depth())
        _flight_record("request", str(req.id), phase="submit",
                       prompt=req.prompt_ids.shape[1],
                       budget=req.max_new_tokens,
                       queue_depth=self._queue_depth())
        if self._thread is None:
            self.start()  # lazy start: a future must always have a server
        self._bump("requests")
        self._queue.put(req)
        if self._draining.is_set():
            # lost the race with a concurrent drain(): its shed sweep may
            # already have passed this request by, and a loop thread (re)
            # started above exits immediately while draining — fail the
            # future here so no caller blocks on a request no server owns
            t = self._thread
            if (t is None or not t.is_alive() or self._drained.is_set()) \
                    and not req.result.done():
                self._bump("shed")
                _safe_inc("paddle_serving_shed_total",
                          "requests shed by serving admission control, "
                          "by reason", reason="draining")
                req.result._set(error=EngineDrainingError(
                    "serving engine drained while the request was being "
                    "submitted"))
        return req.result

    def generate(self, prompt_ids, timeout: float = 300.0, **kw) -> np.ndarray:
        return self.submit(prompt_ids, **kw).result(timeout)

    # -- cold-start control --------------------------------------------------
    def warmup(self) -> Dict[str, object]:
        """Compile the engine's whole plan eagerly so the first request
        never lands on a cold program (the router pre-warms restarted
        replicas through this before re-admission). Static mode has no
        plan to walk — its programs belong to the model's own
        ``generate_cached`` — so it returns a no-op summary rather than
        raising: a fleet can warm heterogeneous replicas blindly."""
        if self._engine is None:
            return {"programs": 0, "compiled": 0, "skipped": 0,
                    "wall_s": 0.0, "mode": "static"}
        return self._engine.warmup()

    def save_serving_bundle(self, path: str) -> Dict[str, object]:
        """Serialize the decode engine's compiled programs + manifest to
        ``path`` — the artifact ``ServingEngine(..., bundle=path)`` then
        serves from with zero retraces (see docs/serving.md)."""
        if self._engine is None:
            raise ValueError(
                "save_serving_bundle requires the continuous engine")
        return self._engine.save_serving_bundle(path)

    def health(self) -> Dict[str, object]:
        """Readiness/liveness snapshot — what a probe endpoint (or the C
        protocol's ``_OP_HEALTH`` frame) reports."""
        now = time.monotonic()
        alive = self._thread is not None and self._thread.is_alive()
        state = ("draining" if self._draining.is_set() and alive
                 else "serving" if alive else "stopped")
        busy = self._engine.busy_slots() if self._engine is not None else 0
        started = self._decode_started_at
        breaker = self._breaker.state
        with self._stats_lock:
            stats = dict(self.stats)
        kv = (self._engine.kv_stats() if self._engine is not None
              else {"layout": "none"})
        mesh = (self._engine.mesh_info() if self._engine is not None
                else {"enabled": False})
        if self._engine is not None:
            compile_block = self._engine.compile_info()
        else:
            from ..core import compile_cache as _cc

            compile_block = {"cache": _cc.stats()}
        est = self._estimator.estimate_wait_s(self._queue_depth(),
                                              self.max_batch_size)
        try:
            from ..observability import reqtrace as _rt

            slo_burn = _rt.burn_snapshot()
        except Exception:
            slo_burn = {"enabled": False}
        try:
            from ..observability import goodput as _goodput

            goodput_block = _goodput.snapshot()
        except Exception:
            goodput_block = {"kinds": {}}
        return {
            "state": state,
            # useful-vs-wasted token ledger (observability.goodput): the
            # remote-fleet bench sums this across replica healths to get
            # fleet goodput_tok_s / waste_pct — a socket replica's ledger
            # lives in ITS process, not the router's
            "goodput": goodput_block,
            "mode": self.mode,
            # sliding-window SLO burn rate vs FLAGS_slo_{ttft,tpot}_ms —
            # the signal the SLO-driven autoscaler (ROADMAP item 5)
            # closes its scale-up/down loop on
            "slo_burn": slo_burn,
            "quant": self.quant or "off",
            "kv": kv,
            # speculative decoding: draft config, k, live acceptance rate
            # and tokens-per-target-step — what a deploy watches to know
            # the speculation is actually paying for its draft overhead
            "spec": (self._engine.spec_info() if self._engine is not None
                     else {"enabled": False}),
            # fused Pallas kernels (docs/kernels.md): which data-movement
            # kernels this engine decodes through — "off", "interpret"
            # (CPU), "compiled" (TPU) or "fallback: <reason>"
            "fused": (self._engine.fused_info() if self._engine is not None
                      else {"enabled": False}),
            # replica parallelism for the fleet router / /metrics: mesh
            # axes+devices and the tp degree this engine decodes at
            "mesh": mesh,
            # cold-start state: compile plan + warmup/bundle status +
            # persistent-cache counters — what a deploy watches to know a
            # restarted replica is warm before routing to it
            "compile": compile_block,
            "ok": alive and not self._draining.is_set()
                  and breaker != "open",
            "queue_depth": self._queue_depth(),
            "busy_slots": busy,
            # the fields the fleet router balances on, surfaced through
            # /healthz unchanged: estimated wait for a NEW request,
            # requests currently being decoded, KV headroom (None when
            # the engine has no paged pool)
            "est_wait_s": est,
            "inflight": busy if self.mode == "continuous"
                        else self._static_inflight,
            "pages_free": kv.get("pages_free"),
            "max_slots": self.max_batch_size,
            "max_queue": self.max_queue,
            "breaker": breaker,
            "breaker_consecutive_failures":
                self._breaker.consecutive_failures,
            "decode_inflight_s":
                0.0 if started is None else now - started,
            "last_decode_ok_age_s":
                None if self._last_decode_ok is None
                else now - self._last_decode_ok,
            "estimated_queue_wait_s": est,
            "stats": stats,
        }

    # -- lifecycle -----------------------------------------------------------
    def start(self):
        if self._thread is None:
            if self._draining.is_set():
                # restart after a COMPLETED drain (thread gone): re-open
                # admission and re-arm the failure machinery — the drained
                # engine's breaker history and hang latch belong to the
                # previous serving epoch, not this one. Rolling restarts
                # (inference/router.py) depend on this: drain -> start
                # must yield a replica that admits again.
                self._draining.clear()
                self._breaker.reset()
                self._hang_tripped = False
                self._decode_started_at = None
            self._stop.clear()
            self._drained.clear()
            self._thread = threading.Thread(target=self._loop, daemon=True)
            self._thread.start()
            if self.decode_timeout_s is not None \
                    and (self._watchdog_thread is None
                         or not self._watchdog_thread.is_alive()):
                self._watchdog_thread = threading.Thread(
                    target=self._watchdog_loop, daemon=True)
                self._watchdog_thread.start()
            if self._drain_on_sigterm:
                self.install_preemption_hook()
            # if this process runs a telemetry exporter, serve this
            # engine's readiness under /healthz (the HTTP analogue of the
            # C protocol's _OP_HEALTH frame)
            try:
                from ..observability import exporter as _exporter

                served = _exporter.get()
                if served is not None:
                    # unique: a second engine in this process must not
                    # clobber the first's provider entry
                    self._health_reg_name = served.register_health(
                        "serving", self.health, unique=True)
            except Exception:
                pass
        return self

    def install_preemption_hook(self, timeout: Optional[float] = None):
        """Register ``drain(timeout)`` as a preemption emergency callback:
        a SIGTERM'd serving host finishes in-flight requests (bounded by
        the drain timeout), sheds the rest with a typed error, and only
        then exits 143 — instead of futures dying mid-decode."""
        from ..resilience.preemption import install_preemption_handler

        return install_preemption_handler(
            lambda: self.drain(timeout, reason="sigterm"))

    def drain(self, timeout: Optional[float] = None,
              reason: str = "drain") -> Dict[str, object]:
        """Graceful shutdown: stop admission (submits raise
        :class:`EngineDrainingError`), let in-flight slots finish up to
        ``timeout`` seconds, shed everything still waiting with a typed
        error, then stop the engine thread. Idempotent. ``reason`` labels
        the shed/drain accounting — a DELIBERATE drain (the fleet
        controller's ``scale_down``, a preemption's ``sigterm``) must read
        as an operator action in the metrics, not as failure evidence."""
        timeout = self.drain_timeout_s if timeout is None else timeout
        t0 = time.monotonic()
        self._drain_reason = str(reason)
        self._draining.set()
        finished = True
        if self._thread is not None:
            finished = self._drained.wait(timeout)
        with self._stats_lock:
            shed_before = self.stats["shed"]
        try:
            self._shutdown(EngineDrainingError(
                "request shed: serving engine drained before it was served"))
        except RuntimeError:
            finished = False       # engine thread overran the stop join
        with self._stats_lock:
            shed = self.stats["shed"] - shed_before
        _safe_inc("paddle_serving_drains_total",
                  "graceful drains completed",
                  outcome="clean" if finished else "timeout",
                  reason=self._drain_reason)
        obs = _obs_srv
        if obs is not None:
            obs("queue_depth", 0)
        return {"clean": finished, "shed": shed,
                "wall_s": round(time.monotonic() - t0, 3)}

    def _shed_waiting(self, error: BaseException) -> int:
        """Fail everything queued or deferred (engine thread must be down
        or draining-idle; the deque is only touched by a live loop)."""
        n = 0
        while True:
            try:
                req = self._queue.get_nowait()
            except queue.Empty:
                break
            if not req.result.done():
                req.result._set(error=error)
                n += 1
        while self._deferred:
            req = self._deferred.popleft()
            if not req.result.done():
                req.result._set(error=error)
                n += 1
        if n:
            self._bump("shed", n)
            _safe_inc("paddle_serving_shed_total",
                      "requests shed by serving admission control, by reason",
                      n, reason=self._drain_reason if isinstance(
                          error, EngineDrainingError) else "stop")
        return n

    def stop(self):
        # deliberate stop: a later /healthz must not keep reporting this
        # engine (a stopped-on-purpose engine is not an unhealthy process)
        try:
            from ..observability import exporter as _exporter

            served = _exporter.get()
            if served is not None:
                # guarded: only drop OUR entry, never a sibling engine's
                served.unregister_health(
                    getattr(self, "_health_reg_name", "serving"),
                    fn=self.health)
        except Exception:
            pass
        self._shutdown(RuntimeError("serving engine stopped"))

    def _shutdown(self, shed_error: BaseException):
        self._stop.set()
        overran = False
        if self._thread is not None:
            self._thread.join(timeout=30)
            if self._thread.is_alive():
                # a mid-compile loop can overrun the join: keep the handle
                # so a later submit() cannot start a SECOND loop over the
                # same slot state; futures are still failed below so no
                # caller blocks, and we raise only after the cleanup
                overran = True
            else:
                self._thread = None
        if self._watchdog_thread is not None \
                and not self._watchdog_thread.is_alive():
            self._watchdog_thread = None
        # fail whatever is still queued or mid-decode: a caller must never
        # block on a future no server will serve
        self._shed_waiting(shed_error)
        if self._engine is not None:
            kind = ("drain" if isinstance(shed_error, EngineDrainingError)
                    else "stop")
            for i, s in enumerate(self._engine._host_slots):
                if s.req is not None and not s.req.result.done():
                    s.req.result._set(error=shed_error)
                    # mid-flight output abandoned by the shutdown
                    _goodput_account(kind, len(s.emitted))
                    self._engine._host_slots[i] = type(s)()
            self._engine.reset_slots()  # no phantom active device lanes
        if overran:
            raise RuntimeError(
                "serving engine thread did not stop within 30s (likely "
                "mid-compile); outstanding futures were failed; call "
                "stop() again to re-wait")

    def __enter__(self):
        return self.start()

    def __exit__(self, exc_type, exc, tb):
        try:
            self.stop()
        except RuntimeError:
            if exc_type is None:
                raise  # don't mask the with-body's original exception
        return False

    # -- scheduler -----------------------------------------------------------
    def _precheck(self, req: GenerationRequest) -> bool:
        """True when a popped request should be served; cancelled/expired
        ones are failed (shed) here, BEFORE they cost any decode."""
        if req.result._event.is_set():  # cancel() already failed the future
            self._bump("cancelled")
            _safe_inc("paddle_serving_cancelled_total",
                      "requests cancelled by clients")
            return False
        if req.deadline is not None and time.monotonic() >= req.deadline:
            self._bump("deadline_expired")
            _safe_inc("paddle_serving_shed_total",
                      "requests shed by serving admission control, by reason",
                      reason="deadline")
            req.result._set(error=DeadlineExceededError(
                "request deadline expired while queued"))
            return False
        return True

    def _next_request(self, block: bool,
                      timeout: float = 0.05) -> Optional[GenerationRequest]:
        """Pop the next serveable request: the deferred FIFO drains ahead
        of the queue (no reordering behind newer arrivals)."""
        while self._deferred:
            req = self._deferred.popleft()
            if self._precheck(req):
                return req
        while True:
            try:
                req = (self._queue.get(timeout=timeout) if block
                       else self._queue.get_nowait())
            except queue.Empty:
                return None
            if self._precheck(req):
                return req

    def _requeue_expired_sweep(self) -> None:
        """While the breaker is open nothing is popped for decode — sweep
        the waiting set so expired/cancelled requests still shed promptly.
        Queue entries migrate to the deferred FIFO (which drains first), so
        arrival order is preserved."""
        while True:
            try:
                self._deferred.append(self._queue.get_nowait())
            except queue.Empty:
                break
        kept = deque(r for r in self._deferred if self._precheck(r))
        self._deferred = kept

    def _collect_batch(self) -> List[GenerationRequest]:
        """One leader request + everything compatible, up to max_batch_size:
        first from the deferred FIFO, then whatever arrives within the
        batching window. Incompatible queue arrivals are parked in the
        deferred FIFO — drained ahead of the queue next cycle, so a
        mismatched request becomes the next leader instead of starving
        behind a stream of compatible newer ones."""
        leader = self._next_request(block=True, timeout=0.1)
        if leader is None:
            return []
        if self._breaker.state == "half_open":
            return [leader]     # one-request probe decides the breaker
        batch = [leader]
        keep: "deque[GenerationRequest]" = deque()
        while self._deferred and len(batch) < self.max_batch_size:
            req = self._deferred.popleft()
            if not self._precheck(req):
                continue
            if req.batch_key() == leader.batch_key():
                batch.append(req)
            else:
                keep.append(req)
        self._deferred.extendleft(reversed(keep))  # keep FIFO order
        deadline = time.monotonic() + self.max_wait
        while len(batch) < self.max_batch_size:
            rest = deadline - time.monotonic()
            if rest <= 0:
                break
            try:
                req = self._queue.get(timeout=rest)
            except queue.Empty:
                break
            if not self._precheck(req):
                continue
            if req.batch_key() == leader.batch_key():
                batch.append(req)
            else:
                self._deferred.append(req)  # FIFO-parked, next cycle's leader
        return batch

    def _watchdog_loop(self):
        """Engine-thread watchdog: a decode attempt that exceeds
        ``decode_timeout_s`` trips the breaker — the hung thread cannot be
        interrupted (it may be inside XLA), but new submits fail fast and
        health() goes not-ok instead of the queue silently growing."""
        interval = max(0.005, min(1.0, self.decode_timeout_s / 4))
        while not self._stop.wait(interval):
            started = self._decode_started_at
            if (started is not None and not self._hang_tripped
                    and time.monotonic() - started > self.decode_timeout_s):
                self._hang_tripped = True
                sys.stderr.write(
                    f"[serving] decode in flight for more than "
                    f"{self.decode_timeout_s:g}s — tripping breaker\n")
                _safe_inc("paddle_serving_decode_hangs_total",
                          "decode attempts the watchdog declared hung")
                self._breaker.trip()

    def _decode_attempt(self, fn) -> bool:
        """Run one decode attempt (a static batch or a continuous chunk)
        under the chaos seam, the hang watchdog and the breaker. Returns
        True on success; on failure the caller has already been handed the
        exception via ``fn``'s own cleanup contract."""
        self._hang_tripped = False
        self._decode_started_at = time.monotonic()
        try:
            from ..observability.recorder import trace_region

            region = trace_region("serving.decode_chunk", "serving")
        except Exception:
            region = None
        try:
            chaos_point("serving.decode")
            if region is not None:
                with region:
                    fn()
            else:
                fn()
        finally:
            dt = time.monotonic() - self._decode_started_at
            self._decode_started_at = None
        self._estimator.observe(dt)
        return True

    def _loop(self):
        try:
            if self.mode == "continuous":
                self._loop_continuous()
            else:
                self._loop_static()
        finally:
            self._drained.set()

    def _loop_static(self):
        obs = None
        while not self._stop.is_set():
            if self._draining.is_set():
                return   # current batch finished; drain() sheds the rest
            obs = _obs_srv
            if obs is not None:
                obs("queue_depth", self._queue_depth())
            if not self._breaker.allow():
                self._requeue_expired_sweep()
                time.sleep(0.02)
                continue
            batch = self._collect_batch()
            if not batch:
                continue
            self._static_inflight = len(batch)
            try:
                self._decode_attempt(lambda: self._run_static_batch(batch))
            except BaseException as e:  # noqa: BLE001 — deliver to callers
                for req in batch:
                    req.result._set(error=e)
                self._bump("batches_failed")
                self._bump("decode_failures")
                self._breaker.record_failure()
                if obs is not None:
                    obs("batch", "error")
                continue
            finally:
                self._static_inflight = 0
            # outcome-tagged accounting AFTER the attempt: a failed batch
            # must not count as served
            self._breaker.record_success()
            self._last_decode_ok = time.monotonic()
            self._bump("batches")
            self._bump("batched_requests", len(batch))
            if obs is not None:
                obs("batch_size", len(batch))
                obs("batch", "ok")

    def _run_static_batch(self, batch: List[GenerationRequest]) -> None:
        ids = np.concatenate([r.prompt_ids for r in batch], axis=0)
        leader = batch[0]
        t_admit = time.perf_counter()
        for req in batch:
            req.result._t_admit = t_admit
            tr = req.result._trace
            if tr is not None:
                tr.event("queue.wait", t0=req.result._t_submit, t1=t_admit)
                tr.event("admit", mode="static", batch=len(batch),
                         plen=leader.prompt_ids.shape[1])
        out = self.model.generate_cached(
            ids,
            max_new_tokens=max(r.max_new_tokens for r in batch),
            temperature=leader.temperature, top_k=leader.top_k,
            eos_token_id=leader.eos_token_id)
        out = np.asarray(out.numpy())
        t_first = time.perf_counter()  # no streaming in static mode: the
        plen = leader.prompt_ids.shape[1]  # first token lands with the batch
        lockstep = max(r.max_new_tokens for r in batch)
        useful = overshoot = 0
        for i, req in enumerate(batch):
            row = out[i, : plen + req.max_new_tokens]
            req.result._t_first = t_first     # TTFT == full latency here
            req.result._streaming = False     # ... and TPOT is undefined,
            # not "microseconds/token" — slo() reports it as None
            gen = row[plen:]
            eos = req.eos_token_id
            if eos is not None and eos in gen:  # don't count post-eos pad
                gen = gen[: int(np.argmax(gen == eos)) + 1]
            req.result._n_new = len(gen)
            # static batches decode max(max_new_tokens) for EVERY row in
            # lockstep: the post-eos / past-budget tail is real decode
            # work the caller never sees. Summed across the batch, two
            # ledger calls total — accounting must not tax the fast path
            useful += len(gen)
            overshoot += lockstep - len(gen)
            tr = req.result._trace
            if tr is not None:
                tr.event("decode.batch", t0=t_admit, t1=t_first,
                         tokens=len(gen))
            req.result._set(output=row)
        _goodput_account("useful", useful)
        _goodput_account("overshoot", overshoot)

    def _sweep_slots(self) -> None:
        """Release in-flight slots whose client departed (cancel) or whose
        deadline passed — the chip stops spending on them mid-decode."""
        eng = self._engine
        now = time.monotonic()
        for i, s in enumerate(eng._host_slots):
            req = s.req
            if req is None:
                continue
            if req.result.done():       # cancelled (first outcome won)
                eng.release_slot(i, reason=getattr(
                    req.result, "_cancel_kind", "cancel"))
                self._bump("cancelled")
                _safe_inc("paddle_serving_cancelled_total",
                          "requests cancelled by clients")
            elif req.deadline is not None and now >= req.deadline:
                req.result._set(error=DeadlineExceededError(
                    "request deadline expired mid-decode"))
                eng.release_slot(i, reason="deadline")
                self._bump("deadline_expired")
                _safe_inc("paddle_serving_shed_total",
                          "requests shed by serving admission control, "
                          "by reason", reason="deadline")

    def _loop_continuous(self):
        """Continuous batching: admit queued requests into free decode slots,
        run multi-step decode chunks, retire finished slots mid-flight. The
        BatchDecodeEngine delivers each request's future on retirement."""
        eng = self._engine
        while not self._stop.is_set():
            self._sweep_slots()
            busy = any(s.req is not None for s in eng._host_slots)
            draining = self._draining.is_set()
            if draining and not busy:
                return               # in-flight finished; drain() sheds rest
            admitted = False
            if not draining:
                if self._breaker.allow():
                    probe = self._breaker.state == "half_open"
                    while True:
                        req = self._next_request(block=not busy)
                        if req is None:
                            break
                        try:
                            if eng._admit(req):
                                admitted = True
                                busy = True
                                self._bump("batched_requests")
                                if probe:
                                    break   # one-request half-open probe
                            else:
                                # no free slot: hold at the FIFO head, decode
                                # to free one — never rotated behind arrivals
                                self._deferred.appendleft(req)
                                break
                        except BaseException as e:  # noqa: BLE001
                            req.result._set(error=e)
                elif not busy:
                    self._requeue_expired_sweep()
                    time.sleep(0.02)
                    continue
            obs = _obs_srv
            if obs is not None:
                obs("queue_depth", self._queue_depth())
            if not busy:
                continue
            if obs is not None:
                obs("batch_size",
                    sum(1 for s in eng._host_slots if s.req is not None))
            before = eng.stats["tokens_out"]
            try:
                self._decode_attempt(eng._decode_chunk)
            except BaseException as e:  # noqa: BLE001 — fail the slots
                for i, s in enumerate(eng._host_slots):
                    if s.req is not None:
                        s.req.result._set(error=e)
                        # partial output discarded with the failed chunk:
                        # wasted as retry_discard (the caller/router owns
                        # any retry; the tokens are gone either way)
                        _goodput_account("retry_discard", len(s.emitted))
                        eng._host_slots[i] = type(s)()
                eng.reset_slots()  # clear phantom device lanes too
                self._bump("batches_failed")
                self._bump("decode_failures")
                self._breaker.record_failure()
                if obs is not None:
                    obs("batch", "error")
                continue
            self._breaker.record_success()
            self._last_decode_ok = time.monotonic()
            self._bump("decode_tokens", eng.stats["tokens_out"] - before)
            if obs is not None:
                obs("batch", "ok")
            if admitted:
                self._bump("batches")
