"""Serving engine — request queue + dynamic batching over KV-cache decode.

Reference surface: the Predictor/predictor-pool deployment layer
(paddle/fluid/inference/api/paddle_inference_api.h:52,229 — config,
zero-copy handles, a pool of predictors serving concurrent callers).

TPU-native: one engine thread owns the chip; concurrent callers submit
GenerationRequests into a queue; the scheduler groups compatible requests
(same prompt length bucket and sampling params — XLA shapes are static) into
one batched ``generate_cached`` call, so B concurrent clients cost one
compiled decode program instead of B. Per-request results come back through
futures. This is iteration-batched serving one level below continuous
batching (slot-level admission needs per-slot cache positions — noted for a
later round); the reference ships no serving engine at all (deployment is
external FastDeploy), so this exceeds L11 parity.
"""

from __future__ import annotations

import queue
import threading
import time
from typing import List, Optional

import numpy as np


class GenerationResult:
    """Future for one request."""

    def __init__(self):
        self._event = threading.Event()
        self._output = None
        self._error: Optional[BaseException] = None

    def done(self) -> bool:
        return self._event.is_set()

    def result(self, timeout: Optional[float] = None) -> np.ndarray:
        if not self._event.wait(timeout):
            raise TimeoutError("generation did not finish in time")
        if self._error is not None:
            raise self._error
        return self._output

    def _set(self, output=None, error=None):
        self._output = output
        self._error = error
        self._event.set()


class GenerationRequest:
    def __init__(self, prompt_ids, max_new_tokens, temperature, top_k,
                 eos_token_id):
        arr = np.asarray(prompt_ids, np.int32)
        if arr.ndim == 2 and arr.shape[0] == 1:
            arr = arr[0]
        if arr.ndim != 1:
            raise ValueError(
                f"submit() takes ONE prompt (1-D ids or [1, L]); got shape "
                f"{arr.shape} — submit a batch as separate requests, the "
                "engine batches compatible ones itself")
        self.prompt_ids = arr.reshape(1, -1)
        self.max_new_tokens = int(max_new_tokens)
        self.temperature = float(temperature)
        self.top_k = int(top_k)
        self.eos_token_id = eos_token_id
        self.result = GenerationResult()

    def batch_key(self):
        # static-shape batching: same prompt length and sampling config share
        # one compiled decode program
        return (self.prompt_ids.shape[1], self.temperature, self.top_k,
                self.eos_token_id)


class ServingEngine:
    """Batched generation server over a model exposing ``generate_cached``."""

    def __init__(self, model, max_batch_size: int = 8,
                 max_wait_ms: float = 5.0):
        self.model = model
        self.max_batch_size = max_batch_size
        self.max_wait = max_wait_ms / 1e3
        self._queue: "queue.Queue[GenerationRequest]" = queue.Queue()
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None
        self._stats_lock = threading.Lock()
        self.stats = {"requests": 0, "batches": 0, "batched_requests": 0}

    def _bump(self, key, n=1):
        with self._stats_lock:
            self.stats[key] += n

    # -- client API ----------------------------------------------------------
    def submit(self, prompt_ids, max_new_tokens=32, temperature=0.0,
               top_k=0, eos_token_id=None) -> GenerationResult:
        req = GenerationRequest(prompt_ids, max_new_tokens, temperature,
                                top_k, eos_token_id)
        if self._thread is None:
            self.start()  # lazy start: a future must always have a server
        self._bump("requests")
        self._queue.put(req)
        return req.result

    def generate(self, prompt_ids, timeout: float = 300.0, **kw) -> np.ndarray:
        return self.submit(prompt_ids, **kw).result(timeout)

    # -- lifecycle -----------------------------------------------------------
    def start(self):
        if self._thread is None:
            self._stop.clear()
            self._thread = threading.Thread(target=self._loop, daemon=True)
            self._thread.start()
        return self

    def stop(self):
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=30)
            self._thread = None
        # fail whatever is still queued: a caller must never block on a
        # future no server will serve
        while True:
            try:
                req = self._queue.get_nowait()
            except queue.Empty:
                break
            req.result._set(error=RuntimeError("serving engine stopped"))

    def __enter__(self):
        return self.start()

    def __exit__(self, *exc):
        self.stop()
        return False

    # -- scheduler -----------------------------------------------------------
    def _collect_batch(self) -> List[GenerationRequest]:
        """One leader request + everything compatible that arrives within the
        batching window, up to max_batch_size."""
        try:
            leader = self._queue.get(timeout=0.1)
        except queue.Empty:
            return []
        batch = [leader]
        deadline = time.monotonic() + self.max_wait
        leftovers = []
        while len(batch) < self.max_batch_size:
            rest = deadline - time.monotonic()
            if rest <= 0:
                break
            try:
                req = self._queue.get(timeout=rest)
            except queue.Empty:
                break
            if req.batch_key() == leader.batch_key():
                batch.append(req)
            else:
                leftovers.append(req)
        for req in leftovers:  # incompatible: back to the queue, keep order
            self._queue.put(req)
        return batch

    def _loop(self):
        while not self._stop.is_set():
            batch = self._collect_batch()
            if not batch:
                continue
            self._bump("batches")
            self._bump("batched_requests", len(batch))
            try:
                ids = np.concatenate([r.prompt_ids for r in batch], axis=0)
                leader = batch[0]
                out = self.model.generate_cached(
                    ids,
                    max_new_tokens=max(r.max_new_tokens for r in batch),
                    temperature=leader.temperature, top_k=leader.top_k,
                    eos_token_id=leader.eos_token_id)
                out = np.asarray(out.numpy())
                plen = leader.prompt_ids.shape[1]
                for i, req in enumerate(batch):
                    row = out[i, : plen + req.max_new_tokens]
                    req.result._set(output=row)
            except BaseException as e:  # noqa: BLE001 — deliver to callers
                for req in batch:
                    req.result._set(error=e)
