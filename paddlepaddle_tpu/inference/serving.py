"""Serving engine — request queue + batched KV-cache decode.

Reference surface: the Predictor/predictor-pool deployment layer
(paddle/fluid/inference/api/paddle_inference_api.h:52,229 — config,
zero-copy handles, a pool of predictors serving concurrent callers) and the
serving-grade batched attention
(paddle/phi/kernels/fusion/gpu/block_multi_head_attention_kernel.cu via
python/paddle/incubate/nn/functional/block_multihead_attention.py).

TPU-native: one engine thread owns the chip; concurrent callers submit
GenerationRequests into a queue; futures deliver per-request results. Two
schedulers:

* ``mode="continuous"`` (default) — slot-based continuous batching over
  the BatchDecodeEngine (decode_engine.py): ragged prompt lengths, mixed
  sampling params and budgets share ONE compiled multi-step decode program
  with per-slot cache positions; finished slots retire and free slots admit
  queued requests mid-flight. The TPU-native equivalent of the reference's
  paged block_multi_head_attention serving path.
* ``mode="static"`` — groups compatible requests (same prompt-length
  bucket and sampling params) into one batched ``generate_cached`` call;
  simpler, kept for models without the cache-vector-position path.
"""

from __future__ import annotations

import queue
import threading
import time
from typing import List, Optional

import numpy as np

# observability hook: _obs_srv(event, value) with events "latency" (seconds
# submit-to-result for one completed request), "error" (a request failed),
# "batch_size" (decode slots / requests active in the current batch).
# None when observability is off.
_obs_srv = None


class GenerationResult:
    """Future for one request."""

    def __init__(self):
        self._event = threading.Event()
        self._output = None
        self._error: Optional[BaseException] = None
        self._t_submit = time.perf_counter()

    def done(self) -> bool:
        return self._event.is_set()

    def result(self, timeout: Optional[float] = None) -> np.ndarray:
        if not self._event.wait(timeout):
            raise TimeoutError("generation did not finish in time")
        if self._error is not None:
            raise self._error
        return self._output

    def _set(self, output=None, error=None):
        if self._event.is_set():
            return  # first outcome wins: a late writer (e.g. a retiring
        self._output = output   # slot racing stop()) must not flip a result
        self._error = error
        self._event.set()
        obs = _obs_srv
        if obs is not None:
            if error is None:
                obs("latency", time.perf_counter() - self._t_submit)
            else:
                obs("error", 1)


class GenerationRequest:
    def __init__(self, prompt_ids, max_new_tokens, temperature, top_k,
                 eos_token_id):
        arr = np.asarray(prompt_ids, np.int32)
        if arr.ndim == 2 and arr.shape[0] == 1:
            arr = arr[0]
        if arr.ndim != 1:
            raise ValueError(
                f"submit() takes ONE prompt (1-D ids or [1, L]); got shape "
                f"{arr.shape} — submit a batch as separate requests, the "
                "engine batches compatible ones itself")
        self.prompt_ids = arr.reshape(1, -1)
        self.max_new_tokens = int(max_new_tokens)
        self.temperature = float(temperature)
        self.top_k = int(top_k)
        self.eos_token_id = eos_token_id
        self.result = GenerationResult()

    def batch_key(self):
        # static-shape batching: same prompt length and sampling config share
        # one compiled decode program
        return (self.prompt_ids.shape[1], self.temperature, self.top_k,
                self.eos_token_id)


class ServingEngine:
    """Batched generation server over a model exposing ``generate_cached``."""

    def __init__(self, model, max_batch_size: int = 8,
                 max_wait_ms: float = 5.0, mode: str = "continuous",
                 max_len: Optional[int] = None, decode_chunk: int = 16):
        if mode not in ("continuous", "static"):
            raise ValueError(f"mode must be 'continuous' or 'static', got {mode!r}")
        self.model = model
        self.mode = mode
        self.max_batch_size = max_batch_size
        self.max_wait = max_wait_ms / 1e3
        self._queue: "queue.Queue[GenerationRequest]" = queue.Queue()
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None
        self._stats_lock = threading.Lock()
        self.stats = {"requests": 0, "batches": 0, "batched_requests": 0,
                      "decode_tokens": 0}
        self._engine = None
        if mode == "continuous":
            from .decode_engine import BatchDecodeEngine

            self._engine = BatchDecodeEngine(
                model, max_slots=max_batch_size, max_len=max_len,
                chunk=decode_chunk)

    def _bump(self, key, n=1):
        with self._stats_lock:
            self.stats[key] += n

    # -- client API ----------------------------------------------------------
    def submit(self, prompt_ids, max_new_tokens=32, temperature=0.0,
               top_k=0, eos_token_id=None) -> GenerationResult:
        req = GenerationRequest(prompt_ids, max_new_tokens, temperature,
                                top_k, eos_token_id)
        if self._thread is None:
            self.start()  # lazy start: a future must always have a server
        self._bump("requests")
        self._queue.put(req)
        return req.result

    def generate(self, prompt_ids, timeout: float = 300.0, **kw) -> np.ndarray:
        return self.submit(prompt_ids, **kw).result(timeout)

    # -- lifecycle -----------------------------------------------------------
    def start(self):
        if self._thread is None:
            self._stop.clear()
            self._thread = threading.Thread(target=self._loop, daemon=True)
            self._thread.start()
        return self

    def stop(self):
        self._stop.set()
        overran = False
        if self._thread is not None:
            self._thread.join(timeout=30)
            if self._thread.is_alive():
                # a mid-compile loop can overrun the join: keep the handle
                # so a later submit() cannot start a SECOND loop over the
                # same slot state; futures are still failed below so no
                # caller blocks, and we raise only after the cleanup
                overran = True
            else:
                self._thread = None
        # fail whatever is still queued or mid-decode: a caller must never
        # block on a future no server will serve
        while True:
            try:
                req = self._queue.get_nowait()
            except queue.Empty:
                break
            req.result._set(error=RuntimeError("serving engine stopped"))
        if self._engine is not None:
            for i, s in enumerate(self._engine._host_slots):
                if s.req is not None and not s.req.result.done():
                    s.req.result._set(
                        error=RuntimeError("serving engine stopped"))
                    self._engine._host_slots[i] = type(s)()
            self._engine.reset_slots()  # no phantom active device lanes
        if overran:
            raise RuntimeError(
                "serving engine thread did not stop within 30s (likely "
                "mid-compile); outstanding futures were failed; call "
                "stop() again to re-wait")

    def __enter__(self):
        return self.start()

    def __exit__(self, exc_type, exc, tb):
        try:
            self.stop()
        except RuntimeError:
            if exc_type is None:
                raise  # don't mask the with-body's original exception
        return False

    # -- scheduler -----------------------------------------------------------
    def _collect_batch(self) -> List[GenerationRequest]:
        """One leader request + everything compatible that arrives within the
        batching window, up to max_batch_size."""
        try:
            leader = self._queue.get(timeout=0.1)
        except queue.Empty:
            return []
        batch = [leader]
        deadline = time.monotonic() + self.max_wait
        leftovers = []
        while len(batch) < self.max_batch_size:
            rest = deadline - time.monotonic()
            if rest <= 0:
                break
            try:
                req = self._queue.get(timeout=rest)
            except queue.Empty:
                break
            if req.batch_key() == leader.batch_key():
                batch.append(req)
            else:
                leftovers.append(req)
        for req in leftovers:  # incompatible: back to the queue, keep order
            self._queue.put(req)
        return batch

    def _loop(self):
        if self.mode == "continuous":
            return self._loop_continuous()
        while not self._stop.is_set():
            batch = self._collect_batch()
            if not batch:
                continue
            self._bump("batches")
            self._bump("batched_requests", len(batch))
            if _obs_srv is not None:
                _obs_srv("batch_size", len(batch))
            try:
                ids = np.concatenate([r.prompt_ids for r in batch], axis=0)
                leader = batch[0]
                out = self.model.generate_cached(
                    ids,
                    max_new_tokens=max(r.max_new_tokens for r in batch),
                    temperature=leader.temperature, top_k=leader.top_k,
                    eos_token_id=leader.eos_token_id)
                out = np.asarray(out.numpy())
                plen = leader.prompt_ids.shape[1]
                for i, req in enumerate(batch):
                    row = out[i, : plen + req.max_new_tokens]
                    req.result._set(output=row)
            except BaseException as e:  # noqa: BLE001 — deliver to callers
                for req in batch:
                    req.result._set(error=e)

    def _loop_continuous(self):
        """Continuous batching: admit queued requests into free decode slots,
        run multi-step decode chunks, retire finished slots mid-flight. The
        BatchDecodeEngine delivers each request's future on retirement."""
        eng = self._engine
        waiting = None  # FIFO head that found no free slot — NOT re-queued
        # behind newer arrivals (that would rotate the queue every chunk and
        # starve early requests under sustained load)
        while not self._stop.is_set():
            admitted = False
            busy = any(s.req is not None for s in eng._host_slots)
            while True:
                if waiting is not None:
                    req, waiting = waiting, None
                else:
                    try:
                        req = self._queue.get(timeout=0.05 if not busy else 0)
                    except queue.Empty:
                        break
                try:
                    if eng._admit(req):
                        admitted = True
                        busy = True
                        self._bump("batched_requests")
                    else:
                        waiting = req   # hold the head; decode to free a slot
                        break
                except BaseException as e:  # noqa: BLE001
                    req.result._set(error=e)
            if busy:
                if _obs_srv is not None:
                    _obs_srv("batch_size",
                             sum(1 for s in eng._host_slots
                                 if s.req is not None))
                before = eng.stats["tokens_out"]
                try:
                    eng._decode_chunk()
                except BaseException as e:  # noqa: BLE001 — fail the slots
                    for i, s in enumerate(eng._host_slots):
                        if s.req is not None:
                            s.req.result._set(error=e)
                            eng._host_slots[i] = type(s)()
                    eng.reset_slots()  # clear phantom device lanes too
                    continue
                self._bump("decode_tokens", eng.stats["tokens_out"] - before)
                if admitted:
                    self._bump("batches")
        if waiting is not None:
            waiting.result._set(error=RuntimeError("serving engine stopped"))
