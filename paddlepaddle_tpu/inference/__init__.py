"""paddle.inference — deployment Predictor API.

Reference surface: paddle/fluid/inference/api/paddle_inference_api.h:52,229
(Config, Predictor, create_predictor, zero-copy tensors). TPU-native: the
222 IR fusion passes and the analysis pipeline are XLA's job; a predictor
wraps a jit.load'ed StableHLO module (or a live Layer) with the
name-indexed input/output handle API deployment code expects.
"""

from __future__ import annotations

from typing import Dict, List, Optional

import numpy as np

from ..core.tensor import Tensor


class Config:
    def __init__(self, prog_file: Optional[str] = None, params_file: Optional[str] = None):
        # paddle convention: both files share a prefix; accept either style
        if prog_file and prog_file.endswith(".pdmodel"):
            prog_file = prog_file[: -len(".pdmodel")]
        self.model_prefix = prog_file
        self._device = "tpu"
        self._memory_pool_mb = 0
        self._ir_optim = True

    def enable_use_gpu(self, memory_pool_init_size_mb=100, device_id=0):
        self._device = "tpu"  # accelerator path

    def disable_gpu(self):
        self._device = "cpu"

    def set_cpu_math_library_num_threads(self, n):
        pass

    def enable_memory_optim(self):
        pass

    def switch_ir_optim(self, flag=True):
        self._ir_optim = flag

    def enable_mkldnn(self):
        pass


class PredictorTensor:
    """Zero-copy style handle (reference ZeroCopyTensor)."""

    def __init__(self, name):
        self.name = name
        self._value = None

    def reshape(self, shape):
        pass  # shape comes from copy_from_cpu

    def copy_from_cpu(self, arr):
        self._value = np.ascontiguousarray(arr)

    def copy_to_cpu(self):
        return np.asarray(self._value)


class Predictor:
    def __init__(self, config: Config):
        from ..jit.save_load import load

        if config.model_prefix is None:
            raise ValueError("Config needs a model path prefix")
        self._layer = load(config.model_prefix)
        self._inputs: Dict[str, PredictorTensor] = {}
        self._outputs: List[PredictorTensor] = []
        # exported avals are the flattened (params..., inputs...) — subtract
        # the param count to recover the real input arity
        n_total = len(self._layer._exported.in_avals)
        n_params = len(self._layer._param_list)
        n_in = max(1, n_total - n_params)
        self._input_names = [f"input_{i}" for i in range(n_in)]

    def get_input_names(self):
        return list(self._input_names)

    def get_input_handle(self, name):
        if name not in self._input_names:
            raise KeyError(f"unknown input {name!r}; inputs are {self._input_names}")
        return self._inputs.setdefault(name, PredictorTensor(name))

    def get_output_names(self):
        return [f"output_{i}" for i in range(len(self._outputs))] or ["output_0"]

    def get_output_handle(self, name):
        i = int(name.split("_")[-1])
        return self._outputs[i]

    def run(self, inputs: Optional[List[np.ndarray]] = None):
        if inputs is None:
            missing = [n for n in self._input_names
                       if n not in self._inputs or self._inputs[n]._value is None]
            if missing:
                raise RuntimeError(
                    f"inputs {missing} not set; call get_input_handle(name)."
                    f"copy_from_cpu(arr) for every input first")
            inputs = [self._inputs[n]._value for n in self._input_names]
        outs = self._layer(*inputs)
        outs = outs if isinstance(outs, (list, tuple)) else [outs]
        self._outputs = []
        results = []
        for i, o in enumerate(outs):
            h = PredictorTensor(f"output_{i}")
            val = o.numpy() if isinstance(o, Tensor) else np.asarray(o)
            h.copy_from_cpu(val)
            self._outputs.append(h)
            results.append(val)
        return results


def create_predictor(config: Config) -> Predictor:
    return Predictor(config)


def _predictor_clone(src: Predictor) -> Predictor:
    """Construction stays in ONE place: a clone shares the compiled program
    (stateless under XLA) but owns its handle sets."""
    clone = Predictor.__new__(Predictor)
    clone.__dict__.update(src.__dict__)
    clone._inputs = {}
    clone._outputs = []
    clone._input_names = list(src._input_names)
    return clone


class PredictorPool:
    """Pool of predictors for concurrent callers (reference:
    paddle_inference_api.h:229 PredictorPool / python inference.wrapper).
    One model load, ``size`` handle sets: retrive(i) hands thread i its own
    input/output handles while the compiled program (stateless under XLA)
    is shared — the TPU-native meaning of a predictor clone."""

    def __init__(self, config: Config, size: int = 1):
        if size < 1:
            raise ValueError(f"pool size must be >= 1, got {size}")
        first = Predictor(config)
        self._preds = [first]
        for _ in range(size - 1):
            self._preds.append(_predictor_clone(first))

    def retrive(self, idx: int) -> Predictor:    # reference spelling
        return self._preds[idx]

    retrieve = retrive

    def __len__(self):
        return len(self._preds)


from .compile_plan import (  # noqa: F401,E402
    BundleMismatchError,
    CompilePlan,
    prompt_buckets,
)
from .fleet import FleetController, FleetPolicy  # noqa: F401,E402
from .robustness import (  # noqa: F401,E402
    CircuitBreaker,
    CircuitOpenError,
    DeadlineExceededError,
    DeployError,
    EngineDrainingError,
    FleetUnavailableError,
    KVCapacityError,
    RequestCancelledError,
    RequestValidationError,
    ServerOverloadedError,
    ServingError,
)
from .remote_replica import (  # noqa: F401,E402
    ProcessReplicaFactory,
    RemoteReplicaClient,
    ReplicaSupervisor,
)
from .router import ReplicaClient, ServingRouter  # noqa: F401,E402
from .serving import GenerationResult, ServingEngine  # noqa: F401,E402
from .speculative import SpeculativeDecoder  # noqa: F401,E402
