"""Elastic fleet control plane — SLO-driven autoscaling + zero-downtime
continuous deploy (ROADMAP item 5: the loop that CLOSES over every signal
PRs 6/8/10/12 built).

Reference surface: the reference framework's fleet executor / PS layer
(``paddle/fluid`` distributed fleet — elastic scaling and deployment as
first-class runtime capability, not an ops afterthought). TPU-native form:
a :class:`FleetController` owns a :class:`~.router.ServingRouter` plus a
VERSIONED replica factory and runs two control loops over them.

**Autoscaler** — a daemon loop reads the router's ``health()`` snapshot
(per-replica ``est_wait_s``/``queue_depth``, healthy census, and the PR 12
``slo_burn`` block) and:

* scales UP on a sustained violation (SLO burn over budget, or estimated
  wait over bound): a fresh replica is built from the CURRENT version's
  factory, started, PRE-WARMED out of rotation (bring-up is seconds, not
  minutes, because the factory arms it from an AOT bundle + persistent
  compile cache — PR 10's 14.5×), and only then joins the pick set;
* scales DOWN sustained-idle replicas by deliberate drain (in-flight
  finishes, queued requests fail over; none of it is breaker evidence);
* is guarded against box noise by HYSTERESIS (a violation/idle reading
  must persist ``up_streak``/``down_streak`` consecutive ticks), COOLDOWN
  windows after any scale action, and hard ``min/max_replicas`` bounds —
  one hot probe cannot flap the fleet.

**Deploy pipeline** — :meth:`FleetController.deploy(bundle_path)`:

1. *validate*: the candidate bundle's manifest + payload sha256s are
   pre-flighted stdlib-cheap (:func:`~.compile_plan.validate_bundle`);
   a corrupt artifact raises :class:`~.robustness.DeployError` before any
   replica is touched;
2. *canary*: ONE replica is restarted onto the candidate (out of
   rotation), pre-warmed, health-gated, then probed with real requests;
   the promotion decision is a perf-gate-shaped check over the canary's
   serving SLO numbers (+ the cold-start facts its warmup reports);
3. *rollout*: replica-by-replica through the router's
   :meth:`~.router.ServingRouter.restart_replica` machinery (the PR 8
   zero-drop path), each one health-gated and burn-checked before the
   next — replicas the autoscaler adds MID-rollout are picked up too;
4. *rollback*: any health/SLO-burn regression mid-rollout automatically
   restores the PREVIOUS bundle on every updated replica — PR 8's
   abort-and-stay-out becomes abort-and-RESTORE: a bad deploy can never
   walk the fleet down, and the fleet ends a failed rollout serving the
   old version everywhere.

Observability: ``paddle_fleet_{replicas_target,replicas,scale_ups,
scale_downs,scaleup_to_healthy_seconds,rollouts,rollbacks}_*`` metrics,
``fleet`` events in the crash flight recorder, ``fleet.scale`` /
``fleet.rollout`` spans in the request-journey plane (reqtrace), and a
``fleet`` block in :meth:`FleetController.health` served as a ``/healthz``
provider (rendered by ``obsctl fleet TARGET``).

Everything here is host-side stdlib — the replicas own the chips.
"""

from __future__ import annotations

import itertools
import os
import sys
import threading
import time
from typing import Callable, Dict, List, Optional

import numpy as np

from ..core import flags as _flags
from . import compile_plan as _cp
from .robustness import DeployError
from .robustness import safe_inc as _safe_inc
from .robustness import safe_set as _safe_set
from .router import ReplicaClient, ServingRouter
from .serving import _flight_record, slo_summary

__all__ = ["FleetPolicy", "FleetController", "decide",
           "perf_verdict_gate", "DeployError"]


class FleetPolicy:
    """Scaling policy: triggers, hysteresis, cooldowns, bounds. Defaults
    are deliberately conservative — a fleet that scales a beat late beats
    one that flaps (docs/serving.md "Elastic fleet" has the full table).

    * scale UP when, for ``up_streak`` consecutive ticks, SLO burn exceeds
      ``scale_up_burn`` (burn 1.0 = the whole error budget is being spent)
      OR the worst healthy replica's ``est_wait_s`` exceeds
      ``scale_up_est_wait_s``;
    * scale DOWN when, for ``down_streak`` consecutive ticks, every
      healthy replica's ``est_wait_s`` is under ``idle_est_wait_s``, the
      queues are empty, and burn is under ``idle_burn``;
    * any scale action starts a cooldown (``cooldown_up_s`` before the
      next up, ``cooldown_down_s`` before the next down — down is slower
      on purpose: adding capacity you did not need costs dollars, removing
      capacity you did need costs SLO);
    * ``min_replicas``/``max_replicas`` are hard bounds.
    """

    def __init__(self,
                 min_replicas: int = 1,
                 max_replicas: int = 4,
                 scale_up_est_wait_s: float = 1.0,
                 scale_up_burn: float = 1.0,
                 up_streak: int = 2,
                 idle_est_wait_s: float = 0.05,
                 idle_burn: float = 0.5,
                 down_streak: int = 5,
                 cooldown_up_s: float = 10.0,
                 cooldown_down_s: float = 30.0,
                 interval_s: float = 1.0,
                 rollback_burn: Optional[float] = None,
                 health_timeout_s: float = 60.0,
                 drain_timeout_s: Optional[float] = None):
        if min_replicas < 1:
            raise ValueError(f"min_replicas must be >= 1, got {min_replicas}")
        if max_replicas < min_replicas:
            raise ValueError(
                f"max_replicas {max_replicas} < min_replicas {min_replicas}")
        if up_streak < 1 or down_streak < 1:
            raise ValueError("up_streak/down_streak must be >= 1")
        self.min_replicas = int(min_replicas)
        self.max_replicas = int(max_replicas)
        self.scale_up_est_wait_s = float(scale_up_est_wait_s)
        self.scale_up_burn = float(scale_up_burn)
        self.up_streak = int(up_streak)
        self.idle_est_wait_s = float(idle_est_wait_s)
        self.idle_burn = float(idle_burn)
        self.down_streak = int(down_streak)
        self.cooldown_up_s = float(cooldown_up_s)
        self.cooldown_down_s = float(cooldown_down_s)
        self.interval_s = float(interval_s)
        # mid-rollout regression bar: default = the scale-up bar (burn
        # past it means the candidate is eating the error budget)
        self.rollback_burn = (self.scale_up_burn if rollback_burn is None
                              else float(rollback_burn))
        self.health_timeout_s = float(health_timeout_s)
        self.drain_timeout_s = drain_timeout_s

    def describe(self) -> Dict[str, object]:
        return {k: v for k, v in vars(self).items()}


def decide(policy: FleetPolicy, sig: Dict[str, object],
           state: Dict[str, object], now: float):
    """One autoscaler tick's decision: ``("up"|"down"|None, reason)``.

    Pure over its inputs except the hysteresis counters in ``state``
    (``hot``/``idle`` streaks) which it advances — the caller owns
    ``last_action_t`` (cooldowns) and resets streaks when it actually
    executes an action. Split out of the controller so the policy is unit-
    testable with synthetic signals, no fleet required."""
    est = float(sig.get("est_wait_max") or 0.0)
    burn = sig.get("burn")           # None = SLO targets not armed
    actual = int(sig.get("replicas") or 0)
    depth = int(sig.get("queue_depth") or 0)
    alerts_sig = sig.get("alerts")   # alert engine armed: ONE definition
    #                                  of "burn is violating" — the rule's,
    #                                  with its multi-window + hold-down
    #                                  semantics, not a re-derived threshold

    hot_reason = None
    burn_violating = None            # None = nothing armed says either way
    if alerts_sig is not None:
        firing = list(alerts_sig.get("burn_firing") or ())
        burn_violating = bool(firing)
        if firing:
            hot_reason = f"burn alert firing: {'+'.join(firing)}"
    elif burn is not None:
        burn_violating = burn > policy.scale_up_burn
        if burn_violating:
            hot_reason = (f"slo_burn {burn:.2f} > budget "
                          f"{policy.scale_up_burn:g}")
    if hot_reason is None and est > policy.scale_up_est_wait_s:
        hot_reason = (f"est_wait {est:.2f}s > "
                      f"{policy.scale_up_est_wait_s:g}s")
    idle = (est <= policy.idle_est_wait_s and depth == 0
            and (burn_violating is None or not burn_violating)
            and (alerts_sig is not None
                 or burn is None or burn <= policy.idle_burn))

    if hot_reason:
        state["hot"] = state.get("hot", 0) + 1
        state["idle"] = 0
    elif idle:
        state["idle"] = state.get("idle", 0) + 1
        state["hot"] = 0
    else:
        state["hot"] = state["idle"] = 0

    last = state.get("last_action_t")
    if hot_reason and state["hot"] >= policy.up_streak:
        if actual >= policy.max_replicas:
            return None, f"{hot_reason} but at max_replicas " \
                         f"{policy.max_replicas}"
        if last is not None and now - last < policy.cooldown_up_s:
            return None, f"{hot_reason} but in scale cooldown " \
                         f"({policy.cooldown_up_s - (now - last):.1f}s left)"
        return "up", f"{hot_reason} for {state['hot']} ticks"
    if idle and state["idle"] >= policy.down_streak:
        if actual <= policy.min_replicas:
            return None, "idle but at min_replicas " \
                         f"{policy.min_replicas}"
        if last is not None and now - last < policy.cooldown_down_s:
            return None, "idle but in scale cooldown " \
                         f"({policy.cooldown_down_s - (now - last):.1f}s left)"
        return "down", (f"idle (est_wait {est:.3f}s, queue 0) for "
                        f"{state['idle']} ticks")
    return None, (hot_reason and f"{hot_reason} (streak {state['hot']}/"
                  f"{policy.up_streak})") or \
        (idle and f"idle (streak {state['idle']}/{policy.down_streak})") \
        or "steady"


def perf_verdict_gate(verdict) -> Callable[[Dict], List[str]]:
    """Build a deploy ``gate=`` callable from a ``tools/perf_gate.py
    --json`` verdict document — a parsed dict, a JSON string, or a path to
    the file ``--json`` wrote. The gate vetoes promotion with one reason
    per non-ok field row (regressions and missing metrics), so CI can run
    the bench against the candidate, gate it, and hand the machine verdict
    straight to :meth:`FleetController.deploy` without parsing the human
    report."""
    import json as _json

    if isinstance(verdict, (str, os.PathLike)):
        s = str(verdict)
        if s.lstrip().startswith("{"):
            verdict = _json.loads(s)
        else:
            with open(s) as f:
                verdict = _json.load(f)
    if not isinstance(verdict, dict):
        raise TypeError(f"verdict must be dict/JSON/path, got "
                        f"{type(verdict).__name__}")
    doc = dict(verdict)

    def gate(_canary_metrics: Dict[str, object]) -> List[str]:
        reasons = []
        for row in doc.get("fields", ()):
            if row.get("verdict") in ("regression", "missing"):
                reasons.append(
                    f"perf_gate {row.get('verdict')}: {row.get('metric')} "
                    f"baseline={row.get('baseline')} "
                    f"candidate={row.get('candidate')} "
                    f"({row.get('direction', '?')} is better)")
        if not doc.get("ok", not reasons):
            reasons = reasons or ["perf_gate verdict not ok"]
        return reasons

    return gate


class FleetController:
    """Owns a :class:`~.router.ServingRouter` + a versioned replica
    factory; closes the elastic control loop over them.

    ``factory`` is ``Callable[[Optional[str]], ServingEngine]`` — called
    with the fleet's current VERSION (a serving-bundle path, or ``None``
    before any deploy) every time a replica engine is (re)built. A
    production factory passes the version through as
    ``ServingEngine(model, bundle=version)`` so replicas arm from the AOT
    artifact; a test factory may key anything off the label.

    The controller itself serves the engine surface through its router
    (``submit``/``generate``/``health``/``drain``), so callers that
    fronted a :class:`~.router.ServingRouter` front a
    :class:`FleetController` unchanged.
    """

    def __init__(self, factory: Callable[[Optional[str]], object],
                 initial_replicas: int = 2,
                 policy: Optional[FleetPolicy] = None,
                 version: Optional[str] = None,
                 name_prefix: str = "r",
                 **router_kw):
        self.policy = policy or FleetPolicy()
        if not (self.policy.min_replicas <= initial_replicas
                <= self.policy.max_replicas):
            raise ValueError(
                f"initial_replicas {initial_replicas} outside policy "
                f"bounds [{self.policy.min_replicas}, "
                f"{self.policy.max_replicas}]")
        self.factory = factory
        self.version = version          # the bundle every replica serves
        self.previous_version: Optional[str] = None
        self.name_prefix = str(name_prefix)
        self._ids = itertools.count(0)
        self._versions: Dict[str, Optional[str]] = {}
        clients = [self._new_client(version)
                   for _ in range(int(initial_replicas))]
        router_kw.setdefault("drain_timeout_s", self.policy.drain_timeout_s)
        self.router = ServingRouter(clients, **router_kw)
        self.target = int(initial_replicas)
        # one lock serializes every replica-set mutation (scale up/down,
        # rollout/rollback steps) — reads stay lock-free on the router's
        # copy-on-write snapshots, so the autoscaler and a deploy can
        # interleave without either seeing a half-mutated fleet
        self._scale_lock = threading.RLock()
        self._deploy_lock = threading.Lock()
        self._state = {"hot": 0, "idle": 0, "last_action_t": None}
        self.last_decision: Dict[str, object] = {
            "action": None, "reason": "never evaluated", "t_mono": None,
            "wall": None}
        self.stats = {"ticks": 0, "scale_ups": 0, "scale_downs": 0,
                      "scale_up_failures": 0, "rollouts": 0,
                      "rollbacks": 0}
        self.rollout: Dict[str, object] = {
            "state": "idle", "version": None, "previous": None,
            "replica": None, "updated": []}
        self.last_scaleup_to_healthy_s: Optional[float] = None
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None
        self._health_reg_name: Optional[str] = None

    # -- plumbing ------------------------------------------------------------
    def _engine_factory(self, version: Optional[str]):
        factory = self.factory
        fn = lambda: factory(version)  # noqa: E731
        # the version rides the closure so a process-backed client's
        # restart(factory=...) can respawn onto the new bundle path
        fn.version = version
        return fn

    def _new_client(self, version: Optional[str]) -> ReplicaClient:
        name = f"{self.name_prefix}{next(self._ids)}"
        self._versions[name] = version
        if getattr(self.factory, "makes_clients", False):
            # a ProcessReplicaFactory builds the whole client (supervisor
            # + RemoteReplicaClient over a fresh OS process), not an
            # engine — the controller manages processes, same surface
            return self.factory(version, name=name)
        return ReplicaClient(self._engine_factory(version), name=name)

    def _journey(self, tag: str):
        try:
            from ..observability import reqtrace as _rt

            if _rt.enabled():
                return _rt.mint(tag)
        except Exception:
            pass
        return None

    def _finish_journey(self, j, outcome: str) -> None:
        if j is None:
            return
        try:
            from ..observability import reqtrace as _rt

            _rt.finish(j, outcome)
        except Exception:
            pass

    def _gauge_census(self) -> None:
        _safe_set("paddle_fleet_replicas_target",
                  "replica count the fleet controller is steering toward",
                  self.target)
        _safe_set("paddle_fleet_replicas",
                  "replicas currently in the fleet",
                  len(self.router._replicas))

    # -- signal + decision ---------------------------------------------------
    def read_signal(self) -> Dict[str, object]:
        """The autoscaler's input, distilled from ``router.health()``:
        worst healthy est-wait, fleet queue depth, healthy census, and
        the worst armed SLO burn (None when no target is armed or nothing
        is measurable yet — a fleet with no SLO flags scales on est-wait
        alone, it does not scale on fake zeros)."""
        h = self.router.health()
        reps = h.get("replicas", {})
        est = [float(r.get("est_wait_s") or 0.0)
               for r in reps.values() if r.get("ok")]
        depth = sum(int(r.get("queue_depth") or 0)
                    for r in reps.values() if r.get("ok"))
        burn = None
        b = h.get("slo_burn") or {}
        if b.get("enabled"):
            for key in ("ttft", "tpot"):
                kb = b.get(key) or {}
                if kb.get("enabled") and kb.get("burn") is not None:
                    v = float(kb["burn"])
                    burn = v if burn is None else max(burn, v)
        sig = {"replicas": len(reps),
               "healthy": int(h.get("router", {}).get("healthy", 0)),
               "est_wait_max": max(est) if est else 0.0,
               "queue_depth": depth,
               "burn": burn,
               "ok": bool(h.get("ok"))}
        # when the alert engine is installed, its AlertState is the single
        # definition of "the burn is violating" (multi-window + hold-down),
        # and decide() defers to it instead of re-deriving a threshold
        try:
            from ..observability import alerts as _alerts

            eng = _alerts.get()
        except Exception:
            eng = None
        if eng is not None:
            sig["alerts"] = eng.signal()
        return sig

    def _tick(self) -> Dict[str, object]:
        """One autoscaler evaluation (the loop calls this every
        ``policy.interval_s``; tests call it directly). Reads the signal,
        decides, executes, records the decision for ``health()``."""
        self.stats["ticks"] += 1
        sig = self.read_signal()
        now = time.monotonic()
        action, reason = decide(self.policy, sig, self._state, now)
        self.last_decision = {"action": action, "reason": reason,
                              "t_mono": now, "wall": time.time()}
        if action == "up":
            self.scale_up(reason=reason)
        elif action == "down":
            self.scale_down(reason=reason)
        self._gauge_census()
        return {"action": action, "reason": reason, "signal": sig}

    # -- scaling -------------------------------------------------------------
    def scale_up(self, n: int = 1, reason: str = "manual") -> List[str]:
        """Add up to ``n`` replicas (bounded by ``max_replicas``): build
        from the current version's factory, start, PRE-WARM out of
        rotation, join the pick set, then wait (bounded) for the health
        probe — ``scaleup_to_healthy_s`` is the wall from decision to
        in-rotation-and-healthy, the number the bundle-armed bring-up
        exists to keep in seconds. A replica that never turns healthy is
        removed again and counted as a failure, not left as a zombie."""
        added: List[str] = []
        with self._scale_lock:
            for _ in range(int(n)):
                if len(self.router._replicas) >= self.policy.max_replicas:
                    break
                t0 = time.monotonic()
                self.target = len(self.router._replicas) + 1
                self._gauge_census()
                client = self._new_client(self.version)
                j = self._journey(f"fleet-scale-{client.name}")
                try:
                    client.start()
                    try:
                        client.warmup()   # compiles land HERE, before the
                        #   replica can be picked — not on live traffic
                    except Exception as e:
                        sys.stderr.write(
                            f"[fleet] replica {client.name} pre-warm "
                            f"failed ({type(e).__name__}: {e})\n")
                    self.router.add_replica(client)
                except Exception as e:
                    self.stats["scale_up_failures"] += 1
                    self._versions.pop(client.name, None)
                    # a FAILED attempt arms the cooldown too: a
                    # persistently failing factory must back off, not
                    # rebuild/tear down an engine every tick
                    self._state["hot"] = 0
                    self._state["last_action_t"] = time.monotonic()
                    sys.stderr.write(
                        f"[fleet] scale-up replica {client.name} failed to "
                        f"start ({type(e).__name__}: {e})\n")
                    if j is not None:
                        j.event("fleet.scale", replica="fleet",
                                action="up", target=client.name,
                                reason=reason, ok=False)
                    self._finish_journey(j, "error")
                    break
                deadline = time.monotonic() + self.policy.health_timeout_s
                ok = False
                while time.monotonic() < deadline:
                    try:
                        ok = bool(client.health().get("ok", False))
                    except Exception:
                        ok = False
                    if ok:
                        break
                    time.sleep(0.02)
                wall = round(time.monotonic() - t0, 3)
                if not ok:
                    self.stats["scale_up_failures"] += 1
                    self._state["hot"] = 0
                    self._state["last_action_t"] = time.monotonic()
                    try:
                        self.router.remove_replica(
                            client.name, stop=True, reason="scaleup_failed")
                    except Exception:
                        pass
                    self._versions.pop(client.name, None)
                    sys.stderr.write(
                        f"[fleet] scale-up replica {client.name} never "
                        f"turned healthy within "
                        f"{self.policy.health_timeout_s:g}s — removed\n")
                    if j is not None:
                        j.event("fleet.scale", replica="fleet", action="up",
                                target=client.name, reason=reason, ok=False,
                                wall_s=wall)
                    self._finish_journey(j, "error")
                    break
                self.last_scaleup_to_healthy_s = wall
                self.stats["scale_ups"] += 1
                self._state["hot"] = self._state["idle"] = 0
                self._state["last_action_t"] = time.monotonic()
                added.append(client.name)
                _safe_inc("paddle_fleet_scale_ups_total",
                          "replicas added by the fleet controller",
                          replica=client.name)
                _safe_set("paddle_fleet_scaleup_to_healthy_seconds",
                          "wall seconds from scale-up decision to the new "
                          "replica healthy and in rotation", wall)
                _flight_record("fleet", client.name, event="scale_up",
                               reason=reason, wall_s=wall,
                               replicas=len(self.router._replicas))
                sys.stderr.write(
                    f"[fleet] scaled UP: +{client.name} in {wall:.2f}s "
                    f"({reason}) — {len(self.router._replicas)} replicas\n")
                if j is not None:
                    j.event("fleet.scale", replica="fleet", action="up",
                            target=client.name, reason=reason, ok=True,
                            wall_s=wall)
                self._finish_journey(j, "ok")
            self.target = len(self.router._replicas)
            self._gauge_census()
        return added

    def scale_down(self, n: int = 1, reason: str = "manual") -> List[str]:
        """Remove up to ``n`` replicas (bounded by ``min_replicas``) by
        DELIBERATE drain: least-loaded in-rotation replica leaves the
        pick set, finishes its in-flight work (queued requests fail over),
        its engine stops (unregistering its ``/healthz`` provider), and
        the router drops its breaker/prober state with it."""
        removed: List[str] = []
        with self._scale_lock:
            for _ in range(int(n)):
                if len(self.router._replicas) <= self.policy.min_replicas:
                    break
                cands = [r for r in self.router._replicas if r.in_rotation]
                # min_replicas bounds SERVING capacity, not fleet census:
                # during a deploy the canary is deliberately out of
                # rotation, and an idle-streak scale-down must not remove
                # the replica(s) actually carrying the traffic
                if len(cands) - 1 < self.policy.min_replicas:
                    break
                rep = min(cands, key=lambda r: (
                    r.inflight,
                    int((r.snapshot or {}).get("queue_depth") or 0),
                    r.name))
                j = self._journey(f"fleet-scale-{rep.name}")
                res = self.router.remove_replica(
                    rep.name, drain_timeout=self.policy.drain_timeout_s,
                    stop=True, reason="scale_down")
                self._versions.pop(rep.name, None)
                self.stats["scale_downs"] += 1
                self._state["hot"] = self._state["idle"] = 0
                self._state["last_action_t"] = time.monotonic()
                removed.append(rep.name)
                _safe_inc("paddle_fleet_scale_downs_total",
                          "replicas removed by the fleet controller",
                          replica=rep.name)
                _flight_record("fleet", rep.name, event="scale_down",
                               reason=reason, clean=res.get("clean"),
                               replicas=len(self.router._replicas))
                sys.stderr.write(
                    f"[fleet] scaled DOWN: -{rep.name} ({reason}) — "
                    f"{len(self.router._replicas)} replicas\n")
                if j is not None:
                    j.event("fleet.scale", replica="fleet", action="down",
                            target=rep.name, reason=reason,
                            ok=bool(res.get("clean", True)))
                self._finish_journey(j, "ok")
            self.target = len(self.router._replicas)
            self._gauge_census()
        return removed

    # -- deploy pipeline -----------------------------------------------------
    def _update_replica(self, rep, version: Optional[str],
                        readmit: bool = True) -> Dict[str, object]:
        """Move one replica to ``version`` through the router's zero-drop
        restart cycle. Under the scale lock so a concurrent scale-down
        cannot remove the replica mid-update."""
        with self._scale_lock:
            if all(r is not rep for r in self.router._replicas):
                # scaled down between selection and update: nothing to do
                return {"replica": rep.name, "ok": True, "skipped": True}
            info = self.router.restart_replica(
                rep, drain_timeout=self.policy.drain_timeout_s,
                health_timeout=self.policy.health_timeout_s,
                warmup=True, factory=self._engine_factory(version),
                readmit=readmit)
            self._versions[rep.name] = version
            return info

    def _canary_probe(self, rep, n: int, prompt, new_tokens: int,
                      timeout: float) -> Dict[str, object]:
        """Promotion evidence from the (out-of-rotation) canary: submit
        ``n`` real requests straight at its client, count completions,
        measure the SLO numbers, and read its post-probe health + the
        cold-start facts its warmup left behind."""
        if prompt is None:
            prompt = np.zeros((4,), np.int32)
        futs, errors = [], []
        for _ in range(int(n)):
            try:
                futs.append(rep.client.submit(
                    prompt, max_new_tokens=int(new_tokens)))
            except Exception as e:  # noqa: BLE001 — the gate's evidence
                errors.append(f"{type(e).__name__}: {e}")
        completed = 0
        for f in futs:
            try:
                f.result(timeout)
                completed += 1
            except Exception as e:  # noqa: BLE001
                errors.append(f"{type(e).__name__}: {e}")
        try:
            snap = rep.client.health()
            health_ok = bool(snap.get("ok", False))
            compile_block = snap.get("compile") or {}
        except Exception as e:
            health_ok, compile_block = False, {}
            errors.append(f"{type(e).__name__}: {e}")
        metrics = {"submitted": int(n), "completed": completed,
                   "failed": int(n) - completed,
                   "errors": errors[:3], "health_ok": health_ok,
                   # cold-start fields (perf_gate's coldstart.* shape):
                   # a candidate whose warmup left the serve window
                   # compiling would regress every restart it ships to
                   "warmup": compile_block.get("warmup"),
                   "bundle": compile_block.get("bundle")}
        metrics.update(slo_summary(futs))
        return metrics

    def _default_gate(self, metrics: Dict[str, object]) -> List[str]:
        """Promotion decision over the canary metrics: every probe request
        completed, the replica is healthy, and — when SLO targets are
        armed (``FLAGS_slo_ttft_ms``) — the canary's TTFT p99 is inside
        the target. Returns the list of violated reasons (empty = promote);
        a custom ``gate=`` callable replaces this wholesale (e.g. a
        tools/perf_gate comparison against a recorded baseline)."""
        reasons = []
        if metrics["completed"] < metrics["submitted"]:
            reasons.append(
                f"{metrics['failed']} of {metrics['submitted']} canary "
                f"requests failed ({'; '.join(metrics['errors'])})")
        if not metrics["health_ok"]:
            reasons.append("canary replica health not ok after probe")
        try:
            slo_ttft = float(_flags.flag_value("slo_ttft_ms"))
        except Exception:
            slo_ttft = 0.0
        p99 = metrics.get("ttft_p99_ms")
        if slo_ttft > 0 and p99 is not None and p99 > slo_ttft:
            reasons.append(f"canary ttft_p99 {p99}ms > SLO target "
                           f"{slo_ttft:g}ms")
        bundle = metrics.get("bundle")
        if isinstance(bundle, dict) and bundle.get("path") \
                and not bundle.get("loaded"):
            reasons.append(
                f"candidate bundle fell back to lazy builds on the canary "
                f"({bundle.get('error', 'unknown cause')})")
        return reasons

    def _rollback(self, prev: Optional[str], reasons: List[str],
                  stage: str, j=None) -> None:
        """Restore ``prev`` on every replica not serving it. A replica
        that fails even the rollback's health gate is left out of
        rotation (the rolling-restart abort rule) — the rest of the fleet
        keeps serving the previous version."""
        self.rollout = dict(self.rollout, state="rolling_back",
                            reasons=list(reasons))
        sys.stderr.write(
            f"[fleet] deploy ROLLBACK ({stage}): {'; '.join(reasons)}\n")
        for rep in list(self.router._replicas):
            if self._versions.get(rep.name) == prev:
                continue
            info = self._update_replica(rep, prev, readmit=True)
            if j is not None:
                j.event("fleet.rollout", replica="fleet", phase="rollback",
                        target=rep.name, ok=bool(info.get("ok")))
            if not info.get("ok"):
                sys.stderr.write(
                    f"[fleet] rollback: replica {rep.name} failed its "
                    "health gate on the PREVIOUS version — left out of "
                    "rotation\n")
        self.version = prev
        self.stats["rollbacks"] += 1
        self.rollout = {"state": "rolled_back", "version": self.version,
                        "previous": self.previous_version,
                        "replica": None,
                        "updated": [], "reasons": list(reasons)}
        _safe_inc("paddle_fleet_rollbacks_total",
                  "deploys rolled back to the previous bundle", stage=stage)
        _safe_inc("paddle_fleet_rollouts_total",
                  "deploy rollouts finished, by outcome",
                  outcome="rolled_back")
        _flight_record("fleet", "deploy", event="rollback", stage=stage,
                       reasons="; ".join(reasons)[:200])

    def deploy(self, bundle_path: str,
               gate: Optional[Callable[[Dict], List[str]]] = None,
               canary_requests: int = 4,
               canary_prompt=None,
               canary_new_tokens: int = 4,
               canary_timeout: float = 120.0,
               validate: bool = True) -> Dict[str, object]:
        """Zero-downtime continuous deploy of ``bundle_path`` (see module
        docstring for the state machine). Raises
        :class:`~.robustness.DeployError` only when the deploy cannot
        START (validation failure, concurrent deploy); a candidate that
        fails its canary gate or regresses mid-rollout is an EXPECTED
        outcome — the fleet rolls back automatically and the returned
        result carries ``ok=False`` plus the stage and reasons."""
        if not self._deploy_lock.acquire(blocking=False):
            raise DeployError("a deploy is already in flight", stage="start")
        try:
            manifest = None
            if validate:
                try:
                    manifest = _cp.validate_bundle(bundle_path)
                except Exception as e:
                    raise DeployError(
                        f"candidate bundle {bundle_path} failed validation "
                        f"({type(e).__name__}: {e})", stage="validate",
                        reasons=[str(e)]) from e
            prev = self.version
            target = str(bundle_path)
            # the mid-rollout regression bar INHERITS any burn already in
            # the sliding window: a fleet that was burning before the
            # deploy (a traffic spike still inside FLAGS_slo_burn_window_s)
            # must not have that burn attributed to the candidate — only
            # burn the rollout PUSHES PAST this bar triggers rollback
            burn_bar = max(self.policy.rollback_burn,
                           float(self.read_signal()["burn"] or 0.0))
            result: Dict[str, object] = {
                "ok": False, "stage": "canary", "candidate": target,
                "previous": prev, "version": prev, "reasons": [],
                "replicas": [],
                "manifest_version": (manifest or {}).get("version")}
            j = self._journey("fleet-rollout")
            self.rollout = {"state": "canary", "version": target,
                            "previous": prev, "replica": None,
                            "updated": [],
                            "manifest_version": result["manifest_version"]}
            _flight_record("fleet", "deploy", event="begin",
                           candidate=target,
                           version=str(result["manifest_version"]))

            # -- canary: one replica onto the candidate, OUT of rotation --
            reps = [r for r in self.router._replicas if r.in_rotation] \
                or list(self.router._replicas)
            canary = reps[0]
            self.rollout["replica"] = canary.name
            if j is not None:
                j.event("fleet.rollout", replica="fleet", phase="canary",
                        target=canary.name, candidate=target)
            info = self._update_replica(canary, target, readmit=False)
            result["replicas"].append(info)
            if not info.get("ok"):
                result["reasons"] = [
                    f"canary {canary.name} never turned healthy on the "
                    f"candidate (within {self.policy.health_timeout_s:g}s)"]
                self._rollback(prev, result["reasons"], "canary", j)
                self._finish_journey(j, "rejected")
                return dict(result, version=self.version)
            metrics = self._canary_probe(
                canary, canary_requests, canary_prompt, canary_new_tokens,
                canary_timeout)
            result["canary"] = metrics
            reasons = (gate or self._default_gate)(metrics)
            if reasons:
                result["reasons"] = list(reasons)
                self._rollback(prev, result["reasons"], "canary", j)
                self._finish_journey(j, "rejected")
                return dict(result, version=self.version)
            with self._scale_lock:
                # promotion: the canary takes traffic on the new version
                canary.breaker.reset()
                canary.in_rotation = True

            # -- rollout: walk every stale replica (incl. any the
            #    autoscaler adds mid-rollout at the previous version) ----
            self.rollout = dict(self.rollout, state="rolling")
            result["stage"] = "rollout"
            while True:
                # stale check AND promotion share the scale lock: a
                # concurrent scale_up holds it while it builds/joins a
                # replica at self.version, so either its old-version
                # replica is visible to this check (and gets updated) or
                # it starts after the promotion below and builds at the
                # NEW version — never a mixed-version fleet
                with self._scale_lock:
                    stale = [r for r in self.router._replicas
                             if self._versions.get(r.name) != target]
                    if not stale:
                        self.previous_version = prev
                        self.version = target
                        break
                rep = stale[0]
                self.rollout["replica"] = rep.name
                if j is not None:
                    j.event("fleet.rollout", replica="fleet",
                            phase="replica", target=rep.name)
                info = self._update_replica(rep, target, readmit=True)
                result["replicas"].append(info)
                if not info.get("ok"):
                    result["reasons"] = [
                        f"replica {rep.name} failed its health gate on "
                        "the candidate mid-rollout"]
                    self._rollback(prev, result["reasons"], "rollout", j)
                    self._finish_journey(j, "rejected")
                    return dict(result, version=self.version)
                burn = self.read_signal()["burn"]
                if burn is not None and burn > burn_bar:
                    result["reasons"] = [
                        f"slo_burn {burn:.2f} > rollback bar "
                        f"{burn_bar:g} after updating "
                        f"{rep.name}"]
                    self._rollback(prev, result["reasons"], "rollout", j)
                    self._finish_journey(j, "rejected")
                    return dict(result, version=self.version)
                self.rollout["updated"] = \
                    list(self.rollout["updated"]) + [rep.name]

            # -- promoted (version flipped under the lock above) ---------
            self.stats["rollouts"] += 1
            self.rollout = {"state": "done", "version": target,
                            "previous": prev, "replica": None,
                            "updated": [r.name
                                        for r in self.router._replicas],
                            "manifest_version": result["manifest_version"]}
            _safe_inc("paddle_fleet_rollouts_total",
                      "deploy rollouts finished, by outcome", outcome="ok")
            _flight_record("fleet", "deploy", event="done", candidate=target)
            sys.stderr.write(
                f"[fleet] deploy PROMOTED: {target} on "
                f"{len(self.router._replicas)} replicas\n")
            if j is not None:
                j.event("fleet.rollout", replica="fleet", phase="done",
                        candidate=target)
            self._finish_journey(j, "ok")
            return dict(result, ok=True, stage="done", version=target)
        finally:
            self._deploy_lock.release()

    # -- engine surface ------------------------------------------------------
    def submit(self, prompt_ids, **kw):
        return self.router.submit(prompt_ids, **kw)

    def generate(self, prompt_ids, timeout: float = 300.0, **kw):
        return self.router.generate(prompt_ids, timeout=timeout, **kw)

    def drain(self, timeout: Optional[float] = None) -> Dict[str, object]:
        return self.router.drain(timeout)

    def health(self) -> Dict[str, object]:
        """The router's fleet snapshot plus the ``fleet`` control-plane
        block (replica census vs target, last scale decision, rollout
        state/version, burn readings) — what ``obsctl fleet`` renders."""
        h = self.router.health()
        now = time.monotonic()
        last = dict(self.last_decision)
        t = last.pop("t_mono", None)
        last["age_s"] = None if t is None else round(now - t, 3)
        h["fleet"] = {
            "replicas_target": self.target,
            "replicas": len(self.router._replicas),
            "healthy": h.get("router", {}).get("healthy", 0),
            "min_replicas": self.policy.min_replicas,
            "max_replicas": self.policy.max_replicas,
            "version": self.version,
            "previous_version": self.previous_version,
            "versions": dict(self._versions),
            "autoscaler": {
                "running": (self._thread is not None
                            and self._thread.is_alive()),
                "interval_s": self.policy.interval_s,
                "streak": {"hot": self._state["hot"],
                           "idle": self._state["idle"]},
                "last_decision": last,
            },
            "rollout": dict(self.rollout),
            "slo_burn": h.get("slo_burn"),
            "stats": dict(
                self.stats,
                scaleup_to_healthy_s=self.last_scaleup_to_healthy_s),
        }
        return h

    # -- lifecycle -----------------------------------------------------------
    def start(self, autoscaler: bool = True) -> "FleetController":
        """Start the router (+ its prober) and, unless ``autoscaler=
        False`` (tests drive :meth:`_tick` directly), the autoscaler
        loop. Registers the ``fleet`` health provider when an exporter is
        live."""
        self.router.start()
        self._gauge_census()
        if autoscaler and (self._thread is None
                           or not self._thread.is_alive()):
            self._stop.clear()
            self._thread = threading.Thread(
                target=self._loop, daemon=True, name="fleet-autoscaler")
            self._thread.start()
        try:
            from ..observability import exporter as _exporter

            served = _exporter.get()
            if served is not None and self._health_reg_name is None:
                self._health_reg_name = served.register_health(
                    "fleet", self.health, unique=True)
        except Exception:
            pass
        return self

    def _loop(self) -> None:
        while not self._stop.wait(self.policy.interval_s):
            try:
                self._tick()
            except Exception as e:  # the loop must survive a bad tick
                sys.stderr.write(
                    f"[fleet] autoscaler tick failed "
                    f"({type(e).__name__}: {e})\n")

    def stop(self) -> None:
        self._stop.set()
        t, self._thread = self._thread, None
        if t is not None:
            t.join(timeout=5)
        try:
            from ..observability import exporter as _exporter

            served = _exporter.get()
            if served is not None and self._health_reg_name is not None:
                served.unregister_health(self._health_reg_name,
                                         fn=self.health)
                self._health_reg_name = None
        except Exception:
            pass
        self.router.stop()

    def __enter__(self) -> "FleetController":
        return self.start()

    def __exit__(self, exc_type, exc, tb):
        self.stop()
        return False
