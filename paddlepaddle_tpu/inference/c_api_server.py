"""Server half of the C inference API (native/paddle_inference_c.cpp).

Reference surface: paddle/fluid/inference/capi_exp/ — there the C API calls
into the in-process C++ predictor; here the predictor is an XLA program
owned by this Python runtime, so the C library is a native client speaking
a length-prefixed binary protocol over a Unix domain socket (or loopback
TCP), and this module is the listener that executes the program on the
chip. One thread per connection; tensors cross as raw little-endian
buffers (f32/i64/i32/u8).

Beyond the predictor ops (``_OP_RUN/_OP_INFO/_OP_HEALTH/_OP_METRICS``)
the server can front a live :class:`~.serving.ServingEngine` (pass
``engine=``), which arms the replica-process ops the remote fleet is
built on (:mod:`~.remote_replica`):

* ``_OP_SUBMIT`` — STREAMING: one generation request per connection.
  Request kwargs cross as JSON + the prompt as a packed tensor; the
  server answers with chunk frames (status 2: admit / first-token /
  progress events) and exactly one terminal frame — status 0 with the
  SLO stamps, the stitched request-journey spans, and the output tensor,
  or status 3 with a TYPED error document
  (:func:`~.robustness.error_to_wire`) so the client rehydrates the
  same exception class the in-process engine would have raised. A client
  that disconnects mid-stream gets its request cancelled — the decode
  slot (and its KV pages) come back on the next scheduler cycle.
* ``_OP_DRAIN`` — graceful admission close (JSON ``{timeout, reason}``).
* ``_OP_RESTART`` — drain + in-place engine restart for native clients;
  the replica supervisor restarts by SIGTERM/respawn instead.

Wire hardening (the netchaos proxy's counterpart — see
``docs/serving.md`` "Wire-protocol hardening"):

* **frame CRC** — a submit header carrying ``"crc": true`` negotiates
  CRC32-protected frames for that stream: the status byte gains the
  ``_ST_CRC_FLAG`` high bit and a ``<u32 crc32(rest)>`` follows it.
  Legacy clients never set the flag and keep the old frames bit-exact.
* **idempotent submit** — a header ``req_uid`` keys a bounded ring of
  recent terminal results; a resubmit whose uid has a cached terminal
  replays it without decoding again (the ambiguous-failure case: the
  decode finished but the terminal frame was lost on the wire).
* **write deadline + bounded send buffer** — ``SO_SNDTIMEO`` +
  ``SO_SNDBUF`` per connection, so a slow-loris client (reads at
  1 byte/s, or never) sheds with a cancelled request instead of wedging
  this handler thread in ``sendall`` forever.
* **mid-frame read deadline** — once a frame STARTS arriving, the rest
  must land within ``frame_timeout_s`` (idle waits between requests stay
  unbounded — persistent native connections are legal). A trickled or
  abandoned half-frame gets an error frame and a close, bounded-time.
"""

from __future__ import annotations

import json
import os
import select
import socket
import struct
import threading
import zlib
from collections import OrderedDict
from time import perf_counter as _now
from typing import Callable, List, Optional, Sequence, Tuple

import numpy as np

_MAGIC = 0x50444331
_DTYPES = [np.dtype("<f4"), np.dtype("<i8"), np.dtype("<i4"), np.dtype("u1")]
_OP_RUN, _OP_INFO, _OP_HEALTH, _OP_METRICS = 1, 2, 3, 4
_OP_SUBMIT, _OP_DRAIN, _OP_RESTART = 5, 6, 7

# reply statuses. 1 carries a plain text message (the predictor ops'
# legacy form); 3 carries a JSON error document that rehydrates into the
# SAME typed exception client-side (robustness.error_from_wire); 2 is a
# mid-stream submit chunk. Every nonzero status has the same
# <u32 len | payload> body shape, so a legacy native client reading any
# nonzero status as "error text" keeps working.
_ST_OK, _ST_ERR, _ST_CHUNK, _ST_TYPED = 0, 1, 2, 3

# status-byte high bit: the frame payload is CRC-protected —
# <u32 magic><u8 status|0x80><u32 crc32(rest)><rest>. Only set on submit
# streams whose client ASKED (hdr {"crc": true}), so legacy peers never
# see it; the low 7 bits still carry the real status.
_ST_CRC_FLAG = 0x80

# the server heartbeats an idle submit stream this often — exported so
# RemoteReplicaClient can cross-check its watchdog against it (a client
# heartbeat_timeout_s at or below this guarantees spurious stalls)
_HB_INTERVAL_S = 0.5

# a frame length past this is garbage (or an attack), not a request: reply
# with an error frame and close instead of trying to buffer it
_MAX_FRAME = 1 << 28  # 256 MiB


class _FrameStall(Exception):
    """A started frame did not finish within ``frame_timeout_s``."""

    def __init__(self, missing: int):
        super().__init__(f"{missing} bytes missing")
        self.missing = int(missing)


def crc_wrap(frame: bytes) -> bytes:
    """Arm a reply frame's CRC: flag the status byte, splice the checksum
    of everything after it. ``frame`` is ``<u32 magic><u8 status><rest>``."""
    rest = frame[5:]
    return (frame[:4] + bytes([frame[4] | _ST_CRC_FLAG])
            + struct.pack("<I", zlib.crc32(rest)) + rest)


class _ResultRing:
    """Bounded req_uid → terminal-frame cache backing idempotent submit.
    Holds the last ``cap`` OK terminals (raw frames, pre-CRC); a resubmit
    that hits replays the bytes instead of decoding twice. Error
    terminals are NOT cached — a retry after a typed failure must re-run."""

    def __init__(self, cap: int = 256):
        self.cap = int(cap)
        self._d: "OrderedDict[str, bytes]" = OrderedDict()
        self._lock = threading.Lock()
        self.replays = 0

    def put(self, uid: str, frame: bytes) -> None:
        with self._lock:
            self._d[uid] = frame
            self._d.move_to_end(uid)
            while len(self._d) > self.cap:
                self._d.popitem(last=False)

    def get(self, uid: str) -> Optional[bytes]:
        with self._lock:
            frame = self._d.get(uid)
            if frame is not None:
                self._d.move_to_end(uid)
            return frame

    def __len__(self) -> int:
        with self._lock:
            return len(self._d)


def _pack_tensor(name: str, arr: np.ndarray) -> bytes:
    arr = np.ascontiguousarray(arr)
    matches = [i for i, d in enumerate(_DTYPES) if d == arr.dtype.newbyteorder("<")]
    if not matches:
        raise ValueError(
            f"tensor {name!r} has dtype {arr.dtype}, which the C API wire "
            f"format does not carry (supported: float32, int64, int32, "
            f"uint8) — cast the model output first")
    code = matches[0]
    head = struct.pack("<I", len(name)) + name.encode()
    head += struct.pack("<B", code) + struct.pack("<I", arr.ndim)
    head += b"".join(struct.pack("<q", d) for d in arr.shape)
    return head + arr.tobytes()


class _Cursor:
    def __init__(self, buf: bytes):
        self.b, self.o = buf, 0

    def take(self, fmt: str):
        v = struct.unpack_from("<" + fmt, self.b, self.o)
        self.o += struct.calcsize("<" + fmt)
        return v if len(v) > 1 else v[0]

    def raw(self, n: int) -> bytes:
        out = self.b[self.o:self.o + n]
        self.o += n
        return out


def _unpack_tensor(c: _Cursor) -> Tuple[str, np.ndarray]:
    name = c.raw(c.take("I")).decode()
    code = c.take("B")
    ndim = c.take("I")
    dims = [c.take("q") for _ in range(ndim)]
    dt = _DTYPES[code]
    n = int(np.prod(dims)) if dims else 1
    arr = np.frombuffer(c.raw(n * dt.itemsize), dtype=dt).reshape(dims)
    return name, arr


class CApiServer:
    """Serves a Predictor (or any (named inputs) -> [outputs] callable).

    ``health_fn`` (optional) backs the ``_OP_HEALTH`` frame — pass
    ``ServingEngine.health`` (or any () -> dict) and native clients get the
    readiness snapshot as JSON without touching Python. ``metrics_fn``
    (optional) backs the ``_OP_METRICS`` frame — it defaults to the
    process-wide ``observability.to_prometheus_text()``, so a native client
    (or a sidecar scraper with a UDS pipe) can pull the same exposition
    text the HTTP exporter serves; an empty registry yields an OK frame
    with a zero-length payload, not an error."""

    def __init__(self, predictor, socket_path: Optional[str] = None,
                 input_names: Optional[Sequence[str]] = None,
                 output_names: Optional[Sequence[str]] = None,
                 health_fn: Optional[Callable[[], dict]] = None,
                 metrics_fn: Optional[Callable[[], str]] = None,
                 engine=None,
                 port: Optional[int] = None,
                 host: str = "127.0.0.1",
                 heartbeat_interval_s: float = _HB_INTERVAL_S,
                 write_timeout_s: float = 10.0,
                 frame_timeout_s: float = 30.0,
                 send_buffer_bytes: Optional[int] = 256 * 1024,
                 result_cache: int = 256):
        if socket_path is None and port is None:
            raise ValueError("CApiServer needs socket_path= (UDS) or "
                             "port= (loopback TCP)")
        self.predictor = predictor
        self.path = socket_path
        self.port = port          # 0 = ephemeral; real port after start()
        self.host = host
        self.engine = engine      # arms _OP_SUBMIT/_OP_DRAIN/_OP_RESTART
        self.health_fn = health_fn
        self.metrics_fn = metrics_fn
        self.heartbeat_interval_s = float(heartbeat_interval_s)
        self.write_timeout_s = float(write_timeout_s)
        self.frame_timeout_s = float(frame_timeout_s)
        self.send_buffer_bytes = send_buffer_bytes
        self._results = _ResultRing(result_cache)
        if predictor is None:
            self.input_names = list(input_names or [])
            self.output_names = list(output_names or [])
        else:
            self.input_names = list(input_names if input_names is not None
                                    else predictor.get_input_names())
            self.output_names = list(
                output_names if output_names is not None
                else predictor.get_output_names())
        self._sock: Optional[socket.socket] = None
        self._threads: List[threading.Thread] = []
        self._conns: List[socket.socket] = []
        self._conns_lock = threading.Lock()
        self._stop = threading.Event()

    # -- protocol -----------------------------------------------------------
    def _reply_ok(self, body: bytes) -> bytes:
        return struct.pack("<IB", _MAGIC, 0) + body

    def _reply_err(self, msg: str) -> bytes:
        m = msg.encode()[:4096]
        return struct.pack("<IB", _MAGIC, 1) + struct.pack("<I", len(m)) + m

    def _reply_json(self, status: int, doc: dict,
                    tail: bytes = b"") -> bytes:
        blob = json.dumps(doc, default=str).encode()
        return (struct.pack("<IB", _MAGIC, status)
                + struct.pack("<I", len(blob)) + blob + tail)

    def _reply_typed(self, exc: BaseException, **extra) -> bytes:
        from .robustness import error_to_wire

        doc = error_to_wire(exc)
        doc.update(extra)
        return self._reply_json(_ST_TYPED, doc)

    @staticmethod
    def _send_frame(conn: socket.socket, frame: bytes) -> None:
        conn.sendall(struct.pack("<Q", len(frame)) + frame)

    def _handle(self, req: bytes) -> Tuple[bytes, bool]:
        """Returns (reply frame, close_connection). A malformed frame (bad
        magic, truncated payload, garbage tensor header) gets an ERROR
        frame and a close — never an unhandled struct.error that kills the
        connection thread with no reply on the wire."""
        c = _Cursor(req)
        try:
            if c.take("I") != _MAGIC:
                return self._reply_err("bad magic"), True
            op = c.take("B")
        except struct.error:
            return self._reply_err("malformed frame: truncated header"), True
        if op == _OP_INFO:
            body = struct.pack("<I", len(self.input_names))
            for n in self.input_names:
                body += struct.pack("<I", len(n)) + n.encode()
            body += struct.pack("<I", len(self.output_names))
            for n in self.output_names:
                body += struct.pack("<I", len(n)) + n.encode()
            return self._reply_ok(body), False
        if op == _OP_HEALTH:
            try:
                snap = self.health_fn() if self.health_fn is not None \
                    else {"state": "serving", "ok": True}
                payload = json.dumps(snap, default=str).encode()
            except Exception as e:
                return self._reply_err(f"health probe failed: {e}"), False
            return (self._reply_ok(struct.pack("<I", len(payload)) + payload),
                    False)
        if op == _OP_METRICS:
            try:
                if self.metrics_fn is not None:
                    text = self.metrics_fn()
                else:
                    from ..observability import to_prometheus_text

                    text = to_prometheus_text()
                payload = text.encode()
            except Exception as e:
                return self._reply_err(f"metrics scrape failed: {e}"), False
            return (self._reply_ok(struct.pack("<I", len(payload)) + payload),
                    False)
        if op == _OP_DRAIN:
            if self.engine is None:
                return self._reply_err("no serving engine attached"), False
            try:
                kw = {}
                if c.o < len(c.b):
                    kw = json.loads(c.raw(c.take("I")).decode() or "{}")
                res = self.engine.drain(kw.get("timeout"),
                                        reason=kw.get("reason", "drain"))
                return self._reply_json(_ST_OK, dict(res)), False
            except Exception as e:
                return self._reply_typed(e), False
        if op == _OP_RESTART:
            if self.engine is None:
                return self._reply_err("no serving engine attached"), False
            try:
                kw = {}
                if c.o < len(c.b):
                    kw = json.loads(c.raw(c.take("I")).decode() or "{}")
                self.engine.drain(kw.get("timeout"), reason="restart")
                self.engine.start()
                return self._reply_json(
                    _ST_OK, {"ok": True,
                             "health": self.engine.health()}), False
            except Exception as e:
                return self._reply_typed(e), False
        if op != _OP_RUN:
            return self._reply_err(f"unknown op {op}"), False
        try:
            n = c.take("I")
            named = dict(_unpack_tensor(c) for _ in range(n))
        except Exception:  # struct.error / bad dtype code / absurd dims
            return (self._reply_err("malformed frame: truncated or invalid "
                                    "tensor payload"), True)
        try:
            inputs = [named[k] for k in self.input_names]
            outs = self.predictor.run(inputs)
            # the name snapshot may predate the first run (Predictor only
            # knows its real output arity after running) — never let the
            # declared count and the serialized tensors disagree
            names = (self.output_names if len(self.output_names) == len(outs)
                     else [f"output_{i}" for i in range(len(outs))])
            self.output_names = names
            body = struct.pack("<I", len(outs))
            for name, o in zip(names, outs):
                body += _pack_tensor(name, np.asarray(o))
            return self._reply_ok(body), False
        except Exception as e:  # surfaced as PD_PredictorGetLastError
            return self._reply_err(f"{type(e).__name__}: {e}"), False

    # -- streaming submit (one request per connection) -----------------------
    def _handle_submit(self, c: _Cursor, conn: socket.socket) -> None:
        """``_OP_SUBMIT``: parse kwargs + prompt, submit to the engine,
        stream lifecycle chunks, finish with ONE terminal frame (typed
        error or SLO header + output tensor). The connection is this
        request's: it closes when the frame lands. A half-written stream
        whose client disconnected cancels the request, releasing its
        decode slot and KV pages — a dead client must not leak pages.

        Hardening seams (all negotiated by the CLIENT's header, so legacy
        peers are untouched): ``"crc": true`` arms CRC32 frames for this
        stream; ``"req_uid"`` keys the idempotent-resubmit ring — a uid
        whose terminal is cached REPLAYS it, zero re-decode. Writes ride
        the connection's ``SO_SNDTIMEO``: a client that stops reading
        (slow-loris) trips it, the request is cancelled and the decode
        slot released instead of this thread wedging in ``sendall``."""
        from .robustness import RequestValidationError, error_to_wire
        from .robustness import safe_inc as _safe_inc

        eng = self.engine
        try:
            hdr = json.loads(c.raw(c.take("I")).decode())
            if not isinstance(hdr, dict):
                raise ValueError("submit kwargs must be a JSON object")
            _, prompt = _unpack_tensor(c)
        except Exception:
            self._send_frame(conn, self._reply_typed(RequestValidationError(
                "malformed _OP_SUBMIT frame: truncated or invalid "
                "kwargs/prompt payload")))
            return
        crc = bool(hdr.pop("crc", False))
        uid = hdr.pop("req_uid", None)

        def send(frame: bytes) -> None:
            self._send_frame(conn, crc_wrap(frame) if crc else frame)

        if eng is None:
            send(self._reply_typed(RequestValidationError(
                "this server has no serving engine attached "
                "(predictor-only endpoint)")))
            return
        if uid:
            cached = self._results.get(str(uid))
            if cached is not None:
                # idempotent resubmit: this uid already decoded to a
                # terminal once — its frame was (presumably) lost on the
                # wire. Replay the cached bytes: token-exact by
                # construction, zero engine work, never a double decode
                self._results.replays += 1
                _safe_inc("paddle_capi_dedup_replays_total",
                          "resubmits served from the terminal-result ring "
                          "instead of decoding again")
                try:
                    send(self._reply_json(_ST_CHUNK, {"ev": "accepted"}))
                    send(self._reply_json(_ST_CHUNK, {"ev": "replay"}))
                    send(cached)
                except OSError:
                    pass
                return
        journey = None
        tr = hdr.pop("trace", None)
        if isinstance(tr, dict):
            # a wire journey: a plain span collector carrying the parent
            # trace id — NOT registered in this process's in-flight ring
            # (the client owns the journey; replica-side spans travel
            # back in the terminal frame and are stitched there)
            try:
                from ..observability import reqtrace as _rt

                journey = _rt.Journey(tr.get("req_id"), 256)
                journey.trace_id = str(tr.get("trace_id")
                                       or journey.trace_id)
            except Exception:
                journey = None
        kw = {k: hdr[k] for k in ("max_new_tokens", "temperature", "top_k",
                                  "eos_token_id", "deadline_s",
                                  "prefix_len")
              if hdr.get(k) is not None}
        if journey is not None:
            kw["trace"] = journey
        try:
            fut = eng.submit(prompt, **kw)
        except Exception as e:       # typed admission refusal, validation
            send(self._reply_typed(e))
            return
        try:
            # the client's submit() blocks on this first frame: accepted
            # here mirrors the in-process contract where a returning
            # submit() call IS the admission decision
            send(self._reply_json(_ST_CHUNK, {"ev": "accepted"}))
            sent_admit = sent_first = False
            last_n = 0
            last_tx = _now()
            while not fut._event.wait(0.005):
                # disconnect probe: the client never writes after the
                # request frame, so any EOF here means it went away
                try:
                    if conn.recv(1, socket.MSG_DONTWAIT) == b"":
                        fut.cancel()
                        return
                except (BlockingIOError, InterruptedError):
                    pass
                except OSError:
                    fut.cancel()
                    return
                events = []
                if not sent_admit and fut._t_admit is not None:
                    sent_admit = True
                    events.append({"ev": "admit"})
                if not sent_first and fut._t_first is not None:
                    sent_first = True
                    last_n = fut._n_at_first
                    events.append({"ev": "first", "n": fut._n_at_first})
                if sent_first and fut._n_new > last_n:
                    last_n = fut._n_new
                    events.append({"ev": "progress", "n": last_n})
                if (not events
                        and _now() - last_tx > self.heartbeat_interval_s):
                    # heartbeat: a long decode with nothing to report
                    # must not read as a dead replica to the client's
                    # stall watchdog
                    events.append({"ev": "hb"})
                for ev in events:
                    send(self._reply_json(_ST_CHUNK, ev))
                if events:
                    last_tx = _now()
            err = fut._error
            if err is not None:
                doc = error_to_wire(err)
                if journey is not None:
                    doc["journey"] = self._journey_wire(journey)
                send(self._reply_json(_ST_TYPED, doc))
                return
            out = np.ascontiguousarray(np.asarray(fut._output))
            head = {
                "n_new": fut._n_new,
                "n_at_first": fut._n_at_first,
                "streaming": bool(fut._streaming),
                # lifecycle stamps as offsets from the REPLICA-side
                # submit: the client re-anchors them on its own clock
                "admit_rel": (None if fut._t_admit is None
                              else fut._t_admit - fut._t_submit),
                "first_rel": (None if fut._t_first is None
                              else fut._t_first - fut._t_submit),
                "done_rel": (None if fut._t_done is None
                             else fut._t_done - fut._t_submit),
            }
            if journey is not None:
                head["journey"] = self._journey_wire(journey)
            terminal = self._reply_json(
                _ST_OK, head, _pack_tensor("output_ids", out))
            if uid:
                # cache BEFORE the send: the case dedup exists for is the
                # terminal frame dying on the wire after decode finished
                self._results.put(str(uid), terminal)
            send(terminal)
        except (socket.timeout, BlockingIOError):
            # the per-connection write deadline (SO_SNDTIMEO) tripped:
            # the client reads too slowly to drain our bounded send
            # buffer (slow-loris) — shed it and release the decode slot
            # instead of wedging this handler thread in sendall
            _safe_inc("paddle_capi_write_timeouts_total",
                      "submit streams shed because the client stopped "
                      "draining its socket before the write deadline")
            try:
                from ..observability import flight
                flight.record("capi", "write_timeout",
                              timeout_s=self.write_timeout_s)
            except Exception:
                pass
            fut.cancel()
        except OSError:
            # client went away mid-stream (BrokenPipe/reset): release the
            # slot — kv.pages_free must come back to its idle value
            fut.cancel()
        finally:
            if not fut.done():
                fut.cancel()

    @staticmethod
    def _journey_wire(j) -> dict:
        return {"trace_id": j.trace_id, "t0_wall": j.t0_wall,
                "spans": list(j.spans), "dropped": j.dropped}

    # -- transport ----------------------------------------------------------
    def _recv_within(self, conn: socket.socket, n: int,
                     deadline: float) -> Optional[bytes]:
        """Read exactly ``n`` bytes before ``deadline`` (monotonic).
        Returns None on EOF, raises :class:`_FrameStall` on deadline.
        select-based so it composes with the connection's blocking
        mode — ``settimeout`` would also put ``recv(1, MSG_DONTWAIT)``
        disconnect probes to sleep, breaking the 5 ms submit poll loop."""
        buf = b""
        while len(buf) < n:
            left = deadline - _now()
            if left <= 0:
                raise _FrameStall(n - len(buf))
            r, _, _ = select.select([conn], [], [], min(left, 1.0))
            if not r:
                continue
            chunk = conn.recv(min(1 << 20, n - len(buf)))
            if not chunk:
                return None
            buf += chunk
        return buf

    def _serve_conn(self, conn: socket.socket):
        from .robustness import safe_inc as _safe_inc

        try:
            # bounded send buffer + kernel write deadline: a peer that
            # stops reading makes sendall raise (socket.timeout /
            # BlockingIOError) after write_timeout_s instead of wedging
            # this thread for the life of the connection
            try:
                if self.send_buffer_bytes:
                    conn.setsockopt(socket.SOL_SOCKET, socket.SO_SNDBUF,
                                    int(self.send_buffer_bytes))
                if self.write_timeout_s:
                    sec = int(self.write_timeout_s)
                    usec = int((self.write_timeout_s - sec) * 1e6)
                    conn.setsockopt(socket.SOL_SOCKET, socket.SO_SNDTIMEO,
                                    struct.pack("ll", sec, usec))
            except OSError:
                pass   # non-fatal: platform without the sockopt
            with conn:
                while not self._stop.is_set():
                    # the wait for a frame's FIRST byte is unbounded — a
                    # persistent legacy connection may idle between ops.
                    # Once a frame starts, the rest must land within
                    # frame_timeout_s or the peer is stalling us mid-frame
                    first = conn.recv(1)
                    if not first:
                        return
                    deadline = _now() + self.frame_timeout_s
                    try:
                        rest = self._recv_within(conn, 7, deadline)
                        if rest is None:
                            return
                        (length,) = struct.unpack("<Q", first + rest)
                        if length > _MAX_FRAME:
                            # status 1 (not typed): the op byte lives
                            # inside the payload we refuse to buffer, so
                            # the peer may be a legacy native client —
                            # keep the legacy error-frame contract here
                            reply = self._reply_err(
                                f"frame length {length} exceeds max "
                                f"{_MAX_FRAME} bytes")
                            conn.sendall(
                                struct.pack("<Q", len(reply)) + reply)
                            return
                        buf = self._recv_within(conn, length, deadline)
                        if buf is None:
                            return
                    except _FrameStall as st:
                        # a frame started but never finished: the peer is
                        # stalling us mid-frame (trunc chaos, wedged
                        # client). Typed close in bounded time — never a
                        # handler thread parked on recv forever
                        _safe_inc(
                            "paddle_capi_frame_timeouts_total",
                            "connections closed because a started frame "
                            "did not complete within frame_timeout_s")
                        try:
                            reply = self._reply_err(
                                f"frame read timed out mid-frame: "
                                f"{st.missing} bytes still missing after "
                                f"{self.frame_timeout_s:.0f}s")
                            conn.sendall(
                                struct.pack("<Q", len(reply)) + reply)
                        except OSError:
                            pass
                        return
                    if (len(buf) >= 5
                            and struct.unpack_from("<IB", buf)
                            == (_MAGIC, _OP_SUBMIT)):
                        # streaming op: owns the connection, one request
                        # per connection, closes when the terminal frame
                        # (or the client) goes away
                        c = _Cursor(buf)
                        c.take("I")
                        c.take("B")
                        self._handle_submit(c, conn)
                        return
                    reply, close = self._handle(buf)
                    conn.sendall(struct.pack("<Q", len(reply)) + reply)
                    if close:
                        return
        finally:
            with self._conns_lock:
                try:
                    self._conns.remove(conn)
                except ValueError:
                    pass   # stop() already cleared the list

    def start(self):
        if self.port is not None:
            self._sock = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
            self._sock.setsockopt(socket.SOL_SOCKET,
                                  socket.SO_REUSEADDR, 1)
            self._sock.bind((self.host, self.port))
            self.port = self._sock.getsockname()[1]   # resolve port 0
        else:
            if os.path.exists(self.path):
                os.unlink(self.path)
            self._sock = socket.socket(socket.AF_UNIX, socket.SOCK_STREAM)
            self._sock.bind(self.path)
        self._sock.listen(8)

        def accept_loop():
            while not self._stop.is_set():
                try:
                    conn, _ = self._sock.accept()
                except OSError:
                    return
                t = threading.Thread(target=self._serve_conn, args=(conn,),
                                     daemon=True)
                t.start()
                with self._conns_lock:
                    self._conns.append(conn)
                # prune finished handlers so a long-lived server does not
                # accumulate dead Thread objects per connection
                self._threads = [x for x in self._threads if x.is_alive()]
                self._threads.append(t)

        t = threading.Thread(target=accept_loop, daemon=True)
        t.start()
        self._threads.append(t)
        return self

    def stop(self):
        self._stop.set()
        if self._sock is not None:
            self._sock.close()
        with self._conns_lock:
            conns, self._conns = self._conns[:], []
        for conn in conns:            # unblock handlers waiting in recv
            try:
                conn.shutdown(socket.SHUT_RDWR)
            except OSError:
                pass
        if self.path is not None and os.path.exists(self.path):
            os.unlink(self.path)

    def __enter__(self):
        return self.start()

    def __exit__(self, *exc):
        self.stop()


def serve_predictor(predictor, socket_path: str,
                    health_fn: Optional[Callable[[], dict]] = None
                    ) -> CApiServer:
    """Start serving ``predictor`` for native clients; returns the server."""
    return CApiServer(predictor, socket_path, health_fn=health_fn).start()
