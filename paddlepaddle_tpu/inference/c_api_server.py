"""Server half of the C inference API (native/paddle_inference_c.cpp).

Reference surface: paddle/fluid/inference/capi_exp/ — there the C API calls
into the in-process C++ predictor; here the predictor is an XLA program
owned by this Python runtime, so the C library is a native client speaking
a length-prefixed binary protocol over a Unix domain socket, and this
module is the listener that executes the program on the chip. One thread
per connection; tensors cross as raw little-endian buffers (f32/i64/i32/u8).
"""

from __future__ import annotations

import json
import os
import socket
import struct
import threading
from typing import Callable, List, Optional, Sequence, Tuple

import numpy as np

_MAGIC = 0x50444331
_DTYPES = [np.dtype("<f4"), np.dtype("<i8"), np.dtype("<i4"), np.dtype("u1")]
_OP_RUN, _OP_INFO, _OP_HEALTH, _OP_METRICS = 1, 2, 3, 4

# a frame length past this is garbage (or an attack), not a request: reply
# with an error frame and close instead of trying to buffer it
_MAX_FRAME = 1 << 28  # 256 MiB


def _pack_tensor(name: str, arr: np.ndarray) -> bytes:
    arr = np.ascontiguousarray(arr)
    matches = [i for i, d in enumerate(_DTYPES) if d == arr.dtype.newbyteorder("<")]
    if not matches:
        raise ValueError(
            f"tensor {name!r} has dtype {arr.dtype}, which the C API wire "
            f"format does not carry (supported: float32, int64, int32, "
            f"uint8) — cast the model output first")
    code = matches[0]
    head = struct.pack("<I", len(name)) + name.encode()
    head += struct.pack("<B", code) + struct.pack("<I", arr.ndim)
    head += b"".join(struct.pack("<q", d) for d in arr.shape)
    return head + arr.tobytes()


class _Cursor:
    def __init__(self, buf: bytes):
        self.b, self.o = buf, 0

    def take(self, fmt: str):
        v = struct.unpack_from("<" + fmt, self.b, self.o)
        self.o += struct.calcsize("<" + fmt)
        return v if len(v) > 1 else v[0]

    def raw(self, n: int) -> bytes:
        out = self.b[self.o:self.o + n]
        self.o += n
        return out


def _unpack_tensor(c: _Cursor) -> Tuple[str, np.ndarray]:
    name = c.raw(c.take("I")).decode()
    code = c.take("B")
    ndim = c.take("I")
    dims = [c.take("q") for _ in range(ndim)]
    dt = _DTYPES[code]
    n = int(np.prod(dims)) if dims else 1
    arr = np.frombuffer(c.raw(n * dt.itemsize), dtype=dt).reshape(dims)
    return name, arr


class CApiServer:
    """Serves a Predictor (or any (named inputs) -> [outputs] callable).

    ``health_fn`` (optional) backs the ``_OP_HEALTH`` frame — pass
    ``ServingEngine.health`` (or any () -> dict) and native clients get the
    readiness snapshot as JSON without touching Python. ``metrics_fn``
    (optional) backs the ``_OP_METRICS`` frame — it defaults to the
    process-wide ``observability.to_prometheus_text()``, so a native client
    (or a sidecar scraper with a UDS pipe) can pull the same exposition
    text the HTTP exporter serves; an empty registry yields an OK frame
    with a zero-length payload, not an error."""

    def __init__(self, predictor, socket_path: str,
                 input_names: Optional[Sequence[str]] = None,
                 output_names: Optional[Sequence[str]] = None,
                 health_fn: Optional[Callable[[], dict]] = None,
                 metrics_fn: Optional[Callable[[], str]] = None):
        self.predictor = predictor
        self.path = socket_path
        self.health_fn = health_fn
        self.metrics_fn = metrics_fn
        self.input_names = list(input_names if input_names is not None
                                else predictor.get_input_names())
        self.output_names = list(output_names if output_names is not None
                                 else predictor.get_output_names())
        self._sock: Optional[socket.socket] = None
        self._threads: List[threading.Thread] = []
        self._conns: List[socket.socket] = []
        self._conns_lock = threading.Lock()
        self._stop = threading.Event()

    # -- protocol -----------------------------------------------------------
    def _reply_ok(self, body: bytes) -> bytes:
        return struct.pack("<IB", _MAGIC, 0) + body

    def _reply_err(self, msg: str) -> bytes:
        m = msg.encode()[:4096]
        return struct.pack("<IB", _MAGIC, 1) + struct.pack("<I", len(m)) + m

    def _handle(self, req: bytes) -> Tuple[bytes, bool]:
        """Returns (reply frame, close_connection). A malformed frame (bad
        magic, truncated payload, garbage tensor header) gets an ERROR
        frame and a close — never an unhandled struct.error that kills the
        connection thread with no reply on the wire."""
        c = _Cursor(req)
        try:
            if c.take("I") != _MAGIC:
                return self._reply_err("bad magic"), True
            op = c.take("B")
        except struct.error:
            return self._reply_err("malformed frame: truncated header"), True
        if op == _OP_INFO:
            body = struct.pack("<I", len(self.input_names))
            for n in self.input_names:
                body += struct.pack("<I", len(n)) + n.encode()
            body += struct.pack("<I", len(self.output_names))
            for n in self.output_names:
                body += struct.pack("<I", len(n)) + n.encode()
            return self._reply_ok(body), False
        if op == _OP_HEALTH:
            try:
                snap = self.health_fn() if self.health_fn is not None \
                    else {"state": "serving", "ok": True}
                payload = json.dumps(snap, default=str).encode()
            except Exception as e:
                return self._reply_err(f"health probe failed: {e}"), False
            return (self._reply_ok(struct.pack("<I", len(payload)) + payload),
                    False)
        if op == _OP_METRICS:
            try:
                if self.metrics_fn is not None:
                    text = self.metrics_fn()
                else:
                    from ..observability import to_prometheus_text

                    text = to_prometheus_text()
                payload = text.encode()
            except Exception as e:
                return self._reply_err(f"metrics scrape failed: {e}"), False
            return (self._reply_ok(struct.pack("<I", len(payload)) + payload),
                    False)
        if op != _OP_RUN:
            return self._reply_err(f"unknown op {op}"), False
        try:
            n = c.take("I")
            named = dict(_unpack_tensor(c) for _ in range(n))
        except Exception:  # struct.error / bad dtype code / absurd dims
            return (self._reply_err("malformed frame: truncated or invalid "
                                    "tensor payload"), True)
        try:
            inputs = [named[k] for k in self.input_names]
            outs = self.predictor.run(inputs)
            # the name snapshot may predate the first run (Predictor only
            # knows its real output arity after running) — never let the
            # declared count and the serialized tensors disagree
            names = (self.output_names if len(self.output_names) == len(outs)
                     else [f"output_{i}" for i in range(len(outs))])
            self.output_names = names
            body = struct.pack("<I", len(outs))
            for name, o in zip(names, outs):
                body += _pack_tensor(name, np.asarray(o))
            return self._reply_ok(body), False
        except Exception as e:  # surfaced as PD_PredictorGetLastError
            return self._reply_err(f"{type(e).__name__}: {e}"), False

    # -- transport ----------------------------------------------------------
    def _serve_conn(self, conn: socket.socket):
        try:
            with conn:
                while not self._stop.is_set():
                    head = b""
                    while len(head) < 8:
                        chunk = conn.recv(8 - len(head))
                        if not chunk:
                            return
                        head += chunk
                    (length,) = struct.unpack("<Q", head)
                    if length > _MAX_FRAME:
                        reply = self._reply_err(
                            f"frame length {length} exceeds max "
                            f"{_MAX_FRAME} bytes")
                        conn.sendall(struct.pack("<Q", len(reply)) + reply)
                        return
                    buf = b""
                    while len(buf) < length:
                        chunk = conn.recv(min(1 << 20, length - len(buf)))
                        if not chunk:
                            return
                        buf += chunk
                    reply, close = self._handle(buf)
                    conn.sendall(struct.pack("<Q", len(reply)) + reply)
                    if close:
                        return
        finally:
            with self._conns_lock:
                try:
                    self._conns.remove(conn)
                except ValueError:
                    pass   # stop() already cleared the list

    def start(self):
        if os.path.exists(self.path):
            os.unlink(self.path)
        self._sock = socket.socket(socket.AF_UNIX, socket.SOCK_STREAM)
        self._sock.bind(self.path)
        self._sock.listen(8)

        def accept_loop():
            while not self._stop.is_set():
                try:
                    conn, _ = self._sock.accept()
                except OSError:
                    return
                t = threading.Thread(target=self._serve_conn, args=(conn,),
                                     daemon=True)
                t.start()
                with self._conns_lock:
                    self._conns.append(conn)
                # prune finished handlers so a long-lived server does not
                # accumulate dead Thread objects per connection
                self._threads = [x for x in self._threads if x.is_alive()]
                self._threads.append(t)

        t = threading.Thread(target=accept_loop, daemon=True)
        t.start()
        self._threads.append(t)
        return self

    def stop(self):
        self._stop.set()
        if self._sock is not None:
            self._sock.close()
        with self._conns_lock:
            conns, self._conns = self._conns[:], []
        for conn in conns:            # unblock handlers waiting in recv
            try:
                conn.shutdown(socket.SHUT_RDWR)
            except OSError:
                pass
        if os.path.exists(self.path):
            os.unlink(self.path)

    def __enter__(self):
        return self.start()

    def __exit__(self, *exc):
        self.stop()


def serve_predictor(predictor, socket_path: str,
                    health_fn: Optional[Callable[[], dict]] = None
                    ) -> CApiServer:
    """Start serving ``predictor`` for native clients; returns the server."""
    return CApiServer(predictor, socket_path, health_fn=health_fn).start()
