"""Continuous-batching decode engine — slot-based KV pool, ragged lengths.

Reference surface: the serving-grade batched attention stack —
paddle/phi/kernels/fusion/gpu/block_multi_head_attention_kernel.cu (paged,
blocked KV) surfaced via python/paddle/incubate/nn/functional/
block_multihead_attention.py, plus the fused-transformer decode loop.

TPU-native redesign: block tables and page indirection exist on GPU because
the allocator hands out scattered pages; under XLA the idiomatic equivalent
is a STATIC slot-contiguous KV pool [slots, max_len, kvh, hd] per layer with
per-slot length counters — same admission/eviction flexibility (a slot is a
page-run), zero gather indirection in the attention inner loop, and every
shape static so each program compiles ONCE:

* PREFILL/DECODE SPLIT: admission is ONE compiled call (per prompt-length
  bucket) that prefills the sequence through a scratch cache, scatters its
  K/V prefix into the pool slot, samples the first token, and updates every
  per-slot state vector in-graph. Decode is one compiled multi-step program
  over ALL slots (b=slots, s=1) with PER-SLOT positions (ragged lengths) —
  rope, cache writes, and causal masking all index by the slot's own length
  (models/llama.py _cached_attention vector pos path).
* DEVICE-RESIDENT BOOKKEEPING: lens/tokens/active/temps/eos live on device;
  eos and budget termination happen in-graph. The host syncs ONCE per
  decode chunk (a packed [slots, chunk+1] array of emitted tokens + active
  flags): on the tunneled platform every host sync costs up to ~100 ms RTT
  (BASELINE.md), so per-admit or per-token syncs would drown the chip —
  the first engine draft did exactly that and measured 0.4x a SINGLE
  sequence; this design is what makes batching actually win.
* CONTINUOUS BATCHING: finished slots (eos / budget) retire and free slots
  admit queued requests mid-flight; per-slot sampling params ride device
  vectors, so mixed requests share one program.
"""

from __future__ import annotations

import time
from typing import Dict, List, Optional

import jax
import jax.numpy as jnp
import numpy as np

from ..core import autograd as _ag
from ..core.dispatch import unwrap


def _bucket(n: int, q: int = 128) -> int:
    return -(-n // q) * q


_perf_mod = None


def _perf():
    """Cached accessor for the perf-attribution plane; the off path costs
    one global read + attribute check per COLD call site (program build,
    chunk boundary) — never per token."""
    global _perf_mod
    if _perf_mod is None:
        try:
            from ..observability import perf as p
        except Exception:
            return None
        _perf_mod = p
    return _perf_mod


def _flight_record(kind: str, name: str, **data) -> None:
    """Request-lifecycle feed into the crash flight recorder (no-op one
    global check when the black box is disarmed)."""
    try:
        from ..observability import flight

        flight.record(kind, name, **data)
    except Exception:
        pass


def _stamp(req, attr: str, value=None) -> None:
    """Best-effort SLO timestamp on the request's result future —
    engine-shaped foreign request objects (tests, benches) without a
    GenerationResult simply don't get stamped."""
    try:
        setattr(req.result, attr,
                time.perf_counter() if value is None else value)
    except Exception:
        pass


class _Slot:
    __slots__ = ("req", "emitted", "budget")

    def __init__(self, req=None, budget=0):
        self.req = req
        self.emitted: List[int] = []
        self.budget = budget


class BatchDecodeEngine:
    """Slot-based continuous-batching decoder for LlamaForCausalLM-shaped
    models (anything exposing ``.model(ids, caches=…, pos=…)``, ``.config``
    and ``.functional_state()``)."""

    def __init__(self, model, max_slots: int = 16, max_len: Optional[int] = None,
                 chunk: int = 16, quant: Optional[str] = None,
                 quant_group_size: int = -1):
        cfg = model.config
        self.model = model
        self.cfg = cfg
        self.S = int(max_slots)
        self.L = int(max_len or cfg.max_position_embeddings)
        self.chunk = int(chunk)
        self.params = model.functional_state()
        # weight-only quantization: params quantized ONCE here; every
        # compiled program after this point (admission prefill + the
        # scan-decode body) reads int8 weight buffers through the
        # QuantizedWeight pytree leaves — cache layout, donation
        # (caches only) and bucketed shapes are untouched. Single-chip
        # decode is HBM-bandwidth-bound, so halving weight bytes read per
        # step is the serving perf lever (tools/quant_ab.py measures it).
        self.quant = quant
        self.quant_meta: Dict[str, object] = {}
        if quant is not None:
            if quant != "weight_only_int8":
                raise ValueError(
                    f"quant={quant!r}: 'weight_only_int8' is the supported "
                    "decode-engine scheme (int4/PTQ honestly absent — "
                    "PARITY.md)")
            from ..nn.quant import quantize_param_tree

            self.params, self.quant_meta = quantize_param_tree(
                self.params, algo=quant, group_size=quant_group_size)
        kvh, hd = cfg.num_key_value_heads, cfg.head_dim
        dtype = jnp.bfloat16 if cfg.dtype == "bfloat16" else jnp.float32
        self.caches = [(jnp.zeros((self.S, self.L, kvh, hd), dtype),
                        jnp.zeros((self.S, self.L, kvh, hd), dtype))
                       for _ in range(cfg.num_hidden_layers)]
        # device-resident per-slot state: [lens, tokens, active, budgets]
        self.lens = jnp.zeros((self.S,), jnp.int32)
        self.tokens = jnp.zeros((self.S,), jnp.int32)     # last emitted token
        self.active = jnp.zeros((self.S,), bool)
        self.temps = jnp.zeros((self.S,), jnp.float32)
        self.eos_ids = jnp.full((self.S,), -1, jnp.int32)  # -1 = no eos
        self.budgets = jnp.zeros((self.S,), jnp.int32)     # new tokens left
        self.top_ks = jnp.zeros((self.S,), jnp.int32)      # 0 = no filter
        self.key = jax.random.PRNGKey(0)
        self._admit_fns: Dict[int, object] = {}
        self._decode_fn = jax.jit(self._decode_program(self.chunk),
                                  donate_argnums=(1,))
        self._decode_captured = False
        self._host_slots = [_Slot() for _ in range(self.S)]
        self._first_pending: Dict[int, object] = {}  # slot -> device scalar
        self.stats = {"tokens_out": 0, "requests": 0, "decode_calls": 0}

    # -- compiled pieces ----------------------------------------------------
    def _forward(self, params, toks, caches, pos):
        """One model step: toks [b, s] -> (logits, caches')."""
        with _ag.no_grad(), self.model.bind_state(params):
            hidden, new_caches = self.model.model(toks, caches=caches, pos=pos)
            if self.model.lm_head is None:
                logits = unwrap(hidden) @ unwrap(
                    self.model.model.embed_tokens.weight).T
            else:
                logits = unwrap(self.model.lm_head(hidden))
        return logits, [(unwrap(k), unwrap(v)) for k, v in new_caches]

    TOP_K_CAP = 128  # static bound for the in-graph per-slot top-k filter

    def _sample(self, rows, temps, top_ks, key):
        """Per-slot sampling: temp==0 -> greedy, else categorical at temp,
        optionally restricted to the slot's top_k logits (k <= TOP_K_CAP;
        one static top_k of the cap serves every slot's k)."""
        kcap = min(self.TOP_K_CAP, rows.shape[-1])
        topv = jax.lax.top_k(rows, kcap)[0]               # [slots, kcap] desc
        kth = jnp.take_along_axis(
            topv, jnp.clip(top_ks[:, None] - 1, 0, kcap - 1), axis=1)
        rows = jnp.where((top_ks[:, None] > 0) & (rows < kth), -jnp.inf, rows)
        greedy = jnp.argmax(rows, axis=-1).astype(jnp.int32)
        scaled = rows / jnp.maximum(temps[:, None], 1e-6)
        sampled = jax.random.categorical(key, scaled).astype(jnp.int32)
        return jnp.where(temps <= 0.0, greedy, sampled)

    def _admit_impl(self, params, caches, lens, tokens, active, temps,
                    eos_ids, budgets, top_ks, ids, plen, slot, temp, eos,
                    budget, top_k, key):
        """ONE compiled admission: prefill ids[1, bucket] through a scratch
        cache, scatter the K/V prefix into pool slot ``slot``, sample the
        first token, set every per-slot state element. No host syncs."""
        bucket = ids.shape[1]
        kvh, hd = self.cfg.num_key_value_heads, self.cfg.head_dim
        dtype = caches[0][0].dtype
        scratch = [(jnp.zeros((1, bucket, kvh, hd), dtype),
                    jnp.zeros((1, bucket, kvh, hd), dtype))
                   for _ in range(self.cfg.num_hidden_layers)]
        logits, scratch = self._forward(params, ids, scratch, jnp.int32(0))
        row = logits[0, plen - 1].astype(jnp.float32)
        key, sub = jax.random.split(key)
        first = self._sample(row[None], temp[None], top_k[None], sub)[0]
        out_caches = []
        zero = jnp.int32(0)
        for (kc, vc), (ks, vs) in zip(caches, scratch):
            kc = jax.lax.dynamic_update_slice(kc, ks, (slot, zero, zero, zero))
            vc = jax.lax.dynamic_update_slice(vc, vs, (slot, zero, zero, zero))
            out_caches.append((kc, vc))
        # the slot is born inactive when its first token already ends it
        done = ((eos >= 0) & (first == eos)) | (budget <= 1)
        return (out_caches,
                lens.at[slot].set(plen),
                tokens.at[slot].set(first),
                active.at[slot].set(~done),
                temps.at[slot].set(temp),
                eos_ids.at[slot].set(eos),
                budgets.at[slot].set(budget - 1),
                top_ks.at[slot].set(top_k),
                key, first)

    def _decode_program(self, n_steps: int):
        """``n_steps`` decode steps over all slots in one program; per-slot
        eos (-1 = none) and budget countdown in-graph. Returns the packed
        [slots, n_steps+1] int32 host-sync payload (emitted tokens, -1
        where idle, last column = active flag). A factory so the perf
        plane can lower an ``n_steps=1`` variant for cost capture — XLA's
        cost analysis counts a scan body ONCE regardless of trip count,
        so the chunk program's own count would under-report by ~chunk."""

        def impl(params, caches, tokens, lens, active, temps,
                 eos_ids, budgets, top_ks, key):
            def body(carry, _):
                caches, tokens, lens, active, budgets, key = carry
                logits, caches = self._forward(params, tokens[:, None],
                                               caches, lens)
                rows = logits[:, 0].astype(jnp.float32)
                key, sub = jax.random.split(key)
                nxt = self._sample(rows, temps, top_ks, sub)
                nxt = jnp.where(active, nxt, tokens)    # frozen when inactive
                lens = lens + active.astype(jnp.int32)
                emitted = jnp.where(active, nxt, -1)    # -1 = no token
                budgets = budgets - active.astype(jnp.int32)
                active = active & ~((eos_ids >= 0) & (nxt == eos_ids)) \
                    & (budgets > 0)
                tokens = nxt
                return (caches, tokens, lens, active, budgets, key), emitted

            (caches_, tokens_, lens_, active_, budgets_, key_), out = \
                jax.lax.scan(
                    body, (caches, tokens, lens, active, budgets, key), None,
                    length=n_steps)
            packed = jnp.concatenate(
                [out.T, active_[:, None].astype(jnp.int32)],
                axis=1)                                 # [slots, n_steps+1]
            return caches_, tokens_, lens_, active_, budgets_, key_, packed

        return impl

    # -- host orchestration --------------------------------------------------
    def _admit(self, req) -> bool:
        """Prefill ``req`` into a free slot (one compiled call, no host
        sync); False when no slot is free."""
        free = [i for i, s in enumerate(self._host_slots) if s.req is None]
        if not free:
            return False
        slot = free[0]
        ids = np.asarray(req.prompt_ids, np.int32).reshape(1, -1)
        plen = ids.shape[1]
        if plen + req.max_new_tokens > self.L:
            raise ValueError(
                f"prompt {plen} + {req.max_new_tokens} new tokens exceeds "
                f"engine max_len {self.L} (model max_position_embeddings "
                f"{self.cfg.max_position_embeddings})")
        bucket = min(_bucket(plen), self.L)
        padded = np.zeros((1, bucket), np.int32)
        padded[0, :plen] = ids
        temp = float(getattr(req, "temperature", 0.0) or 0.0)
        eos = getattr(req, "eos_token_id", None)
        top_k = int(getattr(req, "top_k", 0) or 0)
        if top_k > self.TOP_K_CAP:
            raise ValueError(
                f"top_k {top_k} exceeds the continuous engine's static "
                f"filter cap {self.TOP_K_CAP} (use the static serving mode "
                "or lower top_k)")
        args = (self.params, self.caches, self.lens, self.tokens, self.active,
                self.temps, self.eos_ids, self.budgets, self.top_ks,
                jnp.asarray(padded), jnp.int32(plen), jnp.int32(slot),
                jnp.float32(temp), jnp.int32(-1 if eos is None else int(eos)),
                jnp.int32(req.max_new_tokens), jnp.int32(top_k), self.key)
        fn = self._admit_fns.get(bucket)
        if fn is None:
            fn = jax.jit(self._admit_impl, donate_argnums=(1,))
            p = _perf()
            if p is not None and p.enabled():
                # capture the bucketed prefill program's exact cost; the
                # AOT Compiled replaces the jit entry (one compile total)
                compiled = p.capture_jit("serving.admit", fn, args,
                                         bucket=f"p{bucket}", quant=self.quant
                                         or "off")
                if compiled is not None:
                    fn = compiled
            self._admit_fns[bucket] = fn
        (self.caches, self.lens, self.tokens, self.active, self.temps,
         self.eos_ids, self.budgets, self.top_ks, self.key, first) = fn(*args)
        self._host_slots[slot] = _Slot(req, budget=int(req.max_new_tokens))
        _stamp(req, "_t_admit")
        _flight_record("request", str(getattr(req, "id", "?")),
                       phase="admit", slot=slot, bucket=bucket, plen=plen)
        self._first_pending[slot] = first   # device scalar, synced at collect
        self.stats["requests"] += 1
        return True

    def _retire(self, slot: int):
        s = self._host_slots[slot]
        if s.req is not None:
            prompt = np.asarray(s.req.prompt_ids, np.int32).reshape(-1)
            gen = s.emitted[: s.budget]
            eos = getattr(s.req, "eos_token_id", None)
            if eos is not None and eos in gen:
                gen = gen[: gen.index(eos) + 1]   # trim past eos, keep it
            _stamp(s.req, "_n_new", len(gen))
            s.req.result._set(output=np.concatenate(
                [prompt, np.asarray(gen, np.int32)]))
        self._host_slots[slot] = _Slot()

    def _collect_firsts(self):
        """ONE host sync for every first token admitted since the last
        collect (stacked on device, then a single transfer)."""
        if not self._first_pending:
            return
        slots = sorted(self._first_pending)
        vals = np.asarray(jnp.stack([self._first_pending[i] for i in slots]))
        now = time.perf_counter()
        for i, slot in enumerate(slots):
            s = self._host_slots[slot]
            if s.req is not None:
                s.emitted.append(int(vals[i]))
                self.stats["tokens_out"] += 1
                # the prefill's sampled token reaching the HOST is the
                # honest first-token time (TTFT numerator)
                if getattr(s.req.result, "_t_first", 1) is None:
                    _stamp(s.req, "_t_first", now)
        self._first_pending.clear()

    def reset_slots(self, slots=None):
        """Deactivate device-side slot state (all slots, or the given list)
        — REQUIRED after a failed decode or engine stop, or retired rows
        keep consuming compute as phantom active lanes in every chunk."""
        if slots is None:
            self.active = jnp.zeros((self.S,), bool)
            self._first_pending.clear()
        else:
            for i in slots:
                self.active = self.active.at[int(i)].set(False)
                # only THIS slot's pending first token: other slots' pending
                # syncs must survive a single-slot reset
                self._first_pending.pop(int(i), None)

    def release_slot(self, slot: int):
        """Free one slot without delivering a result — the cancellation /
        deadline path: the device lane goes inactive (no phantom compute),
        the host slot is recycled, and the next admission may reuse it. The
        caller owns failing the request's future."""
        self.reset_slots([slot])
        self._host_slots[int(slot)] = _Slot()

    def busy_slots(self) -> int:
        """Host-visible count of slots holding an in-flight request."""
        return sum(1 for s in self._host_slots if s.req is not None)

    def _decode_chunk(self):
        args = (self.params, self.caches, self.tokens, self.lens, self.active,
                self.temps, self.eos_ids, self.budgets, self.top_ks, self.key)
        p = _perf()
        perf_on = p is not None and p.enabled()
        if perf_on and not self._decode_captured:
            self._decode_captured = True    # capture attempted once only
            # lower (no backend compile) a 1-step variant and scale by
            # chunk: XLA cost analysis counts the scan body once, so the
            # chunk program's own count would under-report by ~chunk
            p.cost_of_lowered(
                "serving.decode", jax.jit(self._decode_program(1)), args,
                bucket=f"s{self.S}c{self.chunk}", scale=float(self.chunk),
                quant=self.quant or "off", slots=self.S, chunk=self.chunk)
        # chunks right after an admission also pay the _collect_firsts
        # readback inside this window; only PURE decode chunks are folded
        # into the program's wall, so wall_min measures the decode
        # program, not an extra link roundtrip
        pure_decode = not self._first_pending
        t0 = time.perf_counter()
        (self.caches, self.tokens, self.lens, self.active, self.budgets,
         self.key, packed) = self._decode_fn(*args)
        self.stats["decode_calls"] += 1
        self._collect_firsts()
        pk = np.asarray(packed)                 # the ONE sync per chunk
        if perf_on and pure_decode:
            # the packed readback IS this chunk's host sync, so the wall
            # is real device time (plus the per-call link floor)
            p.observe("serving.decode", time.perf_counter() - t0,
                      bucket=f"s{self.S}c{self.chunk}")
        em, act = pk[:, :-1], pk[:, -1].astype(bool)
        for slot, s in enumerate(self._host_slots):
            if s.req is None:
                continue
            toks = [int(t) for t in em[slot] if t >= 0]
            s.emitted.extend(toks)
            self.stats["tokens_out"] += len(toks)
            if not act[slot] or len(s.emitted) >= s.budget:
                self._retire(slot)

    def flush(self):
        """Deliver results for slots that finished during admission (first
        token hit eos / budget 1) without waiting for a decode chunk."""
        self._collect_firsts()
        act = np.asarray(self.active)
        for slot, s in enumerate(self._host_slots):
            if s.req is not None and (not act[slot]
                                      or len(s.emitted) >= s.budget):
                self._retire(slot)

    def serve(self, requests, timeout: float = 600.0):
        """Run a list of GenerationRequest-shaped objects to completion with
        continuous batching. Returns aggregate stats (the card number)."""
        pending = list(requests)
        t0 = time.perf_counter()
        n_out0 = self.stats["tokens_out"]
        deadline = t0 + timeout
        while (pending or any(s.req is not None for s in self._host_slots)) \
                and time.perf_counter() < deadline:
            while pending and self._admit(pending[0]):
                pending.pop(0)
            if any(s.req is not None for s in self._host_slots):
                self._decode_chunk()
        self.flush()
        dt = time.perf_counter() - t0
        toks = self.stats["tokens_out"] - n_out0
        return {"wall_s": round(dt, 3),
                "new_tokens": toks,
                "agg_tokens_per_sec": round(toks / max(dt, 1e-9), 1),
                "decode_calls": self.stats["decode_calls"]}
