"""Continuous-batching decode engine — paged KV pool, ragged lengths.

Reference surface: the serving-grade batched attention stack —
paddle/phi/kernels/fusion/gpu/block_multi_head_attention_kernel.cu (paged,
blocked KV) surfaced via python/paddle/incubate/nn/functional/
block_multihead_attention.py, plus the fused-transformer decode loop.

TPU-native redesign: block tables and page indirection exist on GPU because
the allocator hands out scattered pages; the first engine here kept a
STATIC slot-contiguous KV pool [slots, max_len, kvh, hd] per layer instead
(zero gather indirection, every shape static). That shape has the
reference's ORIGINAL problem back: every admitted request reserves
``max_len`` worth of HBM whatever its real length, so mixed long/short
traffic caps concurrency at ``slots``, not at real KV bytes. The paged
layout (``kv_layout="paged"``, the default) fixes it the static-shape way:

* PAGED KV POOL: one ``[num_pages, page_size, kvh, hd]`` buffer per layer
  plus a device-resident page table ``[slots, max_len/page_size]`` int32.
  The decode body GATHERS each layer's logical ``[slots, L]`` view through
  the page table (the XLA equivalent of the GPU block table — a gather
  index, not pointer chasing), runs the UNCHANGED ragged-attention math,
  and scatters the one newly written position back to its physical page.
  Admission allocates pages from a host-side free list
  (:mod:`~.kv_pool`), scatters the prefill prefix page-by-page, and slot
  retirement returns pages — so concurrency is bounded by total KV bytes
  in flight, not ``slots x max_len``. Pages are reserved for the FULL
  prompt+budget at admission (static-shape JAX favors upfront
  reservation over vLLM's lazy growth: no mid-flight OOM preemption
  path needed), which still kills the dominant waste — the
  ``max_len - (prompt+budget)`` tail every request used to hold.
* SHARED-PREFIX (PROMPT) CACHE: page-aligned prompt prefixes declared via
  ``prefix_len`` are content-hashed; a miss runs the normal full prefill
  and pins the prefix pages read-only (ref-counted), a hit prefills ONLY
  the tail against the cached prefix pages gathered as context — N
  requests sharing a system prompt pay one prefill plus N short tails.
  Refcount-0 entries stay cached and are LRU-evicted when the free list
  runs dry.
* PREFILL/DECODE SPLIT, DEVICE-RESIDENT BOOKKEEPING, CONTINUOUS
  BATCHING: unchanged from the slot-contiguous engine — admission is one
  compiled call per prompt-length bucket, decode is one compiled
  multi-step program over all slots with per-slot positions, the host
  syncs ONCE per decode chunk, finished slots retire and free slots admit
  mid-flight. ``kv_layout="contiguous"`` keeps the old pool byte-for-byte
  (the parity/A-B baseline).
"""

from __future__ import annotations

import sys
import time
from typing import Dict, List, Optional

import jax
import jax.numpy as jnp
import numpy as np

from ..core import autograd as _ag
from ..core.dispatch import unwrap
from . import compile_plan as _cp
from .kv_pool import PagePool, PrefixCache, pages_needed, prefix_hash
from .robustness import KVCapacityError
from .robustness import safe_inc as _safe_inc
from .robustness import safe_set as _safe_set


def _bucket(n: int, q: int = 128) -> int:
    return -(-n // q) * q


# -- int8 KV-page quantization (kv_quant="int8") ----------------------------
# KVQuant/KIVI-style symmetric absmax: each K/V page carries one f32 scale
# per kv head ([num_pages, kvh] riding the pool as a parallel buffer), codes
# are int8 in [-127, 127]. Dequant is exactly ``codes * scale`` in f32 —
# the same product whether it runs in the fused kernel's VMEM pass or the
# reference gather, which is what makes kernel-vs-reference token-exact at
# identical pool bytes.

def _kv_quant_pages(x):
    """Quantize whole pages ``x [npg, ps, kvh, hd]`` (f32) at admission:
    per-(page, head) absmax scale. Positions past the prefill length must
    already be zeroed by the caller so padding never inflates a scale."""
    amax = jnp.max(jnp.abs(x), axis=(1, 3))                  # [npg, kvh]
    scale = amax / 127.0
    codes = jnp.clip(
        jnp.round(x / jnp.maximum(scale, 1e-20)[:, None, :, None]),
        -127, 127).astype(jnp.int8)
    return codes, scale


def _kv_dequant_gather(codes, scale, idx, dtype):
    """Gather-dequant pages ``idx`` from an int8 pool: the reference (non-
    kernel) read path. ``codes[idx] [..., ps, kvh, hd]`` times
    ``scale[idx] [..., kvh]`` in f32, cast to the engine's KV dtype."""
    g = codes[idx].astype(jnp.float32)
    s = scale[idx][..., None, :, None]
    return (g * s).astype(dtype)


def _kv_quant_scatter(codes, scales, new_rows, phys, off):
    """Scatter ``new_rows [S, W, kvh, hd]`` (this step's K or V, already in
    the engine's KV dtype) into the int8 pool at physical page ``phys`` /
    in-page offset ``off`` ([S, W] each), quantizing at write time.

    The page scale is a RUNNING absmax: when a new row fits the existing
    scale the rescale factor is exactly 1.0 and ``round(q * 1.0) == q`` —
    existing codes are bit-identical, so steady-state decode appends are
    drift-free; only a genuine absmax growth requantizes the page (the
    standard running-scale tradeoff, documented in docs/quantization.md).
    W is static and small (1 for chunked decode, k+1 for spec verify), so
    the python loop unrolls into W gather/scatter pairs per pool. Duplicate
    physical targets across slots only occur on the sacrificial null page
    0, where last-write-wins garbage is by design never read unmasked."""
    S, W = phys.shape
    sl = jnp.arange(S)
    new_rows = new_rows.astype(jnp.float32)
    for w in range(W):
        pw, ow = phys[:, w], off[:, w]
        new = new_rows[:, w]                                 # [S, kvh, hd]
        old_scale = scales[pw]                               # [S, kvh]
        new_scale = jnp.maximum(old_scale,
                                jnp.max(jnp.abs(new), axis=-1) / 127.0)
        safe = jnp.maximum(new_scale, 1e-20)
        q_new = jnp.clip(jnp.round(new / safe[..., None]),
                         -127, 127).astype(jnp.int8)
        page = codes[pw].astype(jnp.float32)                 # [S, ps, kvh, hd]
        factor = old_scale / safe                            # == 1.0 no-grow
        page = jnp.clip(jnp.round(page * factor[:, None, :, None]),
                        -127, 127).astype(jnp.int8)
        page = page.at[sl, ow].set(q_new)
        codes = codes.at[pw].set(page)
        scales = scales.at[pw].set(new_scale)
    return codes, scales


_perf_mod = None


def _perf():
    """Cached accessor for the perf-attribution plane; the off path costs
    one global read + attribute check per COLD call site (program build,
    chunk boundary) — never per token."""
    global _perf_mod
    if _perf_mod is None:
        try:
            from ..observability import perf as p
        except Exception:
            return None
        _perf_mod = p
    return _perf_mod


def _flight_record(kind: str, name: str, **data) -> None:
    """Request-lifecycle feed into the crash flight recorder (no-op one
    global check when the black box is disarmed)."""
    try:
        from ..observability import flight

        flight.record(kind, name, **data)
    except Exception:
        pass


def _expected_compiles(label: str):
    """Recompile-watchdog region for PLANNED compiles (warmup, bundle
    save): counted, never storm-flagged. Falls back to a no-op context."""
    try:
        from ..observability import watchdog

        return watchdog.expected_compiles(label)
    except Exception:
        import contextlib

        return contextlib.nullcontext()


def _trace_of(req):
    """The request's journey (observability.reqtrace), or None — the off
    path and engine-shaped foreign request objects (benches, tests)
    without a GenerationResult cost one getattr chain here."""
    return getattr(getattr(req, "result", None), "_trace", None)


def _stamp(req, attr: str, value=None) -> None:
    """Best-effort SLO timestamp on the request's result future —
    engine-shaped foreign request objects (tests, benches) without a
    GenerationResult simply don't get stamped."""
    try:
        setattr(req.result, attr,
                time.perf_counter() if value is None else value)
    except Exception:
        pass


def _account(kind: str, n: int) -> None:
    """Goodput-ledger attribution (observability.goodput). The engine is
    the SINGLE accounting point for decoded tokens: every token stamped
    into ``stats["tokens_out"]`` lands here exactly once — as ``useful``/
    ``overshoot`` at retirement or as a waste kind when the slot is
    released without delivering. Never raises into decode."""
    if n <= 0:
        return
    try:
        from ..observability import goodput

        goodput.account(kind, n)
    except Exception:
        pass


class _Slot:
    __slots__ = ("req", "emitted", "budget", "spec_steps", "spec_accepted")

    def __init__(self, req=None, budget=0):
        self.req = req
        self.emitted: List[int] = []
        self.budget = budget
        self.spec_steps = 0       # speculative verify steps this request saw
        self.spec_accepted = 0    # draft tokens the verifier accepted for it


class BatchDecodeEngine:
    """Slot-based continuous-batching decoder for LlamaForCausalLM-shaped
    models (anything exposing ``.model(ids, caches=…, pos=…)``, ``.config``
    and ``.functional_state()``)."""

    def __init__(self, model, max_slots: int = 16, max_len: Optional[int] = None,
                 chunk: int = 16, quant: Optional[str] = None,
                 quant_group_size: int = -1, kv_layout: str = "paged",
                 page_size: int = 64, num_pages: Optional[int] = None,
                 prefix_cache: bool = True, mesh=None, plan=None,
                 bundle: Optional[str] = None, draft=None, spec_k: int = 0,
                 draft_quant: Optional[str] = None,
                 fused_kernels: Optional[bool] = None,
                 kv_quant: Optional[str] = None,
                 kv_host_bytes: Optional[int] = None):
        cfg = model.config
        if kv_layout not in ("paged", "contiguous"):
            raise ValueError(
                f"kv_layout must be 'paged' or 'contiguous', got {kv_layout!r}")
        self.model = model
        self.cfg = cfg
        self.S = int(max_slots)
        self.L = int(max_len or cfg.max_position_embeddings)
        self.chunk = int(chunk)
        self.kv_layout = kv_layout
        self.params = model.functional_state()
        # weight-only quantization: params quantized ONCE here; every
        # compiled program after this point (admission prefill + the
        # scan-decode body) reads int8 weight buffers through the
        # QuantizedWeight pytree leaves — cache layout, donation
        # (caches only) and bucketed shapes are untouched. Single-chip
        # decode is HBM-bandwidth-bound, so halving weight bytes read per
        # step is the serving perf lever (tools/quant_ab.py measures it).
        self.quant = quant
        self.quant_meta: Dict[str, object] = {}
        if quant is not None:
            if quant != "weight_only_int8":
                raise ValueError(
                    f"quant={quant!r}: 'weight_only_int8' is the supported "
                    "decode-engine scheme (int4/PTQ honestly absent — "
                    "PARITY.md)")
            from ..nn.quant import quantize_param_tree

            self.params, self.quant_meta = quantize_param_tree(
                self.params, algo=quant, group_size=quant_group_size)
        # tensor-parallel decode: a sharding plan (distributed.shard_plan)
        # places params — including the int8 QuantizedWeight leaves, whose
        # q and scales shard together — column/row-parallel on its "mp"
        # axis and the KV pools on kv heads, so a model bigger than one
        # chip serves through the same compiled programs (XLA partitions
        # them and inserts the ICI collectives). Order matters: quantize
        # first (host-side, whole tensors), shard second.
        self.plan = plan
        if self.plan is None and mesh is not None:
            from ..distributed.shard_plan import ShardingPlan, decode_plan

            self.plan = (mesh if isinstance(mesh, ShardingPlan)
                         else decode_plan(mesh))
        if self.plan is not None:
            # loud, not silent: a head count tp doesn't divide would fit
            # away to a FULLY REPLICATED pool on every chip — the exact
            # memory surprise tensor parallelism exists to avoid
            self.plan.validate_divisible(
                num_attention_heads=cfg.num_attention_heads,
                num_key_value_heads=cfg.num_key_value_heads,
                intermediate_size=cfg.intermediate_size,
                vocab_size=cfg.vocab_size)  # lm_head is typically the
            #   largest serving weight; a vocab tp doesn't divide would
            #   silently replicate it on every chip
            self.params = self.plan.shard(self.params)
            self._mesh_gauges()
        # KV-cache quantization (ROADMAP item 4a): int8 codes + per-page-
        # per-head scales riding the pool. Resolved AFTER the plan so the
        # tp seam can be rejected loudly; argument wins over the flag,
        # ""/"off" are the explicit off spellings.
        from ..core.flags import flag_value as _flag_value

        if kv_quant is None:
            kv_quant = _flag_value("serving_kv_quant") or None
        if kv_quant in ("", "off"):
            kv_quant = None
        if kv_quant is not None:
            if kv_quant == "int4":
                raise ValueError(
                    "kv_quant='int4': the int8 page format (codes + "
                    "per-page-per-head scales) is the shipped scheme; "
                    "int4 packing is the named follow-up seam on the same "
                    "scale buffers (docs/quantization.md) — honestly "
                    "absent, not silently served as int8")
            if kv_quant != "int8":
                raise ValueError(
                    f"kv_quant={kv_quant!r}: 'int8' is the supported "
                    "KV-cache scheme ('int4' is the named seam)")
            if kv_layout != "paged":
                raise ValueError(
                    "kv_quant='int8' needs kv_layout='paged' — scales "
                    "ride the page pool; the contiguous layout is the "
                    "full-precision parity baseline")
            if self.plan is not None:
                raise ValueError(
                    "kv_quant with a tensor-parallel plan: sharding the "
                    "(codes, scale) pair per layer is a named follow-up "
                    "seam (shard_kv places plain pools only) — serve "
                    "int8 KV single-chip or drop the plan")
            if not self._llama_shaped_layers():
                raise ValueError(
                    "kv_quant='int8' drives the llama decoder submodules "
                    "directly (quantize-at-scatter needs the raw K/V "
                    "projections); this model is not llama-decoder-shaped")
        self.kv_quant = kv_quant
        kvh, hd = cfg.num_key_value_heads, cfg.head_dim
        dtype = jnp.bfloat16 if cfg.dtype == "bfloat16" else jnp.float32
        self._kv_dtype = dtype     # compute dtype for scratch/dequant even
        #   when the pool itself stores int8 codes
        self.kv_host = None        # host-RAM prefix spill tier (item 4b)
        self._restore_ms: List[float] = []
        if kv_layout == "paged":
            self.page_size = int(page_size)
            if self.page_size < 1:
                raise ValueError(f"page_size must be >= 1, got {page_size}")
            self.P = pages_needed(self.L, self.page_size)   # pages per slot
            # default capacity: every slot can hold max_len, ceil'd to
            # whole pages — the contiguous pool's admission CONTRACT, and
            # its exact bytes when page_size divides max_len (otherwise
            # each slot's share rounds up to a whole page, worst case
            # page_size-1 tokens/slot; plus the null page). Size
            # num_pages BELOW S*P to serve more slots than the worst
            # case could ever fit contiguously
            n_pages = (self.S * self.P + 1 if num_pages is None
                       else int(num_pages))
            self.pool = PagePool(n_pages, self.page_size)
            self.prefix = PrefixCache()
            self.prefix_enabled = bool(prefix_cache)
            self.page_table = jnp.zeros((self.S, self.P), jnp.int32)
            if self.kv_quant == "int8":
                # each pool entry is (codes int8, scale f32 [pages, kvh]):
                # a nested pytree, so program args / scan carries /
                # donation / bundle templates thread it unchanged
                self.caches = [
                    ((jnp.zeros((n_pages, self.page_size, kvh, hd),
                                jnp.int8),
                      jnp.zeros((n_pages, kvh), jnp.float32)),
                     (jnp.zeros((n_pages, self.page_size, kvh, hd),
                                jnp.int8),
                      jnp.zeros((n_pages, kvh), jnp.float32)))
                    for _ in range(cfg.num_hidden_layers)]
            else:
                self.caches = [
                    (jnp.zeros((n_pages, self.page_size, kvh, hd), dtype),
                     jnp.zeros((n_pages, self.page_size, kvh, hd), dtype))
                    for _ in range(cfg.num_hidden_layers)]
            if kv_host_bytes is None:
                kv_host_bytes = int(
                    _flag_value("serving_kv_host_bytes") or 0)
            if kv_host_bytes and prefix_cache:
                from .kv_pool import HostPrefixTier

                self.kv_host = HostPrefixTier(int(kv_host_bytes))
            self._slot_pages: List[List[int]] = [[] for _ in range(self.S)]
            self._slot_prefix: List[Optional[str]] = [None] * self.S
            self._kv_gauges(total=True)
            if self.kv_quant is not None:
                _safe_set("paddle_serving_kv_quant_enabled",
                          "KV-cache quantization live on this engine "
                          "(1 = yes)", 1, mode=self.kv_quant)
        else:
            self.page_size = 0
            self.P = 0
            self.pool = None
            self.prefix = None
            self.prefix_enabled = False
            self.page_table = None
            self.caches = [(jnp.zeros((self.S, self.L, kvh, hd), dtype),
                            jnp.zeros((self.S, self.L, kvh, hd), dtype))
                           for _ in range(cfg.num_hidden_layers)]
        if self.plan is not None:
            # commit the pools (kv heads on "mp") and every host-rebuilt
            # array (replicated): deterministic placements, so the jitted
            # programs never re-specialize on a sharding-inference guess
            self.caches = [(self.plan.shard_kv(k), self.plan.shard_kv(v))
                           for k, v in self.caches]
            if self.page_table is not None:
                self.page_table = self.plan.replicate(self.page_table)
        # device-resident per-slot state: [lens, tokens, active, budgets]
        self.lens = self._repl(jnp.zeros((self.S,), jnp.int32))
        self.tokens = self._repl(jnp.zeros((self.S,), jnp.int32))  # last tok
        self.active = self._repl(jnp.zeros((self.S,), bool))
        self.temps = self._repl(jnp.zeros((self.S,), jnp.float32))
        self.eos_ids = self._repl(jnp.full((self.S,), -1, jnp.int32))
        self.budgets = self._repl(jnp.zeros((self.S,), jnp.int32))  # left
        self.top_ks = self._repl(jnp.zeros((self.S,), jnp.int32))  # 0 = off
        self.key = self._repl(jax.random.PRNGKey(0))
        # program registry: every compiled program the engine serves with,
        # keyed by compile-plan key ("decode" / "admit_p<bucket>" /
        # "admit_pfx<n>t<bucket>"). Values are lazy jax.jit wrappers until
        # first use, warmup, or a bundle load replaces them with AOT
        # Compiled executables; _warmed tracks keys whose compile already
        # happened so warmup never double-compiles
        self._programs: Dict[str, object] = {}
        self._warmed: set = set()
        self._warm_info: Optional[Dict[str, object]] = None
        self._bundle_info: Optional[Dict[str, object]] = None
        self._decode_captured = False
        self._host_slots = [_Slot() for _ in range(self.S)]
        self._first_pending: Dict[int, object] = {}  # slot -> device scalar
        self.stats = {"tokens_out": 0, "requests": 0, "decode_calls": 0,
                      "peak_busy": 0}
        # speculative decoding: a draft model proposes spec_k greedy
        # tokens per slot and ONE batched target forward verifies all
        # k+1 positions — same emitted stream (greedy acceptance is
        # token-exact by construction), >1 token per target weight-read
        # at any nonzero acceptance rate. See inference/speculative.py.
        self.spec = None
        if draft is not None or spec_k:
            if draft is None or not spec_k:
                raise ValueError(
                    "speculative decoding needs BOTH draft= (a small "
                    "model or its config) and spec_k= (proposals per "
                    "target step)")
            from .speculative import SpeculativeDecoder

            self.spec = SpeculativeDecoder(self, draft, spec_k,
                                           draft_quant=draft_quant)
            self._spec_steps_per_chunk = max(
                1, self.chunk // (self.spec.k + 1))
        # fused Pallas kernels (ops/kernels/paged_attention.py): resolved
        # ONCE here — the decision (off / interpret / compiled /
        # fallback+reason) is immutable engine state that joins the
        # CompilePlan fingerprint, so a bundle built under a different
        # kernel config is rejected loudly at load instead of silently
        # serving a different program
        self.fused = self._resolve_fused(fused_kernels)
        self.compile_plan = _cp.CompilePlan.for_engine(self)
        try:
            # weak registration: the memory ledger attributes this
            # engine's params/KV/draft buckets and reconciles its page
            # pool for leaks — it must never extend the engine's lifetime
            from ..observability import memledger as _memledger

            _memledger.register_engine(self)
        except Exception:
            pass
        if bundle is not None:
            # never fatal: a stale/foreign bundle logs and falls back to
            # the lazy build path — a deploy with a bad artifact serves
            # slow, it does not crash-loop
            self.load_serving_bundle(bundle)

    def _repl(self, x):
        """Replicate-commit under a plan (identity single-chip)."""
        return x if self.plan is None else self.plan.replicate(x)

    def mesh_info(self) -> Dict[str, object]:
        """Mesh/sharding snapshot for ``health()``/``/healthz`` — the
        parallelism block the fleet router and ``/metrics`` see."""
        if self.plan is None:
            return {"enabled": False}
        return self.plan.describe()

    def _mesh_gauges(self) -> None:
        """One-time (construction, cold path) mesh gauges."""
        axes = "x".join(f"{a}{s}" for a, s in self.plan.axes.items())
        _safe_set("paddle_mesh_devices",
                  "devices in the serving engine's mesh",
                  self.plan.n_devices, axes=axes)
        _safe_set("paddle_mesh_axes",
                  "named axes in the serving engine's mesh",
                  len(self.plan.axes), axes=axes)
        _safe_set("paddle_tp_degree",
                  "tensor-parallel degree of the decode engine",
                  self.plan.tp_degree)

    # -- paged-pool observability -------------------------------------------
    def _kv_gauges(self, total: bool = False) -> None:
        """Pool occupancy gauges — refreshed on the per-request host paths
        (admit/retire), never per token."""
        if self.kv_layout != "paged":
            return
        if total:
            _safe_set("paddle_serving_kv_pages_total",
                      "allocatable KV pages in the paged pool",
                      self.pool.usable)
        _safe_set("paddle_serving_kv_pages_free",
                  "KV pages currently on the free list",
                  self.pool.free_count)
        if self.kv_host is not None:
            _safe_set("paddle_serving_kv_host_bytes",
                      "bytes of spilled prefix slabs resident in the "
                      "host-RAM tier", self.kv_host.used_bytes)
            _safe_set("paddle_serving_kv_host_occupancy",
                      "host-tier bytes used over its byte budget "
                      "(the kv_host_tier_full alert input)",
                      round(self.kv_host.occupancy, 4))

    def _restore_percentile(self, q: float) -> Optional[float]:
        """p-th percentile of recent host-tier restore latencies (ms)."""
        if not self._restore_ms:
            return None
        xs = sorted(self._restore_ms)
        return round(xs[min(len(xs) - 1, int(q * len(xs)))], 3)

    def kv_stats(self) -> Dict[str, object]:
        """KV-pool snapshot for ``health()``/``/healthz`` and the serving
        bench: layout, page accounting, prefix-cache hit data, host-tier
        spill/restore counters."""
        cfg = self.cfg
        kvh, hd = cfg.num_key_value_heads, cfg.head_dim
        if self.kv_quant == "int8":
            itemsize = 1                      # int8 codes cross HBM
        else:
            itemsize = np.dtype(self._kv_dtype).itemsize
        per_tok = 2 * kvh * hd * itemsize * cfg.num_hidden_layers
        if self.kv_layout != "paged":
            return {"layout": "contiguous",
                    "kv_bytes": int(self.S * self.L * per_tok)}
        pool, pfx = self.pool, self.prefix
        # per-page scale overhead in int8 mode: one f32 per (page, head)
        # per K and V per layer — the honest page_bytes the memledger's
        # pinned-prefix reconciliation multiplies by
        scale_bytes = (2 * kvh * 4 * cfg.num_hidden_layers
                       if self.kv_quant == "int8" else 0)
        page_bytes = int(self.page_size * per_tok + scale_bytes)
        host = {"enabled": False}
        if self.kv_host is not None:
            host = dict(self.kv_host.stats(), enabled=True,
                        restore_ms_p50=self._restore_percentile(0.50),
                        restore_ms_p99=self._restore_percentile(0.99))
        return {
            "layout": "paged",
            "kv_quant": self.kv_quant or "off",
            "page_size": self.page_size,
            "pages_total": pool.usable,
            "pages_free": pool.free_count,
            "pages_used": pool.used,
            "pages_peak": pool.peak_used,
            "occupancy": round(pool.used / max(pool.usable, 1), 4),
            "page_bytes": page_bytes,
            "kv_bytes": int(pool.num_pages * page_bytes),
            "prefix": {
                "enabled": self.prefix_enabled,
                "entries": len(pfx),
                "cached_pages": pfx.cached_pages,
                "hits": pfx.hits,
                "misses": pfx.misses,
                "evictions": pfx.evictions,
            },
            "host": host,
        }

    def spec_info(self) -> Dict[str, object]:
        """The ``spec`` block of ``health()``/``/healthz``: draft config,
        k, and live acceptance — ``{"enabled": False}`` when speculative
        decoding is off."""
        return {"enabled": False} if self.spec is None else self.spec.info()

    # -- fused kernels -------------------------------------------------------
    def _llama_shaped_layers(self) -> bool:
        """The fused decode path drives the layer's submodules directly
        (projections, norms, mlp); anything not llama-decoder-shaped —
        or carrying extra residual branches (shared_mlp) the fused loop
        would silently skip — must fall back to the reference path."""
        try:
            layer = self.model.model.layers[0]
            mdl = self.model.model
        except Exception:
            return False
        attn = getattr(layer, "self_attn", None)
        return (all(hasattr(attn, a)
                    for a in ("q_proj", "k_proj", "v_proj", "o_proj"))
                and all(hasattr(layer, a)
                        for a in ("input_layernorm",
                                  "post_attention_layernorm", "mlp"))
                and getattr(layer, "shared_mlp", None) is None
                and all(hasattr(mdl, a)
                        for a in ("embed_tokens", "norm", "rope_cos",
                                  "rope_sin")))

    def _resolve_fused(self, fused_kernels: Optional[bool]) -> Dict[str, object]:
        """Resolve the fused-kernel config for this engine: explicit
        argument wins, else ``FLAGS_fused_kernels``. Requested-but-
        unsupported is a LOUD non-fatal fallback (one stderr line + a
        labeled counter) to the reference formulation — never a silent
        behavior change and never wrong results."""
        from ..core.flags import flag_value

        want = (flag_value("fused_kernels") if fused_kernels is None
                else bool(fused_kernels))
        info: Dict[str, object] = {"enabled": False,
                                   "paged_attention": "off"}
        if not want:
            return info
        from ..ops.kernels import paged_attention as _pa

        if self.kv_layout != "paged":
            ok, reason = False, "kv_layout contiguous (no page table)"
        else:
            ok, reason = _pa.paged_attention_supported(
                page_size=self.page_size, head_dim=self.cfg.head_dim,
                num_heads=self.cfg.num_attention_heads,
                num_kv_heads=self.cfg.num_key_value_heads, plan=self.plan,
                kv_quant=self.kv_quant)
            if ok and not self._llama_shaped_layers():
                ok, reason = False, "model layers not llama-decoder-shaped"
        if ok:
            mode = "interpret" if _pa.interpret_mode() else "compiled"
            info.update(enabled=True, paged_attention=mode)
            return info
        info["paged_attention"] = f"fallback: {reason}"
        sys.stderr.write(
            f"[serving] fused paged-attention kernel unavailable "
            f"({reason}); serving the reference pool[page_table] "
            "formulation\n")
        _safe_inc("paddle_fused_kernel_fallbacks_total",
                  "fused-kernel requests that fell back to the reference "
                  "formulation", kernel="paged_attention",
                  reason=reason.split(" ")[0])
        _flight_record("compile", "fused_fallback",
                       kernel="paged_attention", reason=reason)
        return info

    def fused_info(self) -> Dict[str, object]:
        """The ``fused`` block of ``health()``/``/healthz``: which fused
        kernels this engine decodes through (and why not, when it fell
        back)."""
        return dict(self.fused)

    # -- compiled pieces ----------------------------------------------------
    def _forward(self, params, toks, caches, pos):
        """One model step: toks [b, s] -> (logits, caches')."""
        with _ag.no_grad(), self.model.bind_state(params):
            hidden, new_caches = self.model.model(toks, caches=caches, pos=pos)
            if self.model.lm_head is None:
                logits = unwrap(hidden) @ unwrap(
                    self.model.model.embed_tokens.weight).T
            else:
                logits = unwrap(self.model.lm_head(hidden))
        return logits, [(unwrap(k), unwrap(v)) for k, v in new_caches]

    def _forward_paged(self, params, toks, pools, page_table, lens):
        """One forward over ``toks [S, W]`` at per-slot positions
        ``lens..lens+W-1`` through the page table: each layer gathers its
        logical ``[S, P*page_size]`` K/V view (the page table IS the gather
        index), runs the unchanged ragged-attention math against it, and
        scatters all W newly written positions back to their physical
        pages. W=1 is the chunked decode step; the speculative verify
        program runs W=k+1 through the SAME implementation, so the two
        paths cannot diverge. Retired slots' table rows are zeroed and
        positions past ``max_len`` are redirected explicitly, so
        out-of-stream writes land in the sacrificial null page — never in
        another slot's pages."""
        S, ps, P, L = self.S, self.page_size, self.P, self.L
        W = toks.shape[1]
        rows = jnp.arange(S, dtype=jnp.int32)[:, None]         # [S, 1]
        pos = lens[:, None] + jnp.arange(W, dtype=jnp.int32)[None, :]
        pos_g = jnp.minimum(pos, P * ps - 1)
        page_idx = jnp.minimum(pos // ps, P - 1)
        phys = jnp.where(
            pos < L,
            page_table[jnp.broadcast_to(rows, pos.shape), page_idx], 0)
        off = pos % ps
        if self.fused.get("enabled") or self.kv_quant == "int8":
            # int8 KV always takes the direct-submodule path even without
            # the kernel: quantize-at-scatter must happen BEFORE attention
            # reads the pool, so kernel and reference attend the SAME
            # quantized bytes (that identity is what makes the parity
            # test token-exact) — the generic layer call below would
            # attend this step's full-precision rows instead
            return self._forward_paged_fused(params, toks, pools,
                                             page_table, lens, phys, off)
        with _ag.no_grad(), self.model.bind_state(params):
            mdl = self.model.model
            x = mdl.embed_tokens(toks)
            cos, sin = mdl.rope_cos, mdl.rope_sin
            new_pools = []
            for layer, (kp, vp) in zip(mdl.layers, pools):
                kview = kp[page_table].reshape(
                    S, P * ps, *kp.shape[2:])
                vview = vp[page_table].reshape(
                    S, P * ps, *vp.shape[2:])
                x, (kc, vc) = layer(x, cos, sin, None,
                                    cache=(kview, vview), pos=lens)
                kc, vc = unwrap(kc), unwrap(vc)
                kp = kp.at[phys, off].set(kc[rows, pos_g])
                vp = vp.at[phys, off].set(vc[rows, pos_g])
                new_pools.append((kp, vp))
            hidden = mdl.norm(x)
            if self.model.lm_head is None:
                logits = unwrap(hidden) @ unwrap(mdl.embed_tokens.weight).T
            else:
                logits = unwrap(self.model.lm_head(hidden))
        return logits, new_pools

    def _ref_gqa_attention(self, q, kview, vview, lens, *, rep, scale):
        """Reference gather-dequant attention over a materialized logical
        view [S, T, kvh, hd]: the same bottom-right causal rule, GQA
        grouping (q head g*rep+r reads kv head g) and f32 accumulation as
        the Pallas kernel — the non-kernel half of the int8-KV parity
        pair (docs/kernels.md fallback matrix)."""
        S, W, h, hd = q.shape
        kvh = kview.shape[2]
        T = kview.shape[1]
        qg = q.astype(jnp.float32).reshape(S, W, kvh, rep, hd) * scale
        att = jnp.einsum("swgrd,stgd->swgrt", qg,
                         kview.astype(jnp.float32))
        k_pos = jnp.arange(T, dtype=jnp.int32)
        q_pos = lens[:, None] + jnp.arange(W, dtype=jnp.int32)[None, :]
        mask = k_pos[None, None, :] <= q_pos[:, :, None]      # [S, W, T]
        att = jnp.where(mask[:, :, None, None, :], att, -1e30)
        p = jax.nn.softmax(att, axis=-1)
        out = jnp.einsum("swgrt,stgd->swgrd", p,
                         vview.astype(jnp.float32))
        return out.reshape(S, W, h, hd).astype(q.dtype)

    def _forward_paged_fused(self, params, toks, pools, page_table, lens,
                             phys, off):
        """The fused-kernel form of :meth:`_forward_paged`: identical
        math (same projections, rope offsets, write positions and causal
        rule — parity is test-pinned token-exact), but each layer
        scatters the W new K/V rows straight to their physical pages and
        the attention WALKS THE PAGE TABLE IN-KERNEL
        (ops/kernels/paged_attention.py) instead of materializing
        ``pool[page_table]`` in HBM. The layer loop drives the llama
        submodules directly — `_resolve_fused` verified the shape.

        Under ``kv_quant="int8"`` this is ALSO the reference path (kernel
        off → gather-dequant + :meth:`_ref_gqa_attention`): both forms
        quantize-scatter first and attend the identical int8 bytes, which
        is the parity contract."""
        import math as _math

        from ..models.llama import _apply_rope
        from ..ops.kernels.paged_attention import paged_attention

        S = self.S
        W = toks.shape[1]
        cfg = self.cfg
        nh, kvh, hd = (cfg.num_attention_heads, cfg.num_key_value_heads,
                       cfg.head_dim)
        rep = nh // kvh
        scale = 1.0 / _math.sqrt(hd)
        quant = self.kv_quant == "int8"
        use_kernel = bool(self.fused.get("enabled"))
        interp = self.fused.get("paged_attention") == "interpret"
        ps, P = self.page_size, self.P
        with _ag.no_grad(), self.model.bind_state(params):
            mdl = self.model.model
            x = mdl.embed_tokens(toks)
            cos, sin = mdl.rope_cos, mdl.rope_sin
            new_pools = []
            for layer, (kp, vp) in zip(mdl.layers, pools):
                attn = layer.self_attn
                h_pre = layer.input_layernorm(x)
                q = attn.q_proj(h_pre).reshape([S, W, nh, hd])
                k = attn.k_proj(h_pre).reshape([S, W, kvh, hd])
                v = attn.v_proj(h_pre).reshape([S, W, kvh, hd])
                q, k = _apply_rope(q, k, cos, sin, offset=lens)
                # write first, then attend: the causal mask admits this
                # step's own positions, exactly like the reference
                # view-write in _cached_attention
                if quant:
                    (kq, ksc), (vq, vsc) = kp, vp
                    kq, ksc = _kv_quant_scatter(
                        kq, ksc, unwrap(k).astype(self._kv_dtype),
                        phys, off)
                    vq, vsc = _kv_quant_scatter(
                        vq, vsc, unwrap(v).astype(self._kv_dtype),
                        phys, off)
                    if use_kernel:
                        out = paged_attention(
                            unwrap(q), kq, vq, page_table, lens, rep=rep,
                            scale=scale, k_scale=ksc, v_scale=vsc,
                            interpret=interp)
                    else:
                        kview = _kv_dequant_gather(
                            kq, ksc, page_table, self._kv_dtype).reshape(
                                S, P * ps, kvh, hd)
                        vview = _kv_dequant_gather(
                            vq, vsc, page_table, self._kv_dtype).reshape(
                                S, P * ps, kvh, hd)
                        out = self._ref_gqa_attention(
                            unwrap(q), kview, vview, lens, rep=rep,
                            scale=scale)
                    new_pools.append(((kq, ksc), (vq, vsc)))
                else:
                    kp = kp.at[phys, off].set(unwrap(k).astype(kp.dtype))
                    vp = vp.at[phys, off].set(unwrap(v).astype(vp.dtype))
                    out = paged_attention(unwrap(q), kp, vp, page_table,
                                          lens, rep=rep, scale=scale,
                                          interpret=interp)
                    new_pools.append((kp, vp))
                x = x + attn.o_proj(out.reshape(S, W, nh * hd))
                x = x + layer.mlp(layer.post_attention_layernorm(x))
            hidden = mdl.norm(x)
            if self.model.lm_head is None:
                logits = unwrap(hidden) @ unwrap(mdl.embed_tokens.weight).T
            else:
                logits = unwrap(self.model.lm_head(hidden))
        return logits, new_pools

    TOP_K_CAP = 128  # static bound for the in-graph per-slot top-k filter

    def _sample(self, rows, temps, top_ks, key):
        """Per-slot sampling: temp==0 -> greedy, else categorical at temp,
        optionally restricted to the slot's top_k logits (k <= TOP_K_CAP;
        one static top_k of the cap serves every slot's k)."""
        kcap = min(self.TOP_K_CAP, rows.shape[-1])
        topv = jax.lax.top_k(rows, kcap)[0]               # [slots, kcap] desc
        kth = jnp.take_along_axis(
            topv, jnp.clip(top_ks[:, None] - 1, 0, kcap - 1), axis=1)
        rows = jnp.where((top_ks[:, None] > 0) & (rows < kth), -jnp.inf, rows)
        greedy = jnp.argmax(rows, axis=-1).astype(jnp.int32)
        scaled = rows / jnp.maximum(temps[:, None], 1e-6)
        sampled = jax.random.categorical(key, scaled).astype(jnp.int32)
        return jnp.where(temps <= 0.0, greedy, sampled)

    def _set_slot_state(self, caches, lens, tokens, active, temps, eos_ids,
                        budgets, top_ks, key, slot, plen, temp, eos, budget,
                        top_k, first):
        """Shared admission epilogue: every per-slot state element set
        in-graph; the slot is born inactive when its first token already
        ends it."""
        done = ((eos >= 0) & (first == eos)) | (budget <= 1)
        return (caches,
                lens.at[slot].set(plen),
                tokens.at[slot].set(first),
                active.at[slot].set(~done),
                temps.at[slot].set(temp),
                eos_ids.at[slot].set(eos),
                budgets.at[slot].set(budget - 1),
                top_ks.at[slot].set(top_k),
                key, first)

    def _admit_impl(self, params, caches, lens, tokens, active, temps,
                    eos_ids, budgets, top_ks, ids, plen, slot, temp, eos,
                    budget, top_k, key):
        """ONE compiled admission (contiguous layout): prefill ids[1, bucket]
        through a scratch cache, scatter the K/V prefix into pool slot
        ``slot``, sample the first token, set every per-slot state element.
        No host syncs."""
        bucket = ids.shape[1]
        kvh, hd = self.cfg.num_key_value_heads, self.cfg.head_dim
        dtype = caches[0][0].dtype
        scratch = [(jnp.zeros((1, bucket, kvh, hd), dtype),
                    jnp.zeros((1, bucket, kvh, hd), dtype))
                   for _ in range(self.cfg.num_hidden_layers)]
        logits, scratch = self._forward(params, ids, scratch, jnp.int32(0))
        row = logits[0, plen - 1].astype(jnp.float32)
        key, sub = jax.random.split(key)
        first = self._sample(row[None], temp[None], top_k[None], sub)[0]
        out_caches = []
        zero = jnp.int32(0)
        for (kc, vc), (ks, vs) in zip(caches, scratch):
            kc = jax.lax.dynamic_update_slice(kc, ks, (slot, zero, zero, zero))
            vc = jax.lax.dynamic_update_slice(vc, vs, (slot, zero, zero, zero))
            out_caches.append((kc, vc))
        return self._set_slot_state(out_caches, lens, tokens, active, temps,
                                    eos_ids, budgets, top_ks, key, slot,
                                    plen, temp, eos, budget, top_k, first)

    def _admit_paged_impl(self, params, pools, page_table, lens, tokens,
                          active, temps, eos_ids, budgets, top_ks, ids, plen,
                          slot, temp, eos, budget, top_k, key):
        """Paged admission: same scratch prefill, but the K/V prefix is
        scattered PAGE-BY-PAGE to the physical pages the host wrote into
        this slot's page-table row before the call. Scratch positions past
        the slot's reservation hit row entries of 0 — the null page."""
        bucket = ids.shape[1]
        ps = self.page_size
        npg = pages_needed(bucket, ps)
        pad = npg * ps - bucket
        kvh, hd = self.cfg.num_key_value_heads, self.cfg.head_dim
        dtype = self._kv_dtype
        scratch = [(jnp.zeros((1, bucket, kvh, hd), dtype),
                    jnp.zeros((1, bucket, kvh, hd), dtype))
                   for _ in range(self.cfg.num_hidden_layers)]
        logits, scratch = self._forward(params, ids, scratch, jnp.int32(0))
        row = logits[0, plen - 1].astype(jnp.float32)
        key, sub = jax.random.split(key)
        first = self._sample(row[None], temp[None], top_k[None], sub)[0]
        dest = jax.lax.dynamic_slice(page_table, (slot, jnp.int32(0)),
                                     (1, npg))[0]
        # positions past the prompt hold prefill activations for the
        # bucket's zero-padding — mask them out of the int8 scale (the
        # attention mask already hides them; decode overwrites them)
        valid = (jnp.arange(npg * ps, dtype=jnp.int32)
                 < plen).reshape(npg, ps)[:, :, None, None]
        out_pools = []
        for (kp, vp), (ks, vs) in zip(pools, scratch):
            if pad:
                ks = jnp.pad(ks, ((0, 0), (0, pad), (0, 0), (0, 0)))
                vs = jnp.pad(vs, ((0, 0), (0, pad), (0, 0), (0, 0)))
            kpg = ks[0].reshape(npg, ps, kvh, hd)
            vpg = vs[0].reshape(npg, ps, kvh, hd)
            if self.kv_quant == "int8":
                (kq, kscale), (vq, vscale) = kp, vp
                kc, ksc = _kv_quant_pages(
                    jnp.where(valid, kpg.astype(jnp.float32), 0.0))
                vc, vsc = _kv_quant_pages(
                    jnp.where(valid, vpg.astype(jnp.float32), 0.0))
                out_pools.append(((kq.at[dest].set(kc),
                                   kscale.at[dest].set(ksc)),
                                  (vq.at[dest].set(vc),
                                   vscale.at[dest].set(vsc))))
            else:
                kp = kp.at[dest].set(kpg)
                vp = vp.at[dest].set(vpg)
                out_pools.append((kp, vp))
        return self._set_slot_state(out_pools, lens, tokens, active, temps,
                                    eos_ids, budgets, top_ks, key, slot,
                                    plen, temp, eos, budget, top_k, first)

    def _admit_prefix_program(self, n_pfx: int, tail_bucket: int):
        """Prefix-HIT admission factory (compiled per (prefix pages, tail
        bucket)): gather the cached prefix pages as read-only context,
        prefill ONLY the tail at positions [aligned, aligned+tail), scatter
        the tail's K/V to the slot's private pages, sample the first token.
        The prefix pages are never written — that is what makes them
        shareable across slots."""
        ps = self.page_size
        aligned = n_pfx * ps
        npg_tail = pages_needed(tail_bucket, ps)
        pad = npg_tail * ps - tail_bucket

        def impl(params, pools, page_table, lens, tokens, active, temps,
                 eos_ids, budgets, top_ks, ids, tail_plen, slot, temp, eos,
                 budget, top_k, key):
            kvh, hd = self.cfg.num_key_value_heads, self.cfg.head_dim
            dtype = self._kv_dtype
            quant = self.kv_quant == "int8"
            row_pages = jax.lax.dynamic_slice(
                page_table, (slot, jnp.int32(0)), (1, self.P))[0]
            pfx = row_pages[:n_pfx]
            scratch = []
            for kp, vp in pools:
                if quant:
                    (kq, ksc), (vq, vsc) = kp, vp
                    kpfx = _kv_dequant_gather(kq, ksc, pfx, dtype).reshape(
                        1, aligned, kvh, hd)
                    vpfx = _kv_dequant_gather(vq, vsc, pfx, dtype).reshape(
                        1, aligned, kvh, hd)
                else:
                    kpfx = kp[pfx].reshape(1, aligned, kvh, hd)
                    vpfx = vp[pfx].reshape(1, aligned, kvh, hd)
                zk = jnp.zeros((1, tail_bucket, kvh, hd), dtype)
                scratch.append((jnp.concatenate([kpfx, zk], axis=1),
                                jnp.concatenate([vpfx, zk], axis=1)))
            logits, scratch = self._forward(params, ids, scratch,
                                            jnp.int32(aligned))
            row = logits[0, tail_plen - 1].astype(jnp.float32)
            key2, sub = jax.random.split(key)
            first = self._sample(row[None], temp[None], top_k[None], sub)[0]
            dest = row_pages[n_pfx:n_pfx + npg_tail]
            valid = (jnp.arange(npg_tail * ps, dtype=jnp.int32)
                     < tail_plen).reshape(npg_tail, ps)[:, :, None, None]
            out_pools = []
            for (kp, vp), (ks, vs) in zip(pools, scratch):
                kt = ks[:, aligned:]
                vt = vs[:, aligned:]
                if pad:
                    kt = jnp.pad(kt, ((0, 0), (0, pad), (0, 0), (0, 0)))
                    vt = jnp.pad(vt, ((0, 0), (0, pad), (0, 0), (0, 0)))
                ktp = kt[0].reshape(npg_tail, ps, kvh, hd)
                vtp = vt[0].reshape(npg_tail, ps, kvh, hd)
                if quant:
                    (kq, kscale), (vq, vscale) = kp, vp
                    kc, ksc = _kv_quant_pages(
                        jnp.where(valid, ktp.astype(jnp.float32), 0.0))
                    vc, vsc = _kv_quant_pages(
                        jnp.where(valid, vtp.astype(jnp.float32), 0.0))
                    out_pools.append(((kq.at[dest].set(kc),
                                       kscale.at[dest].set(ksc)),
                                      (vq.at[dest].set(vc),
                                       vscale.at[dest].set(vsc))))
                else:
                    kp = kp.at[dest].set(ktp)
                    vp = vp.at[dest].set(vtp)
                    out_pools.append((kp, vp))
            return self._set_slot_state(
                out_pools, lens, tokens, active, temps, eos_ids, budgets,
                top_ks, key2, slot, aligned + tail_plen, temp, eos, budget,
                top_k, first)

        return impl

    def _decode_program(self, n_steps: int):
        """``n_steps`` decode steps over all slots in one program; per-slot
        eos (-1 = none) and budget countdown in-graph. Returns the packed
        [slots, n_steps+1] int32 host-sync payload (emitted tokens, -1
        where idle, last column = active flag). A factory so the perf
        plane can lower an ``n_steps=1`` variant for cost capture — XLA's
        cost analysis counts a scan body ONCE regardless of trip count,
        so the chunk program's own count would under-report by ~chunk.
        Paged layout threads the pool through the scan carry and reads the
        (loop-invariant) page table as a plain capture-free argument."""

        paged = self.kv_layout == "paged"

        def step(caches, tokens, lens, active, temps, budgets, top_ks,
                 eos_ids, key, params, page_table):
            if paged:
                logits, caches = self._forward_paged(
                    params, tokens[:, None], caches, page_table, lens)
            else:
                logits, caches = self._forward(params, tokens[:, None],
                                               caches, lens)
            rows = logits[:, 0].astype(jnp.float32)
            key, sub = jax.random.split(key)
            nxt = self._sample(rows, temps, top_ks, sub)
            nxt = jnp.where(active, nxt, tokens)    # frozen when inactive
            lens = lens + active.astype(jnp.int32)
            emitted = jnp.where(active, nxt, -1)    # -1 = no token
            budgets = budgets - active.astype(jnp.int32)
            active = active & ~((eos_ids >= 0) & (nxt == eos_ids)) \
                & (budgets > 0)
            return caches, nxt, lens, active, budgets, key, emitted

        def run(params, caches, page_table, tokens, lens, active, temps,
                eos_ids, budgets, top_ks, key):
            def body(carry, _):
                caches, tokens, lens, active, budgets, key = carry
                caches, tokens, lens, active, budgets, key, emitted = step(
                    caches, tokens, lens, active, temps, budgets, top_ks,
                    eos_ids, key, params, page_table)
                return (caches, tokens, lens, active, budgets, key), emitted

            (caches_, tokens_, lens_, active_, budgets_, key_), out = \
                jax.lax.scan(
                    body, (caches, tokens, lens, active, budgets, key), None,
                    length=n_steps)
            packed = jnp.concatenate(
                [out.T, active_[:, None].astype(jnp.int32)],
                axis=1)                                 # [slots, n_steps+1]
            return caches_, tokens_, lens_, active_, budgets_, key_, packed

        if paged:
            return run

        def run_contiguous(params, caches, tokens, lens, active, temps,
                           eos_ids, budgets, top_ks, key):
            return run(params, caches, None, tokens, lens, active, temps,
                       eos_ids, budgets, top_ks, key)

        return run_contiguous

    # -- compile plan: program registry, warmup, bundles ---------------------
    def _build_program(self, key: str):
        """The lazy ``jax.jit`` wrapper for one plan key (no compile yet).
        The single construction seam: _admit, warmup() and bundle save all
        build through here, so the plan IS what the engine compiles."""
        kind, info = _cp.parse_key(key)
        if kind == "decode":
            return jax.jit(self._decode_program(self.chunk),
                           donate_argnums=(1,))
        if kind == "prefix":
            return jax.jit(
                self._admit_prefix_program(info["n_pfx"],
                                           info["tail_bucket"]),
                donate_argnums=(1,))
        if kind in ("draft_admit", "draft", "verify"):
            if self.spec is None:
                raise ValueError(
                    f"program key {key!r} needs speculative decoding "
                    "(draft=/spec_k=) armed on this engine")
            if kind == "draft_admit":
                return jax.jit(self.spec.draft_admit_impl,
                               donate_argnums=(1,))
            if kind == "draft":
                return jax.jit(self.spec.draft_program(info["k"]),
                               donate_argnums=(1,))
            return jax.jit(self.spec.verify_program(info["k"]),
                           donate_argnums=(1,))
        impl = (self._admit_paged_impl if self.kv_layout == "paged"
                else self._admit_impl)
        return jax.jit(impl, donate_argnums=(1,))

    def _program(self, key: str):
        """Registry lookup with lazy build — the serve-path accessor the
        spec chunk and draft admission share with warmup/bundles."""
        fn = self._programs.get(key)
        if fn is None:
            fn = self._build_program(key)
            self._programs[key] = fn
        return fn

    def _decode_args(self) -> tuple:
        """THE decode program's argument tuple — shared by the serve path
        (_decode_chunk) and the plan seam (warmup/bundle lowering), so an
        AOT Compiled can never be specialized to avals the serve path
        doesn't pass."""
        if self.kv_layout == "paged":
            return (self.params, self.caches, self.page_table, self.tokens,
                    self.lens, self.active, self.temps, self.eos_ids,
                    self.budgets, self.top_ks, self.key)
        return (self.params, self.caches, self.tokens, self.lens,
                self.active, self.temps, self.eos_ids, self.budgets,
                self.top_ks, self.key)

    def _admit_args(self, key: str, ids, plen: int, slot: int, temp: float,
                    eos: int, budget: int, top_k: int) -> tuple:
        """THE admission argument tuple for one program key — shared by
        _admit (live request values) and the plan seam (zero examples:
        only avals matter for lowering and treedefs)."""
        kind, _ = _cp.parse_key(key)
        state = (self.lens, self.tokens, self.active, self.temps,
                 self.eos_ids, self.budgets, self.top_ks)
        tail = (ids, jnp.int32(plen), jnp.int32(slot), jnp.float32(temp),
                jnp.int32(eos), jnp.int32(budget), jnp.int32(top_k),
                self.key)
        head = ((self.params, self.caches, self.page_table)
                if kind == "prefix" or self.kv_layout == "paged"
                else (self.params, self.caches))
        return head + state + tail

    def _example_args(self, key: str) -> tuple:
        """Concrete arguments with the EXACT avals (shape/dtype/sharding)
        the serve path passes for ``key`` — used to AOT-lower in warmup()/
        save, and to rebuild bundle pytree structures at load. Never
        executed, so live state buffers double as examples."""
        kind, info = _cp.parse_key(key)
        if kind == "decode":
            return self._decode_args()
        if kind == "draft_admit":
            return (self.spec.draft_params, self.spec.draft_caches,
                    self.spec.prev_tokens,
                    jnp.zeros((1, info["bucket"]), jnp.int32),
                    jnp.int32(1), jnp.int32(0))
        if kind == "draft":
            return (self.spec.draft_params, self.spec.draft_caches,
                    self.spec.prev_tokens, self.tokens, self.lens,
                    self.active)
        if kind == "verify":
            return (self.params, self.caches, self.page_table, self.lens,
                    self.tokens, self.spec.prev_tokens, self.active,
                    self.budgets, self.eos_ids,
                    jnp.zeros((self.S, info["k"]), jnp.int32))
        width = (info["tail_bucket"] if kind == "prefix"
                 else info["bucket"])
        return self._admit_args(key, jnp.zeros((1, width), jnp.int32),
                                plen=1, slot=0, temp=0.0, eos=-1, budget=1,
                                top_k=0)

    def _out_template(self, key: str) -> tuple:
        """A pytree with the program's OUTPUT structure (leaves are
        placeholders — treedefs carry structure only). Lets a bundle load
        reconstruct out_trees from the live engine instead of pickling
        treedefs with custom (QuantizedWeight) nodes."""
        kind, info = _cp.parse_key(key)
        if kind == "decode":
            return (self.caches, self.tokens, self.lens, self.active,
                    self.budgets, self.key, jnp.int32(0))
        if kind == "draft_admit":
            return (self.spec.draft_caches, self.spec.prev_tokens)
        if kind == "draft":
            return (self.spec.draft_caches,
                    jnp.zeros((self.S, info["k"]), jnp.int32))
        if kind == "verify":
            return (self.caches, self.lens, self.tokens,
                    self.spec.prev_tokens, self.active, self.budgets,
                    jnp.zeros((self.S, info["k"] + 3), jnp.int32))
        return (self.caches, self.lens, self.tokens, self.active,
                self.temps, self.eos_ids, self.budgets, self.top_ks,
                self.key, jnp.int32(0))

    def warmup(self, keys: Optional[List[str]] = None) -> Dict[str, object]:
        """Compile the plan EAGERLY (AOT lower+compile, nothing executed)
        so no request ever lands on a cold program — the explicit form of
        what the first requests used to pay implicitly. Idempotent per
        program; already-served or bundle-loaded keys are skipped. With a
        persistent compile cache armed, a warm-disk restart's warmup is
        retrieval, not compilation. Returns the warmup summary also kept
        in ``compile_info()``."""
        from ..core import compile_cache as _cc

        if keys is None:
            keys = self.compile_plan.keys()
        t0 = time.perf_counter()
        cache0 = _cc.stats()
        compiled_n = skipped = 0
        p = _perf()
        perf_on = p is not None and p.enabled()
        with _expected_compiles("warmup"):
            for key in keys:
                if key in self._warmed:
                    skipped += 1
                    continue
                fn = self._programs.get(key)
                if fn is None:
                    fn = self._build_program(key)
                if not hasattr(fn, "lower"):    # already an AOT Compiled
                    self._warmed.add(key)
                    skipped += 1
                    continue
                compiled = None
                kind, info = _cp.parse_key(key)
                if perf_on and kind in ("admit", "prefix"):
                    # same capture the lazy path does: the Compiled
                    # replaces the jit entry, one compile total, exact
                    # costs recorded. Only the TARGET admission kinds:
                    # draft_admit under "serving.admit" would collide
                    # with the target's bucket label in the cost
                    # registry, and draft/verify keys carry no bucket
                    bucket = (f"pfx{info['n_pfx']}t{info['tail_bucket']}"
                              if kind == "prefix" else f"p{info['bucket']}")
                    compiled = p.capture_jit(
                        "serving.admit", fn, self._example_args(key),
                        bucket=bucket, quant=self.quant or "off")
                if compiled is None:
                    compiled = fn.lower(*self._example_args(key)).compile()
                self._programs[key] = compiled
                self._warmed.add(key)
                compiled_n += 1
            self._warm_bookkeeping_ops()
        cache1 = _cc.stats()
        self._warm_info = {
            "programs": len(keys),
            "compiled": compiled_n,
            "skipped": skipped,
            "wall_s": round(time.perf_counter() - t0, 3),
            "cache_hits": cache1["hits"] - cache0["hits"],
        }
        _safe_set("paddle_serving_warmup_seconds",
                  "wall seconds the last engine warmup spent compiling",
                  self._warm_info["wall_s"])
        _safe_set("paddle_serving_warmup_programs",
                  "programs compiled by the last engine warmup",
                  compiled_n)
        _flight_record("compile", "warmup", **self._warm_info)
        return dict(self._warm_info)

    def _warm_bookkeeping_ops(self) -> None:
        """Flush the tiny host-side op compiles the first requests would
        otherwise pay (page-table row writes use STATIC slot indices, so
        each slot is its own ~10 ms program; likewise the first-token
        stack per pending count). Pure copies — engine state untouched.
        Without this, a fully warmed/bundled engine still shows a handful
        of ms-scale compiles in its first serve window."""
        try:
            if self.kv_layout == "paged":
                pt = self.page_table
                zrow = jnp.zeros((self.P,), jnp.int32)
                for slot in range(self.S):
                    pt = pt.at[slot].set(zrow)
                pt.block_until_ready()
            act = self.active
            for slot in range(self.S):
                act = act.at[slot].set(False)
            act.block_until_ready()
            firsts = [jnp.int32(0)] * self.S
            for k in range(1, self.S + 1):
                np.asarray(jnp.stack(firsts[:k]))
            if self.spec is not None and self._spec_steps_per_chunk > 1:
                # the spec chunk's payload concat is the one host-level op
                # its serve path adds — flush its ~ms compile here too
                parts = [jnp.zeros((self.S, self.spec.k + 3), jnp.int32)
                         ] * self._spec_steps_per_chunk
                np.asarray(jnp.concatenate(parts, axis=1))
        except Exception:
            pass          # best-effort: a miss here costs ms, not minutes

    def save_serving_bundle(self, path: str,
                            keys: Optional[List[str]] = None
                            ) -> Dict[str, object]:
        """Serialize the engine's compiled programs + manifest to ``path``
        (every plan entry plus traffic-built prefix variants; programs not
        yet compiled are AOT-compiled first). A process built with
        ``bundle=path`` then serves without a single retrace or backend
        compile. See :mod:`~.compile_plan` for format and commit rules."""
        with _expected_compiles("bundle_save"):
            manifest = _cp.save_bundle(self, path, keys=keys)
        _flight_record("compile", "bundle_save", path=str(path),
                       programs=len(manifest["entries"]),
                       wall_s=manifest.get("save_wall_s"))
        return manifest

    def load_serving_bundle(self, path: str, strict: bool = False) -> bool:
        """Load an AOT bundle into the program registry. Non-strict (the
        constructor path) NEVER raises: any mismatch/corruption logs one
        stderr line, bumps ``paddle_serving_bundle_fallbacks_total`` and
        leaves the engine on the normal lazy-build path."""
        try:
            manifest = _cp.load_bundle(self, path)
        except Exception as e:
            if strict:
                raise
            sys.stderr.write(
                f"[serving] bundle {path} not loaded "
                f"({type(e).__name__}: {e}); falling back to lazy program "
                "builds\n")
            _safe_inc("paddle_serving_bundle_fallbacks_total",
                      "serving bundles rejected at load (engine fell back "
                      "to compiling)", reason=type(e).__name__)
            self._bundle_info = {"loaded": False, "path": str(path),
                                 "error": f"{type(e).__name__}: {e}"}
            _flight_record("compile", "bundle_fallback", path=str(path),
                           error=f"{type(e).__name__}: {str(e)[:200]}")
            return False
        self._bundle_info = {
            "loaded": True,
            "path": str(path),
            "programs": len(manifest.get("entries", [])),
            "fingerprint": str(manifest.get("fingerprint"))[:16],
            # the version identity the fleet deploy pipeline rolls back
            # by — health() surfaces which artifact this engine serves
            "version": manifest.get("version") or _cp.bundle_version_id(
                manifest.get("fingerprint", "?"),
                manifest.get("created_unix", 0) or 0),
        }
        _safe_set("paddle_serving_bundle_loaded",
                  "an AOT serving bundle is live in this engine (1 = yes)",
                  1)
        _safe_set("paddle_serving_bundle_programs",
                  "programs loaded from the serving bundle",
                  self._bundle_info["programs"])
        _flight_record("compile", "bundle_load", path=str(path),
                       programs=self._bundle_info["programs"])
        return True

    def compile_info(self) -> Dict[str, object]:
        """The ``compile`` block of ``health()``/``/healthz``: plan size/
        fingerprint, how many programs are built/warm, bundle + warmup
        status, persistent-cache counters."""
        from ..core import compile_cache as _cc

        plan = self.compile_plan
        return {
            "plan": {"entries": len(plan.entries),
                     "fingerprint": plan.fingerprint()[:16]},
            "programs_built": len(self._programs),
            "programs_warmed": len(self._warmed),
            "warmup": self._warm_info,
            "bundle": self._bundle_info or {"loaded": False},
            "cache": _cc.stats(),
        }

    # -- host orchestration --------------------------------------------------
    def _prefix_plan(self, req, ids, plen):
        """(aligned, n_pfx, hash, entry) for a request's declared shared
        prefix — only FULL pages are shareable, and at least one tail token
        must remain so the first sample has logits to read."""
        pfx_len = int(getattr(req, "prefix_len", 0) or 0)
        if (self.kv_layout != "paged" or not self.prefix_enabled
                or pfx_len <= 0):
            return 0, 0, None, None
        if pfx_len > plen:
            raise ValueError(
                f"prefix_len {pfx_len} exceeds the prompt length {plen}")
        ps = self.page_size
        aligned = (pfx_len // ps) * ps
        if aligned == plen:
            aligned -= ps            # keep >= 1 tail token to sample from
        if aligned < ps:
            return 0, 0, None, None  # too short to share a full page
        n_pfx = aligned // ps
        h = prefix_hash(ids, aligned)
        return aligned, n_pfx, h, self.prefix.lookup(h)

    def _reserve_pages(self, plen: int, budget: int, n_pfx_cached: int,
                       exclude: Optional[str] = None):
        """Allocate the request's private pages (full prompt+budget
        reservation minus cached prefix pages). Returns the page list, or
        None when the pool cannot satisfy it RIGHT NOW (caller waits for
        retirements); raises :class:`KVCapacityError` when it could never
        fit — judged on the TOTAL need (a hit's pinned prefix pages count
        against capacity too, so a hit that would fit privately but not
        alongside its own prefix is typed-rejected, not spun on). LRU
        refcount-0 prefixes are evicted when the free list runs dry;
        ``exclude`` protects the entry this request is about to hit."""
        total = pages_needed(plen + budget, self.page_size)
        need = total - n_pfx_cached
        if total > self.pool.usable:
            raise KVCapacityError(
                f"prompt {plen} + {budget} new tokens needs {total} KV "
                f"pages (page_size {self.page_size}) but the pool holds "
                f"only {self.pool.usable} even when empty — raise "
                "num_pages or shorten the request", pages_needed=total,
                pages_capacity=self.pool.usable)
        if self.pool.free_count < need:
            spill = (self._spill_prefix if self.kv_host is not None
                     else None)
            evicted = self.prefix.evict_until(self.pool, need,
                                              exclude=exclude, spill=spill)
            if evicted:
                _safe_inc("paddle_serving_kv_prefix_evictions_total",
                          "prefix-cache entries LRU-evicted for pages",
                          evicted)
            if self.pool.free_count < need:
                return None
        return self.pool.alloc(need)

    # -- host-RAM prefix spill tier (ROADMAP item 4b) ------------------------
    def _slab_meta(self) -> Dict[str, object]:
        """The engine-compatibility facts a slab must match to restore —
        a mismatch (config change across a restart, foreign slab) is a
        loud miss, never silently-wrong KV."""
        cfg = self.cfg
        return {"page_size": self.page_size,
                "kvh": cfg.num_key_value_heads, "hd": cfg.head_dim,
                "layers": cfg.num_hidden_layers,
                "kv_quant": self.kv_quant or "off",
                "dtype": np.dtype(self._kv_dtype).name}

    def _spill_prefix(self, h: str, entry) -> bool:
        """``evict_until``'s spill callback: serialize the entry's live
        device pages (+ scales under int8) into a slab and hand it to the
        host tier. Runs BEFORE the pages return to the free list. False
        (tier rejected it — bigger than the whole budget) means the
        eviction proceeds as a true discard."""
        from .kv_pool import HostSlab, serialize_page_slab

        idx = np.asarray(entry.pages, np.int32)
        arrays = []
        for kp, vp in self.caches:
            if self.kv_quant == "int8":
                (kq, ksc), (vq, vsc) = kp, vp
                arrays += [np.asarray(kq[idx]), np.asarray(ksc[idx]),
                           np.asarray(vq[idx]), np.asarray(vsc[idx])]
            else:
                arrays += [np.asarray(kp[idx]), np.asarray(vp[idx])]
        meta = dict(self._slab_meta(), length=entry.length,
                    n_pages=len(entry.pages))
        blob = serialize_page_slab(meta, arrays)
        slab = HostSlab(blob, entry.length, len(entry.pages),
                        entry.last_used)
        ok = self.kv_host.put(h, slab)
        if ok:
            _safe_inc("paddle_serving_kv_prefix_spills_total",
                      "prefix entries spilled to the host-RAM tier "
                      "instead of discarded")
            _flight_record("kv", "prefix_spill", hash=h[:16],
                           pages=len(entry.pages), bytes=len(blob))
        return ok

    def _restore_prefix(self, h: str, slab, pfx_pages: List[int]) -> bool:
        """Write a popped host slab back into freshly reserved device
        pages and re-register the prefix (refcount 0 — the hit path about
        to run takes the slot's ref). False on any mismatch/corruption:
        the caller folds the pages back into a full-prefill miss."""
        from .kv_pool import deserialize_page_slab

        try:
            meta, arrays = deserialize_page_slab(slab.blob)
            want = dict(self._slab_meta(), length=meta.get("length"),
                        n_pages=len(pfx_pages))
            if meta != want:
                raise ValueError(f"slab/engine mismatch: {meta} != {want}")
            idx = jnp.asarray(np.asarray(pfx_pages, np.int32))
            per = 4 if self.kv_quant == "int8" else 2
            out = []
            for li, (kp, vp) in enumerate(self.caches):
                a = arrays[li * per:(li + 1) * per]
                if self.kv_quant == "int8":
                    (kq, ksc), (vq, vsc) = kp, vp
                    out.append(((kq.at[idx].set(jnp.asarray(a[0])),
                                 ksc.at[idx].set(jnp.asarray(a[1]))),
                                (vq.at[idx].set(jnp.asarray(a[2])),
                                 vsc.at[idx].set(jnp.asarray(a[3])))))
                else:
                    out.append((kp.at[idx].set(jnp.asarray(a[0])),
                                vp.at[idx].set(jnp.asarray(a[1]))))
            self.caches = out
            entry = self.prefix.register(h, pfx_pages, int(meta["length"]))
            entry.refcount = 0
            _safe_inc("paddle_serving_kv_prefix_restores_total",
                      "prefix entries restored from the host tier into "
                      "device pages")
            _flight_record("kv", "prefix_restore", hash=h[:16],
                           pages=len(pfx_pages), bytes=len(slab.blob))
            return True
        except Exception as e:
            sys.stderr.write(
                f"[serving] host-tier slab {h[:16]} failed to restore "
                f"({type(e).__name__}: {e}); serving the request as a "
                "full-prefill miss\n")
            _safe_inc("paddle_serving_kv_host_restore_failures_total",
                      "host-tier slabs that failed validation/restore "
                      "(request served as a miss)",
                      reason=type(e).__name__)
            return False

    def _admit(self, req) -> bool:
        """Prefill ``req`` into a free slot (one compiled call, no host
        sync); False when no slot (or, paged, no pages) is free."""
        free = [i for i, s in enumerate(self._host_slots) if s.req is None]
        if not free:
            return False
        slot = free[0]
        ids = np.asarray(req.prompt_ids, np.int32).reshape(1, -1)
        plen = ids.shape[1]
        if plen + req.max_new_tokens > self.L:
            raise ValueError(
                f"prompt {plen} + {req.max_new_tokens} new tokens exceeds "
                f"engine max_len {self.L} (model max_position_embeddings "
                f"{self.cfg.max_position_embeddings})")
        bucket = min(_bucket(plen), self.L)
        temp = float(getattr(req, "temperature", 0.0) or 0.0)
        eos = getattr(req, "eos_token_id", None)
        top_k = int(getattr(req, "top_k", 0) or 0)
        if top_k > self.TOP_K_CAP:
            raise ValueError(
                f"top_k {top_k} exceeds the continuous engine's static "
                f"filter cap {self.TOP_K_CAP} (use the static serving mode "
                "or lower top_k)")
        if self.spec is not None and temp > 0.0:
            raise ValueError(
                f"temperature {temp:g} with speculative decoding armed: "
                "greedy acceptance is token-exact for temperature 0 only "
                "(sampling-correct rejection resampling is a planned "
                "seam) — send temperature=0 or serve without spec_k")
        aligned = n_pfx = 0
        h = entry = None
        pages_reserved = None
        restored = False
        if self.kv_layout == "paged":
            aligned, n_pfx, h, entry = self._prefix_plan(req, ids, plen)
            hit = entry is not None
            slab = None
            if not hit and h is not None and self.kv_host is not None:
                # device miss with a spilled copy: POP the slab before the
                # reservation below — its own spills could otherwise push
                # this very slab over the host budget's LRU edge. We own
                # it now: restore it, or put it back on every early exit.
                slab = self.kv_host.pop(h)
            try:
                private = self._reserve_pages(
                    plen, req.max_new_tokens, n_pfx if hit else 0,
                    exclude=h if hit else None)
            except BaseException:
                if slab is not None:
                    self.kv_host.put_back(h, slab)
                raise
            if private is None:
                if slab is not None:
                    self.kv_host.put_back(h, slab)
                return False          # pool dry: decode frees pages later
            if slab is not None:
                # the no-prefix reservation covers prompt+budget in full:
                # its first n_pfx pages become the restored prefix, the
                # rest stay private — exactly a hit's reservation split
                t0r = time.perf_counter()
                pfx_pages, rest = private[:n_pfx], private[n_pfx:]
                if self._restore_prefix(h, slab, pfx_pages):
                    entry = self.prefix.lookup(h)
                    hit = restored = True
                    private = rest
                    self._restore_ms.append(
                        (time.perf_counter() - t0r) * 1e3)
                    del self._restore_ms[:-512]
                else:
                    private = pfx_pages + rest   # bad slab: full miss
            pages_reserved = len(private)
            self._slot_pages[slot] = private
            row = np.zeros((self.P,), np.int32)
            if hit:
                # safe: the reservation above excluded this entry from
                # eviction, so the hash still resolves
                self.prefix.ref(h)
                row[:n_pfx] = entry.pages
                row[n_pfx:n_pfx + len(private)] = private
                self._slot_prefix[slot] = h
                _safe_inc("paddle_serving_kv_prefix_hits_total",
                          "prefix-cache hits (prefill work skipped)")
            else:
                row[:len(private)] = private
            self.page_table = self.page_table.at[slot].set(jnp.asarray(row))
            self._kv_gauges()
        if self.kv_layout == "paged" and entry is not None:
            # HIT: prefill only the tail against the cached prefix pages
            tail = plen - aligned
            tail_bucket = min(_bucket(tail),
                              self.cfg.max_position_embeddings - aligned,
                              self.P * self.page_size - aligned)
            padded = np.zeros((1, tail_bucket), np.int32)
            padded[0, :tail] = ids[0, aligned:]
            fn_key = _cp.prefix_admit_key(n_pfx, tail_bucket)
            prog_plen = tail
            perf_bucket = f"pfx{n_pfx}t{tail_bucket}"
        else:
            padded = np.zeros((1, bucket), np.int32)
            padded[0, :plen] = ids
            fn_key = _cp.admit_key(bucket)
            prog_plen = plen
            perf_bucket = f"p{bucket}"
        args = self._admit_args(
            fn_key, jnp.asarray(padded), plen=prog_plen, slot=slot,
            temp=temp, eos=-1 if eos is None else int(eos),
            budget=req.max_new_tokens, top_k=top_k)
        fn = self._programs.get(fn_key)
        if fn is None:
            fn = self._build_program(fn_key)
            p = _perf()
            if p is not None and p.enabled():
                # capture the bucketed prefill program's exact cost; the
                # AOT Compiled replaces the jit entry (one compile total)
                compiled = p.capture_jit("serving.admit", fn, args,
                                         bucket=perf_bucket, quant=self.quant
                                         or "off")
                if compiled is not None:
                    fn = compiled
            self._programs[fn_key] = fn
        try:
            (self.caches, self.lens, self.tokens, self.active, self.temps,
             self.eos_ids, self.budgets, self.top_ks, self.key, first) = \
                fn(*args)
        except BaseException:
            # the reservation must not outlive a failed admission (a
            # compile/dispatch error here would otherwise leak the pages
            # until a full reset)
            self._release_kv(slot)
            raise
        # only AFTER the first call succeeds: a failed first admission
        # (chaos, OOM) must not mask this key from a later warmup()
        self._warmed.add(fn_key)
        if self.kv_layout == "paged" and h is not None and entry is None:
            # MISS with a declared prefix: the full prefill just wrote the
            # prefix pages — pin them shared (this slot holds the first
            # ref); the slot keeps only its private tail/decode pages
            self.prefix.register(h, self._slot_pages[slot][:n_pfx], aligned)
            self.prefix.misses += 1
            self._slot_pages[slot] = self._slot_pages[slot][n_pfx:]
            self._slot_prefix[slot] = h
        if self.spec is not None:
            # draft prefill rides every admission: the draft keeps no
            # prefix cache, so it prefills the FULL prompt at the plain
            # bucket even when the target admission was a prefix HIT
            dpad = np.zeros((1, bucket), np.int32)
            dpad[0, :plen] = ids
            dkey = _cp.draft_admit_key(bucket)
            try:
                (self.spec.draft_caches, self.spec.prev_tokens) = \
                    self._program(dkey)(
                        self.spec.draft_params, self.spec.draft_caches,
                        self.spec.prev_tokens, jnp.asarray(dpad),
                        jnp.int32(plen), jnp.int32(slot))
            except BaseException:
                # the target-side admission already committed: deactivate
                # the device lane and return the pages, or a failed draft
                # prefill leaks the whole reservation
                self.reset_slots([slot])
                raise
            self._warmed.add(dkey)
        self._host_slots[slot] = _Slot(req, budget=int(req.max_new_tokens))
        self.stats["peak_busy"] = max(self.stats["peak_busy"],
                                      self.busy_slots())
        _stamp(req, "_t_admit")
        tr = _trace_of(req)
        if tr is not None:
            try:
                res = req.result
                tr.event("queue.wait", t0=res._t_submit, t1=res._t_admit)
                tr.event(
                    "admit", slot=slot, bucket=bucket, plen=plen,
                    **({} if pages_reserved is None
                       else {"pages": pages_reserved}),
                    **({} if h is None
                       else {"prefix": "restore" if restored
                             else ("hit" if entry is not None
                                   else "miss"),
                             "prefix_pages": n_pfx}))
                if self.spec is not None:
                    tr.event("spec.draft_prefill", bucket=bucket)
            except Exception:
                pass
        _flight_record("request", str(getattr(req, "id", "?")),
                       phase="admit", slot=slot, bucket=bucket, plen=plen,
                       **({"prefix_hit": entry is not None} if h else {}))
        self._first_pending[slot] = first   # device scalar, synced at collect
        self.stats["requests"] += 1
        return True

    def _release_kv(self, slot: int, zero_row: bool = True) -> None:
        """Return a slot's private pages to the free list, drop its prefix
        ref, and (by default) zero its page-table row so in-flight decode
        writes land in the null page. Idempotent."""
        if self.kv_layout != "paged":
            return
        pages = self._slot_pages[slot]
        if pages:
            self.pool.free(pages)
            self._slot_pages[slot] = []
        h = self._slot_prefix[slot]
        if h is not None:
            self.prefix.unref(h)
            self._slot_prefix[slot] = None
        if zero_row:
            self.page_table = self.page_table.at[slot].set(
                jnp.zeros((self.P,), jnp.int32))
        self._kv_gauges()

    def _retire(self, slot: int):
        s = self._host_slots[slot]
        if s.req is not None:
            prompt = np.asarray(s.req.prompt_ids, np.int32).reshape(-1)
            gen = s.emitted[: s.budget]
            eos = getattr(s.req, "eos_token_id", None)
            if eos is not None and eos in gen:
                gen = gen[: gen.index(eos) + 1]   # trim past eos, keep it
            res = getattr(s.req, "result", None)
            if res is not None and getattr(res, "_event", None) is not None \
                    and res._event.is_set():
                # the future already has an outcome (a client cancel
                # raced this chunk's retirement): the _set below will
                # lose, nobody receives these tokens — attribute ALL of
                # them to the cancel kind, not to useful
                _account(getattr(res, "_cancel_kind", "cancel"),
                         len(s.emitted))
            else:
                _account("useful", len(gen))
                # tokens emitted past eos/budget and trimmed here: real
                # decode work nobody receives (the spec chunk's tail,
                # the chunk that overshot the budget)
                _account("overshoot", len(s.emitted) - len(gen))
            _stamp(s.req, "_n_new", len(gen))
            if self.spec is not None:
                # accepted counts ride the result future so slo()
                # consumers and benches can report tokens-per-target-step
                # per request, not just engine-wide
                _stamp(s.req, "_spec_steps", s.spec_steps)
                _stamp(s.req, "_spec_accepted", s.spec_accepted)
            s.req.result._set(output=np.concatenate(
                [prompt, np.asarray(gen, np.int32)]))
        self._release_kv(slot)
        self._host_slots[slot] = _Slot()

    def _collect_firsts(self):
        """ONE host sync for every first token admitted since the last
        collect (stacked on device, then a single transfer). Returns the
        slots whose ``_t_first`` was stamped by THIS collect — the spec
        chunk uses it to count tokens that landed at the same sync."""
        if not self._first_pending:
            return []
        slots = sorted(self._first_pending)
        vals = np.asarray(jnp.stack([self._first_pending[i] for i in slots]))
        now = time.perf_counter()
        stamped = []
        for i, slot in enumerate(slots):
            s = self._host_slots[slot]
            if s.req is not None:
                s.emitted.append(int(vals[i]))
                self.stats["tokens_out"] += 1
                # the prefill's sampled token reaching the HOST is the
                # honest first-token time (TTFT numerator)
                if getattr(s.req.result, "_t_first", 1) is None:
                    _stamp(s.req, "_t_first", now)
                    stamped.append(slot)
                    tr = _trace_of(s.req)
                    if tr is not None:
                        tr.event("first_token", t0=now)
        self._first_pending.clear()
        return stamped

    def reset_slots(self, slots=None):
        """Deactivate device-side slot state (all slots, or the given list)
        — REQUIRED after a failed decode or engine stop, or retired rows
        keep consuming compute as phantom active lanes in every chunk.
        Paged layout also returns the slots' pages to the free list."""
        if slots is None:
            self.active = self._repl(jnp.zeros((self.S,), bool))
            self._first_pending.clear()
            if self.kv_layout == "paged":
                for i in range(self.S):
                    self._release_kv(i, zero_row=False)
                self.page_table = self._repl(
                    jnp.zeros((self.S, self.P), jnp.int32))
        else:
            for i in slots:
                self.active = self.active.at[int(i)].set(False)
                # only THIS slot's pending first token: other slots' pending
                # syncs must survive a single-slot reset
                self._first_pending.pop(int(i), None)
                self._release_kv(int(i))

    def release_slot(self, slot: int, reason: str = "cancel"):
        """Free one slot without delivering a result — the cancellation /
        deadline path: the device lane goes inactive (no phantom compute),
        the host slot is recycled, and the next admission may reuse it. The
        caller owns failing the request's future. ``reason`` names the
        goodput kind the slot's already-decoded tokens are wasted as."""
        s = self._host_slots[int(slot)]
        if s.req is not None:
            _account(reason, len(s.emitted))
        self.reset_slots([slot])
        self._host_slots[int(slot)] = _Slot()

    def busy_slots(self) -> int:
        """Host-visible count of slots holding an in-flight request."""
        return sum(1 for s in self._host_slots if s.req is not None)

    def _spec_chunk(self):
        """The speculative serve step: per outer step, ONE draft program
        call (k greedy proposals) then ONE verify call (batched target
        forward + masked accept/reject); the chunk's payloads stay on
        device and sync to the host as a single transfer, exactly the
        non-spec chunk's cadence. Rejected tokens cost nothing to roll
        back — ``lens`` simply didn't advance past them."""
        spec = self.spec
        k = spec.k
        steps = self._spec_steps_per_chunk
        t0 = time.perf_counter()
        dkey, vkey = _cp.draft_key(k), _cp.verify_key(k)
        dfn = self._program(dkey)
        vfn = self._program(vkey)
        parts = []
        for _ in range(steps):
            spec.draft_caches, props = dfn(
                spec.draft_params, spec.draft_caches, spec.prev_tokens,
                self.tokens, self.lens, self.active)
            (self.caches, self.lens, self.tokens, spec.prev_tokens,
             self.active, self.budgets, payload) = vfn(
                self.params, self.caches, self.page_table, self.lens,
                self.tokens, spec.prev_tokens, self.active, self.budgets,
                self.eos_ids, props)
            parts.append(payload)
        # post-success, exactly like the non-spec chunk: a failed first
        # call must not mask these keys from a later warmup()
        self._warmed.add(dkey)
        self._warmed.add(vkey)
        self.stats["decode_calls"] += 1
        stamped = self._collect_firsts()
        pk = np.asarray(parts[0] if steps == 1
                        else jnp.concatenate(parts, axis=1))
        blocks = pk.reshape(self.S, steps, k + 3)
        em = blocks[:, :, : k + 1]           # emitted tokens, -1 padded
        acc = blocks[:, :, k + 1]            # raw accepted-run lengths
        act = blocks[:, -1, k + 2].astype(bool)
        chunk_emitted = 0
        for slot, s in enumerate(self._host_slots):
            if s.req is None:
                continue
            toks = [int(t) for t in em[slot].ravel() if t >= 0]
            s.emitted.extend(toks)
            chunk_emitted += len(toks)
            self.stats["tokens_out"] += len(toks)
            live = acc[slot][acc[slot] >= 0]
            s.spec_steps += int(live.size)
            s.spec_accepted += int(live.sum())
            # drafted-but-rejected proposals: k drafted per live verify
            # step minus the accepted run — real draft work the target
            # never advanced past (outside the tokens_out identity)
            _account("spec_rejected", int(k * live.size - live.sum()))
            tr = _trace_of(s.req)
            if tr is not None and live.size:
                tr.event("spec.round", t0=t0, t1=time.perf_counter(),
                         tokens=len(toks), **spec.round_summary(acc[slot]))
            if slot in stamped and toks:
                # this sync delivered the admission's first token AND the
                # chunk's tokens at the same instant — record how many, so
                # slo()'s TPOT divides by tokens that arrived AFTER
                # _t_first instead of fabricating a k-times-faster stream
                _stamp(s.req, "_n_at_first", 1 + len(toks))
            if not act[slot] or len(s.emitted) >= s.budget:
                self._retire(slot)
        spec.record_chunk(acc, chunk_emitted)

    def _decode_chunk(self):
        if self.spec is not None:
            return self._spec_chunk()
        args = self._decode_args()
        p = _perf()
        perf_on = p is not None and p.enabled()
        # fused engines get their own cost-registry bucket so an A/B in
        # one process records the reference and fused decode programs as
        # SEPARATE rows — the hbm_bytes delta between them is the
        # data-movement claim the kernel makes (docs/kernels.md)
        cost_bucket = (f"s{self.S}c{self.chunk}"
                       + ("-fused" if self.fused.get("enabled") else ""))
        if perf_on and not self._decode_captured:
            self._decode_captured = True    # capture attempted once only
            # lower (no backend compile) a 1-step variant and scale by
            # chunk: XLA cost analysis counts the scan body once, so the
            # chunk program's own count would under-report by ~chunk
            p.cost_of_lowered(
                "serving.decode", jax.jit(self._decode_program(1)), args,
                bucket=cost_bucket, scale=float(self.chunk),
                quant=self.quant or "off", slots=self.S, chunk=self.chunk,
                fused=self.fused.get("paged_attention", "off"))
        # chunks right after an admission also pay the _collect_firsts
        # readback inside this window; only PURE decode chunks are folded
        # into the program's wall, so wall_min measures the decode
        # program, not an extra link roundtrip
        pure_decode = not self._first_pending
        fn = self._programs.get("decode")
        if fn is None:
            fn = self._build_program("decode")
            self._programs["decode"] = fn
        t0 = time.perf_counter()
        (self.caches, self.tokens, self.lens, self.active, self.budgets,
         self.key, packed) = fn(*args)
        # post-success: a failed first chunk must not mask the key from a
        # later warmup()
        self._warmed.add("decode")
        self.stats["decode_calls"] += 1
        self._collect_firsts()
        pk = np.asarray(packed)                 # the ONE sync per chunk
        if perf_on and pure_decode:
            # the packed readback IS this chunk's host sync, so the wall
            # is real device time (plus the per-call link floor)
            p.observe("serving.decode", time.perf_counter() - t0,
                      bucket=cost_bucket)
        em, act = pk[:, :-1], pk[:, -1].astype(bool)
        t_sync = None
        for slot, s in enumerate(self._host_slots):
            if s.req is None:
                continue
            toks = [int(t) for t in em[slot] if t >= 0]
            s.emitted.extend(toks)
            self.stats["tokens_out"] += len(toks)
            tr = _trace_of(s.req)
            if tr is not None and toks:
                if t_sync is None:
                    t_sync = time.perf_counter()
                tr.event("decode.chunk", t0=t0, t1=t_sync,
                         tokens=len(toks))
            if not act[slot] or len(s.emitted) >= s.budget:
                self._retire(slot)

    def flush(self):
        """Deliver results for slots that finished during admission (first
        token hit eos / budget 1) without waiting for a decode chunk."""
        self._collect_firsts()
        act = np.asarray(self.active)
        for slot, s in enumerate(self._host_slots):
            if s.req is not None and (not act[slot]
                                      or len(s.emitted) >= s.budget):
                self._retire(slot)

    def serve(self, requests, timeout: float = 600.0):
        """Run a list of GenerationRequest-shaped objects to completion with
        continuous batching. Returns aggregate stats (the card number)."""
        pending = list(requests)
        t0 = time.perf_counter()
        n_out0 = self.stats["tokens_out"]
        deadline = t0 + timeout
        while (pending or any(s.req is not None for s in self._host_slots)) \
                and time.perf_counter() < deadline:
            while pending:
                try:
                    if not self._admit(pending[0]):
                        break                  # no slot/pages free: decode
                except ValueError as e:
                    # unservable request (max_len / top_k / KV capacity):
                    # fail ITS future and keep serving the rest — one bad
                    # request must not abandon the whole list
                    try:
                        pending[0].result._set(error=e)
                    except Exception:
                        pass
                pending.pop(0)
            if any(s.req is not None for s in self._host_slots):
                self._decode_chunk()
        self.flush()
        dt = time.perf_counter() - t0
        toks = self.stats["tokens_out"] - n_out0
        return {"wall_s": round(dt, 3),
                "new_tokens": toks,
                "agg_tokens_per_sec": round(toks / max(dt, 1e-9), 1),
                "decode_calls": self.stats["decode_calls"]}
