"""Compile plan + AOT serving bundles — the cold-start kill switch.

Reference surface: the deployment layer's ``save_inference_model`` /
``jit.save`` contract (paddle/fluid/inference — a serving process loads a
ready artifact instead of rebuilding programs). JAX-native equivalent,
split in three:

* **CompilePlan** — a declarative enumeration of every compiled program a
  :class:`~.decode_engine.BatchDecodeEngine` config implies: the chunked
  decode program plus one admission program per prompt-length bucket
  (``prompt_buckets``), each entry carrying its donate/static facts. The
  plan is the single seam the engine's formerly scattered program
  construction (lazy per-bucket ``jax.jit`` builds, prefix-HIT factories)
  now flows through: ``engine.warmup()`` walks it eagerly,
  ``save_bundle``/``load_bundle`` serialize it, ``health()`` reports it,
  and a future mesh-planning pass can rewrite it before anything
  compiles.
* **Fingerprint** — a sha256 over the plan's *facts* (model architecture,
  slots/len/chunk, KV layout + page geometry, quant scheme, mesh, jax/
  jaxlib/platform). Two engines with equal fingerprints compile
  interchangeable programs; a bundle is only loaded into an engine whose
  fingerprint matches its manifest.
* **Bundle** — a directory of AOT-serialized compiled executables
  (``jax.experimental.serialize_executable`` — the XLA executable itself,
  not StableHLO, so loading performs ZERO retrace and ZERO backend
  compile) plus ``manifest.json``. Argument/output pytree structures are
  NOT pickled into the bundle: they are reconstructed at load time from
  the live engine's own state templates (``_example_args`` /
  ``_out_template``), which sidesteps custom-pytree (QuantizedWeight)
  serialization entirely and is one more reason the fingerprint gate must
  pass first.

Commit discipline mirrors checkpoint format v3: bundles are written to a
staging directory and renamed into place, so a killed save leaves the
previous bundle intact or the path absent — never a half-written artifact.
"""

from __future__ import annotations

import hashlib
import json
import os
import re
import shutil
import time
from typing import Dict, List, Optional, Tuple

BUNDLE_FORMAT_VERSION = 1
MANIFEST_NAME = "manifest.json"

# program keys are strings so they double as bundle file names:
#   "decode"                 — the chunked multi-step decode program
#   "admit_p<bucket>"        — admission prefill at one prompt bucket
#   "admit_pfx<n>t<bucket>"  — prefix-HIT admission (n cached pages,
#                              tail bucket) — built on traffic, bundled
#                              when present
#   "draft_admit_p<bucket>"  — speculative: draft-model prompt prefill
#   "draft_k<K>"             — speculative: K greedy draft proposals
#   "verify_k<K>"            — speculative: one batched target verify
#                              over K+1 positions + masked accept/reject
_ADMIT_RE = re.compile(r"^admit_p(\d+)$")
_PREFIX_RE = re.compile(r"^admit_pfx(\d+)t(\d+)$")
_DRAFT_ADMIT_RE = re.compile(r"^draft_admit_p(\d+)$")
_DRAFT_RE = re.compile(r"^draft_k(\d+)$")
_VERIFY_RE = re.compile(r"^verify_k(\d+)$")


def decode_key() -> str:
    return "decode"


def admit_key(bucket: int) -> str:
    return f"admit_p{int(bucket)}"


def prefix_admit_key(n_pfx: int, tail_bucket: int) -> str:
    return f"admit_pfx{int(n_pfx)}t{int(tail_bucket)}"


def draft_admit_key(bucket: int) -> str:
    return f"draft_admit_p{int(bucket)}"


def draft_key(k: int) -> str:
    return f"draft_k{int(k)}"


def verify_key(k: int) -> str:
    return f"verify_k{int(k)}"


def parse_key(key: str) -> Tuple[str, Dict[str, int]]:
    """(kind, info) for a program key; raises ValueError on garbage so a
    tampered bundle entry fails loud instead of building nonsense."""
    if key == "decode":
        return "decode", {}
    m = _ADMIT_RE.match(key)
    if m:
        return "admit", {"bucket": int(m.group(1))}
    m = _PREFIX_RE.match(key)
    if m:
        return "prefix", {"n_pfx": int(m.group(1)),
                          "tail_bucket": int(m.group(2))}
    m = _DRAFT_ADMIT_RE.match(key)
    if m:
        return "draft_admit", {"bucket": int(m.group(1))}
    m = _DRAFT_RE.match(key)
    if m:
        return "draft", {"k": int(m.group(1))}
    m = _VERIFY_RE.match(key)
    if m:
        return "verify", {"k": int(m.group(1))}
    raise ValueError(f"unrecognized compile-plan program key {key!r}")


def prompt_buckets(max_len: int, q: int = 128) -> List[int]:
    """Every admission bucket the engine can compile: multiples of ``q``
    below ``max_len``, then ``max_len`` itself (the engine clips
    ``_bucket(plen)`` to ``max_len``, so the top bucket is always L)."""
    buckets = []
    b = q
    while b < max_len:
        buckets.append(b)
        b += q
    buckets.append(int(max_len))
    return buckets


class PlanEntry:
    """One compiled program the plan implies."""

    __slots__ = ("key", "kind", "meta")

    def __init__(self, key: str, kind: str, meta: Optional[Dict] = None):
        self.key = key
        self.kind = kind
        self.meta = dict(meta or {})

    def describe(self) -> Dict[str, object]:
        return {"key": self.key, "kind": self.kind, **self.meta}

    def __repr__(self):
        return f"PlanEntry({self.key})"


class CompilePlan:
    """Declarative program inventory for one engine config + the facts
    that make its compiled programs exchangeable (the fingerprint)."""

    def __init__(self, entries: List[PlanEntry], facts: Dict[str, object]):
        self.entries = list(entries)
        self.facts = facts
        self._fingerprint: Optional[str] = None

    @classmethod
    def for_engine(cls, engine) -> "CompilePlan":
        """Enumerate what ``engine``'s config implies: one decode program
        and one admission program per prompt bucket. Prefix-HIT programs
        are traffic-shaped (cached pages x tail bucket) so they are not
        pre-enumerated — once built they ride warmup state and bundles
        like any other program."""
        import jax
        import jaxlib

        cfg = engine.cfg
        model = {k: v for k, v in sorted(vars(cfg).items())
                 if isinstance(v, (int, float, str, bool, type(None)))}
        facts: Dict[str, object] = {
            "model": model,
            "max_slots": engine.S,
            "max_len": engine.L,
            "chunk": engine.chunk,
            "kv_layout": engine.kv_layout,
            "page_size": engine.page_size,
            "num_pages": (engine.pool.num_pages
                          if engine.pool is not None else 0),
            "prefix_cache": bool(engine.prefix_enabled),
            "quant": engine.quant or "off",
            "quant_group_size": (engine.quant_meta.get("group_size", -1)
                                 if engine.quant else -1),
            # int8 KV pages change every program that touches the pool
            # (admission quantize-scatter, decode dequant, verify) AND the
            # cache pytree's treedef — a bundle built under the other
            # scheme must be rejected at load, not deserialized into the
            # wrong structure. The host spill tier is deliberately NOT a
            # fact: it never changes a compiled program.
            "kv_quant": getattr(engine, "kv_quant", None) or "off",
            "mesh": (engine.plan.describe()
                     if engine.plan is not None else None),
            # speculative decoding: draft arch + quant + k make the
            # draft/verify programs (and the decode path's semantics)
            # exchangeable — a draft-model swap MUST change the
            # fingerprint so a stale bundle falls back loudly instead of
            # serving another draft's executables
            "spec": (engine.spec.facts()
                     if getattr(engine, "spec", None) is not None else None),
            # fused-kernel resolution, NORMALIZED to the program identity
            # actually compiled: "fused" (kernel in the decode/verify
            # programs) vs "reference" (off OR fell back — byte-identical
            # programs, so a fallback engine still loads a reference
            # bundle). A kernel-config change compiles DIFFERENT programs
            # and must reject foreign bundles loudly; the human-readable
            # fallback reason stays in health()["fused"], not the hash
            "fused": {
                "paged_attention": (
                    "fused" if getattr(engine, "fused", {}).get("enabled")
                    else "reference")},
            "jax": jax.__version__,
            "jaxlib": jaxlib.__version__,
            "platform": jax.default_backend(),
            "n_devices": jax.device_count(),
        }
        spec_on = getattr(engine, "spec", None) is not None
        entries = []
        if not spec_on:
            # a speculative engine routes EVERY chunk through the
            # draft/verify programs, so the plain chunked-decode scan —
            # the single most expensive compile in the plan — would be
            # dead weight in warmup and bundles
            entries.append(PlanEntry(decode_key(), "decode",
                                     {"slots": engine.S,
                                      "chunk": engine.chunk}))
        for b in prompt_buckets(engine.L):
            entries.append(PlanEntry(admit_key(b), "admit", {"bucket": b}))
        if spec_on:
            k = engine.spec.k
            for b in prompt_buckets(engine.L):
                entries.append(PlanEntry(draft_admit_key(b), "draft_admit",
                                         {"bucket": b}))
            entries.append(PlanEntry(draft_key(k), "draft", {"k": k}))
            entries.append(PlanEntry(verify_key(k), "verify", {"k": k}))
        return cls(entries, facts)

    def keys(self) -> List[str]:
        return [e.key for e in self.entries]

    def fingerprint(self) -> str:
        """Stable content hash of the facts — NOT of the entry list, so a
        bundle carrying extra traffic-built programs (prefix variants)
        still matches an engine whose static plan lacks them."""
        if self._fingerprint is None:
            blob = json.dumps(self.facts, sort_keys=True, default=str)
            self._fingerprint = hashlib.sha256(blob.encode()).hexdigest()
        return self._fingerprint

    def describe(self) -> Dict[str, object]:
        """The ``health()``/``/healthz`` compile-plan block."""
        return {
            "entries": len(self.entries),
            "keys": self.keys(),
            "fingerprint": self.fingerprint()[:16],
        }


class BundleMismatchError(RuntimeError):
    """A bundle exists but cannot serve this engine: fingerprint/platform/
    version/integrity mismatch. Carries the differing fields so the
    fallback log says WHY the artifact was rejected."""

    def __init__(self, msg: str, mismatches: Optional[List[str]] = None):
        super().__init__(msg)
        self.mismatches = list(mismatches or [])


def _facts_diff(a: Dict, b: Dict) -> List[str]:
    keys = sorted(set(a) | set(b))
    return [k for k in keys if a.get(k) != b.get(k)]


# -- bundle version identity (stdlib — the fleet deploy pipeline reads
#    these without importing jax) ------------------------------------------

def bundle_version_id(fingerprint: str, created_unix: float) -> str:
    """Short human-safe version id: enough fingerprint to name the
    compiled-program identity, plus the save second so two rebuilds of
    the SAME facts are still tellable apart in a rollout/rollback log."""
    return f"{str(fingerprint)[:12]}@{int(created_unix)}"


def read_manifest(path: str) -> Dict[str, object]:
    """Load a bundle's manifest (stdlib, no jax). Older bundles saved
    before the ``version`` field get one derived from their fingerprint +
    timestamp, so every manifest this returns carries a version identity
    the rollback machinery can key on."""
    with open(os.path.join(path, MANIFEST_NAME)) as f:
        manifest = json.load(f)
    if not isinstance(manifest, dict):
        raise ValueError(f"{path}: manifest is not a JSON object")
    if not manifest.get("version"):
        manifest["version"] = bundle_version_id(
            manifest.get("fingerprint", "?"),
            manifest.get("created_unix", 0) or 0)
    return manifest


def validate_bundle(path: str) -> Dict[str, object]:
    """Pre-flight a candidate bundle for the fleet deploy pipeline —
    cheap, stdlib-only, BEFORE any replica is touched: the manifest
    parses, the format version is supported, a fingerprint is present,
    and every entry's payload exists and matches its sha256. Returns the
    manifest (with ``version``). Raises :class:`BundleMismatchError` /
    OSError / ValueError on any problem; whether the fingerprint matches
    a given ENGINE is still decided at load time per replica."""
    manifest = read_manifest(path)
    if manifest.get("format_version") != BUNDLE_FORMAT_VERSION:
        raise BundleMismatchError(
            f"bundle format {manifest.get('format_version')!r} != "
            f"{BUNDLE_FORMAT_VERSION}", ["format_version"])
    if not manifest.get("fingerprint"):
        raise BundleMismatchError("bundle manifest carries no fingerprint",
                                  ["fingerprint"])
    for entry in manifest.get("entries", []):
        key = entry.get("key", "?")
        parse_key(key)
        fpath = os.path.join(path, entry.get("file", ""))
        with open(fpath, "rb") as f:
            digest = hashlib.sha256(f.read()).hexdigest()
        if digest != entry.get("sha256"):
            raise BundleMismatchError(
                f"bundle entry {key}: payload sha256 mismatch "
                "(corrupted or tampered artifact)", [key])
    return manifest


def save_bundle(engine, path: str,
                keys: Optional[List[str]] = None) -> Dict[str, object]:
    """Serialize the engine's compiled programs (every plan entry plus any
    traffic-built extras, e.g. prefix-HIT variants) into a bundle
    directory at ``path``. Programs not yet compiled are AOT-compiled
    here — saving from a warmed engine serializes the exact executables
    it serves with. Returns the manifest. Atomic: staging dir + rename."""
    import jax
    import jaxlib
    from jax.experimental import serialize_executable as _se

    if keys is None:
        plan_keys = engine.compile_plan.keys()
        extra = sorted(k for k in engine._programs if k not in plan_keys)
        keys = plan_keys + extra
    staging = f"{path}.staging.{os.getpid()}"
    shutil.rmtree(staging, ignore_errors=True)
    os.makedirs(staging)
    t0 = time.perf_counter()
    entries = []
    try:
        for key in keys:
            parse_key(key)                     # refuse unsaveable keys early
            fn = engine._programs.get(key)
            if fn is None or hasattr(fn, "lower"):
                # still a lazy jit (or never built): AOT-compile now and
                # keep the Compiled so the live engine serves what it saved
                jit_fn = fn if fn is not None else engine._build_program(key)
                fn = jit_fn.lower(*engine._example_args(key)).compile()
                engine._programs[key] = fn
                engine._warmed.add(key)
            payload, in_tree, out_tree = _se.serialize(fn)
            try:
                _se.deserialize_and_load(payload, in_tree, out_tree)
            except Exception:
                # a payload that cannot load back is worse than no bundle
                # (it fails at RESTART, the moment the bundle exists for).
                # Known cause on this jaxlib's CPU backend: ``fn`` was
                # itself deserialized (a persistent-cache hit), and
                # re-serializing such an executable drops the kernel
                # object code. Recompile for real with the cache detached
                # and serialize THAT; a second probe failure is fatal.
                from ..core.compile_cache import cache_bypassed

                with cache_bypassed():
                    fn = engine._build_program(key).lower(
                        *engine._example_args(key)).compile()
                engine._programs[key] = fn
                payload, in_tree, out_tree = _se.serialize(fn)
                _se.deserialize_and_load(payload, in_tree, out_tree)
            fname = f"{key}.xc"
            with open(os.path.join(staging, fname), "wb") as f:
                f.write(payload)
            entries.append({
                "key": key,
                "file": fname,
                "bytes": len(payload),
                "sha256": hashlib.sha256(payload).hexdigest(),
            })
        created = time.time()
        manifest = {
            "format_version": BUNDLE_FORMAT_VERSION,
            "created_unix": round(created, 3),
            "version": bundle_version_id(
                engine.compile_plan.fingerprint(), created),
            "fingerprint": engine.compile_plan.fingerprint(),
            "facts": engine.compile_plan.facts,
            "jax": jax.__version__,
            "jaxlib": jaxlib.__version__,
            "platform": jax.default_backend(),
            "n_devices": jax.device_count(),
            "entries": entries,
        }
        with open(os.path.join(staging, MANIFEST_NAME), "w") as f:
            json.dump(manifest, f, indent=1, default=str)
        # committed-or-absent (checkpoint v3 discipline): the only
        # non-atomic window is between removing an OLD bundle and the
        # rename; a failed commit (path occupied by a non-directory,
        # concurrent recreation) must not leak the staging dir either
        if os.path.isdir(path):
            shutil.rmtree(path)
        os.rename(staging, path)
    except BaseException:
        shutil.rmtree(staging, ignore_errors=True)
        raise
    manifest["save_wall_s"] = round(time.perf_counter() - t0, 3)
    return manifest


def load_bundle(engine, path: str) -> Dict[str, object]:
    """Deserialize a bundle into the engine's program registry — zero
    retrace, zero backend compile. All-or-nothing: the registry is only
    touched after every entry loads and verifies. Raises
    :class:`BundleMismatchError` (or OSError/ValueError) on any problem;
    the engine's non-strict wrapper turns that into a logged fallback."""
    import jax
    import jaxlib
    from jax.experimental import serialize_executable as _se
    from jax.tree_util import tree_structure

    mpath = os.path.join(path, MANIFEST_NAME)
    with open(mpath) as f:
        manifest = json.load(f)
    if manifest.get("format_version") != BUNDLE_FORMAT_VERSION:
        raise BundleMismatchError(
            f"bundle format {manifest.get('format_version')!r} != "
            f"{BUNDLE_FORMAT_VERSION}", ["format_version"])
    env_mismatch = []
    if manifest.get("platform") != jax.default_backend():
        env_mismatch.append(
            f"platform {manifest.get('platform')}!={jax.default_backend()}")
    if manifest.get("jaxlib") != jaxlib.__version__:
        env_mismatch.append(
            f"jaxlib {manifest.get('jaxlib')}!={jaxlib.__version__}")
    if env_mismatch:
        # serialized executables are jaxlib+platform artifacts; a partial
        # deserialize crash is exactly what this check pre-empts
        raise BundleMismatchError(
            "bundle was built for a different runtime: "
            + ", ".join(env_mismatch), env_mismatch)
    fp = engine.compile_plan.fingerprint()
    if manifest.get("fingerprint") != fp:
        diff = _facts_diff(manifest.get("facts") or {},
                           engine.compile_plan.facts)
        raise BundleMismatchError(
            f"bundle fingerprint {str(manifest.get('fingerprint'))[:16]} != "
            f"engine {fp[:16]} (differing facts: {', '.join(diff) or '?'})",
            diff)
    loaded: Dict[str, object] = {}
    for entry in manifest.get("entries", []):
        key = entry["key"]
        parse_key(key)                          # garbage keys fail loud
        fpath = os.path.join(path, entry["file"])
        with open(fpath, "rb") as f:
            payload = f.read()
        digest = hashlib.sha256(payload).hexdigest()
        if digest != entry.get("sha256"):
            raise BundleMismatchError(
                f"bundle entry {key}: payload sha256 mismatch "
                "(corrupted or tampered artifact)", [key])
        # pytree structures come from the LIVE engine, not the disk: the
        # fingerprint gate already proved both sides build identical arg
        # trees, and this keeps custom pytree leaves (QuantizedWeight)
        # out of the serialization format entirely
        in_tree = tree_structure((engine._example_args(key), {}))
        out_tree = tree_structure(engine._out_template(key))
        try:
            loaded[key] = _se.deserialize_and_load(payload, in_tree,
                                                   out_tree)
        except Exception as e:
            raise BundleMismatchError(
                f"bundle entry {key}: executable failed to deserialize "
                f"({type(e).__name__}: {e})", [key]) from e
    engine._programs.update(loaded)
    engine._warmed.update(loaded)
    return manifest
