"""Host-side KV page-pool bookkeeping for the paged decode engine.

Reference surface: the paged serving path — paddle/phi/kernels/fusion/gpu/
block_multi_head_attention_kernel.cu's block tables, the vLLM
PagedAttention allocator design ROADMAP item 1 points at. On GPU the
allocator hands out scattered physical blocks and the kernel chases the
block table; under static-shape XLA the *device* half is a
``[slots, max_len/page_size]`` int32 page table used as a gather index
(decode_engine.py), and everything here is the *host* half: a free list, a
per-slot page ledger, and a ref-counted LRU registry of shared prompt
prefixes.

Deliberately jax-free and lock-free: the one engine thread owns every
mutation (admission, retirement, eviction) exactly like the rest of the
decode engine's host bookkeeping, and the unit tests
(tests/test_paged_kv.py) exercise it standalone.

Page 0 is the NULL page: every unmapped page-table entry points at it, so
an in-graph scatter past a slot's reservation lands in one sacrificial
page and a gather through an unmapped entry reads finite garbage that the
causal/length mask already hides. It is never allocated.
"""

from __future__ import annotations

import hashlib
import itertools
from typing import Dict, List, Optional

__all__ = ["PagePool", "PrefixCache", "PrefixEntry", "pages_needed",
           "prefix_hash"]


def pages_needed(tokens: int, page_size: int) -> int:
    """Pages covering ``tokens`` KV positions (ceil division)."""
    return -(-int(tokens) // int(page_size))


def prefix_hash(prompt_ids, aligned: int) -> str:
    """Content hash of the page-aligned shared prefix. Keyed by the token
    bytes AND the aligned length, so a prefix cached at 128 tokens never
    answers a lookup for its own 64-token head."""
    import numpy as np

    ids = np.ascontiguousarray(np.asarray(prompt_ids, np.int32).reshape(-1))
    return f"{aligned}:" + hashlib.sha1(ids[:aligned].tobytes()).hexdigest()


class PagePool:
    """Free list over ``num_pages`` physical KV pages (page 0 reserved as
    the null page). ``alloc``/``free`` are O(n) list ops on the host path
    that already does per-request Python bookkeeping."""

    def __init__(self, num_pages: int, page_size: int):
        if num_pages < 2:
            raise ValueError(
                f"num_pages must be >= 2 (page 0 is the reserved null "
                f"page), got {num_pages}")
        if page_size < 1:
            raise ValueError(f"page_size must be >= 1, got {page_size}")
        self.num_pages = int(num_pages)
        self.page_size = int(page_size)
        # LIFO free list: recently-freed pages are re-used first, which
        # keeps the working set of physical pages small and cache-warm.
        # A parallel set keeps the double-free guard O(1) per page
        # (retiring a long request frees hundreds of pages on the engine
        # thread between decode chunks)
        self._free: List[int] = list(range(1, self.num_pages))
        self._free_set = set(self._free)
        self.peak_used = 0

    @property
    def usable(self) -> int:
        """Allocatable pages (total minus the null page)."""
        return self.num_pages - 1

    @property
    def free_count(self) -> int:
        return len(self._free)

    @property
    def used(self) -> int:
        return self.usable - len(self._free)

    def alloc(self, n: int) -> List[int]:
        if n < 0:
            raise ValueError(f"alloc({n})")
        if n > len(self._free):
            raise RuntimeError(
                f"page pool exhausted: need {n}, free {len(self._free)} "
                "(caller must check free_count / evict first)")
        pages = [self._free.pop() for _ in range(n)]
        self._free_set.difference_update(pages)
        self.peak_used = max(self.peak_used, self.used)
        return pages

    def free(self, pages: List[int]) -> None:
        for p in pages:
            if not 1 <= p < self.num_pages:
                raise ValueError(f"free of invalid page id {p}")
            if p in self._free_set:
                raise ValueError(f"double free of page {p}")
        self._free.extend(pages)
        self._free_set.update(pages)


class PrefixEntry:
    """One cached shared prefix: its physical pages, how many live slots
    reference it, and an LRU stamp for eviction."""

    __slots__ = ("pages", "refcount", "last_used", "length", "hits")

    def __init__(self, pages: List[int], length: int, stamp: int):
        self.pages = list(pages)
        self.refcount = 1          # the registering slot holds the first ref
        self.last_used = stamp
        self.length = int(length)  # aligned token length the pages hold
        self.hits = 0


class PrefixCache:
    """Ref-counted, LRU-evicted registry of shared (system-prompt)
    prefixes. Entries with ``refcount == 0`` stay cached — that IS the
    cache — and are evicted oldest-first only when the page pool's free
    list runs dry."""

    def __init__(self):
        self._entries: Dict[str, PrefixEntry] = {}
        self._clock = itertools.count(1)
        self.hits = 0
        self.misses = 0
        self.evictions = 0

    def __len__(self) -> int:
        return len(self._entries)

    @property
    def cached_pages(self) -> int:
        # list() snapshot: health() probes read this from client threads
        # while the engine thread registers/evicts entries
        return sum(len(e.pages) for e in list(self._entries.values()))

    def lookup(self, h: str) -> Optional[PrefixEntry]:
        return self._entries.get(h)

    def register(self, h: str, pages: List[int], length: int) -> PrefixEntry:
        if h in self._entries:
            raise ValueError(f"prefix {h} already registered")
        entry = PrefixEntry(pages, length, next(self._clock))
        self._entries[h] = entry
        return entry

    def ref(self, h: str) -> PrefixEntry:
        entry = self._entries[h]
        entry.refcount += 1
        entry.last_used = next(self._clock)
        entry.hits += 1
        self.hits += 1
        return entry

    def unref(self, h: str) -> None:
        entry = self._entries.get(h)
        if entry is None:
            return                # already evicted under us: nothing to do
        entry.refcount -= 1
        if entry.refcount < 0:
            raise ValueError(f"prefix {h} refcount underflow")

    def evict_until(self, pool: PagePool, need_free: int,
                    exclude: Optional[str] = None) -> int:
        """Evict refcount-0 entries oldest-first until ``pool`` has at
        least ``need_free`` free pages (or no evictable entry remains).
        Returns the number of entries evicted. ``exclude`` protects one
        hash — the entry a prefix HIT is about to reference must not be
        evicted to make room for that very request's private pages."""
        evicted = 0
        while pool.free_count < need_free:
            victims = [(e.last_used, h) for h, e in self._entries.items()
                       if e.refcount == 0 and h != exclude]
            if not victims:
                break
            _, h = min(victims)
            pool.free(self._entries.pop(h).pages)
            evicted += 1
            self.evictions += 1
        return evicted

    def clear(self, pool: PagePool) -> None:
        """Drop every entry regardless of refcount (engine teardown)."""
        for e in self._entries.values():
            pool.free(e.pages)
        self._entries.clear()
