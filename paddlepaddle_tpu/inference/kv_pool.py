"""Host-side KV page-pool bookkeeping for the paged decode engine.

Reference surface: the paged serving path — paddle/phi/kernels/fusion/gpu/
block_multi_head_attention_kernel.cu's block tables, the vLLM
PagedAttention allocator design ROADMAP item 1 points at. On GPU the
allocator hands out scattered physical blocks and the kernel chases the
block table; under static-shape XLA the *device* half is a
``[slots, max_len/page_size]`` int32 page table used as a gather index
(decode_engine.py), and everything here is the *host* half: a free list, a
per-slot page ledger, and a ref-counted LRU registry of shared prompt
prefixes.

Deliberately jax-free and lock-free: the one engine thread owns every
mutation (admission, retirement, eviction) exactly like the rest of the
decode engine's host bookkeeping, and the unit tests
(tests/test_paged_kv.py) exercise it standalone.

Page 0 is the NULL page: every unmapped page-table entry points at it, so
an in-graph scatter past a slot's reservation lands in one sacrificial
page and a gather through an unmapped entry reads finite garbage that the
causal/length mask already hides. It is never allocated.
"""

from __future__ import annotations

import hashlib
import itertools
import json
import struct
from typing import Callable, Dict, List, Optional, Tuple

__all__ = ["PagePool", "PrefixCache", "PrefixEntry", "HostPrefixTier",
           "HostSlab", "pages_needed", "prefix_hash",
           "serialize_page_slab", "deserialize_page_slab"]


def pages_needed(tokens: int, page_size: int) -> int:
    """Pages covering ``tokens`` KV positions (ceil division)."""
    return -(-int(tokens) // int(page_size))


def prefix_hash(prompt_ids, aligned: int) -> str:
    """Content hash of the page-aligned shared prefix. Keyed by the token
    bytes AND the aligned length, so a prefix cached at 128 tokens never
    answers a lookup for its own 64-token head."""
    import numpy as np

    ids = np.ascontiguousarray(np.asarray(prompt_ids, np.int32).reshape(-1))
    return f"{aligned}:" + hashlib.sha1(ids[:aligned].tobytes()).hexdigest()


_SLAB_MAGIC = b"KVS1"


def serialize_page_slab(meta: dict, arrays) -> bytes:
    """Pack the physical content of a prefix's KV pages — per-layer page
    tensors, their quantization scales when present, and the table-row
    metadata — into one contiguous byte string.

    Wire format (little-endian, versioned by the magic):

        [4B magic "KVS1"][u32 header_len][header JSON][raw array bytes...]

    where the header carries ``meta`` verbatim plus a per-array manifest of
    ``{"dtype": <numpy dtype str>, "shape": [...]}`` in order. The round
    trip is byte-exact (tests pin it) — this is the same slab a future
    prefill/decode disaggregation ships KV over (ROADMAP item 2), so the
    format stays self-describing and carries no engine object references.
    """
    import numpy as np

    manifest = []
    chunks = []
    for a in arrays:
        a = np.ascontiguousarray(np.asarray(a))
        # dtype by NAME, not .str: ml_dtypes types (bfloat16) stringify to
        # an anonymous void ('<V2') that cannot reconstruct the dtype
        manifest.append({"dtype": a.dtype.name, "shape": list(a.shape)})
        chunks.append(a.tobytes())
    header = json.dumps({"meta": meta, "arrays": manifest},
                        sort_keys=True).encode("utf-8")
    out = bytearray()
    out += _SLAB_MAGIC
    out += struct.pack("<I", len(header))
    out += header
    for c in chunks:
        out += c
    return bytes(out)


def deserialize_page_slab(blob: bytes) -> Tuple[dict, list]:
    """Inverse of :func:`serialize_page_slab`: ``(meta, [np.ndarray])``.
    Raises ``ValueError`` on a bad magic or truncated payload — a corrupt
    slab must surface loudly, never as silently-wrong KV."""
    import numpy as np

    if blob[:4] != _SLAB_MAGIC:
        raise ValueError("page slab: bad magic (not a KVS1 slab)")
    (hlen,) = struct.unpack("<I", blob[4:8])
    header = json.loads(blob[8:8 + hlen].decode("utf-8"))
    meta, manifest = header["meta"], header["arrays"]

    def _dtype_of(name: str):
        try:
            return np.dtype(name)
        except TypeError:
            # bfloat16/fp8 names resolve only through ml_dtypes (always
            # present alongside jax; this module itself stays jax-free)
            import ml_dtypes

            return np.dtype(getattr(ml_dtypes, name))

    arrays = []
    off = 8 + hlen
    for spec in manifest:
        dt = _dtype_of(spec["dtype"])
        shape = tuple(spec["shape"])
        n = dt.itemsize * int(np.prod(shape, dtype=np.int64)) if shape \
            else dt.itemsize
        raw = blob[off:off + n]
        if len(raw) != n:
            raise ValueError("page slab: truncated array payload")
        arrays.append(np.frombuffer(raw, dtype=dt).reshape(shape).copy())
        off += n
    if off != len(blob):
        raise ValueError("page slab: trailing bytes after last array")
    return meta, arrays


class HostSlab:
    """One spilled prefix resident in the host tier: its serialized page
    slab plus the LRU stamp it carried on the device tier (so host-tier
    discard order continues the device-tier LRU, not insertion order)."""

    __slots__ = ("blob", "length", "n_pages", "stamp", "hits")

    def __init__(self, blob: bytes, length: int, n_pages: int, stamp: int):
        self.blob = blob
        self.length = int(length)
        self.n_pages = int(n_pages)
        self.stamp = int(stamp)
        self.hits = 0

    @property
    def nbytes(self) -> int:
        return len(self.blob)


class HostPrefixTier:
    """Bounded host-RAM spill tier for refcount-0 prefix entries. The two
    tiers are EXCLUSIVE: a prefix lives either in device pages (PrefixCache)
    or here as a serialized slab, never both — restore pops the slab before
    device pages are written, so reconciliation can assert zero overlap.

    LRU spans both tiers: ``put`` carries the device entry's ``last_used``
    stamp across, and when the byte budget is exceeded the smallest stamp is
    discarded first. A host-tier discard is the TRUE eviction — the bytes
    are gone; the device-tier "eviction" above it was only a spill.

    Same threading contract as the rest of this module: the one engine
    thread owns every mutation; stats reads from client threads see a
    consistent-enough snapshot."""

    def __init__(self, max_bytes: int):
        self.max_bytes = int(max_bytes)
        if self.max_bytes <= 0:
            raise ValueError(
                f"host tier byte budget must be > 0, got {max_bytes} "
                "(use no tier at all for 'off')")
        self._entries: Dict[str, HostSlab] = {}
        self.used_bytes = 0
        self.spills = 0      # slabs accepted into the tier
        self.restores = 0    # slabs popped for device restore
        self.discards = 0    # true evictions (budget pressure or rejects)

    def __len__(self) -> int:
        return len(self._entries)

    def __contains__(self, h: str) -> bool:
        return h in self._entries

    def keys(self):
        return list(self._entries.keys())

    @property
    def occupancy(self) -> float:
        return self.used_bytes / self.max_bytes

    def put(self, h: str, slab: HostSlab) -> bool:
        """Admit a slab, discarding oldest-stamp entries until it fits.
        Returns False (counted as a discard — the bytes are dropped) when
        the slab alone exceeds the whole budget."""
        if h in self._entries:
            # exclusive tiers make this unreachable from the engine; keep
            # the accounting honest for direct users
            self.used_bytes -= self._entries.pop(h).nbytes
        if slab.nbytes > self.max_bytes:
            self.discards += 1
            return False
        while self.used_bytes + slab.nbytes > self.max_bytes:
            victim = min(self._entries.items(),
                         key=lambda kv: kv[1].stamp)[0]
            self.used_bytes -= self._entries.pop(victim).nbytes
            self.discards += 1
        self._entries[h] = slab
        self.used_bytes += slab.nbytes
        self.spills += 1
        return True

    def pop(self, h: str) -> Optional[HostSlab]:
        """Remove and return the slab for ``h`` (None on miss). The caller
        is now the only owner — on a failed restore it must either re-``put``
        the slab or accept the discard."""
        slab = self._entries.pop(h, None)
        if slab is not None:
            self.used_bytes -= slab.nbytes
            slab.hits += 1
            self.restores += 1
        return slab

    def put_back(self, h: str, slab: HostSlab) -> None:
        """Undo a ``pop`` whose restore could not proceed (reservation dry,
        admission rollback): re-admit without counting a second spill or
        a phantom restore."""
        if self.put(h, slab):
            self.spills -= 1
        self.restores -= 1

    def clear(self) -> None:
        self._entries.clear()
        self.used_bytes = 0

    def stats(self) -> dict:
        return {
            "entries": len(self._entries),
            "budget_bytes": self.max_bytes,
            "used_bytes": self.used_bytes,
            "occupancy": self.occupancy,
            "spills": self.spills,
            "restores": self.restores,
            "discards": self.discards,
        }


class PagePool:
    """Free list over ``num_pages`` physical KV pages (page 0 reserved as
    the null page). ``alloc``/``free`` are O(n) list ops on the host path
    that already does per-request Python bookkeeping."""

    def __init__(self, num_pages: int, page_size: int):
        if num_pages < 2:
            raise ValueError(
                f"num_pages must be >= 2 (page 0 is the reserved null "
                f"page), got {num_pages}")
        if page_size < 1:
            raise ValueError(f"page_size must be >= 1, got {page_size}")
        self.num_pages = int(num_pages)
        self.page_size = int(page_size)
        # LIFO free list: recently-freed pages are re-used first, which
        # keeps the working set of physical pages small and cache-warm.
        # A parallel set keeps the double-free guard O(1) per page
        # (retiring a long request frees hundreds of pages on the engine
        # thread between decode chunks)
        self._free: List[int] = list(range(1, self.num_pages))
        self._free_set = set(self._free)
        self.peak_used = 0

    @property
    def usable(self) -> int:
        """Allocatable pages (total minus the null page)."""
        return self.num_pages - 1

    @property
    def free_count(self) -> int:
        return len(self._free)

    @property
    def used(self) -> int:
        return self.usable - len(self._free)

    def alloc(self, n: int) -> List[int]:
        if n < 0:
            raise ValueError(f"alloc({n})")
        if n > len(self._free):
            raise RuntimeError(
                f"page pool exhausted: need {n}, free {len(self._free)} "
                "(caller must check free_count / evict first)")
        pages = [self._free.pop() for _ in range(n)]
        self._free_set.difference_update(pages)
        self.peak_used = max(self.peak_used, self.used)
        return pages

    def free(self, pages: List[int]) -> None:
        for p in pages:
            if not 1 <= p < self.num_pages:
                raise ValueError(f"free of invalid page id {p}")
            if p in self._free_set:
                raise ValueError(f"double free of page {p}")
        self._free.extend(pages)
        self._free_set.update(pages)


class PrefixEntry:
    """One cached shared prefix: its physical pages, how many live slots
    reference it, and an LRU stamp for eviction."""

    __slots__ = ("pages", "refcount", "last_used", "length", "hits")

    def __init__(self, pages: List[int], length: int, stamp: int):
        self.pages = list(pages)
        self.refcount = 1          # the registering slot holds the first ref
        self.last_used = stamp
        self.length = int(length)  # aligned token length the pages hold
        self.hits = 0


class PrefixCache:
    """Ref-counted, LRU-evicted registry of shared (system-prompt)
    prefixes. Entries with ``refcount == 0`` stay cached — that IS the
    cache — and are evicted oldest-first only when the page pool's free
    list runs dry."""

    def __init__(self):
        self._entries: Dict[str, PrefixEntry] = {}
        self._clock = itertools.count(1)
        self.hits = 0
        self.misses = 0
        self.evictions = 0

    def __len__(self) -> int:
        return len(self._entries)

    @property
    def cached_pages(self) -> int:
        # list() snapshot: health() probes read this from client threads
        # while the engine thread registers/evicts entries
        return sum(len(e.pages) for e in list(self._entries.values()))

    def lookup(self, h: str) -> Optional[PrefixEntry]:
        return self._entries.get(h)

    def register(self, h: str, pages: List[int], length: int) -> PrefixEntry:
        if h in self._entries:
            raise ValueError(f"prefix {h} already registered")
        entry = PrefixEntry(pages, length, next(self._clock))
        self._entries[h] = entry
        return entry

    def ref(self, h: str) -> PrefixEntry:
        entry = self._entries[h]
        entry.refcount += 1
        entry.last_used = next(self._clock)
        entry.hits += 1
        self.hits += 1
        return entry

    def unref(self, h: str) -> None:
        entry = self._entries.get(h)
        if entry is None:
            return                # already evicted under us: nothing to do
        entry.refcount -= 1
        if entry.refcount < 0:
            raise ValueError(f"prefix {h} refcount underflow")

    def evict_until(self, pool: PagePool, need_free: int,
                    exclude: Optional[str] = None,
                    spill: Optional[Callable[[str, PrefixEntry], bool]]
                    = None) -> int:
        """Evict refcount-0 entries oldest-first until ``pool`` has at
        least ``need_free`` free pages (or no evictable entry remains).
        Returns the number of entries removed from the device tier.
        ``exclude`` protects one hash — the entry a prefix HIT is about to
        reference must not be evicted to make room for that very request's
        private pages.

        ``spill``, when given, is called with ``(hash, entry)`` BEFORE the
        entry's pages return to the pool (the page content is still live on
        device). A True return means the entry moved to a lower tier — the
        pages are still freed here, but ``evictions`` (the true-discard
        counter) is not bumped; the host tier's own discard is the real
        eviction."""
        evicted = 0
        while pool.free_count < need_free:
            victims = [(e.last_used, h) for h, e in self._entries.items()
                       if e.refcount == 0 and h != exclude]
            if not victims:
                break
            _, h = min(victims)
            entry = self._entries.pop(h)
            spilled = bool(spill(h, entry)) if spill is not None else False
            pool.free(entry.pages)
            evicted += 1
            if not spilled:
                self.evictions += 1
        return evicted

    def clear(self, pool: PagePool) -> None:
        """Drop every entry regardless of refcount (engine teardown)."""
        for e in self._entries.values():
            pool.free(e.pages)
        self._entries.clear()
