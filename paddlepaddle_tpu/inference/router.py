"""Serving fleet router — health-aware load balancing, replica failover,
rolling restart over N decode engines.

Reference surface: the reference deployment layer's predictor POOL
(paddle/fluid/inference/api/paddle_inference_api.h:229 PredictorPool) scaled
from "a pool of handles in one process" to "a fleet of replica engines
behind one front door". PRs 3–7 built every signal a fleet needs —
``health()`` snapshots with ``est_wait_s``/``inflight``/``pages_free``,
``drain(timeout)``, per-engine circuit breakers, per-request SLO stamps,
and a replica-local prefix cache; :class:`ServingRouter` is the component
that finally *uses* them together, turning one engine into a service.

Mechanics (all stdlib, no JAX imports — the replicas own the chips):

* **health-aware balancing** — a prober thread polls every replica's
  ``health()`` each ``probe_interval_s``; picks go to the healthy replica
  with the least estimated wait (snapshot ``est_wait_s``, live router-side
  in-flight count as the tiebreak). A per-replica
  :class:`~.robustness.CircuitBreaker` evicts a replica whose probes or
  requests keep failing and re-admits it via the half-open window once a
  probe sees ``ok`` again.
* **failover with retry** — a request whose replica dies mid-flight
  (breaker-open, typed infra shed, chaos kill) is re-submitted to another
  replica under a :class:`~..resilience.retry.RetryPolicy`: bounded
  attempts, jittered exponential backoff between fleet-wide rounds, and
  deadline-aware — no retry is ever scheduled past the request's
  ``deadline_s``. Requests that can never succeed anywhere (validation,
  expired deadline, client cancel) are NOT retried. When every replica is
  out of rotation, submits raise a typed
  :class:`~.robustness.FleetUnavailableError` (with the soonest half-open
  window as the retry hint).
* **rolling restart** — :meth:`ServingRouter.rolling_restart` takes one
  replica out of rotation, drains it (in-flight requests finish; queued
  ones shed typed and FAIL OVER to the other replicas), restarts it with a
  fresh engine, waits until its health probe reads ok, re-admits it, then
  proceeds to the next — a deploy drops zero requests. The per-replica
  cycle is :meth:`ServingRouter.restart_replica` (``factory=`` swaps the
  build recipe, ``readmit=False`` holds a healthy replica out of rotation)
  — the unit the fleet controller's deploy/rollback pipeline reuses.
* **elastic membership** — :meth:`ServingRouter.add_replica` joins a
  started (ideally pre-warmed) replica to the rotation under live traffic;
  :meth:`ServingRouter.remove_replica` leaves DELIBERATELY by drain
  (sheds fail over, no breaker evidence, the engine's ``/healthz``
  provider unregisters, router-side breaker/prober state is dropped).
  Rendezvous hashing bounds prefix-key movement to the joining/leaving
  replica — the fleet-wide cache hit rate survives scaling. The
  SLO-driven autoscaler that drives these lives in :mod:`~.fleet`.
* **prefix-affine routing** — requests declaring ``prefix_len`` rendezvous-
  hash their prefix tokens over the healthy replicas, so every request
  sharing a system prompt lands on the replica whose paged prefix cache
  (PR 7) already holds its pages; the router falls back to least-loaded
  when the preferred replica is unhealthy or saturated
  (``affinity_max_wait_s``).

The replica seam is :class:`ReplicaClient` — the four-method surface the
router needs (``submit/health/drain/restart``). The in-process form wraps a
:class:`~.serving.ServingEngine` factory; a remote replica (HTTP
``/healthz`` + the C-API submit protocol) slots in by implementing the same
surface.

Observability: ``paddle_router_{picks,retries,failovers,evictions,
readmissions}_total`` counters + ``paddle_router_replicas_healthy`` gauge
(cold paths, via safe_inc/safe_set), a ``router`` block in
:meth:`ServingRouter.health`, and eviction/failover/rolling-restart events
through the crash flight recorder.

Invariant the chaos drill enforces (tests/test_router.py): every submitted
request's future resolves — completed, or failed with a typed error. Zero
silently-lost futures, whatever dies.
"""

from __future__ import annotations

import hashlib
import heapq
import itertools
import sys
import threading
import time
import uuid
from typing import Callable, Dict, List, Optional, Sequence, Union

import numpy as np

from ..resilience.retry import RetryPolicy, compute_delay
from .robustness import (
    CircuitBreaker,
    CircuitOpenError,
    DeadlineExceededError,
    EngineDrainingError,
    FleetUnavailableError,
    ReplicaStalledError,
    RequestCancelledError,
    ServerOverloadedError,
    ServingError,
    WireCorruptionError,
)
from .robustness import safe_inc as _safe_inc
from .robustness import safe_set as _safe_set
from .serving import _REQ_IDS, GenerationResult, ServingEngine
from .serving import _flight_record  # one disarmed-check wrapper, not two


def _retryable(exc: BaseException) -> bool:
    """May another replica serve this request? Infra failures — overload,
    open breaker, draining replica, or anything that is NOT a typed
    serving error (decode blew up, chaos, dead replica) — yes. Failures
    that travel with the request (validation, expired deadline, client
    cancel) or with the whole fleet (FleetUnavailableError) — no. The
    wire-hardening errors are typed ServingErrors but travel with the
    CONNECTION, not the request — a stalled or corrupted stream says
    nothing about whether another replica can serve it."""
    if isinstance(exc, (CircuitOpenError, EngineDrainingError,
                        ServerOverloadedError, ReplicaStalledError,
                        WireCorruptionError)):
        return True
    return not isinstance(exc, ServingError)


class ReplicaClient:
    """The seam between the router and ONE replica. In-process form: owns a
    :class:`~.serving.ServingEngine` built by ``factory`` (a zero-arg
    callable), rebuilt fresh on :meth:`restart`. The remote form —
    :class:`~.remote_replica.RemoteReplicaClient`, speaking the C-API
    frame protocol to a supervised OS process — implements this same
    surface and slots in unchanged.

    ``kill()`` is the chaos seam: abrupt replica death. In-flight futures
    fail untyped (the router's failover path), and the replica refuses
    everything — including health probes — until :meth:`restart`.
    """

    # a client advertising req_uid support accepts submit(req_uid=...)
    # and guarantees a resubmitted uid is never decoded twice — the
    # precondition for the router's hedged requests (cancelling the
    # loser is safe) and ambiguous-failure resubmission
    supports_req_uid = False

    def __init__(self, factory: Callable[[], ServingEngine],
                 name: str = "replica"):
        self._factory = factory
        self.name = name
        self.engine = factory()
        self.generation = 0          # bumped per fresh engine
        self._killed = False

    def start(self) -> "ReplicaClient":
        if self._killed:
            raise ConnectionError(f"replica {self.name} is dead")
        self.engine.start()
        return self

    def submit(self, prompt_ids, **kw) -> GenerationResult:
        if self._killed:
            raise ConnectionError(f"replica {self.name} is dead")
        return self.engine.submit(prompt_ids, **kw)

    def health(self) -> Dict[str, object]:
        if self._killed:
            raise ConnectionError(f"replica {self.name} is dead")
        return self.engine.health()

    def warmup(self) -> Dict[str, object]:
        """Pre-compile the replica's whole plan (rolling restart calls
        this between the fresh build and re-admission). Duck-typed: an
        engine without a warmup surface — a bare test double, a remote
        replica that warms itself at boot — reports a no-op."""
        if self._killed:
            raise ConnectionError(f"replica {self.name} is dead")
        fn = getattr(self.engine, "warmup", None)
        return fn() if callable(fn) else {"programs": 0, "compiled": 0}

    def drain(self, timeout: Optional[float] = None,
              reason: Optional[str] = None) -> Dict[str, object]:
        if reason is None:
            return self.engine.drain(timeout)
        try:
            # deliberate drains (scale-down) carry their reason into the
            # engine's shed/drain accounting; a foreign engine predating
            # the kwarg still drains fine
            return self.engine.drain(timeout, reason=reason)
        except TypeError:
            return self.engine.drain(timeout)

    def stop(self) -> None:
        try:
            self.engine.stop()
        except RuntimeError:
            pass          # overran the join: futures were already failed

    def restart(self, drain_timeout: Optional[float] = None,
                factory: Optional[Callable[[], ServingEngine]] = None
                ) -> None:
        """Drain the current engine (in-flight finishes, queued sheds
        typed), replace it with a FRESH one from the factory, start it.
        ``factory`` REPLACES the build recipe for this and every later
        restart — the deploy pipeline's version-switch seam (candidate
        bundle on rollout, previous bundle on rollback). Also the
        recovery path after :meth:`kill`."""
        old = self.engine
        try:
            old.drain(drain_timeout)
        except Exception:
            pass
        try:
            old.stop()
        except RuntimeError:
            pass
        if factory is not None:
            self._factory = factory
        self.engine = self._factory()
        self.engine.start()
        self.generation += 1
        self._killed = False

    def kill(self) -> None:
        """Chaos seam: the replica dies NOW. ``stop()`` fails its in-flight
        and queued futures (untyped RuntimeError — exactly what a crashed
        process looks like to its callers), and every later submit/health
        raises until :meth:`restart`."""
        self._killed = True
        try:
            self.engine.stop()
        except RuntimeError:
            pass


class _Replica:
    """Router-side state for one replica: breaker, rotation flag, live
    in-flight count, last health snapshot."""

    __slots__ = ("name", "client", "breaker", "in_rotation", "inflight",
                 "snapshot", "no_trace")

    def __init__(self, name: str, client: ReplicaClient,
                 breaker: CircuitBreaker):
        self.name = name
        self.client = client
        self.breaker = breaker
        self.in_rotation = True      # False only during rolling restart
        self.inflight = 0            # router-submitted, not yet resolved
        self.snapshot: Optional[Dict[str, object]] = None
        self.no_trace = False        # client rejected the trace kwarg (a
        #   remote implementation of the seam predating request-journey
        #   tracing): submits to it go out without the journey


class _Pending:
    """One router request across its (re)submission attempts."""

    __slots__ = ("prompt_ids", "kw", "future", "deadline", "prefix_key",
                 "attempts", "tried", "last_error", "inner", "trace",
                 "t_attempt", "req_uid", "cur_rep", "hedge_inner",
                 "hedge_armed", "delivered", "in_submit")

    def __init__(self, prompt_ids, kw, future, deadline, prefix_key):
        self.prompt_ids = prompt_ids
        self.kw = kw                          # engine submit kwargs
        self.future = future                  # the router-owned future
        self.deadline = deadline              # absolute monotonic, or None
        self.prefix_key = prefix_key          # rendezvous key bytes, or None
        self.attempts = 0                     # submissions tried so far
        self.tried: set = set()               # replica names this round
        self.last_error: Optional[BaseException] = None
        self.inner: Optional[GenerationResult] = None   # current replica fut
        self.trace = None                     # reqtrace Journey, or None
        self.t_attempt: Optional[float] = None  # current attempt's dispatch
        #                                         stamp (perf_counter)
        self.req_uid = uuid.uuid4().hex       # idempotency key: the SAME
        #   uid rides every attempt and the hedge, so cancelling a loser
        #   (or resubmitting after an ambiguous loss) never decodes twice
        self.cur_rep: Optional[str] = None    # current attempt's replica
        self.hedge_inner: Optional[GenerationResult] = None
        self.hedge_armed = False              # hedge timer scheduled
        self.delivered = False                # terminal delivered (under
        #   the router's stats lock: primary and hedge race to deliver)
        self.in_submit: Optional[str] = None  # replica a dispatcher is
        #   currently BLOCKED submitting to — a gray accept (delayed or
        #   black-holed accepted frame) wedges the dispatch thread there
        #   for up to heartbeat_timeout_s, and the hedge must cover that
        #   window too, not just the post-accept stream


class ServingRouter:
    """Front door over N replica engines with the engine's own surface:
    ``submit()/generate()/drain()/health()`` (plus ``rolling_restart()``).

    ``replicas`` is a list of zero-arg engine factories (each wrapped in a
    :class:`ReplicaClient` named ``r0..rN-1``) and/or ready
    :class:`ReplicaClient` instances. Factories matter: rolling restart
    replaces a replica's engine with a FRESH build, it does not resurrect
    the old object.
    """

    def __init__(self, replicas: Sequence,
                 probe_interval_s: float = 0.25,
                 breaker_threshold: int = 3,
                 breaker_reset_s: float = 1.0,
                 retry_policy: Optional[RetryPolicy] = None,
                 affinity_max_wait_s: float = 1.0,
                 drain_timeout_s: Optional[float] = None,
                 hedge_after_s: Union[float, str, None] = "auto",
                 hedge_budget_pct: float = 10.0):
        if not replicas:
            raise ValueError("ServingRouter needs at least one replica")
        self.breaker_threshold = int(breaker_threshold)
        self.breaker_reset_s = float(breaker_reset_s)
        # _replicas is treated as an IMMUTABLE snapshot: every reader takes
        # one attribute load and iterates its own list; add/remove swap in
        # a fresh list (GIL-atomic), so the fleet controller can grow and
        # shrink the rotation under live traffic without a reader lock
        self._replicas: List[_Replica] = []
        for i, r in enumerate(replicas):
            client = r if isinstance(r, ReplicaClient) \
                else ReplicaClient(r, name=f"r{i}")
            self._replicas.append(self._make_replica(client))
        if len({r.name for r in self._replicas}) != len(self._replicas):
            raise ValueError("replica names must be unique")
        self.probe_interval_s = float(probe_interval_s)
        self.retry_policy = retry_policy or RetryPolicy(
            max_attempts=3, base_delay=0.05, max_delay=1.0)
        self.affinity_max_wait_s = float(affinity_max_wait_s)
        self.drain_timeout_s = drain_timeout_s
        # hedged requests (Dean & Barroso, "The Tail at Scale"): a request
        # with no first token after hedge_after_s gets ONE duplicate on a
        # different healthy replica; first terminal wins, the loser is
        # cancelled (safe: req_uid dedup means a cancelled twin never
        # cost a second decode). "auto" derives the delay from observed
        # TTFT (p99, floor 2x p50) via the tsdb history plane — with no
        # history armed, auto hedging stays off. hedge_budget_pct caps
        # hedges at a fraction of submits so hedging cannot melt an
        # already-overloaded fleet
        self.hedge_after_s = hedge_after_s
        self.hedge_budget_pct = float(hedge_budget_pct)
        self._hedge_cache: Optional[float] = None
        self._hedge_cache_t = 0.0
        self._stats_lock = threading.Lock()
        self.stats = {"submitted": 0, "completed": 0, "failed": 0,
                      "picks": 0, "retries": 0, "failovers": 0,
                      "evictions": 0, "readmissions": 0,
                      "rolling_restarts": 0, "replicas_added": 0,
                      "replicas_removed": 0,
                      "hedges": 0, "hedge_wins": 0}
        self._stop = threading.Event()
        self._draining = threading.Event()
        self._prober: Optional[threading.Thread] = None
        self._retrier: Optional[threading.Thread] = None
        self._retry_cv = threading.Condition()
        self._retry_heap: List = []          # (due, seq, _Pending)
        self._retry_seq = itertools.count()
        self._started = False
        self._health_reg_name = None

    # -- bookkeeping ---------------------------------------------------------
    def _bump(self, key: str, n: int = 1) -> None:
        with self._stats_lock:
            self.stats[key] += n

    def _make_replica(self, client: ReplicaClient) -> _Replica:
        rep = _Replica(client.name, client, CircuitBreaker(
            threshold=self.breaker_threshold, reset_s=self.breaker_reset_s))
        # transition callback needs the replica it guards
        rep.breaker._on_transition = \
            (lambda old, new, _rep=rep:
             self._on_breaker_transition(_rep, old, new))
        return rep

    def _replica(self, name: str) -> _Replica:
        for rep in self._replicas:
            if rep.name == name:
                return rep
        raise KeyError(f"no replica named {name!r} "
                       f"(have: {[r.name for r in self._replicas]})")

    def _on_breaker_transition(self, rep: _Replica, old: str,
                               new: str) -> None:
        sys.stderr.write(
            f"[router] replica {rep.name} breaker {old} -> {new}\n")
        if new == "open":
            self._bump("evictions")
            _safe_inc("paddle_router_evictions_total",
                      "replicas evicted from rotation by their breaker",
                      replica=rep.name)
            _flight_record("router", rep.name, event="eviction",
                           consecutive=rep.breaker.consecutive_failures)
        elif new == "closed" and old in ("open", "half_open"):
            self._bump("readmissions")
            _safe_inc("paddle_router_readmissions_total",
                      "evicted replicas re-admitted to rotation",
                      replica=rep.name)
            _flight_record("router", rep.name, event="readmission")

    # -- health probing ------------------------------------------------------
    def _probe_once(self) -> int:
        """Poll every replica's health; feed the per-replica breaker
        (failures accumulate to eviction; a half-open window + an ok probe
        re-admits). Returns — and gauges — the healthy count."""
        healthy = 0
        for rep in self._replicas:
            try:
                snap = rep.client.health()
                ok = bool(snap.get("ok", False))
            except Exception:
                snap, ok = None, False
            rep.snapshot = snap
            # per-replica load gauges on the probe tick: the same numbers
            # picks are made on, published so the tsdb history plane (and
            # `obsctl top`'s sparklines) can see per-replica load over time
            if snap is not None:
                if snap.get("est_wait_s") is not None:
                    _safe_set("paddle_router_replica_est_wait_seconds",
                              "probed per-replica estimated wait",
                              float(snap["est_wait_s"]), replica=rep.name)
                _safe_set("paddle_router_replica_inflight",
                          "router-submitted requests in flight per replica",
                          rep.inflight, replica=rep.name)
            b = rep.breaker
            if not rep.in_rotation:
                continue     # deliberately out (rolling restart): its
                #              transitional not-ok is neither failure
                #              evidence nor re-admission input
            if ok:
                # an ok probe re-admits ONLY through the half-open window
                # (evicted + reset elapsed): it must neither let a replica
                # jump its reset window nor clear a closed breaker's
                # REQUEST-failure streak — "/healthz looks fine but
                # requests fail" is still grounds for eviction
                if b.state != "closed" and b.allow():
                    b.record_success()
            else:
                b.record_failure()
            if rep.in_rotation and ok and b.state != "open":
                healthy += 1
        _safe_set("paddle_router_replicas_healthy",
                  "replicas currently in rotation and passing health probes",
                  healthy)
        return healthy

    def _probe_loop(self) -> None:
        while not self._stop.wait(self.probe_interval_s):
            self._probe_once()

    # -- retry scheduling ----------------------------------------------------
    def _retry_loop(self) -> None:
        while True:
            with self._retry_cv:
                while not self._stop.is_set() and (
                        not self._retry_heap
                        or self._retry_heap[0][0] > time.monotonic()):
                    wait = (None if not self._retry_heap else
                            max(0.0, self._retry_heap[0][0]
                                - time.monotonic()))
                    self._retry_cv.wait(wait)
                if self._stop.is_set():
                    return
                due = []
                now = time.monotonic()
                while self._retry_heap and self._retry_heap[0][0] <= now:
                    due.append(heapq.heappop(self._retry_heap)[2:])
            for kind, pend in due:
                if kind == "hedge":
                    self._maybe_hedge(pend)
                else:
                    self._dispatch(pend)

    def _schedule(self, pend: _Pending, delay: float,
                  kind: str = "retry") -> None:
        with self._retry_cv:
            # drain()/stop() set their flag BEFORE sweeping the heap under
            # this same lock — so a push either lands before the sweep
            # (and is swept) or observes the flag here. No entry can
            # strand behind an exiting retrier thread: zero lost futures
            if self._draining.is_set() or self._stop.is_set():
                if kind == "retry":
                    self._finish_fail(pend, EngineDrainingError(
                        "request shed: serving router drained before it "
                        "was served"))
                return     # a dropped hedge timer loses nothing: the
                #            primary attempt still owns the future
            heapq.heappush(self._retry_heap,
                           (time.monotonic() + delay,
                            next(self._retry_seq), kind, pend))
            self._retry_cv.notify()

    # -- pick policy ---------------------------------------------------------
    def _candidates(self, exclude=()) -> List[_Replica]:
        out = []
        for rep in self._replicas:
            if not rep.in_rotation or rep.name in exclude:
                continue
            if not rep.breaker.allow():
                continue              # evicted; half-open lets a probe pick
            snap = rep.snapshot
            if ((snap is None or not snap.get("ok", False))
                    and rep.breaker.state == "closed"):
                # the probe already knows this replica is not serving
                # (draining, stopped, dead — health() raising leaves
                # snapshot None) even though our breaker hasn't tripped
                # yet — don't route into a known wall. Half-open still
                # lets one traffic probe through
                continue
            out.append(rep)
        return out

    @staticmethod
    def _load_score(rep: _Replica):
        snap = rep.snapshot or {}
        est = snap.get("est_wait_s")
        if est is None:
            est = snap.get("estimated_queue_wait_s") or 0.0
        depth = snap.get("queue_depth") or 0
        return (round(float(est), 6), rep.inflight + int(depth), rep.name)

    def _pick(self, pend: _Pending) -> Optional[_Replica]:
        """Least-estimated-wait among healthy replicas; prefix-carrying
        requests prefer their rendezvous-hash replica (stable as replicas
        come and go — only keys owned by a lost replica move) unless it is
        saturated."""
        cands = self._candidates(exclude=pend.tried)
        if not cands:
            return None
        if pend.prefix_key is not None:
            pref = max(cands, key=lambda r: hashlib.sha1(
                pend.prefix_key + r.name.encode()).digest())
            est = self._load_score(pref)[0]
            if est <= self.affinity_max_wait_s:
                return pref
        return min(cands, key=self._load_score)

    # -- dispatch / failover -------------------------------------------------
    def _claim_delivery(self, pend: _Pending) -> bool:
        """Exactly-once delivery gate: with a hedge in flight, the primary
        and the duplicate race to resolve the future — the loser of this
        claim must neither double-count stats nor overwrite SLO stamps."""
        with self._stats_lock:
            if pend.delivered:
                return False
            pend.delivered = True
            return True

    @staticmethod
    def _cancel_losers(pend: _Pending, winner) -> None:
        # safe by construction: both attempts carried the same req_uid,
        # so a cancelled twin whose decode already finished left a cached
        # terminal, not a second decode
        for other in (pend.inner, pend.hedge_inner):
            if other is not None and other is not winner \
                    and not other.done():
                try:
                    # thread the goodput reason: the loser's decoded
                    # tokens are hedge waste, not a client cancel. A
                    # remote replica future's cancel() is a socket
                    # disconnect with no reason channel — its replica
                    # books the tokens as "cancel" on its own ledger.
                    other.cancel(reason="hedge_loser")
                except TypeError:
                    try:
                        other.cancel()
                    except Exception:
                        pass
                except Exception:
                    pass

    def _finish_ok(self, pend: _Pending, inner: GenerationResult) -> None:
        if not self._claim_delivery(pend):
            return
        self._cancel_losers(pend, inner)
        fut = pend.future
        # carry the replica future's SLO stamps so fleet-level slo_summary
        # reports real TTFT/latency (measured from ROUTER submit time).
        # Queue wait is PER ATTEMPT: the winning inner's own submit time
        # becomes the wrapper's dispatch stamp, so a failed-over request
        # reports the wait of the attempt that served it — not the first
        # attempt's decode plus the backoff booked as "queue wait"
        fut._t_admit = inner._t_admit
        fut._t_first = inner._t_first
        fut._t_dispatch = inner._t_submit
        fut._n_new = inner._n_new
        fut._n_at_first = inner._n_at_first
        fut._streaming = inner._streaming
        self._bump("completed")
        fut._set(output=inner._output)

    def _finish_fail(self, pend: _Pending, err: BaseException,
                     sync: bool = False) -> None:
        if not self._claim_delivery(pend):
            return
        self._cancel_losers(pend, None)
        self._bump("failed")
        if sync:
            # the raise IS the delivery: the future is never set, so the
            # journey must close here or it would sit in the in-flight
            # map forever (one leak per refused request)
            tr = pend.trace
            if tr is not None:
                try:
                    from ..observability import reqtrace as _rt

                    tr.event("router.reject", replica="router",
                             error=f"{type(err).__name__}: {err}"[:200],
                             retryable=False)
                    _rt.finish_future(tr, pend.future, "rejected")
                except Exception:
                    pass
            raise err
        pend.future._set(error=err)

    def _fleet_unavailable(self) -> FleetUnavailableError:
        # soonest POSITIVE half-open window among evicted replicas; a
        # fleet that is out without open breakers (all dead/draining,
        # breakers still closed) hints one probe interval — never 0.0,
        # which would invite a tight resubmit loop against a dead fleet
        windows = [w for w in (r.breaker.retry_after_s()
                               for r in self._replicas) if w > 0]
        return FleetUnavailableError(
            f"no healthy replica in rotation ({len(self._replicas)} total; "
            "all evicted, draining or dead)",
            replicas=len(self._replicas), healthy=0,
            retry_after_s=min(windows) if windows else self.probe_interval_s)

    def _may_retry(self, pend: _Pending, delay: float = 0.0) -> bool:
        """Budget check before any resubmission: bounded attempts, and
        never schedule work past the request's deadline."""
        if pend.attempts >= self.retry_policy.max_attempts:
            return False
        if pend.deadline is not None and (
                time.monotonic() + delay >= pend.deadline):
            return False
        return True

    def _backoff_or_fail(self, pend: _Pending,
                         err: BaseException) -> None:
        """End of a fleet-wide round (every candidate tried, or none
        existed): back off jittered-exponentially and try a fresh round,
        or fail the future typed when the budget (attempts or deadline)
        is spent."""
        delay = compute_delay(self.retry_policy, max(pend.attempts, 1))
        if self._draining.is_set() or not self._may_retry(pend, delay):
            self._finish_fail(pend, err)
            return
        pend.tried.clear()
        if pend.trace is not None:
            pend.trace.event("router.backoff", replica="router",
                             delay_s=round(delay, 4),
                             after_attempt=pend.attempts)
        self._schedule(pend, delay)   # the retry counter ticks when the
        #                               resubmission actually dispatches

    def _dispatch(self, pend: _Pending, sync: bool = False) -> None:
        """Submit ``pend`` to the best replica; on submit-time infra
        errors walk the remaining replicas in the same round. ``sync``
        (the caller's first attempt) reports terminal failures by raising
        — the engine's own submit contract — instead of failing the
        future."""
        while True:
            if pend.future.done():
                return                     # cancelled while waiting
            if self._draining.is_set():
                self._finish_fail(pend, EngineDrainingError(
                    "serving router is draining; no new requests admitted"),
                    sync)
                return
            now = time.monotonic()
            if pend.deadline is not None and now >= pend.deadline:
                self._finish_fail(
                    pend, pend.last_error or DeadlineExceededError(
                        "request deadline expired before a replica could "
                        "serve it"), sync)
                return
            rep = self._pick(pend)
            tr = pend.trace
            if rep is not None and tr is not None:
                try:
                    cand = {r.name: (self._load_score(r)[0])
                            for r in self._candidates(exclude=pend.tried)}
                except Exception:
                    cand = {}
                tr.set_replica(rep.name)
                tr.event("router.pick", replica=rep.name,
                         attempt=pend.attempts + 1, candidates=cand)
            if rep is None:
                # no candidate left this round: with no failure seen yet
                # the whole fleet is out (typed FleetUnavailableError);
                # otherwise surface the last replica's typed refusal
                err = pend.last_error or self._fleet_unavailable()
                if sync:
                    self._finish_fail(pend, err, True)  # fail fast at submit
                self._backoff_or_fail(pend, err)
                return
            pend.attempts += 1
            pend.tried.add(rep.name)
            if pend.attempts > 1:
                # a resubmission actually performed (same-round walk,
                # post-backoff round, or mid-flight failover redispatch)
                self._bump("retries")
                _safe_inc("paddle_router_retries_total",
                          "request resubmissions performed by the router")
            kw = dict(pend.kw)
            if rep.no_trace:
                kw.pop("trace", None)
            if pend.deadline is not None:
                kw["deadline_s"] = max(pend.deadline - now, 1e-3)
            if getattr(rep.client, "supports_req_uid", False):
                kw["req_uid"] = pend.req_uid
            pend.t_attempt = time.perf_counter()
            # arm the hedge timer BEFORE the blocking submit: the accept
            # round trip itself can gray-fail (delayed or black-holed
            # accepted frame), wedging this thread until the stall
            # watchdog fires — exactly the tail a hedge exists to cut
            if not pend.hedge_armed and len(self._replicas) > 1:
                delay = self._hedge_delay()
                if delay is not None:
                    pend.hedge_armed = True
                    self._schedule(pend, delay, kind="hedge")
            pend.in_submit = rep.name
            try:
                inner = rep.client.submit(pend.prompt_ids, **kw)
            except BaseException as e:  # noqa: BLE001 — classify below
                pend.in_submit = None
                if (isinstance(e, TypeError) and "trace" in kw
                        and "trace" in f"{e}"):
                    # a trace-unaware replica client choked on the
                    # journey kwarg: remember, undo this pick's
                    # bookkeeping, and retry — arming an observability
                    # flag must never burn breaker evidence or take a
                    # healthy fleet out of rotation
                    rep.no_trace = True
                    pend.attempts -= 1
                    pend.tried.discard(rep.name)
                    if tr is not None and tr.replicas \
                            and tr.replicas[-1] == rep.name:
                        tr.attempts -= 1
                        tr.replicas.pop()
                        for i in range(len(tr.spans) - 1, -1, -1):
                            s = tr.spans[i]
                            if (s.get("name") == "router.pick"
                                    and s.get("replica") == rep.name):
                                del tr.spans[i]
                                break
                    continue
                if tr is not None:
                    # submit-time refusal: breaker rejection, overload
                    # backpressure, draining replica, dead connection —
                    # each lands as its own span with the typed cause
                    tr.event("router.reject", t0=pend.t_attempt,
                             replica=rep.name,
                             error=f"{type(e).__name__}: {e}"[:200],
                             retryable=_retryable(e))
                if _retryable(e):
                    if rep.in_rotation and not isinstance(
                            e, ServerOverloadedError):
                        # overload is BACKPRESSURE from a healthy engine
                        # (typed, retry_after hint), not sickness — route
                        # around it without burning eviction evidence, or
                        # a fleet-wide burst would evict every healthy
                        # replica at once
                        rep.breaker.record_failure()
                    pend.last_error = e
                    if not self._may_retry(pend):
                        self._finish_fail(pend, e, sync)
                        return
                    continue          # same round, next replica
                self._finish_fail(pend, e, sync)
                return
            pend.in_submit = None
            pend.inner = inner
            pend.cur_rep = rep.name
            if pend.future.done():
                # cancel landed between the top-of-loop check and the
                # submit: the stale-inner cancel callback missed this
                # brand-new inner — don't decode a full budget for a
                # departed client
                inner.cancel()
                return
            with self._stats_lock:
                rep.inflight += 1
                self.stats["picks"] += 1
            _safe_inc("paddle_router_picks_total",
                      "requests routed to a replica, by replica",
                      replica=rep.name)
            inner._add_done_callback(
                lambda _inner, _pend=pend, _rep=rep:
                self._on_inner_done(_pend, _rep, _inner))
            return

    def _on_inner_done(self, pend: _Pending, rep: _Replica,
                       inner: GenerationResult) -> None:
        """A replica future resolved (runs on that replica's engine
        thread). Success delivers; retryable failure fails over to another
        replica within the retry budget — the mid-flight path the chaos
        drill exists for."""
        with self._stats_lock:
            rep.inflight = max(0, rep.inflight - 1)
        err = inner._error
        fut = pend.future
        tr = pend.trace
        if tr is not None and pend.t_attempt is not None:
            # the attempt child span: dispatch -> inner resolution, tagged
            # with the replica and (on failure) the typed cause — the
            # stitched journey's failover evidence
            tr.event("router.attempt", t0=pend.t_attempt,
                     t1=time.perf_counter(), replica=rep.name,
                     attempt=pend.attempts, ok=err is None,
                     **({} if err is None else
                        {"error": f"{type(err).__name__}: {err}"[:200]}))
        if fut.done():
            return                    # client cancelled the router future
        if err is None:
            rep.breaker.record_success()
            self._finish_ok(pend, inner)
            return
        if isinstance(err, RequestCancelledError) or not _retryable(err):
            self._finish_fail(pend, err)
            return
        if rep.in_rotation:
            # a deliberately-restarting replica's drain sheds are not
            # evidence of sickness — only in-rotation failures evict
            rep.breaker.record_failure()
        pend.last_error = err
        pend.tried = {rep.name}       # new round, but not straight back
        self._bump("failovers")
        _safe_inc("paddle_router_failovers_total",
                  "requests re-routed after a mid-flight replica failure",
                  replica=rep.name)
        _flight_record("router", rep.name, event="failover",
                       req=str(fut._req_id or "?"),
                       error=f"{type(err).__name__}: {err}"[:200])
        if not self._may_retry(pend):
            self._finish_fail(pend, err)
            return
        self._dispatch(pend)

    # -- hedged requests -----------------------------------------------------
    def _hedge_delay(self) -> Optional[float]:
        """The armed hedge delay in seconds, or None for no hedging.
        ``hedge_after_s`` numeric → that; ``"auto"`` → observed TTFT p99
        (floor 2× p50) from the tsdb history plane, cached ~1 s — with no
        history armed (or no TTFT data yet), auto stays OFF: hedging
        without a measured tail is just doubled load."""
        h = self.hedge_after_s
        if h is None or h == "off":
            return None
        if h != "auto":
            v = float(h)
            return v if v > 0 else None
        now = time.monotonic()
        if now - self._hedge_cache_t < 1.0:
            return self._hedge_cache
        val = None
        try:
            from ..observability import tsdb as _tsdb

            hist = _tsdb.get()
            if hist is not None:
                p99 = hist.window_agg("paddle_serving_ttft_seconds:p99",
                                      60.0, "avg")
                p50 = hist.window_agg("paddle_serving_ttft_seconds:p50",
                                      60.0, "avg")
                if p99:
                    v99 = max(p99.values())
                    v50 = max(p50.values()) if p50 else 0.0
                    val = max(float(v99), 2.0 * float(v50))
                    if val <= 0:
                        val = None
        except Exception:
            val = None
        self._hedge_cache, self._hedge_cache_t = val, now
        return val

    def _hedge_outcome(self, outcome: str) -> None:
        _safe_inc("paddle_router_hedges_total",
                  "hedged duplicate attempts by outcome "
                  "(launched/won/lost/failed/suppressed)",
                  outcome=outcome)

    def _maybe_hedge(self, pend: _Pending) -> None:
        """The hedge timer fired: the request has been in flight for
        hedge_after_s. If its primary attempt still has no first token,
        dispatch ONE duplicate to a different healthy replica — first
        terminal wins, the loser is cancelled. A hedge failure is
        fire-and-forget: it never burns breaker evidence and never
        triggers failover (the primary attempt still owns the request's
        retry budget)."""
        fut = pend.future
        if fut.done() or self._draining.is_set():
            return
        inner = pend.inner
        primary = pend.in_submit or pend.cur_rep
        if inner is None:
            if pend.in_submit is None:
                # between attempts: failover owns it
                return
            # else the dispatcher is BLOCKED in client.submit — a gray
            # accept (delayed/black-holed accepted frame); this is a tail
            # the hedge must cut, not skip
        elif inner.done() or inner._t_first is not None:
            # already terminal, or the first token arrived — the tail
            # this hedge would cut no longer exists
            return
        cands = self._candidates(
            exclude=() if primary is None else (primary,))
        if not cands:
            self._hedge_outcome("suppressed")
            return
        with self._stats_lock:
            budget = max(1.0, self.stats["submitted"]
                         * self.hedge_budget_pct / 100.0)
            if self.stats["hedges"] + 1 > budget:
                suppressed = True
            else:
                suppressed = False
                self.stats["hedges"] += 1
        if suppressed:
            self._hedge_outcome("suppressed")
            return
        rep = min(cands, key=self._load_score)
        kw = dict(pend.kw)
        kw.pop("trace", None)     # one journey cannot ride two live
        #   streams; the hedge is recorded as a router span instead
        if pend.deadline is not None:
            kw["deadline_s"] = max(pend.deadline - time.monotonic(), 1e-3)
        if getattr(rep.client, "supports_req_uid", False):
            kw["req_uid"] = pend.req_uid
        t0 = time.perf_counter()
        try:
            hinner = rep.client.submit(pend.prompt_ids, **kw)
        except Exception as e:
            self._hedge_outcome("failed")
            if pend.trace is not None:
                pend.trace.event("router.hedge", t0=t0, replica=rep.name,
                                 launched=False,
                                 error=f"{type(e).__name__}: {e}"[:200])
            return
        pend.hedge_inner = hinner
        if fut.done():
            hinner.cancel()
            return
        with self._stats_lock:
            rep.inflight += 1
        self._hedge_outcome("launched")
        _flight_record("router", rep.name, event="hedge",
                       req=str(fut._req_id or "?"),
                       primary=str(primary))
        if pend.trace is not None:
            pend.trace.event("router.hedge", t0=t0, replica=rep.name,
                             primary=primary, launched=True)
        hinner._add_done_callback(
            lambda _i, _pend=pend, _rep=rep:
            self._on_hedge_done(_pend, _rep, _i))

    def _on_hedge_done(self, pend: _Pending, rep: _Replica,
                       hinner: GenerationResult) -> None:
        with self._stats_lock:
            rep.inflight = max(0, rep.inflight - 1)
        fut = pend.future
        err = hinner._error
        if fut.done() or pend.delivered:
            # the primary delivered first (and _finish_ok cancelled us),
            # or the client went away — either way this duplicate lost
            self._hedge_outcome(
                "lost" if isinstance(err, RequestCancelledError)
                else "lost" if err is None else "failed")
            return
        if err is None:
            rep.breaker.record_success()
            with self._stats_lock:
                self.stats["hedge_wins"] += 1
            self._hedge_outcome("won")
            if pend.trace is not None:
                pend.trace.event("router.hedge_win", replica=rep.name)
            self._finish_ok(pend, hinner)
            return
        # hedge failed while the primary is still working: drop it on the
        # floor — no failover, no breaker evidence (one duplicate's death
        # must not evict a replica the primary path hasn't judged)
        self._hedge_outcome("failed")

    # -- client API ----------------------------------------------------------
    def submit(self, prompt_ids, max_new_tokens: int = 32,
               temperature: float = 0.0, top_k: int = 0,
               eos_token_id=None, deadline_s: Optional[float] = None,
               prefix_len: Optional[int] = None) -> GenerationResult:
        """Route one generation request into the fleet. Raises typed at
        submit exactly like the engine (validation, expired deadline,
        :class:`FleetUnavailableError` when no replica is in rotation);
        infra failures AFTER admission fail over transparently and
        surface only when the retry budget is spent."""
        if self._draining.is_set():
            raise EngineDrainingError(
                "serving router is draining; no new requests admitted")
        self.start()
        fut = GenerationResult()
        fut._req_id = next(_REQ_IDS)
        fut._obs_emit = False   # the replica-side inner future feeds the
        #       SLO histograms + flight ring; the wrapper must not record
        #       the same request twice (slo()/slo_summary still work — the
        #       inner stamps are copied over on delivery)
        deadline = (None if deadline_s is None
                    else time.monotonic() + float(deadline_s))
        fut._deadline = deadline
        prefix_key = None
        if prefix_len:
            arr = np.asarray(prompt_ids, np.int32).reshape(-1)
            prefix_key = arr[: int(prefix_len)].tobytes()
        tr = None
        try:
            from ..observability import reqtrace as _rt

            if _rt.enabled():
                # the journey is minted HERE, at the fleet front door, and
                # crosses the ReplicaClient seam as a submit kwarg — the
                # wrapper future owns it (closes it on delivery); every
                # replica-side stage stamps into the same object
                tr = _rt.mint(fut._req_id)
        except Exception:
            tr = None
        fut._trace = tr
        fut._trace_owner = tr is not None
        kw = {"max_new_tokens": max_new_tokens, "temperature": temperature,
              "top_k": top_k, "eos_token_id": eos_token_id,
              "prefix_len": prefix_len}
        if tr is not None:
            # only when tracing is armed: a foreign replica engine built
            # before the trace kwarg existed keeps working with it off
            kw["trace"] = tr
        pend = _Pending(prompt_ids, kw, fut, deadline, prefix_key)
        pend.trace = tr
        if tr is not None:
            arr = np.asarray(prompt_ids, np.int32).reshape(-1)
            tr.event("submit", replica="router", prompt=int(arr.size),
                     budget=int(max_new_tokens),
                     **({} if deadline_s is None
                        else {"deadline_s": float(deadline_s)}))
        self._bump("submitted")
        # a client cancel must reach the replica currently decoding it
        fut._add_done_callback(
            lambda f, _pend=pend: (_pend.inner.cancel()
                                   if f.cancelled() and _pend.inner is not None
                                   else None))
        self._dispatch(pend, sync=True)
        return fut

    def generate(self, prompt_ids, timeout: float = 300.0,
                 **kw) -> np.ndarray:
        return self.submit(prompt_ids, **kw).result(timeout)

    def health(self) -> Dict[str, object]:
        """Fleet snapshot: the ``router`` block (census + pick/retry/
        failover/eviction counters) plus one per-replica summary of the
        fields picks are made on."""
        reps: Dict[str, object] = {}
        healthy = 0
        for rep in self._replicas:
            snap = rep.snapshot or {}
            ok = (rep.in_rotation and rep.breaker.state != "open"
                  and bool(snap.get("ok", False)))
            healthy += ok
            reps[rep.name] = {
                "ok": ok,
                "in_rotation": rep.in_rotation,
                "breaker": rep.breaker.state,
                "inflight": rep.inflight,
                "est_wait_s": snap.get("est_wait_s"),
                "queue_depth": snap.get("queue_depth"),
                "pages_free": snap.get("pages_free"),
                "generation": rep.client.generation,
            }
            # process-backed replicas (RemoteReplicaClient over a
            # ReplicaSupervisor) carry their supervisor block — pid,
            # spawn/restart/crash counters, last exit — for obsctl
            if snap.get("supervisor") is not None:
                reps[rep.name]["supervisor"] = snap["supervisor"]
        with self._stats_lock:
            stats = dict(self.stats)
        alive = self._started and not self._stop.is_set()
        state = ("draining" if self._draining.is_set() and alive
                 else "serving" if alive else "stopped")
        try:
            from ..observability import reqtrace as _rt

            slo_burn = _rt.burn_snapshot()
        except Exception:
            slo_burn = {"enabled": False}
        return {
            "state": state,
            "ok": alive and not self._draining.is_set() and healthy > 0,
            "router": {"replicas": len(self._replicas), "healthy": healthy,
                       **stats},
            # fleet-level SLO burn rate (sliding window vs FLAGS_slo_*_ms
            # targets): the autoscaler's scale-up/down input signal
            "slo_burn": slo_burn,
            "replicas": reps,
        }

    # -- lifecycle -----------------------------------------------------------
    def start(self) -> "ServingRouter":
        if self._started and not self._stop.is_set():
            return self
        self._stop.clear()
        self._draining.clear()
        for rep in self._replicas:
            try:
                rep.client.start()
            except Exception:
                pass                  # the prober will keep it evicted
        self._probe_once()
        self._prober = threading.Thread(target=self._probe_loop,
                                        daemon=True, name="router-prober")
        self._prober.start()
        self._retrier = threading.Thread(target=self._retry_loop,
                                         daemon=True, name="router-retrier")
        self._retrier.start()
        self._started = True
        try:
            from ..observability import exporter as _exporter

            served = _exporter.get()
            if served is not None:
                self._health_reg_name = served.register_health(
                    "router", self.health, unique=True)
        except Exception:
            pass
        return self

    def _fail_scheduled(self, err: BaseException) -> int:
        """Fail every pending resubmission waiting in the retry heap —
        drain/stop must leave no future unresolved."""
        with self._retry_cv:
            waiting = [(k, p) for _, _, k, p in self._retry_heap]
            self._retry_heap.clear()
            self._retry_cv.notify()
        n = 0
        for kind, pend in waiting:
            if kind != "retry":
                continue     # a swept hedge timer just never fires: the
                #              primary attempt still resolves the future
            if not pend.future.done():
                self._finish_fail(pend, err)
                n += 1
        return n

    def drain(self, timeout: Optional[float] = None) -> Dict[str, object]:
        """Fleet-wide graceful shutdown: stop admission (submits raise
        :class:`EngineDrainingError`), fail queued resubmissions typed,
        drain every replica (their in-flight requests finish, their queued
        ones shed — and, with admission closed, fail typed rather than
        failing over). Idempotent."""
        timeout = self.drain_timeout_s if timeout is None else timeout
        t0 = time.monotonic()
        self._draining.set()
        shed = self._fail_scheduled(EngineDrainingError(
            "request shed: serving router drained before it was served"))
        clean = True
        for rep in self._replicas:
            try:
                res = rep.client.drain(timeout)
                clean = clean and bool(res.get("clean", True))
                shed += int(res.get("shed", 0))
            except Exception:
                clean = False
        _safe_inc("paddle_router_drains_total", "fleet drains completed",
                  outcome="clean" if clean else "timeout")
        return {"clean": clean, "shed": shed,
                "wall_s": round(time.monotonic() - t0, 3)}

    def stop(self) -> None:
        self._draining.set()
        self._fail_scheduled(RuntimeError("serving router stopped"))
        self._stop.set()
        with self._retry_cv:
            self._retry_cv.notify()
        for t in (self._prober, self._retrier):
            if t is not None:
                t.join(timeout=5)
        self._prober = self._retrier = None
        self._started = False
        for rep in self._replicas:
            rep.client.stop()
        try:
            from ..observability import exporter as _exporter

            served = _exporter.get()
            if served is not None:
                served.unregister_health(
                    self._health_reg_name or "router", fn=self.health)
        except Exception:
            pass

    def __enter__(self) -> "ServingRouter":
        return self.start()

    def __exit__(self, exc_type, exc, tb):
        self.stop()
        return False

    # -- elastic membership --------------------------------------------------
    def _free_name(self) -> str:
        taken = {r.name for r in self._replicas}
        i = len(self._replicas)
        while f"r{i}" in taken:
            i += 1
        return f"r{i}"

    def add_replica(self, replica, name: Optional[str] = None) -> str:
        """Join one replica to the rotation under live traffic. ``replica``
        is a ready :class:`ReplicaClient` (the fleet controller hands one
        in already started and PRE-WARMED, so its first routed request
        never lands on a cold program) or a zero-arg engine factory.
        Rendezvous-hashed prefix keys move ONLY onto the joining replica —
        every other prefix keeps its home — so the fleet-wide cache hit
        rate survives a scale-up. Returns the replica name."""
        client = replica if isinstance(replica, ReplicaClient) \
            else ReplicaClient(replica, name=name or self._free_name())
        if any(r.name == client.name for r in self._replicas):
            raise ValueError(f"replica name {client.name!r} already "
                             "in the rotation")
        rep = self._make_replica(client)
        if self._started and not self._stop.is_set():
            try:
                client.start()
            except Exception:
                pass              # the probe below keeps it out of picks
            try:
                rep.snapshot = rep.client.health()
            except Exception:
                rep.snapshot = None
        self._replicas = self._replicas + [rep]     # atomic snapshot swap
        self._bump("replicas_added")
        _safe_inc("paddle_router_replicas_added_total",
                  "replicas joined to the rotation", replica=rep.name)
        _flight_record("router", rep.name, event="add")
        return client.name

    def remove_replica(self, name: str,
                       drain_timeout: Optional[float] = None,
                       stop: bool = True,
                       reason: str = "scale_down") -> Dict[str, object]:
        """Leave the rotation DELIBERATELY (scale-down): the replica stops
        receiving picks, drains (in-flight finishes; queued sheds fail
        over to the rest — none of it is breaker failure evidence), is
        removed from the pick set (rendezvous keys it owned redistribute;
        nobody else's move), and — unless ``stop=False`` — its engine is
        stopped, which unregisters its ``/healthz`` provider. The router-
        side breaker/prober state is dropped with the replica, so a later
        replica reusing the name starts with a clean slate. Returns the
        drain summary plus the final breaker state."""
        rep = self._replica(name)
        if len(self._replicas) <= 1:
            raise ValueError("cannot remove the last replica; drain() or "
                             "stop() the router instead")
        rep.in_rotation = False    # no new picks; the prober stops feeding
        #                            its breaker (deliberate, not sickness)
        drain_timeout = (self.drain_timeout_s if drain_timeout is None
                         else drain_timeout)
        clean, shed = True, 0
        try:
            res = rep.client.drain(drain_timeout, reason=reason)
            clean = bool(res.get("clean", True))
            shed = int(res.get("shed", 0))
        except Exception:
            clean = False
        self._replicas = [r for r in self._replicas if r is not rep]
        if stop:
            try:
                rep.client.stop()   # unregisters the /healthz provider
            except Exception:
                pass
        self._bump("replicas_removed")
        _safe_inc("paddle_router_replicas_removed_total",
                  "replicas removed from the rotation, by reason",
                  replica=rep.name, reason=reason)
        _flight_record("router", rep.name, event="remove", reason=reason,
                       clean=clean, shed=shed)
        return {"replica": name, "clean": clean, "shed": shed,
                "breaker": rep.breaker.state,
                "generation": rep.client.generation}

    # -- rolling restart -----------------------------------------------------
    def restart_replica(self, replica, drain_timeout: Optional[float] = None,
                        health_timeout: float = 60.0, warmup: bool = True,
                        factory: Optional[Callable] = None,
                        readmit: bool = True) -> Dict[str, object]:
        """One replica's zero-downtime replacement cycle — the unit both
        :meth:`rolling_restart` and the fleet controller's deploy rollout
        are built from: out of rotation → drain (queued requests fail over
        to the rest) → fresh engine (``factory`` swaps the build recipe:
        a deploy hands in the candidate-bundle factory, a rollback the
        previous one) → pre-warm while still out of rotation → health
        gate → breaker reset + re-admission. On a failed health gate the
        replica is LEFT out of rotation and ``ok`` is False — the caller
        decides between abort (rolling restart) and rollback (deploy).
        ``readmit=False`` keeps a HEALTHY replica out of rotation too: the
        deploy pipeline probes its canary before letting it take traffic."""
        rep = replica if isinstance(replica, _Replica) \
            else self._replica(replica)
        t0 = time.monotonic()
        _flight_record("router", rep.name, event="rolling_restart",
                       phase="begin")
        rep.in_rotation = False
        if factory is not None:
            rep.client.restart(drain_timeout, factory=factory)
        else:
            # positional form: keeps drop-in ReplicaClient substitutes
            # (and test doubles) with the pre-deploy signature working
            rep.client.restart(drain_timeout)
        warm_info = None
        if warmup:
            # compiles happen HERE, outside rotation — not on the
            # first unlucky routed request after re-admission
            try:
                warm_info = rep.client.warmup()
                _safe_inc("paddle_router_prewarms_total",
                          "replicas pre-warmed during rolling restart",
                          replica=rep.name)
                _flight_record("router", rep.name, event="prewarm",
                               **(warm_info or {}))
            except Exception as e:
                # warm-later is degraded, not fatal: the health gate
                # below still decides re-admission
                sys.stderr.write(
                    f"[router] replica {rep.name} pre-warm failed "
                    f"({type(e).__name__}: {e}); first requests may "
                    "pay compiles\n")
        deadline = time.monotonic() + health_timeout
        ok = False
        while time.monotonic() < deadline:
            try:
                snap = rep.client.health()
                ok = bool(snap.get("ok", False))
            except Exception:
                ok = False
            if ok:
                rep.snapshot = snap
                break
            time.sleep(0.02)
        if ok and readmit:
            # fresh engine: forget the old one's failure history so the
            # replica is immediately pickable, not half-open-gated
            rep.breaker.reset()
            rep.in_rotation = True
        _flight_record("router", rep.name, event="rolling_restart",
                       phase="end", ok=ok)
        return {"replica": rep.name, "ok": ok,
                "generation": rep.client.generation,
                "warmup": warm_info,
                "wall_s": round(time.monotonic() - t0, 3)}

    def rolling_restart(self, drain_timeout: Optional[float] = None,
                        health_timeout: float = 60.0,
                        warmup: bool = True) -> Dict[str, object]:
        """Restart every replica one at a time without dropping traffic:
        take it out of rotation (no new picks), drain it (in-flight
        finishes; queued requests shed typed and fail over to the rest),
        build a fresh engine, PRE-WARM its compile plan while it is still
        out of rotation (``warmup=False`` skips it — e.g. replicas that
        load an AOT bundle and are warm by construction), wait until its
        health probe reads ok, put it back. The first request routed to
        the restarted replica therefore never lands on a cold program.
        Stops early — replica left OUT of rotation — if a restarted
        replica never turns healthy, so a bad deploy cannot take the whole
        fleet down one "upgrade" at a time."""
        self.start()
        drain_timeout = (self.drain_timeout_s if drain_timeout is None
                         else drain_timeout)
        rounds = []
        all_ok = True
        for rep in list(self._replicas):
            round_info = self.restart_replica(
                rep, drain_timeout=drain_timeout,
                health_timeout=health_timeout, warmup=warmup)
            rounds.append(round_info)
            if not round_info["ok"]:
                all_ok = False
                sys.stderr.write(
                    f"[router] rolling restart ABORTED: replica {rep.name} "
                    f"did not turn healthy within {health_timeout:g}s — "
                    "left out of rotation, remaining replicas not "
                    "restarted\n")
                break
        self._bump("rolling_restarts")
        _safe_inc("paddle_router_rolling_restarts_total",
                  "fleet rolling restarts", outcome="ok" if all_ok
                  else "aborted")
        return {"ok": all_ok, "replicas": rounds}
