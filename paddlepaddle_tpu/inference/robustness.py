"""Serving robustness primitives — typed shed errors, circuit breaker,
queue-wait estimation.

Reference surface: the reference deployment layer serves concurrent callers
through a BOUNDED pool of predictors (paddle/fluid/inference/api/
paddle_inference_api.h:229 PredictorPool) — a caller either gets a predictor
or is told to come back, and a sick predictor is contained to its slot. This
module gives the :class:`~.serving.ServingEngine` the same containment
properties around its single engine thread:

* typed admission errors (:class:`ServerOverloadedError`,
  :class:`DeadlineExceededError`, :class:`RequestCancelledError`,
  :class:`CircuitOpenError`, :class:`EngineDrainingError`) so clients can
  distinguish "back off and retry" from "your request was wrong" — the
  load-shedding half of "The Tail at Scale" (Dean & Barroso, CACM'13);
* :class:`CircuitBreaker` — N consecutive decode failures open the breaker
  (submits fail fast, nothing is decoded), a reset window later one probe
  is let through half-open, and a probe success closes it again;
* :class:`QueueWaitEstimator` — EWMA over decode-attempt wall time, used to
  turn a queue depth into a ``retry_after_s`` hint and to shed requests
  whose estimated queue wait already exceeds the configured bound.

Everything here is plain host-side bookkeeping: no JAX imports, safe to use
from any thread, and cheap enough that the no-limits-configured fast path
stays within a few attribute reads (enforced by
``tools/check_serving_overhead.py``).
"""

from __future__ import annotations

import threading
import time
from typing import Callable, Optional

__all__ = [
    "ServingError", "ServerOverloadedError", "DeadlineExceededError",
    "RequestCancelledError", "CircuitOpenError", "EngineDrainingError",
    "RequestValidationError", "KVCapacityError", "FleetUnavailableError",
    "DeployError", "ReplicaStalledError", "WireCorruptionError",
    "CircuitBreaker", "QueueWaitEstimator", "safe_inc",
    "safe_set", "error_to_wire", "error_from_wire",
]


def safe_inc(name: str, help_: str, n: float = 1, **labels) -> None:
    """Cold-path fault/event counter (sheds, breaker flips, drains,
    prefix hits/evictions): always records, never raises, costs nothing
    on the serve path. Shared by serving.py and decode_engine.py — one
    lazy-import-and-swallow wrapper, not three copies."""
    try:
        from ..observability import safe_inc as inc

        inc(name, help_, n, **labels)
    except Exception:
        pass


def safe_set(name: str, help_: str, value: float, **labels) -> None:
    """Best-effort cold-path gauge write, same contract as
    :func:`safe_inc`."""
    try:
        from ..observability import safe_set as set_

        set_(name, help_, value, **labels)
    except Exception:
        pass


class ServingError(RuntimeError):
    """Base of every typed serving-robustness error."""


class ServerOverloadedError(ServingError):
    """Load shed: the queue is full (or its estimated wait is over the
    bound). Carries the observed depth and a retry-after hint so a client
    can back off instead of hammering."""

    def __init__(self, msg: str, queue_depth: int = 0,
                 retry_after_s: float = 0.0):
        super().__init__(msg)
        self.queue_depth = int(queue_depth)
        self.retry_after_s = float(retry_after_s)


class DeadlineExceededError(ServingError):
    """The request's deadline passed before (or while) it was served."""


class RequestCancelledError(ServingError):
    """The client cancelled the request (``GenerationResult.cancel()``)."""


class CircuitOpenError(ServingError):
    """The decode circuit breaker is open: recent decodes failed (or hung),
    so submits fail fast instead of queueing behind a sick engine."""

    def __init__(self, msg: str, retry_after_s: float = 0.0):
        super().__init__(msg)
        self.retry_after_s = float(retry_after_s)


class EngineDrainingError(ServingError):
    """The engine is draining (or drained): admission is closed for good."""


class RequestValidationError(ValueError, ServingError):
    """The request can never be served (prompt + budget over ``max_len``,
    non-positive budget) — rejected at submit, before it costs a queue
    slot. A ``ValueError`` so pre-existing callers' handlers still match."""


class KVCapacityError(RequestValidationError):
    """The request's prompt + token budget needs more KV pages than the
    paged pool holds EVEN WHEN EMPTY — waiting for retirements can never
    help, so it is rejected at submit (shed, reason ``kv_capacity``)
    instead of deadlocking at the head of the queue. Before the paged
    pool, admission only checked against ``max_len``; a pool sized below
    ``slots x max_len`` makes this its own failure mode."""

    def __init__(self, msg: str, pages_needed: int = 0,
                 pages_capacity: int = 0):
        super().__init__(msg)
        self.pages_needed = int(pages_needed)
        self.pages_capacity = int(pages_capacity)


class FleetUnavailableError(ServingError):
    """Every replica behind the :class:`~.router.ServingRouter` is out of
    rotation (evicted by its breaker, draining, or dead) — the fleet as a
    whole cannot admit the request. Carries the replica census and a
    retry-after hint (the soonest half-open probe window among the evicted
    replicas) so clients back off instead of hammering a dead fleet."""

    def __init__(self, msg: str, replicas: int = 0, healthy: int = 0,
                 retry_after_s: float = 0.0):
        super().__init__(msg)
        self.replicas = int(replicas)
        self.healthy = int(healthy)
        self.retry_after_s = float(retry_after_s)


class ReplicaStalledError(ServingError):
    """The stream-progress watchdog tripped: a replica connection accepted
    the request (or was mid-stream) but produced NO frame — chunk, progress
    or heartbeat — within ``heartbeat_timeout_s``. A black-holed or
    partitioned connection, not a slow decode: the server heartbeats every
    ``heartbeat_interval_s`` even when there is nothing to report, so
    silence means the wire (or the peer) is gone. Retryable — another
    replica can serve the request, and the stalled connection is closed so
    the server's disconnect probe releases the decode slot."""

    def __init__(self, msg: str, stalled_after_s: float = 0.0):
        super().__init__(msg)
        self.stalled_after_s = float(stalled_after_s)


class WireCorruptionError(ServingError):
    """A frame failed its CRC32 payload check: bytes were damaged in
    transit. The connection is abandoned (a desynced stream cannot be
    trusted for one more frame) and the request is retryable — corruption
    must surface as a typed infra failure, NEVER as wrong tokens."""


class DeployError(ServingError):
    """A :meth:`~.fleet.FleetController.deploy` could not START: the
    candidate bundle failed pre-flight validation (missing/garbled
    manifest, corrupt payload, unsupported format), or another deploy is
    already in flight. Raised BEFORE any replica is touched — a rejected
    candidate costs nothing. (A deploy that starts and then fails its
    canary gate or regresses mid-rollout does NOT raise: it rolls back
    and reports ``ok=False`` in its result, because a bad candidate is an
    expected outcome the pipeline exists to absorb.) Carries the stage
    that refused and the reasons."""

    def __init__(self, msg: str, stage: str = "validate",
                 reasons: Optional[list] = None):
        super().__init__(msg)
        self.stage = str(stage)
        self.reasons = list(reasons or [])


# ---------------------------------------------------------------------------
# wire (de)serialization — the process boundary's half of the taxonomy.
#
# A remote replica (inference/replica_main.py) reports failures as a typed
# error frame: {"type": <class name>, "msg": str(exc), "fields": {...}}.
# error_from_wire rebuilds the SAME exception class with the SAME extra
# fields (retry_after_s, queue_depth, ...) on the client side, so the
# router's _retryable() classification, breaker evidence, and client
# backoff hints are byte-identical whether the replica is a thread or a
# process. An unknown type (a replica running newer code, or a raw engine
# crash) rehydrates as an untyped RuntimeError — which the router treats
# as retryable infra failure, exactly what a crashed process should be.
# ---------------------------------------------------------------------------

_WIRE_FIELDS = {
    "ServerOverloadedError": ("queue_depth", "retry_after_s"),
    "CircuitOpenError": ("retry_after_s",),
    "KVCapacityError": ("pages_needed", "pages_capacity"),
    "FleetUnavailableError": ("replicas", "healthy", "retry_after_s"),
    "DeployError": ("stage", "reasons"),
    "ReplicaStalledError": ("stalled_after_s",),
}


def error_to_wire(exc: BaseException) -> dict:
    """One JSON-able dict per exception: class name, message, and the
    class's extra constructor fields (so hints like ``retry_after_s``
    survive the hop). Never raises — a serialization failure degrades to
    an untyped record, not a lost error."""
    doc = {"type": type(exc).__name__, "msg": str(exc)}
    try:
        fields = {}
        for f in _WIRE_FIELDS.get(doc["type"], ()):
            v = getattr(exc, f, None)
            if v is not None:
                fields[f] = v
        if fields:
            doc["fields"] = fields
    except Exception:
        pass
    return doc


def error_from_wire(doc: dict) -> BaseException:
    """Rebuild the typed exception a replica process reported. Unknown
    (or untyped) error types come back as ``RuntimeError`` — the router
    classifies those as retryable infra failures, which is the correct
    reading of \"the remote engine blew up\"."""
    name = str(doc.get("type") or "RuntimeError")
    msg = str(doc.get("msg") or "remote replica error")
    fields = doc.get("fields") or {}
    cls = globals().get(name)
    if (not isinstance(cls, type) or not issubclass(cls, ServingError)):
        # deliberate: client-side cancellation/timeouts keep their stdlib
        # types so caller except-clauses (TimeoutError) still match
        if name == "TimeoutError":
            return TimeoutError(msg)
        return RuntimeError(f"{name}: {msg}" if name != "RuntimeError"
                            else msg)
    try:
        known = {f: fields[f] for f in _WIRE_FIELDS.get(name, ())
                 if f in fields}
        return cls(msg, **known)
    except Exception:
        return cls(msg)


class CircuitBreaker:
    """Consecutive-failure circuit breaker with half-open probe recovery.

    States: ``closed`` (normal), ``open`` (fail fast until ``reset_s``
    elapses), ``half_open`` (one probe in flight; its outcome decides).
    ``trip()`` force-opens regardless of counts — the hung-decode watchdog
    uses it. Thread-safe: submits check it from client threads while the
    engine thread records outcomes.
    """

    def __init__(self, threshold: int = 5, reset_s: float = 30.0,
                 on_transition: Optional[Callable[[str, str], None]] = None):
        if threshold < 1:
            raise ValueError(f"breaker threshold must be >= 1, got {threshold}")
        self.threshold = int(threshold)
        self.reset_s = float(reset_s)
        self._state = "closed"
        self._consecutive = 0
        self._opened_at = 0.0
        self._lock = threading.Lock()
        self._on_transition = on_transition

    @property
    def state(self) -> str:
        if self._state == "closed":
            return "closed"     # lock-free steady state (see allow())
        with self._lock:
            self._maybe_half_open()
            return self._state

    @property
    def consecutive_failures(self) -> int:
        return self._consecutive

    def _transition(self, new: str) -> None:
        # lock held by caller
        old = self._state
        if old == new:
            return
        self._state = new
        if new == "open":
            self._opened_at = time.monotonic()
        cb = self._on_transition
        if cb is not None:
            try:
                cb(old, new)
            except Exception:
                pass  # observability must not break the breaker

    def _maybe_half_open(self) -> None:
        # lock held by caller
        if (self._state == "open"
                and time.monotonic() - self._opened_at >= self.reset_s):
            self._transition("half_open")

    def record_failure(self) -> None:
        with self._lock:
            self._consecutive += 1
            if self._state == "half_open":
                self._transition("open")      # probe failed: back to open
            elif (self._state == "closed"
                    and self._consecutive >= self.threshold):
                self._transition("open")

    def record_success(self) -> None:
        if self._state == "closed" and self._consecutive == 0:
            return      # steady state: one decode attempt per batch must
        with self._lock:  # not pay a lock round-trip
            self._consecutive = 0
            if self._state != "closed":       # probe (or late hung decode
                self._transition("closed")    # returning) succeeded

    def trip(self) -> None:
        """Force-open (watchdog: a decode is hung, stop queueing behind it)."""
        with self._lock:
            self._consecutive = max(self._consecutive, self.threshold)
            self._transition("open")

    def reset(self) -> None:
        """Return to ``closed`` with zero failures. For backend
        replacement (engine restart after drain, a router replica swapped
        for a fresh one): the new backend must not inherit its
        predecessor's failure history or sit out a stale reset window."""
        with self._lock:
            self._consecutive = 0
            self._transition("closed")

    def allow(self) -> bool:
        """True when work may proceed (closed, or open long enough that a
        half-open probe is due). False = fail fast.

        Lock-free when closed: the submit fast path must cost attribute
        reads, and a submit that races the closed->open transition merely
        queues one request the decode loop will hold anyway."""
        if self._state == "closed":
            return True
        with self._lock:
            self._maybe_half_open()
            return self._state != "open"

    def retry_after_s(self) -> float:
        """Hint for fail-fast errors: time until the next half-open probe."""
        with self._lock:
            if self._state != "open":
                return 0.0
            return max(0.0, self.reset_s
                       - (time.monotonic() - self._opened_at))


class QueueWaitEstimator:
    """EWMA of decode-attempt wall time → estimated queue wait.

    One sample per decode attempt (a static batch or a continuous chunk);
    the estimated wait for a request entering at depth ``d`` with ``b``
    requests served per attempt is ``(d / b) * ewma`` — the time spent
    behind others, not its own service. Crude on purpose — the point is a
    load-shedding signal and a retry-after hint, not an SLA; it converges
    within a handful of attempts either way.
    """

    def __init__(self, alpha: float = 0.2):
        self.alpha = float(alpha)
        self._ewma = 0.0

    def observe(self, seconds: float) -> None:
        if self._ewma == 0.0:
            self._ewma = float(seconds)
        else:
            self._ewma += self.alpha * (float(seconds) - self._ewma)

    @property
    def ewma_s(self) -> float:
        return self._ewma

    def estimate_wait_s(self, depth: int, per_attempt: int) -> float:
        """Estimated seconds a request entering now waits before decoding
        starts; 0.0 until the first sample lands (never shed blind)."""
        if self._ewma == 0.0:
            return 0.0
        return (depth / max(1, per_attempt)) * self._ewma
