"""Replica server: one serving engine per OS process.

``python -m paddlepaddle_tpu.inference.replica_main --bundle PATH
--socket SOCK`` (or ``--port N`` for loopback TCP) boots a
:class:`~.serving.ServingEngine` in a FRESH process — exactly the shape
the compile-plan suite proves bundles need (a process that has executed
persistent-cache-retrieved executables cannot reliably deserialize
bundles; a fresh process always can) — then serves submit/health/drain/
restart over the C-API frame protocol (:mod:`~.c_api_server`) for a
:class:`~.remote_replica.RemoteReplicaClient`.

Lifecycle contract (what :class:`~.remote_replica.ReplicaSupervisor`
builds on):

* stdout line ``REPLICA_READY {json}`` exactly once, after the engine is
  started (and warmed/bundle-armed) and the socket is listening — the
  JSON carries pid, socket/port, and the bundle status;
* ``--bundle`` is STRICT by default: a bundle that falls back to lazy
  builds exits 3 before serving (a deploy must never silently serve the
  slow path as the new version) — ``--allow-bundle-fallback`` restores
  the engine's forgiving production default;
* SIGTERM drains via the preemption hook (in-flight requests finish,
  queued ones shed typed) and exits 143 — the supervisor's graceful
  restart half; SIGKILL is the chaos half, no cooperation required.
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import threading
import time

# mirror tools/coldstart_bench.py: the tiny preset is the test fleet's
# model, the small preset the CPU bench's
PRESETS = {
    "tiny": dict(vocab_size=128, hidden_size=64, intermediate_size=192,
                 num_hidden_layers=2, num_attention_heads=4,
                 num_key_value_heads=2, max_position_embeddings=96),
    "small": dict(vocab_size=512, hidden_size=256, intermediate_size=768,
                  num_hidden_layers=4, num_attention_heads=8,
                  num_key_value_heads=4, max_position_embeddings=512),
}


def _build_model(preset: str, model_json: str | None):
    import paddlepaddle_tpu as paddle
    from paddlepaddle_tpu.models import LlamaConfig, LlamaForCausalLM

    kw = dict(PRESETS[preset])
    if model_json:
        kw.update(json.loads(model_json))
    kw.setdefault("dtype", "float32")
    paddle.seed(0)
    return LlamaForCausalLM(LlamaConfig(**kw))


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        prog="python -m paddlepaddle_tpu.inference.replica_main",
        description=__doc__.split("\n")[0])
    ap.add_argument("--bundle", default=None,
                    help="AOT serving bundle to arm the engine from "
                    "(strict: a fallback to lazy builds exits 3)")
    ap.add_argument("--allow-bundle-fallback", action="store_true",
                    help="serve even when the bundle did not load "
                    "(the engine's forgiving lazy-build fallback)")
    ap.add_argument("--socket", default=None,
                    help="Unix domain socket path to serve on")
    ap.add_argument("--port", type=int, default=None,
                    help="loopback TCP port (0 = ephemeral; the REPLICA_"
                    "READY line reports the resolved port)")
    ap.add_argument("--preset", choices=sorted(PRESETS), default="tiny")
    ap.add_argument("--model-json", default=None,
                    help="JSON dict of LlamaConfig overrides on the preset")
    ap.add_argument("--engine-json", default=None,
                    help="JSON dict of ServingEngine kwargs "
                    "(max_batch_size, decode_chunk, kv_page_size, ...)")
    ap.add_argument("--warmup", choices=["auto", "on", "off"],
                    default="auto",
                    help="auto: warm only when no bundle loaded (a loaded "
                    "bundle already has every program)")
    ap.add_argument("--metrics-port", type=int, default=None,
                    help="start the Prometheus /metrics + /healthz "
                    "exporter on this port (0 = ephemeral)")
    ap.add_argument("--drain-timeout", type=float, default=None,
                    help="SIGTERM drain bound (seconds)")
    ap.add_argument("--server-json", default=None,
                    help="JSON dict of CApiServer kwargs "
                    "(heartbeat_interval_s, write_timeout_s, "
                    "frame_timeout_s, send_buffer_bytes, result_cache "
                    "— the wire-hardening knobs)")
    args = ap.parse_args(argv)
    if (args.socket is None) == (args.port is None):
        ap.error("exactly one of --socket / --port is required")

    t0 = time.perf_counter()
    from paddlepaddle_tpu.inference.c_api_server import CApiServer
    from paddlepaddle_tpu.inference.serving import ServingEngine

    model = _build_model(args.preset, args.model_json)
    t_model = time.perf_counter()
    eng_kw = json.loads(args.engine_json) if args.engine_json else {}
    eng_kw.setdefault("max_batch_size", 2)
    eng_kw.setdefault("decode_chunk", 4)
    eng_kw.setdefault("kv_page_size", 16)
    eng = ServingEngine(model, bundle=args.bundle,
                        drain_on_sigterm=True,
                        drain_timeout_s=args.drain_timeout, **eng_kw)
    bundle_info = dict(getattr(eng._engine, "_bundle_info", None) or {})
    if args.bundle and not bundle_info.get("loaded") \
            and not args.allow_bundle_fallback:
        sys.stderr.write(
            f"[replica_main] bundle did not load ({bundle_info}); "
            "refusing to serve the lazy path as this version "
            "(--allow-bundle-fallback to override)\n")
        return 3
    eng.start()
    if args.warmup == "on" or (args.warmup == "auto" and args.bundle
                               and not bundle_info.get("loaded")):
        eng.warmup()

    exporter_port = None
    if args.metrics_port is not None:
        from paddlepaddle_tpu.observability import exporter

        exp = exporter.start(port=args.metrics_port)
        exporter_port = getattr(exp, "port", args.metrics_port)

    srv_kw = json.loads(args.server_json) if args.server_json else {}
    srv = CApiServer(None, socket_path=args.socket, port=args.port,
                     engine=eng, health_fn=eng.health, **srv_kw)
    srv.start()
    ready = {"pid": os.getpid(), "socket": args.socket, "port": srv.port,
             "metrics_port": exporter_port,
             "bundle": {"path": args.bundle,
                        "loaded": bool(bundle_info.get("loaded"))},
             # the coldstart bench's comparable window: imports + model
             # build (checkpoint-shaped, identical in-process) vs engine
             # bring-up (ctor + bundle load + warmup — what a restart
             # strategy actually changes)
             "t_model_build_s": round(t_model - t0, 3),
             "t_engine_ready_s": round(time.perf_counter() - t_model, 3)}
    print("REPLICA_READY " + json.dumps(ready), flush=True)
    # serve until SIGTERM: the preemption hook (installed by
    # drain_on_sigterm=True at engine start) drains and exits 143
    try:
        threading.Event().wait()
    except KeyboardInterrupt:
        eng.drain(args.drain_timeout, reason="sigint")
        srv.stop()
    return 0


if __name__ == "__main__":
    sys.exit(main())
