"""ASP — automatic structured (n:m) sparsity.

Reference surface: python/paddle/incubate/asp/asp.py (prune_model /
decorate / calculate_density) + utils.py mask algorithms. The reference
prunes FC/conv weights to n:m patterns (2:4 by default — the shape
sparse tensor cores consume) and re-applies the masks after every
optimizer step so training stays inside the pruned support.

TPU-native note: the MXU has no 2:4 sparse mode, so here the masks buy
model compression / sparsity research semantics, not a kernel speedup —
the pruning, density accounting, and mask-preserving training loop match
the reference contract and are what the API promises.
"""

from __future__ import annotations

from typing import List

import numpy as np

from ..core.tensor import Tensor


def _supported(p: Tensor, m: int = 4) -> bool:
    # reference supported_layer_list: FC/conv weights, i.e. >=2-D params.
    # Conv weights (out, in, kh, kw) are masked over the FLATTENED trailing
    # dims (the reference reshapes to 2-D the same way), so the gate is the
    # flattened width, not the raw last axis.
    if p is None or len(p.shape) < 2:
        return False
    flat = 1
    for d in p.shape[1:]:
        flat *= int(d)
    return flat >= m


def get_mask_1d(weight: np.ndarray, n: int = 2, m: int = 4) -> np.ndarray:
    """n:m mask along the LAST axis: in every group of m consecutive
    elements keep the n largest |values| (reference utils.get_mask_1d).
    Ties break deterministically toward the earlier element (stable
    argsort) — a threshold compare would mis-keep on ties (an all-equal
    group must keep exactly n, not 0 or m)."""
    w = np.asarray(weight)
    if w.shape[-1] % m:
        pad = m - w.shape[-1] % m
        w = np.concatenate([w, np.zeros(w.shape[:-1] + (pad,), w.dtype)], -1)
    else:
        pad = 0
    g = np.abs(w.reshape(-1, m).astype(np.float32))
    order = np.argsort(-g, axis=-1, kind="stable")
    mask = np.zeros(g.shape, w.dtype)
    np.put_along_axis(mask, order[:, :n], 1, axis=-1)
    mask = mask.reshape(w.shape)
    if pad:
        mask = mask[..., :-pad]
    return mask


def check_mask_1d(mat: np.ndarray, n: int = 2, m: int = 4) -> bool:
    """True when every m-group along the last axis has <= n nonzeros."""
    w = np.asarray(mat)
    if w.shape[-1] % m:
        pad = m - w.shape[-1] % m
        w = np.concatenate([w, np.zeros(w.shape[:-1] + (pad,), w.dtype)], -1)
    nz = (w.reshape(-1, m) != 0).sum(-1)
    return bool((nz <= n).all())


def calculate_density(mat) -> float:
    w = np.asarray(mat.numpy() if isinstance(mat, Tensor) else mat)
    return float((w != 0).sum() / w.size)


def prune_model(model, n: int = 2, m: int = 4, mask_algo: str = "mask_1d",
                with_mask: bool = True) -> List[Tensor]:
    """Prune every supported weight of ``model`` to an n:m pattern and
    (with_mask) register the masks so ``decorate``d optimizers keep the
    support fixed (reference asp.py:319)."""
    if mask_algo != "mask_1d":
        raise NotImplementedError(
            f"mask_algo {mask_algo!r}: only 'mask_1d' is implemented (a 1-D "
            "mask does NOT satisfy the 2-D n:m invariant, so silently "
            "downgrading would be wrong)")
    pruned = []
    for p in model.parameters():
        pname = getattr(p, "name", "") or ""
        if pname and any(t in pname for t in _excluded_names):
            continue
        if not _supported(p, m):
            continue
        w = np.asarray(p.numpy())
        # conv (out, in, kh, kw) and any >=2-D weight: n:m over the
        # flattened trailing dims, the reference's reshape-to-2D semantics
        w2 = w.reshape(w.shape[0], -1)
        mask = get_mask_1d(w2, n=n, m=m).reshape(w.shape)
        import jax.numpy as jnp

        p._replace_data(jnp.asarray(w * mask, dtype=p._data.dtype))
        if with_mask:
            # mask rides ON the parameter (no global registry: no leaks, no
            # id-reuse collisions — the reference keys by param name for
            # the same reason)
            p._asp_mask = mask
        pruned.append(p)
    return pruned


class OptimizerWithSparsityGuarantee:
    """Wraps an optimizer so each step re-applies the registered masks
    (reference asp.py:233 decorate): gradients may be dense, but pruned
    coordinates are zeroed back after the update."""

    def __init__(self, optimizer):
        self._optimizer = optimizer

    def __getattr__(self, name):
        if name == "_optimizer":   # not yet set (e.g. copy/pickle probing a
            raise AttributeError(name)  # bare instance) — avoid recursion
        return getattr(self._optimizer, name)

    def step(self):
        self._optimizer.step()
        self.step_mask_only()

    def minimize(self, loss, *a, **k):
        out = self._optimizer.minimize(loss, *a, **k)
        self.step_mask_only()
        return out

    def step_mask_only(self):
        import jax.numpy as jnp

        for p in getattr(self._optimizer, "_parameter_list", None) or []:
            mask = getattr(p, "_asp_mask", None)
            if mask is not None:
                p._replace_data(p._data * jnp.asarray(mask, p._data.dtype))


def decorate(optimizer) -> OptimizerWithSparsityGuarantee:
    return OptimizerWithSparsityGuarantee(optimizer)


def reset_excluded_layers(*a, **k):
    """Clear the name-based exclusion set (reference asp.py
    reset_excluded_layers)."""
    _excluded_names.clear()


_excluded_names: set = set()
_extra_supported: set = set()


def set_excluded_layers(param_names, main_program=None):
    """Names (or name substrings) of parameters that prune_model must skip
    (reference incubate/asp/asp.py:55)."""
    _excluded_names.update(param_names)


def add_supported_layer(layer, pruning_func=None):
    """Register a layer type as prunable (reference
    asp/supported_layer_list add_supported_layer). The reference needs
    this because it prunes a fixed TYPE list (Linear/Conv); here
    ``_supported`` gates by SHAPE (any >=2-D weight whose flattened
    trailing width fits the n:m pattern), which is a superset of every
    registrable type — so registration is recorded for introspection but
    cannot widen the prune set. A custom ``pruning_func`` is not
    supported (the mask algorithm is fixed to mask_1d) and raises rather
    than being silently ignored."""
    if pruning_func is not None:
        raise NotImplementedError(
            "add_supported_layer(pruning_func=...): custom mask functions "
            "are not supported — the n:m mask algorithm is fixed "
            "(mask_1d); shapes it can mask are already auto-included")
    name = layer if isinstance(layer, str) else getattr(
        layer, "__name__", str(layer))
    _extra_supported.add(name)
