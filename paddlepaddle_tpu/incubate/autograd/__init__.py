"""Functional higher-order autodiff (reference: python/paddle/incubate/autograd/
+ python/paddle/autograd/autograd.py:461,587 jacobian/hessian).

TPU-native: direct jax transforms — exact, composable, jit-compatible."""

from __future__ import annotations

import jax
import jax.numpy as jnp

from ...core.dispatch import unwrap, wrap
from ...core.tensor import Tensor


def _pure(func):
    def f(*arrs):
        out = func(*[wrap(a) for a in arrs])
        return unwrap(out)

    return f


def _args(xs):
    xs = xs if isinstance(xs, (list, tuple)) else [xs]
    return [unwrap(x) if isinstance(x, Tensor) else jnp.asarray(x) for x in xs]


def jacobian(func, xs, create_graph=False, allow_unused=False):
    arrs = _args(xs)
    if not isinstance(xs, (list, tuple)):
        return wrap(jax.jacobian(_pure(func))(arrs[0]))
    jac = jax.jacobian(_pure(func), argnums=tuple(range(len(arrs))))(*arrs)
    return [wrap(j) for j in jac]


def hessian(func, xs, create_graph=False, allow_unused=False):
    arrs = _args(xs)
    if len(arrs) == 1:
        return wrap(jax.hessian(_pure(func))(arrs[0]))
    h = jax.hessian(_pure(func), argnums=tuple(range(len(arrs))))(*arrs)
    return jax.tree_util.tree_map(wrap, h)


def jvp(func, xs, v=None):
    arrs = _args(xs)
    tangents = _args(v) if v is not None else [jnp.ones_like(a) for a in arrs]
    out, tangent_out = jax.jvp(_pure(func), tuple(arrs), tuple(tangents))
    return wrap(out), wrap(tangent_out)


def vjp(func, xs, v=None):
    arrs = _args(xs)
    out, vjp_fn = jax.vjp(_pure(func), *arrs)
    cot = unwrap(v) if v is not None else jnp.ones_like(out)
    grads = vjp_fn(cot)
    grads = [wrap(g) for g in grads]
    return wrap(out), grads if len(grads) > 1 else grads[0]


def grad(func, xs, v=None):
    _, g = vjp(func, xs, v)
    return g


class Jacobian:
    """Lazy row-indexable Jacobian object (reference incubate/autograd
    functional.Jacobian): J[i, j] etc. materialize from jax.jacrev."""

    def __init__(self, func, xs, is_batched=False):
        self._mat = jacobian(func, xs)

    def __getitem__(self, idx):
        return self._mat[idx]

    @property
    def shape(self):
        return self._mat.shape

    def numpy(self):
        return self._mat.numpy()


class Hessian(Jacobian):
    def __init__(self, func, xs, is_batched=False):
        self._mat = hessian(func, xs)


def forward_grad(func, xs, v=None):
    """Forward-mode gradient (reference incubate.autograd.forward_grad):
    jvp with an all-ones (or given) tangent."""
    return jvp(func, xs, v)[1]


def enable_prim():
    """Primitive-decomposition mode: XLA always decomposes; no-op."""


def disable_prim():
    """No-op (see enable_prim)."""
