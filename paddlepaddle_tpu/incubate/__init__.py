"""paddle.incubate — experimental API surface (reference: python/paddle/incubate/)."""

from . import autograd, nn  # noqa: F401
