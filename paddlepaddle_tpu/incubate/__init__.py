"""paddle.incubate — experimental API surface (reference: python/paddle/incubate/)."""

from . import asp  # noqa: F401
from . import autograd, nn  # noqa: F401
from . import autotune, layers, xpu  # noqa: F401

# top-level incubate surface (reference python/paddle/incubate/__init__.py)
from ..geometric import (  # noqa: F401,E402  — graph ops live in geometric
    segment_max,
    segment_mean,
    segment_min,
    segment_sum,
)
from . import optimizer  # noqa: F401,E402


def identity_loss(x, reduction="none"):
    """Mark a value as a loss for IPU-style pipelining (reference
    incubate.identity_loss): reduce-and-return here."""
    if reduction in ("none", 2):
        return x
    if reduction in ("sum", 0):
        return x.sum()
    return x.mean()


def softmax_mask_fuse(x, mask, name=None):
    """softmax(x + mask) fused by XLA (reference fused op)."""
    import jax
    import jax.numpy as jnp

    from ..core.dispatch import apply_op

    return apply_op(lambda a, m: jax.nn.softmax(a + m, axis=-1), x, mask,
                    op_name="softmax_mask_fuse")


def softmax_mask_fuse_upper_triangle(x):
    """Causal-masked softmax (reference fused upper-triangle mask op)."""
    import jax
    import jax.numpy as jnp

    from ..core.dispatch import apply_op

    def f(a):
        s = a.shape[-1]
        mask = jnp.tril(jnp.ones((a.shape[-2], s), bool))
        return jax.nn.softmax(jnp.where(mask, a, -1e30), axis=-1)

    return apply_op(f, x, op_name="softmax_mask_fuse_upper_triangle")


def graph_send_recv(x, src_index, dst_index, pool_type="sum",
                    out_size=None, name=None):
    from ..geometric import send_u_recv

    return send_u_recv(x, src_index, dst_index, reduce_op=pool_type,
                       out_size=out_size)


def graph_reindex(x, neighbors, count, value_buffer=None, index_buffer=None,
                  flag_buffer_hashtable=False, name=None):
    """Compact global node ids to local ids (reference graph_reindex)."""
    import numpy as np

    import jax.numpy as jnp

    from ..core.dispatch import unwrap, wrap

    xs = np.asarray(unwrap(x)).reshape(-1)
    nb = np.asarray(unwrap(neighbors)).reshape(-1)
    cnt = np.asarray(unwrap(count)).reshape(-1)
    uniq = list(dict.fromkeys(xs.tolist() + nb.tolist()))
    remap = {v: i for i, v in enumerate(uniq)}
    reindex_src = np.array([remap[v] for v in nb], np.int64)
    reindex_dst = np.repeat(np.array([remap[v] for v in xs], np.int64), cnt)
    return (wrap(jnp.asarray(reindex_src)), wrap(jnp.asarray(reindex_dst)),
            wrap(jnp.asarray(np.array(uniq, np.int64))))


def graph_sample_neighbors(row, colptr, input_nodes, eids=None,
                           perm_buffer=None, sample_size=-1,
                           return_eids=False, flag_perm_buffer=False,
                           name=None):
    """CSC neighbor sampling (reference graph_sample_neighbors)."""
    import numpy as np

    import jax.numpy as jnp

    from ..core.dispatch import unwrap, wrap

    rows = np.asarray(unwrap(row)).reshape(-1)
    cp = np.asarray(unwrap(colptr)).reshape(-1)
    nodes = np.asarray(unwrap(input_nodes)).reshape(-1)
    rng = np.random.default_rng(0)
    out_n, out_count = [], []
    for v in nodes:
        lo, hi = int(cp[v]), int(cp[v + 1])
        nbrs = rows[lo:hi]
        if 0 <= sample_size < len(nbrs):
            nbrs = rng.choice(nbrs, size=sample_size, replace=False)
        out_n.append(nbrs)
        out_count.append(len(nbrs))
    flat = (np.concatenate(out_n) if out_n else np.zeros((0,), np.int64))
    return (wrap(jnp.asarray(flat.astype(np.int64))),
            wrap(jnp.asarray(np.array(out_count, np.int32))))


def graph_khop_sampler(row, colptr, input_nodes, sample_sizes,
                       sorted_eids=None, return_eids=False, name=None):
    """Multi-hop sampling built on graph_sample_neighbors + reindex."""
    import numpy as np

    import jax.numpy as jnp

    from ..core.dispatch import unwrap, wrap

    cur = np.asarray(unwrap(input_nodes)).reshape(-1)
    all_src, all_dst = [], []
    for size in sample_sizes:
        nbrs, counts = graph_sample_neighbors(row, colptr, cur,
                                              sample_size=size)
        nb = np.asarray(unwrap(nbrs))
        ct = np.asarray(unwrap(counts))
        all_src.append(nb)
        all_dst.append(np.repeat(cur, ct))
        cur = np.unique(np.concatenate([cur, nb]))
    src = np.concatenate(all_src) if all_src else np.zeros((0,), np.int64)
    dst = np.concatenate(all_dst) if all_dst else np.zeros((0,), np.int64)
    return (wrap(jnp.asarray(src)), wrap(jnp.asarray(dst)),
            wrap(jnp.asarray(cur)))


class inference:  # namespace parity: paddle.incubate.inference decorator kit
    @staticmethod
    def enable(func=None, **kwargs):
        """Reference incubate.inference.enable: wrap a layer/function for
        cached compiled inference — here jit IS the inference engine."""

        def deco(f):
            return f

        return deco(func) if func is not None else deco

from .optimizer import LookAhead, ModelAverage  # noqa: F401,E402
