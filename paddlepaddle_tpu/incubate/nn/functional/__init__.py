"""Fused-op API surface (reference: python/paddle/incubate/nn/functional/ —
fused_rms_norm, fused_rotary_position_embedding, swiglu, fused_moe,
fused_multi_head_attention, variable_length_memory_efficient_attention...).

On TPU "fused" means: one jnp expression XLA fuses, or a Pallas kernel for
the attention path — the incubate names are thin aliases onto those."""

from __future__ import annotations

from typing import Optional

import jax
import jax.numpy as jnp

from ....core.dispatch import apply_op, unwrap, wrap
from ....nn import functional as F
from ....nn.functional import swiglu  # noqa: F401  (already fused)


def fused_rms_norm(x, norm_weight=None, norm_bias=None, epsilon=1e-6,
                   begin_norm_axis=-1, bias=None, residual=None,
                   quant_scale=-1, quant_round_type=0, quant_max_bound=0,
                   quant_min_bound=0):
    """Reference: incubate fused_rms_norm(x, w, b, eps, begin_norm_axis).
    Returns (out, residual_out) like the reference when residual given."""
    if residual is not None:
        x = x + residual
    if bias is not None:
        x = x + bias
    out = F.rms_norm(x, norm_weight, norm_bias, epsilon, begin_norm_axis)
    if residual is not None:
        return out, x
    return out


def fused_layer_norm(x, norm_weight=None, norm_bias=None, epsilon=1e-5,
                     begin_norm_axis=-1, bias=None, residual=None, **kw):
    if residual is not None:
        x = x + residual
    if bias is not None:
        x = x + bias
    out = F.layer_norm(x, list(x.shape[begin_norm_axis:]), norm_weight, norm_bias, epsilon)
    if residual is not None:
        return out, x
    return out


def fused_rotary_position_embedding(q, k=None, v=None, sin=None, cos=None,
                                    position_ids=None, use_neox_rotary_style=True,
                                    time_major=False, rotary_emb_base=10000.0):
    """Reference: fused_rope — BSHD q/k(/v passthrough), neox rotate-half.
    ``position_ids`` [b, s] selects per-token table rows (KV-cache decode)."""

    def rope(x, c, s):
        def f(xa, ca, sa, pos):
            seq = xa.shape[1]
            ca = ca.reshape(-1, ca.shape[-1])
            sa = sa.reshape(-1, sa.shape[-1])
            if pos is not None:
                ca = ca[pos.astype(jnp.int32)][:, :, None, :]   # [b, s, 1, dim]
                sa = sa[pos.astype(jnp.int32)][:, :, None, :]
            else:
                ca = ca[:seq].reshape(1, seq, 1, -1)
                sa = sa[:seq].reshape(1, seq, 1, -1)
            ca, sa = ca.astype(xa.dtype), sa.astype(xa.dtype)
            half = xa.shape[-1] // 2
            rot = jnp.concatenate([-xa[..., half:], xa[..., :half]], axis=-1)
            return xa * ca + rot * sa

        return apply_op(f, x, c, s, position_ids, op_name="fused_rope")

    outs = [rope(q, cos, sin)]
    outs.append(rope(k, cos, sin) if k is not None else None)
    outs.append(v)
    return tuple(outs)


def fused_multi_head_attention(x, qkv_weight, linear_weight, pre_layer_norm=False,
                               pre_ln_scale=None, pre_ln_bias=None, ln_scale=None,
                               ln_bias=None, pre_ln_epsilon=1e-5, qkv_bias=None,
                               linear_bias=None, cache_kv=None, attn_mask=None,
                               dropout_rate=0.0, attn_dropout_rate=0.0,
                               ln_epsilon=1e-5, training=True, mode="upscale_in_train",
                               ring_id=-1, add_residual=True, num_heads=None, name=None):
    """Condensed reference fused_attention: (pre-)LN -> qkv -> sdpa -> proj ->
    residual (+post-LN)."""
    residual = x
    if pre_layer_norm:
        x = F.layer_norm(x, [x.shape[-1]], pre_ln_scale, pre_ln_bias, pre_ln_epsilon)
    b, s, h = x.shape[0], x.shape[1], x.shape[-1]

    def qkv_fn(xa, w, bias_arr):
        # w: [3, n_heads, head_dim, h] (reference layout)
        out = jnp.einsum("bsh,kndh->bsknd", xa, w)
        if bias_arr is not None:
            out = out + bias_arr[None, None]
        return out

    qkv = apply_op(qkv_fn, x, qkv_weight, qkv_bias, op_name="fused_qkv")
    q, k, v = qkv[:, :, 0], qkv[:, :, 1], qkv[:, :, 2]
    ctx = F.scaled_dot_product_attention(q, k, v, attn_mask=attn_mask,
                                         dropout_p=attn_dropout_rate,
                                         training=training)
    ctx = ctx.reshape([b, s, -1])
    out = F.linear(ctx, linear_weight, linear_bias)
    if dropout_rate:
        out = F.dropout(out, dropout_rate, training=training)
    if add_residual:
        out = residual + out
    if not pre_layer_norm:
        out = F.layer_norm(out, [out.shape[-1]], ln_scale, ln_bias, ln_epsilon)
    return out


def fused_feedforward(x, linear1_weight, linear2_weight, linear1_bias=None,
                      linear2_bias=None, ln1_scale=None, ln1_bias=None,
                      ln2_scale=None, ln2_bias=None, dropout1_rate=0.5,
                      dropout2_rate=0.5, activation="relu", ln1_epsilon=1e-5,
                      ln2_epsilon=1e-5, pre_layer_norm=False, training=True,
                      mode="upscale_in_train", ring_id=-1, name=None):
    residual = x
    if pre_layer_norm:
        x = F.layer_norm(x, [x.shape[-1]], ln1_scale, ln1_bias, ln1_epsilon)
    h = F.linear(x, linear1_weight, linear1_bias)
    h = getattr(F, activation)(h)
    if dropout1_rate:
        h = F.dropout(h, dropout1_rate, training=training)
    out = F.linear(h, linear2_weight, linear2_bias)
    if dropout2_rate:
        out = F.dropout(out, dropout2_rate, training=training)
    out = residual + out
    if not pre_layer_norm:
        out = F.layer_norm(out, [out.shape[-1]], ln2_scale, ln2_bias, ln2_epsilon)
    return out


def fused_moe(x, gate_weight, ffn1_weight, ffn2_weight, ffn1_bias=None,
              ffn2_bias=None, quant_method="None", moe_topk=2, norm_topk_prob=True):
    """Reference: incubate fused_moe — top-k routed expert FFN bank."""
    from ....parallel.moe import MoELayer, NaiveGate

    b, s, d = x.shape[0], x.shape[1], x.shape[-1]

    def run(xa, gw, w1, w2, b1, b2):
        logits = xa.reshape(-1, d).astype(jnp.float32) @ gw.astype(jnp.float32)
        probs = jax.nn.softmax(logits, -1)
        topv, topi = jax.lax.top_k(probs, moe_topk)
        if norm_topk_prob:
            topv = topv / (topv.sum(-1, keepdims=True) + 1e-9)
        xf = xa.reshape(-1, d)
        out = jnp.zeros_like(xf)
        for j in range(moe_topk):
            sel = topi[:, j]
            w1_t = w1[sel]           # [T, d, hidden]
            w2_t = w2[sel]
            hmid = jnp.einsum("td,tdh->th", xf, w1_t)
            if b1 is not None:
                hmid = hmid + b1[sel]
            act = jax.nn.silu(hmid[..., : hmid.shape[-1] // 2]) * hmid[..., hmid.shape[-1] // 2:] \
                if hmid.shape[-1] % 2 == 0 else jax.nn.silu(hmid)
            o = jnp.einsum("th,thd->td", act, w2_t)
            if b2 is not None:
                o = o + b2[sel]
            out = out + o * topv[:, j:j + 1].astype(out.dtype)
        return out.reshape(b, s, d)

    return apply_op(run, x, gate_weight, ffn1_weight, ffn2_weight, ffn1_bias,
                    ffn2_bias, op_name="fused_moe")


def fused_linear(x, weight, bias=None, transpose_weight=False, name=None):
    def f(xa, w, ba):
        w = w.T if transpose_weight else w
        out = xa @ w
        return out + ba if ba is not None else out

    return apply_op(f, x, weight, bias, op_name="fused_linear")


def fused_bias_act(x, bias=None, act_method="gelu", **kw):
    def f(xa, ba):
        if ba is not None:
            xa = xa + ba
        return getattr(jax.nn, act_method if act_method != "geglu" else "gelu")(xa)

    return apply_op(f, x, bias, op_name="fused_bias_act")


def fused_dropout_add(x, y, p=0.5, training=True, mode="upscale_in_train", name=None):
    return F.dropout(x, p, training=training, mode=mode) + y


def fused_matmul_bias(x, y, bias=None, transpose_x=False, transpose_y=False,
                      name=None):
    """Reference incubate fused_matmul_bias — one XLA-fused matmul+add."""

    def f(a, b, bb):
        if transpose_x:
            a = jnp.swapaxes(a, -1, -2)
        if transpose_y:
            b = jnp.swapaxes(b, -1, -2)
        out = a @ b
        return out if bb is None else out + bb

    return apply_op(f, x, y, bias, op_name="fused_matmul_bias")


def fused_linear_activation(x, y, bias, trans_x=False, trans_y=False,
                            activation="gelu", name=None):
    out = fused_matmul_bias(x, y, bias, trans_x, trans_y)
    from ....nn import functional as F

    if activation in (None, "none", ""):
        return out
    return getattr(F, activation)(out)


def fused_bias_dropout_residual_layer_norm(
        x, residual, bias=None, ln_scale=None, ln_bias=None,
        dropout_rate=0.5, ln_epsilon=1e-5, training=True, mode=
        "upscale_in_train", name=None):
    """residual + dropout(x + bias), then LayerNorm (reference fused op)."""
    from ....nn import functional as F

    y = x if bias is None else x + bias
    y = F.dropout(y, p=dropout_rate, training=training, mode=mode)
    out = residual + y
    return F.layer_norm(out, out.shape[-1], weight=ln_scale, bias=ln_bias,
                        epsilon=ln_epsilon)


def fused_multi_transformer(x, ln_scales, ln_biases, qkv_weights, qkv_biases,
                            linear_weights, linear_biases, ffn_ln_scales,
                            ffn_ln_biases, ffn1_weights, ffn1_biases,
                            ffn2_weights, ffn2_biases, pre_layer_norm=True,
                            epsilon=1e-5, cache_kvs=None, time_step=None,
                            attn_mask=None, dropout_rate=0.0,
                            activation="gelu", training=False, name=None):
    """Stacked pre-LN transformer layers from raw weight lists (reference
    fused_multi_transformer inference op); one fused XLA program under jit."""
    from ....nn import functional as F

    out = x
    n_layers = len(qkv_weights)
    for i in range(n_layers):
        h = F.layer_norm(out, out.shape[-1], weight=ln_scales[i],
                         bias=ln_biases[i], epsilon=epsilon) \
            if pre_layer_norm else out
        attn = fused_multi_head_attention(
            h, qkv_weights[i], linear_weights[i],
            pre_layer_norm=False, qkv_bias=qkv_biases[i],
            linear_bias=linear_biases[i], attn_mask=attn_mask,
            dropout_rate=dropout_rate, training=training)
        out = out + attn
        h2 = F.layer_norm(out, out.shape[-1], weight=ffn_ln_scales[i],
                          bias=ffn_ln_biases[i], epsilon=epsilon) \
            if pre_layer_norm else out
        ffn = fused_feedforward(
            h2, ffn1_weights[i], ffn2_weights[i], linear1_bias=ffn1_biases[i],
            linear2_bias=ffn2_biases[i], activation=activation,
            pre_layer_norm=False, training=training)
        out = out + ffn
    return out


def variable_length_memory_efficient_attention(query, key, value, seq_lens,
                                               kv_seq_lens, mask=None,
                                               scale=None, causal=False,
                                               pre_cache_length=0, name=None):
    """Varlen attention at the incubate API (reference memory-efficient
    kernel): lowered onto the segment-masked flash path. query/key/value are
    [b, heads, s, d]; seq_lens give per-batch valid lengths."""
    import numpy as _np

    from ....nn import functional as F

    q = unwrap(query)
    b, h, sq, d = q.shape
    lens_q = _np.asarray(unwrap(seq_lens)).reshape(-1)
    lens_k = _np.asarray(unwrap(kv_seq_lens)).reshape(-1)
    cu_q = _np.concatenate([[0], _np.cumsum(lens_q)]).astype(_np.int32)
    cu_k = _np.concatenate([[0], _np.cumsum(lens_k)]).astype(_np.int32)

    def pack(t, lens):
        a = unwrap(t)
        rows = [a[i, :, : lens[i]].swapaxes(0, 1) for i in range(b)]
        return jnp.concatenate(rows, axis=0)  # [total, h, d]

    qp, kp, vp = pack(query, lens_q), pack(key, lens_k), pack(value, lens_k)
    out, _ = F.flash_attn_unpadded(qp, kp, vp, cu_q, cu_k, scale=scale,
                                   causal=causal)
    out_np = unwrap(out)
    res = jnp.zeros((b, h, sq, d), out_np.dtype)
    for i in range(b):
        res = res.at[i, :, : lens_q[i]].set(
            out_np[cu_q[i]:cu_q[i + 1]].swapaxes(0, 1))
    return wrap(res)
