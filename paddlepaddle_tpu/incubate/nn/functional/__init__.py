"""Fused-op API surface (reference: python/paddle/incubate/nn/functional/ —
fused_rms_norm, fused_rotary_position_embedding, swiglu, fused_moe,
fused_multi_head_attention, variable_length_memory_efficient_attention...).

On TPU "fused" means: one jnp expression XLA fuses, or a Pallas kernel for
the attention path — the incubate names are thin aliases onto those."""

from __future__ import annotations

from typing import Optional

import jax
import jax.numpy as jnp

from ....core.dispatch import apply_op, unwrap, wrap
from ....nn import functional as F
from ....nn.functional import swiglu  # noqa: F401  (already fused)


def fused_rms_norm(x, norm_weight=None, norm_bias=None, epsilon=1e-6,
                   begin_norm_axis=-1, bias=None, residual=None,
                   quant_scale=-1, quant_round_type=0, quant_max_bound=0,
                   quant_min_bound=0):
    """Reference: incubate fused_rms_norm(x, w, b, eps, begin_norm_axis).
    Returns (out, residual_out) like the reference when residual given."""
    if residual is not None:
        x = x + residual
    if bias is not None:
        x = x + bias
    out = F.rms_norm(x, norm_weight, norm_bias, epsilon, begin_norm_axis)
    if residual is not None:
        return out, x
    return out


def fused_layer_norm(x, norm_weight=None, norm_bias=None, epsilon=1e-5,
                     begin_norm_axis=-1, bias=None, residual=None, **kw):
    if residual is not None:
        x = x + residual
    if bias is not None:
        x = x + bias
    out = F.layer_norm(x, list(x.shape[begin_norm_axis:]), norm_weight, norm_bias, epsilon)
    if residual is not None:
        return out, x
    return out


def fused_rotary_position_embedding(q, k=None, v=None, sin=None, cos=None,
                                    position_ids=None, use_neox_rotary_style=True,
                                    time_major=False, rotary_emb_base=10000.0):
    """Reference: fused_rope — BSHD q/k(/v passthrough), neox rotate-half.
    ``position_ids`` [b, s] selects per-token table rows (KV-cache decode)."""

    def rope(x, c, s):
        def f(xa, ca, sa, pos):
            seq = xa.shape[1]
            ca = ca.reshape(-1, ca.shape[-1])
            sa = sa.reshape(-1, sa.shape[-1])
            if pos is not None:
                ca = ca[pos.astype(jnp.int32)][:, :, None, :]   # [b, s, 1, dim]
                sa = sa[pos.astype(jnp.int32)][:, :, None, :]
            else:
                ca = ca[:seq].reshape(1, seq, 1, -1)
                sa = sa[:seq].reshape(1, seq, 1, -1)
            ca, sa = ca.astype(xa.dtype), sa.astype(xa.dtype)
            half = xa.shape[-1] // 2
            rot = jnp.concatenate([-xa[..., half:], xa[..., :half]], axis=-1)
            return xa * ca + rot * sa

        return apply_op(f, x, c, s, position_ids, op_name="fused_rope")

    outs = [rope(q, cos, sin)]
    outs.append(rope(k, cos, sin) if k is not None else None)
    outs.append(v)
    return tuple(outs)


def fused_multi_head_attention(x, qkv_weight, linear_weight, pre_layer_norm=False,
                               pre_ln_scale=None, pre_ln_bias=None, ln_scale=None,
                               ln_bias=None, pre_ln_epsilon=1e-5, qkv_bias=None,
                               linear_bias=None, cache_kv=None, attn_mask=None,
                               dropout_rate=0.0, attn_dropout_rate=0.0,
                               ln_epsilon=1e-5, training=True, mode="upscale_in_train",
                               ring_id=-1, add_residual=True, num_heads=None, name=None):
    """Condensed reference fused_attention: (pre-)LN -> qkv -> sdpa -> proj ->
    residual (+post-LN)."""
    residual = x
    if pre_layer_norm:
        x = F.layer_norm(x, [x.shape[-1]], pre_ln_scale, pre_ln_bias, pre_ln_epsilon)
    b, s, h = x.shape[0], x.shape[1], x.shape[-1]

    def qkv_fn(xa, w, bias_arr):
        # w: [3, n_heads, head_dim, h] (reference layout)
        out = jnp.einsum("bsh,kndh->bsknd", xa, w)
        if bias_arr is not None:
            out = out + bias_arr[None, None]
        return out

    qkv = apply_op(qkv_fn, x, qkv_weight, qkv_bias, op_name="fused_qkv")
    q, k, v = qkv[:, :, 0], qkv[:, :, 1], qkv[:, :, 2]
    ctx = F.scaled_dot_product_attention(q, k, v, attn_mask=attn_mask,
                                         dropout_p=attn_dropout_rate,
                                         training=training)
    ctx = ctx.reshape([b, s, -1])
    out = F.linear(ctx, linear_weight, linear_bias)
    if dropout_rate:
        out = F.dropout(out, dropout_rate, training=training)
    if add_residual:
        out = residual + out
    if not pre_layer_norm:
        out = F.layer_norm(out, [out.shape[-1]], ln_scale, ln_bias, ln_epsilon)
    return out


def fused_feedforward(x, linear1_weight, linear2_weight, linear1_bias=None,
                      linear2_bias=None, ln1_scale=None, ln1_bias=None,
                      ln2_scale=None, ln2_bias=None, dropout1_rate=0.5,
                      dropout2_rate=0.5, activation="relu", ln1_epsilon=1e-5,
                      ln2_epsilon=1e-5, pre_layer_norm=False, training=True,
                      mode="upscale_in_train", ring_id=-1, name=None):
    residual = x
    if pre_layer_norm:
        x = F.layer_norm(x, [x.shape[-1]], ln1_scale, ln1_bias, ln1_epsilon)
    h = F.linear(x, linear1_weight, linear1_bias)
    h = getattr(F, activation)(h)
    if dropout1_rate:
        h = F.dropout(h, dropout1_rate, training=training)
    out = F.linear(h, linear2_weight, linear2_bias)
    if dropout2_rate:
        out = F.dropout(out, dropout2_rate, training=training)
    out = residual + out
    if not pre_layer_norm:
        out = F.layer_norm(out, [out.shape[-1]], ln2_scale, ln2_bias, ln2_epsilon)
    return out


def fused_moe(x, gate_weight, ffn1_weight, ffn2_weight, ffn1_bias=None,
              ffn2_bias=None, quant_method="None", moe_topk=2, norm_topk_prob=True):
    """Reference: incubate fused_moe — top-k routed expert FFN bank."""
    from ....parallel.moe import MoELayer, NaiveGate

    b, s, d = x.shape[0], x.shape[1], x.shape[-1]

    def run(xa, gw, w1, w2, b1, b2):
        logits = xa.reshape(-1, d).astype(jnp.float32) @ gw.astype(jnp.float32)
        probs = jax.nn.softmax(logits, -1)
        topv, topi = jax.lax.top_k(probs, moe_topk)
        if norm_topk_prob:
            topv = topv / (topv.sum(-1, keepdims=True) + 1e-9)
        xf = xa.reshape(-1, d)
        out = jnp.zeros_like(xf)
        for j in range(moe_topk):
            sel = topi[:, j]
            w1_t = w1[sel]           # [T, d, hidden]
            w2_t = w2[sel]
            hmid = jnp.einsum("td,tdh->th", xf, w1_t)
            if b1 is not None:
                hmid = hmid + b1[sel]
            act = jax.nn.silu(hmid[..., : hmid.shape[-1] // 2]) * hmid[..., hmid.shape[-1] // 2:] \
                if hmid.shape[-1] % 2 == 0 else jax.nn.silu(hmid)
            o = jnp.einsum("th,thd->td", act, w2_t)
            if b2 is not None:
                o = o + b2[sel]
            out = out + o * topv[:, j:j + 1].astype(out.dtype)
        return out.reshape(b, s, d)

    return apply_op(run, x, gate_weight, ffn1_weight, ffn2_weight, ffn1_bias,
                    ffn2_bias, op_name="fused_moe")


def fused_linear(x, weight, bias=None, transpose_weight=False, name=None):
    def f(xa, w, ba):
        w = w.T if transpose_weight else w
        out = xa @ w
        return out + ba if ba is not None else out

    return apply_op(f, x, weight, bias, op_name="fused_linear")


def fused_bias_act(x, bias=None, act_method="gelu", **kw):
    def f(xa, ba):
        if ba is not None:
            xa = xa + ba
        return getattr(jax.nn, act_method if act_method != "geglu" else "gelu")(xa)

    return apply_op(f, x, bias, op_name="fused_bias_act")


def fused_dropout_add(x, y, p=0.5, training=True, mode="upscale_in_train", name=None):
    return F.dropout(x, p, training=training, mode=mode) + y


def fused_matmul_bias(x, y, bias=None, transpose_x=False, transpose_y=False,
                      name=None):
    """Reference incubate fused_matmul_bias — one XLA-fused matmul+add."""

    def f(a, b, bb):
        if transpose_x:
            a = jnp.swapaxes(a, -1, -2)
        if transpose_y:
            b = jnp.swapaxes(b, -1, -2)
        out = a @ b
        return out if bb is None else out + bb

    return apply_op(f, x, y, bias, op_name="fused_matmul_bias")


def fused_linear_activation(x, y, bias, trans_x=False, trans_y=False,
                            activation="gelu", name=None):
    out = fused_matmul_bias(x, y, bias, trans_x, trans_y)
    from ....nn import functional as F

    if activation in (None, "none", ""):
        return out
    return getattr(F, activation)(out)


def fused_bias_dropout_residual_layer_norm(
        x, residual, bias=None, ln_scale=None, ln_bias=None,
        dropout_rate=0.5, ln_epsilon=1e-5, training=True, mode=
        "upscale_in_train", name=None):
    """residual + dropout(x + bias), then LayerNorm (reference fused op)."""
    from ....nn import functional as F

    y = x if bias is None else x + bias
    y = F.dropout(y, p=dropout_rate, training=training, mode=mode)
    out = residual + y
    return F.layer_norm(out, out.shape[-1], weight=ln_scale, bias=ln_bias,
                        epsilon=ln_epsilon)


def fused_multi_transformer(x, ln_scales, ln_biases, qkv_weights, qkv_biases,
                            linear_weights, linear_biases, ffn_ln_scales,
                            ffn_ln_biases, ffn1_weights, ffn1_biases,
                            ffn2_weights, ffn2_biases, pre_layer_norm=True,
                            epsilon=1e-5, cache_kvs=None, time_step=None,
                            attn_mask=None, dropout_rate=0.0,
                            activation="gelu", training=False, name=None):
    """Stacked pre-LN transformer layers from raw weight lists (reference
    fused_multi_transformer inference op); one fused XLA program under jit."""
    from ....nn import functional as F

    out = x
    n_layers = len(qkv_weights)
    for i in range(n_layers):
        h = F.layer_norm(out, out.shape[-1], weight=ln_scales[i],
                         bias=ln_biases[i], epsilon=epsilon) \
            if pre_layer_norm else out
        attn = fused_multi_head_attention(
            h, qkv_weights[i], linear_weights[i],
            pre_layer_norm=False, qkv_bias=qkv_biases[i],
            linear_bias=linear_biases[i], attn_mask=attn_mask,
            dropout_rate=dropout_rate, training=training)
        out = out + attn
        h2 = F.layer_norm(out, out.shape[-1], weight=ffn_ln_scales[i],
                          bias=ffn_ln_biases[i], epsilon=epsilon) \
            if pre_layer_norm else out
        ffn = fused_feedforward(
            h2, ffn1_weights[i], ffn2_weights[i], linear1_bias=ffn1_biases[i],
            linear2_bias=ffn2_biases[i], activation=activation,
            pre_layer_norm=False, training=training)
        out = out + ffn
    return out


def variable_length_memory_efficient_attention(query, key, value, seq_lens,
                                               kv_seq_lens, mask=None,
                                               scale=None, causal=False,
                                               pre_cache_length=0, name=None):
    """Varlen attention at the incubate API (reference memory-efficient
    kernel): lowered onto the segment-masked flash path. query/key/value are
    [b, heads, s, d]; seq_lens give per-batch valid lengths."""
    import numpy as _np

    from ....nn import functional as F

    q = unwrap(query)
    b, h, sq, d = q.shape
    lens_q = _np.asarray(unwrap(seq_lens)).reshape(-1)
    lens_k = _np.asarray(unwrap(kv_seq_lens)).reshape(-1)
    cu_q = _np.concatenate([[0], _np.cumsum(lens_q)]).astype(_np.int32)
    cu_k = _np.concatenate([[0], _np.cumsum(lens_k)]).astype(_np.int32)

    def pack(t, lens):
        a = unwrap(t)
        rows = [a[i, :, : lens[i]].swapaxes(0, 1) for i in range(b)]
        return jnp.concatenate(rows, axis=0)  # [total, h, d]

    qp, kp, vp = pack(query, lens_q), pack(key, lens_k), pack(value, lens_k)
    out, _ = F.flash_attn_unpadded(qp, kp, vp, cu_q, cu_k, scale=scale,
                                   causal=causal)
    out_np = unwrap(out)
    res = jnp.zeros((b, h, sq, d), out_np.dtype)
    for i in range(b):
        res = res.at[i, :, : lens_q[i]].set(
            out_np[cu_q[i]:cu_q[i + 1]].swapaxes(0, 1))
    return wrap(res)


def blha_get_max_len(seq_lens_encoder, seq_lens_decoder, batch_size,
                     name=None):
    """Max encoder/decoder lengths this step (reference
    incubate/nn/functional/blha_get_max_len.py:26) — the scheduling scalars
    fed to block_multihead_attention."""
    enc = jnp.max(unwrap(seq_lens_encoder).astype(jnp.int32).reshape(-1))
    dec = jnp.max(unwrap(seq_lens_decoder).astype(jnp.int32).reshape(-1))
    return wrap(enc.reshape(1)), wrap(dec.reshape(1))


def _reject_quant(name, **kw):
    bad = [k for k, v in kw.items() if v is not None and v is not False]
    if bad:
        raise NotImplementedError(
            f"{name}: int8/quantized serving args {bad} are CUDA-specific "
            "in the reference; the TPU path serves bf16 (use "
            "paddlepaddle_tpu.quantization for PTQ of weights)")


def _apply_rope_pair(q, k, cos, sin, neox):
    """Rotate q,k by per-position cos/sin [..., D/2]; neox rotates the two
    halves, the default rotates adjacent pairs (reference mmha/blha
    use_neox_rotary_style switch)."""
    D = q.shape[-1]
    if neox:
        def rot(x):
            x1, x2 = x[..., :D // 2], x[..., D // 2:]
            return jnp.concatenate(
                [x1 * cos - x2 * sin, x2 * cos + x1 * sin], -1)
    else:
        def rot(x):
            x1, x2 = x[..., 0::2], x[..., 1::2]
            out = jnp.stack(
                [x1 * cos - x2 * sin, x2 * cos + x1 * sin], -1)
            return out.reshape(x.shape)
    return rot(q), rot(k)


def masked_multihead_attention(x, cache_kv=None, bias=None, src_mask=None,
                               cum_offsets=None, sequence_lengths=None,
                               rotary_tensor=None, beam_cache_offset=None,
                               qkv_out_scale=None, out_shift=None,
                               out_smooth=None, seq_len=1, rotary_emb_dims=0,
                               use_neox_rotary_style=False,
                               compute_dtype="default", out_scale=-1,
                               quant_round_type=1, quant_max_bound=127.0,
                               quant_min_bound=-127.0):
    """Fused single-token decode attention (reference
    incubate/nn/functional/masked_multihead_attention.py:74, MMHA kernel
    lineage): x is one new token's qkv per sequence
    [bsz, 3*H*D]; cache_kv [2, bsz, H, max_seq, D] is updated at the
    per-sequence write position and attention runs over positions
    [0, pos]. Returns (out [bsz, H*D], cache_kv_out) — the cache is
    returned (XLA arrays are immutable; the reference mutates in place).

    The write position is sequence_lengths[:, 0] when given, else
    ``src_mask.shape[-1] - 1``, else ``seq_len - 1`` (the kernel's
    timestep resolution order). rotary_tensor follows the reference
    kernel's read layout (masked_multihead_attention_kernel.cu
    rotary load): the first bsz*D floats are the CURRENT position's
    full-D cos per batch, the next bsz*D the sin — the kernel never
    indexes it by timestep."""
    _reject_quant("masked_multihead_attention",
                  qkv_out_scale=qkv_out_scale, out_shift=out_shift,
                  out_smooth=out_smooth,
                  quant=None if out_scale == -1 else out_scale)
    if beam_cache_offset is not None:
        raise NotImplementedError(
            "masked_multihead_attention: beam search decode "
            "(beam_cache_offset) is not in the TPU-v1 surface")
    if cache_kv is None:
        raise ValueError("masked_multihead_attention requires cache_kv")

    xv = unwrap(x)
    ck = unwrap(cache_kv)
    _, bsz, H, max_seq, D = ck.shape
    qkv = xv.reshape(bsz, 3, H, D).astype(jnp.float32)
    if bias is not None:
        qkv = qkv + unwrap(bias).reshape(1, 3, H, D).astype(jnp.float32)
    q, k, v = qkv[:, 0], qkv[:, 1], qkv[:, 2]        # [bsz, H, D]

    if sequence_lengths is not None:
        pos = unwrap(sequence_lengths).reshape(-1).astype(jnp.int32)
    elif src_mask is not None:
        pos = jnp.full((bsz,), unwrap(src_mask).shape[-1] - 1, jnp.int32)
    else:
        pos = jnp.full((bsz,), seq_len - 1, jnp.int32)

    if rotary_tensor is not None and rotary_emb_dims:
        flat = unwrap(rotary_tensor).astype(jnp.float32).reshape(-1)
        cos = flat[:bsz * D].reshape(bsz, 1, D)          # full-D, per batch
        sin = flat[bsz * D:2 * bsz * D].reshape(bsz, 1, D)
        if use_neox_rotary_style:
            c, s = cos[..., :D // 2], sin[..., :D // 2]
        else:
            c, s = cos[..., 0::2], sin[..., 0::2]
        q, k = _apply_rope_pair(q, k, c, s, use_neox_rotary_style)

    ib = jnp.arange(bsz)
    ck = ck.astype(jnp.float32)
    ck = ck.at[0, ib, :, pos].set(k).at[1, ib, :, pos].set(v)

    scale = 1.0 / jnp.sqrt(jnp.asarray(D, jnp.float32))
    logits = jnp.einsum("bhd,bhsd->bhs", q, ck[0]) * scale
    span = jnp.arange(max_seq)[None, None, :]
    logits = jnp.where(span <= pos[:, None, None], logits, -1e30)
    if src_mask is not None:
        sm = unwrap(src_mask).astype(jnp.float32).reshape(bsz, 1, -1)
        logits = logits.at[:, :, :sm.shape[-1]].add(sm)
    probs = jax.nn.softmax(logits, axis=-1)
    out = jnp.einsum("bhs,bhsd->bhd", probs, ck[1])
    dt = xv.dtype
    return (wrap(out.reshape(bsz, H * D).astype(dt)),
            wrap(ck.astype(unwrap(cache_kv).dtype)))


def block_multihead_attention(
        qkv, key_cache, value_cache, seq_lens_encoder, seq_lens_decoder,
        seq_lens_this_time, padding_offsets, cum_offsets, cu_seqlens_q,
        cu_seqlens_k, block_tables, pre_key_cache=None, pre_value_cache=None,
        cache_k_quant_scales=None, cache_v_quant_scales=None,
        cache_k_dequant_scales=None, cache_v_dequant_scales=None,
        qkv_out_scale=None, qkv_bias=None, out_shift=None, out_smooth=None,
        max_enc_len_this_time=None, max_dec_len_this_time=None,
        rope_emb=None, mask=None, tgt_mask=None, max_seq_len=-1,
        block_size=64, use_neox_style=False, use_dynamic_cachekv_quant=False,
        quant_round_type=1, quant_max_bound=127.0, quant_min_bound=-127.0,
        out_scale=-1, compute_dtype="default", rope_theta=10000.0):
    """Paged-KV batched attention (reference
    incubate/nn/functional/block_multihead_attention.py:33, kernel
    fusion/gpu/block_multi_head_attention_kernel.cu): one call serves a
    mixed batch where each sequence is either PREFILLING
    (seq_lens_encoder[b] > 0: causal attention over its own packed
    tokens) or DECODING (seq_lens_decoder[b] = past length, one new
    token attending to the paged cache). qkv is varlen-packed
    [token_num, (H + 2*kv_H) * D]; the caches are paged
    [max_block_num, kv_H, block_size, D] indexed through block_tables.
    Returns (out [token_num, H*D], qkv, key_cache, value_cache) — caches
    returned, not mutated (XLA immutability).

    Eager-only: per-sequence lengths are data, so this op shapes on host
    values (the compiled serving path is inference/decode_engine.py,
    which keeps one static compiled decode step). Quant/pre-cache args
    raise; GQA inferred from key_cache's head dim."""
    import numpy as _np

    _reject_quant("block_multihead_attention",
                  cache_k_quant_scales=cache_k_quant_scales,
                  cache_v_quant_scales=cache_v_quant_scales,
                  cache_k_dequant_scales=cache_k_dequant_scales,
                  cache_v_dequant_scales=cache_v_dequant_scales,
                  qkv_out_scale=qkv_out_scale, out_shift=out_shift,
                  out_smooth=out_smooth,
                  dynamic_quant=use_dynamic_cachekv_quant or None,
                  quant=None if out_scale == -1 else out_scale)
    if pre_key_cache is not None or pre_value_cache is not None:
        raise NotImplementedError(
            "block_multihead_attention: pre-cache (system prompt cache) "
            "is not in the TPU-v1 surface")

    qkv_v = unwrap(qkv)
    kc = unwrap(key_cache).astype(jnp.float32)
    vc = unwrap(value_cache).astype(jnp.float32)
    _, kv_H, bs_, D = kc.shape
    if bs_ != block_size:
        if block_size != 64:                  # explicit AND contradictory
            raise ValueError(
                f"block_multihead_attention: block_size={block_size} "
                f"contradicts the cache page dimension {bs_}")
        block_size = bs_                      # default: trust the cache
    H = qkv_v.shape[1] // D - 2 * kv_H
    bsz = unwrap(block_tables).shape[0]
    enc = _np.asarray(unwrap(seq_lens_encoder)).reshape(-1).astype(int)
    dec = _np.asarray(unwrap(seq_lens_decoder)).reshape(-1).astype(int)
    this = _np.asarray(unwrap(seq_lens_this_time)).reshape(-1).astype(int)
    cu_q = _np.asarray(unwrap(cu_seqlens_q)).reshape(-1).astype(int)
    btab = unwrap(block_tables)
    packed = qkv_v.astype(jnp.float32)
    if qkv_bias is not None:
        packed = packed + unwrap(qkv_bias).astype(jnp.float32)[None, :]

    rope = None if rope_emb is None else unwrap(rope_emb).astype(jnp.float32)
    if rope is not None:
        # reference layout [2, bsz, max_seq, 1, D/2] (the py docstring /
        # decoder RoPE kernel); the transposed [2, bsz, 1, max_seq, D/2]
        # is normalized too — both reduce to [2, bsz, S, D/2]
        if rope.ndim == 5:
            rope = jnp.squeeze(rope, axis=2 if rope.shape[2] == 1 else 3)
        if rope.ndim != 4 or rope.shape[0] != 2 or rope.shape[-1] != D // 2:
            raise ValueError(
                f"block_multihead_attention: rope_emb shape "
                f"{unwrap(rope_emb).shape} is not [2, bsz, max_seq, 1, "
                f"D/2] (D={D})")
    scale = 1.0 / float(_np.sqrt(D))
    group = H // kv_H
    out = jnp.zeros((qkv_v.shape[0], H * D), jnp.float32)

    # pass 1: rope + collect every sequence's page writes for ONE scatter
    # (a per-sequence .at[].set would copy the whole cache bsz times)
    qs, ks, vs = {}, [], []
    w_blk, w_off = [], []
    for b in range(bsz):
        n = int(this[b])
        if n == 0:
            continue
        past = int(dec[b])
        rows = packed[cu_q[b]:cu_q[b] + n]
        q = rows[:, :H * D].reshape(n, H, D)
        k = rows[:, H * D:(H + kv_H) * D].reshape(n, kv_H, D)
        v = rows[:, (H + kv_H) * D:].reshape(n, kv_H, D)
        positions = past + _np.arange(n)
        if rope is not None:
            cs = rope[0, b, positions]                    # [n, D/2]
            sn = rope[1, b, positions]
            q, k = _apply_rope_pair(q, k, cs[:, None, :], sn[:, None, :],
                                    use_neox_style)
        qs[b] = q
        ks.append(k)
        vs.append(v)
        w_blk.append(btab[b, positions // block_size])
        w_off.append(positions % block_size)
    if ks:
        blk = jnp.asarray(_np.concatenate(w_blk), jnp.int32)
        off = jnp.asarray(_np.concatenate(w_off), jnp.int32)
        kc = kc.at[blk, :, off].set(jnp.concatenate(ks, 0))
        vc = vc.at[blk, :, off].set(jnp.concatenate(vs, 0))

    # pass 2: attention against the updated pages
    for b in range(bsz):
        n = int(this[b])
        if n == 0:
            continue
        past = int(dec[b])
        positions = past + _np.arange(n)
        L = past + n
        nblk = (L + block_size - 1) // block_size
        blocks = jnp.asarray(btab[b, :nblk], jnp.int32)
        K = kc[blocks].transpose(1, 0, 2, 3).reshape(kv_H, -1, D)[:, :L]
        V = vc[blocks].transpose(1, 0, 2, 3).reshape(kv_H, -1, D)[:, :L]

        qg = qs[b].reshape(n, kv_H, group, D)
        logits = jnp.einsum("nkgd,ksd->nkgs", qg, K) * scale
        causal = jnp.asarray(positions)[:, None] >= jnp.arange(L)[None, :]
        logits = jnp.where(causal[:, None, None, :], logits, -1e30)
        if past == 0 and mask is not None:
            m = unwrap(mask).astype(jnp.float32)[b, 0][:n, :L]
            logits = logits + m[:, None, None, :]
        elif past > 0 and tgt_mask is not None:
            m = unwrap(tgt_mask).astype(jnp.float32)[b, 0][:, :L]
            logits = logits + m[:, None, None, :]
        probs = jax.nn.softmax(logits, axis=-1)
        o = jnp.einsum("nkgs,ksd->nkgd", probs, V).reshape(n, H * D)
        out = out.at[cu_q[b]:cu_q[b] + n].set(o)

    dt = qkv_v.dtype
    return (wrap(out.astype(dt)), qkv,
            wrap(kc.astype(unwrap(key_cache).dtype)),
            wrap(vc.astype(unwrap(value_cache).dtype)))
