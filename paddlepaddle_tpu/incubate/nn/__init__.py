"""paddle.incubate.nn (reference: python/paddle/incubate/nn/)."""

from . import functional  # noqa: F401


# fused layer wrappers (reference python/paddle/incubate/nn/layer/...):
# on TPU "fused" is XLA fusion of the plain formulation, so these layers
# carry the reference constructor surfaces over the stock nn layers.

from ...nn import functional as _F
from ...nn.layer import Layer as _Layer


class FusedLinear(_Layer):
    def __init__(self, in_features, out_features, weight_attr=None,
                 bias_attr=None, transpose_weight=False, name=None):
        super().__init__()
        self.transpose_weight = transpose_weight
        shape = ([out_features, in_features] if transpose_weight
                 else [in_features, out_features])
        self.weight = self.create_parameter(shape, attr=weight_attr)
        self.bias = self.create_parameter([out_features], attr=bias_attr,
                                          is_bias=True)

    def forward(self, x):
        from .functional import fused_matmul_bias

        return fused_matmul_bias(x, self.weight, self.bias,
                                 transpose_y=self.transpose_weight)


class FusedDropoutAdd(_Layer):
    def __init__(self, p=0.5, mode="upscale_in_train", name=None):
        super().__init__()
        self.p, self.mode = p, mode

    def forward(self, x, y):
        from .functional import fused_dropout_add

        return fused_dropout_add(x, y, p=self.p, training=self.training,
                                 mode=self.mode)


class FusedBiasDropoutResidualLayerNorm(_Layer):
    def __init__(self, embed_dim, dropout_rate=0.5, weight_attr=None,
                 bias_attr=None, epsilon=1e-5, name=None):
        super().__init__()
        self.dropout_rate = dropout_rate
        self.epsilon = epsilon
        self.linear_bias = self.create_parameter([embed_dim], is_bias=True)
        self.ln_scale = self.create_parameter(
            [embed_dim], default_initializer=None)
        self.ln_bias = self.create_parameter([embed_dim], is_bias=True)

    def forward(self, x, residual):
        from .functional import fused_bias_dropout_residual_layer_norm

        return fused_bias_dropout_residual_layer_norm(
            x, residual, self.linear_bias, self.ln_scale, self.ln_bias,
            dropout_rate=self.dropout_rate, ln_epsilon=self.epsilon,
            training=self.training)


class FusedMultiHeadAttention(_Layer):
    """Reference fused_attention layer: stock MultiHeadAttention + pre-LN
    and residual, fused by XLA."""

    def __init__(self, embed_dim, num_heads, dropout_rate=0.5,
                 attn_dropout_rate=0.5, kdim=None, vdim=None,
                 normalize_before=False, need_weights=False, qkv_weight_attr=None,
                 qkv_bias_attr=None, linear_weight_attr=None,
                 linear_bias_attr=None, pre_ln_scale_attr=None,
                 pre_ln_bias_attr=None, ln_scale_attr=None, ln_bias_attr=None,
                 epsilon=1e-5, nranks=1, ring_id=-1, name=None):
        super().__init__()
        from ...nn.transformer import MultiHeadAttention
        from ...nn.norm import LayerNorm

        self.normalize_before = normalize_before
        self.ln = LayerNorm(embed_dim, epsilon=epsilon)
        self.attn = MultiHeadAttention(embed_dim, num_heads,
                                       dropout=attn_dropout_rate)
        self.dropout_rate = dropout_rate

    def forward(self, x, attn_mask=None, cache=None):
        residual = x
        if self.normalize_before:
            x = self.ln(x)
        out = self.attn(x, x, x, attn_mask=attn_mask)
        out = _F.dropout(out, p=self.dropout_rate, training=self.training)
        # Tensor-on-the-left: a numpy residual would otherwise consume the
        # Tensor via __array__ and return a bare ndarray
        out = out + residual
        if not self.normalize_before:
            out = self.ln(out)
        return out


class FusedFeedForward(_Layer):
    def __init__(self, d_model, dim_feedforward, dropout_rate=0.1,
                 epsilon=1e-5, activation="relu", act_dropout_rate=None,
                 normalize_before=False, linear1_weight_attr=None,
                 linear1_bias_attr=None, linear2_weight_attr=None,
                 linear2_bias_attr=None, ln1_scale_attr=None,
                 ln1_bias_attr=None, ln2_scale_attr=None, ln2_bias_attr=None,
                 nranks=1, ring_id=-1, name=None):
        super().__init__()
        from ...nn.common import Linear
        from ...nn.norm import LayerNorm

        self.normalize_before = normalize_before
        self.activation = activation
        self.dropout_rate = dropout_rate
        self.act_dropout = (act_dropout_rate if act_dropout_rate is not None
                            else dropout_rate)
        self.linear1 = Linear(d_model, dim_feedforward)
        self.linear2 = Linear(dim_feedforward, d_model)
        self.ln = LayerNorm(d_model, epsilon=epsilon)

    def forward(self, x):
        residual = x
        if self.normalize_before:
            x = self.ln(x)
        h = getattr(_F, self.activation)(self.linear1(x))
        h = _F.dropout(h, p=self.act_dropout, training=self.training)
        h = self.linear2(h)
        h = _F.dropout(h, p=self.dropout_rate, training=self.training)
        out = h + residual  # Tensor-on-the-left (see FusedMultiHeadAttention)
        if not self.normalize_before:
            out = self.ln(out)
        return out


class FusedTransformerEncoderLayer(_Layer):
    def __init__(self, d_model, nhead, dim_feedforward, dropout_rate=0.1,
                 activation="relu", attn_dropout_rate=None,
                 act_dropout_rate=None, normalize_before=False,
                 weight_attr=None, bias_attr=None, name=None):
        super().__init__()
        self.attn = FusedMultiHeadAttention(
            d_model, nhead, dropout_rate=dropout_rate,
            attn_dropout_rate=(attn_dropout_rate if attn_dropout_rate
                               is not None else dropout_rate),
            normalize_before=normalize_before)
        self.ffn = FusedFeedForward(
            d_model, dim_feedforward, dropout_rate=dropout_rate,
            activation=activation, act_dropout_rate=act_dropout_rate,
            normalize_before=normalize_before)

    def forward(self, src, src_mask=None, cache=None):
        return self.ffn(self.attn(src, attn_mask=src_mask))


class FusedMultiTransformer(_Layer):
    """Stacked fused transformer layers (reference fused_multi_transformer
    inference layer)."""

    def __init__(self, embed_dim, num_heads, dim_feedforward,
                 dropout_rate=0.0, activation="gelu", normalize_before=True,
                 num_layers=1, nranks=1, ring_id=-1, name=None, **kw):
        super().__init__()
        from ...nn.container import LayerList

        self.layers = LayerList([
            FusedTransformerEncoderLayer(
                embed_dim, num_heads, dim_feedforward,
                dropout_rate=dropout_rate, activation=activation,
                normalize_before=normalize_before)
            for _ in range(num_layers)])

    def forward(self, src, attn_mask=None, caches=None, time_step=None):
        out = src
        for layer in self.layers:
            out = layer(out, src_mask=attn_mask)
        return out
