"""reference: python/paddle/incubate/xpu/resnet_block.py — a Kunlun-XPU
fused resnet basic block. XLA performs this fusion from the plain layer
composition on TPU, so the fused op has no role here."""

__all__ = ["resnet_basic_block", "ResNetBasicBlock"]


def resnet_basic_block(*args, **kwargs):
    raise NotImplementedError(
        "resnet_basic_block is a Kunlun-XPU fused kernel; on TPU compose "
        "nn.Conv2D/BatchNorm2D/ReLU directly — XLA fuses the block "
        "(see vision/models/resnet.py BasicBlock)")


class ResNetBasicBlock:
    def __init__(self, *args, **kwargs):
        resnet_basic_block()
