"""paddle.incubate.xpu (reference: python/paddle/incubate/xpu/) — the XPU
(Kunlun) fused-kernel surface. Not applicable on this backend: the TPU
equivalents of these fusions are XLA's own (conv+bn+relu fuse in the
compiler); the names raise with that story."""

from . import resnet_block  # noqa: F401

__all__ = []
