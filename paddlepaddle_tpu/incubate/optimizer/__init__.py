"""paddle.incubate.optimizer (reference: python/paddle/incubate/optimizer/ —
LookAhead, ModelAverage, the functional LBFGS re-export)."""

from __future__ import annotations

import jax.numpy as jnp

from ...optimizer import LBFGS  # noqa: F401  (reference re-exports it here)
from ...optimizer.optimizer import Optimizer
from . import functional  # noqa: F401
from .functional import minimize_bfgs, minimize_lbfgs  # noqa: F401


class LookAhead(Optimizer):
    """Lookahead wrapper (reference lookahead.py): every k steps the slow
    weights move alpha toward the fast (inner-optimizer) weights."""

    def __init__(self, inner_optimizer, alpha=0.5, k=5, name=None):
        self.inner = inner_optimizer
        self.alpha = alpha
        self.k = int(k)
        self._step_num = 0
        self._slow = {}

    @property
    def _parameter_list(self):
        return self.inner._parameter_list

    def get_lr(self):
        return self.inner.get_lr()

    def step(self):
        self.inner.step()
        self._step_num += 1
        if self._step_num % self.k:
            return
        for p in self.inner._parameter_list:
            pid = id(p)
            if pid not in self._slow:
                self._slow[pid] = p._data
                continue
            slow = self._slow[pid] + self.alpha * (p._data - self._slow[pid])
            self._slow[pid] = slow
            p._replace_data(slow)

    def clear_grad(self):
        self.inner.clear_grad()

    def state_dict(self):
        return {"inner": self.inner.state_dict(), "step": self._step_num}

    def set_state_dict(self, state):
        self.inner.set_state_dict(state.get("inner", {}))
        self._step_num = state.get("step", 0)


class ModelAverage(Optimizer):
    """Running average of parameters (reference model_average.py): apply()
    swaps in the averaged weights, restore() swaps back."""

    def __init__(self, average_window_rate=0.15, parameters=None,
                 min_average_window=10000, max_average_window=10000,
                 name=None):
        super().__init__(learning_rate=0.0, parameters=parameters)
        self._sums = {}
        self._counts = {}
        self._backup = {}

    def step(self):
        for p in self._parameter_list:
            pid = id(p)
            self._sums[pid] = self._sums.get(pid, 0.0) + p._data
            self._counts[pid] = self._counts.get(pid, 0) + 1

    def apply(self, executor=None, need_restore=True):
        for p in self._parameter_list:
            pid = id(p)
            if self._counts.get(pid):
                self._backup[pid] = p._data
                p._replace_data(self._sums[pid] / self._counts[pid])

    def restore(self, executor=None):
        for p in self._parameter_list:
            pid = id(p)
            if pid in self._backup:
                p._replace_data(self._backup.pop(pid))

    def minimize(self, loss):
        self.step()
