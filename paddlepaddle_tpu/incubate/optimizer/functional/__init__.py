"""paddle.incubate.optimizer.functional (reference:
python/paddle/incubate/optimizer/functional/{bfgs,lbfgs}.py): functional
quasi-Newton minimizers over a differentiable ``objective_func(x) ->
scalar``. Gradients come from the framework's autograd; the strong-Wolfe
line search follows Nocedal & Wright's bracket+zoom, as upstream."""

from __future__ import annotations

import numpy as np

__all__ = ["minimize_bfgs", "minimize_lbfgs"]


def _value_and_grad(objective_func, x_np, dtype, counter):
    import paddlepaddle_tpu as paddle

    t = paddle.to_tensor(x_np.astype(dtype), stop_gradient=False)
    y = objective_func(t)
    counter[0] += 1
    (g,) = paddle.grad(y, [t])
    return float(y.numpy()), np.asarray(g.numpy(), np.float64)


def _strong_wolfe(fg, x, d, f0, g0, a1, max_iters, c1=1e-4, c2=0.9):
    """Bracket + zoom line search returning a step satisfying the strong
    Wolfe conditions (or the best point found)."""
    d0 = float(g0 @ d)
    if d0 >= 0:                                 # not a descent direction
        return 0.0, f0, g0

    def phi(a):
        f, g = fg(x + a * d)
        return f, g, float(g @ d)

    def zoom(lo, f_lo, hi):
        best = (lo, f_lo)
        for _ in range(max_iters):
            a = 0.5 * (lo + hi)
            f, g, dd = phi(a)
            if f > f0 + c1 * a * d0 or f >= f_lo:
                hi = a
            else:
                if abs(dd) <= -c2 * d0:
                    return a, f, g
                if dd * (hi - lo) >= 0:
                    hi = lo
                lo, f_lo = a, f
                best = (a, f)
            if abs(hi - lo) < 1e-16:
                break
        a = best[0]
        f, g, _ = phi(a)
        return a, f, g

    a_prev, f_prev, g_prev = 0.0, f0, g0
    a = a1
    for it in range(max_iters):
        f, g, dd = phi(a)
        if f > f0 + c1 * a * d0 or (it > 0 and f >= f_prev):
            return zoom(a_prev, f_prev, a)
        if abs(dd) <= -c2 * d0:
            return a, f, g
        if dd >= 0:
            return zoom(a, f, a_prev)
        a_prev, f_prev, g_prev = a, f, g
        a = min(2 * a, 1e10)
    return a_prev, f_prev, g_prev


def _minimize(objective_func, initial_position, max_iters, tolerance_grad,
              tolerance_change, H0, line_search_fn, max_line_search_iters,
              initial_step_length, dtype, history_size=None):
    if line_search_fn != "strong_wolfe":
        raise NotImplementedError(
            f"line_search_fn {line_search_fn!r}: only 'strong_wolfe' is "
            "supported (as in the reference)")
    import paddlepaddle_tpu as paddle

    counter = [0]

    def fg(x_np):
        return _value_and_grad(objective_func, x_np, dtype, counter)

    x = np.asarray(
        initial_position.numpy()
        if hasattr(initial_position, "numpy") else initial_position,
        np.float64).reshape(-1)
    n = x.size
    f, g = fg(x)
    if H0 is not None:
        H = np.asarray(H0.numpy() if hasattr(H0, "numpy") else H0,
                       np.float64)
    else:
        # bfgs needs a live estimate; lbfgs centers its two-loop on the
        # gamma scaling unless an explicit H0 is given
        H = np.eye(n) if history_size is None else None
    sk_yk = []                                   # lbfgs history
    converged = False

    for _ in range(max_iters):
        if np.max(np.abs(g)) <= tolerance_grad:
            converged = True
            break
        if history_size is None:
            d = -(H @ g)
        else:
            # two-loop recursion; an explicit H0 replaces the standard
            # gamma * I center scaling
            q = g.copy()
            alphas = []
            for s, y, rho in reversed(sk_yk):
                a = rho * (s @ q)
                alphas.append(a)
                q -= a * y
            if H is not None:
                q = H @ q
            elif sk_yk:
                s, y, _ = sk_yk[-1]
                q *= (s @ y) / max(y @ y, 1e-30)
            for (s, y, rho), a in zip(sk_yk, reversed(alphas)):
                q += (a - rho * (y @ q)) * s
            d = -q
        a, f_new, g_new = _strong_wolfe(fg, x, d, f, g,
                                        initial_step_length,
                                        max_line_search_iters)
        s = a * d
        if np.max(np.abs(s)) <= tolerance_change or a == 0.0:
            converged = np.max(np.abs(g_new)) <= tolerance_grad
            x, f, g = x + s, f_new, g_new
            break
        y = g_new - g
        sy = s @ y
        if sy > 1e-10:
            if history_size is None:
                rho = 1.0 / sy
                V = np.eye(n) - rho * np.outer(s, y)
                H = V @ H @ V.T + rho * np.outer(s, s)
            else:
                sk_yk.append((s, y, 1.0 / sy))
                if len(sk_yk) > history_size:
                    sk_yk.pop(0)
        x, f, g = x + s, f_new, g_new

    shape = tuple(np.asarray(
        initial_position.numpy() if hasattr(initial_position, "numpy")
        else initial_position).shape)
    to_t = lambda v: paddle.to_tensor(np.asarray(v, dtype))  # noqa: E731
    results = (bool(converged), to_t(counter[0]).astype("int64"),
               to_t(x.reshape(shape)), to_t(f), to_t(g.reshape(shape)))
    if history_size is None:
        results = results + (to_t(H),)
    return results


def minimize_bfgs(objective_func, initial_position, max_iters=50,
                  tolerance_grad=1e-7, tolerance_change=1e-9,
                  initial_inverse_hessian_estimate=None,
                  line_search_fn="strong_wolfe", max_line_search_iters=50,
                  initial_step_length=1.0, dtype="float32", name=None):
    """Reference incubate/optimizer/functional/bfgs.py:36. Returns
    (is_converge, num_func_calls, position, objective_value,
    objective_gradient, inverse_hessian_estimate)."""
    return _minimize(objective_func, initial_position, max_iters,
                     tolerance_grad, tolerance_change,
                     initial_inverse_hessian_estimate, line_search_fn,
                     max_line_search_iters, initial_step_length, dtype)


def minimize_lbfgs(objective_func, initial_position, history_size=100,
                   max_iters=50, tolerance_grad=1e-7, tolerance_change=1e-9,
                   initial_inverse_hessian_estimate=None,
                   line_search_fn="strong_wolfe", max_line_search_iters=50,
                   initial_step_length=1.0, dtype="float32", name=None):
    """Reference incubate/optimizer/functional/lbfgs.py. Returns
    (is_converge, num_func_calls, position, objective_value,
    objective_gradient)."""
    return _minimize(objective_func, initial_position, max_iters,
                     tolerance_grad, tolerance_change,
                     initial_inverse_hessian_estimate, line_search_fn,
                     max_line_search_iters, initial_step_length, dtype,
                     history_size=history_size)
