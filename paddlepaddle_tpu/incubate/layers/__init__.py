"""paddle.incubate.layers (reference: python/paddle/incubate/layers/nn.py —
legacy static-graph helper ops; its public ``__all__`` is empty). The
generic tensor helpers are implemented; the PS-stack ops (sparse pulls,
TDM tree sampling, pyramid hash) stay out of TPU-v1 scope with the rest
of the parameter-server runtime (SURVEY §2.10) and raise by name."""

from __future__ import annotations

import jax
import jax.numpy as jnp

from ...core.dispatch import apply_op
from ...core import random as prandom

__all__ = []


def partial_concat(input, start_index=0, length=-1):
    """Concat the [start:start+length] column slice of every input
    (reference incubate/layers/nn.py partial_concat)."""

    def f(*xs):
        outs = []
        for x in xs:
            end = x.shape[1] if length < 0 else start_index + length
            outs.append(x[:, start_index:end])
        return jnp.concatenate(outs, axis=1)

    return apply_op(f, *input, op_name="partial_concat")


def partial_sum(input, start_index=0, length=-1):
    """Sum the same column slice across inputs (reference partial_sum)."""

    def f(*xs):
        end = xs[0].shape[1] if length < 0 else start_index + length
        acc = xs[0][:, start_index:end]
        for x in xs[1:]:
            acc = acc + x[:, start_index:end]
        return acc

    return apply_op(f, *input, op_name="partial_sum")


def shuffle_batch(x, seed=None):
    """Random row permutation (reference shuffle_batch)."""

    def f(v):
        key = jax.random.PRNGKey(seed) if seed is not None \
            else prandom.next_key()
        return v[jax.random.permutation(key, v.shape[0])]

    return apply_op(f, x, op_name="shuffle_batch")


def batch_fc(input, param_size, param_attr, bias_size, bias_attr, act=None):
    """Per-slot batched FC (reference batch_fc): input [slot, B, in],
    weight [slot, in, out], bias [slot, 1, out]."""
    import paddlepaddle_tpu as paddle

    w = paddle.create_parameter(shape=param_size, dtype="float32",
                                attr=param_attr)
    b = paddle.create_parameter(shape=bias_size, dtype="float32",
                                attr=bias_attr)

    def f(x, w, b):
        out = jnp.einsum("sbi,sio->sbo", x, w) + b
        return jax.nn.relu(out) if act == "relu" else out

    return apply_op(f, input, w, b, op_name="batch_fc")


def fused_bn_add_act(x, y, momentum=0.9, epsilon=1e-5, **kw):
    """batch_norm(x) + y |> relu (reference fused_bn_add_act; XLA fuses
    the chain on TPU, so this is the composition, not a kernel)."""

    def f(xb, yb):
        mean = xb.mean((0, 2, 3), keepdims=True)
        var = xb.var((0, 2, 3), keepdims=True)
        norm = (xb - mean) * jax.lax.rsqrt(var + epsilon)
        return jax.nn.relu(norm + yb)

    return apply_op(f, x, y, op_name="fused_bn_add_act")


def pow2_decay_with_linear_warmup(warmup_steps, total_steps, base_lr, end_lr,
                                  dtype="float32", name=None):
    """LR schedule value factory (reference pow2_decay_with_linear_warmup):
    linear warmup then (1 - t)^2 decay to end_lr. Returns a step->lr
    callable (the reference builds a global-step op graph)."""
    if total_steps <= warmup_steps:
        raise ValueError("total_steps must exceed warmup_steps")

    def lr_at(step):
        if step < warmup_steps:
            return base_lr * (step / max(warmup_steps, 1))
        t = min(step - warmup_steps, total_steps - warmup_steps)
        frac = 1.0 - t / (total_steps - warmup_steps)
        return (base_lr - end_lr) * frac * frac + end_lr

    return lr_at


def _ps_only(name):
    def fn(*a, **k):
        raise NotImplementedError(
            f"{name} belongs to the parameter-server stack "
            "(paddle/fluid/distributed/ps/), which is documented out of "
            "TPU-v1 scope (SURVEY §2.10)")

    fn.__name__ = name
    return fn


_pull_box_sparse = _ps_only("_pull_box_sparse")
_pull_gpups_sparse = _ps_only("_pull_gpups_sparse")
fused_seqpool_cvm = _ps_only("fused_seqpool_cvm")
search_pyramid_hash = _ps_only("search_pyramid_hash")
tdm_child = _ps_only("tdm_child")
tdm_sampler = _ps_only("tdm_sampler")
rank_attention = _ps_only("rank_attention")
correlation = _ps_only("correlation")
