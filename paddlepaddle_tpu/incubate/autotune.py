"""paddle.incubate.autotune (reference: python/paddle/incubate/autotune.py).

TPU mapping: the kernel/layout tuners are XLA's job (its autotuner picks
tilings and the compiler owns layout), so those sections validate and
record but change nothing — which IS the tuned behavior here. The
dataloader section is live: it feeds the DataLoader's num_workers
auto-selection default.
"""

from __future__ import annotations

import json
import warnings

__all__ = ["set_config"]

_config = {
    "kernel": {"enable": True, "tuning_range": [1, 10]},
    "layout": {"enable": False},
    "dataloader": {"enable": False},
}


def get_config():
    return {k: dict(v) for k, v in _config.items()}


def set_config(config=None):
    """Set kernel/layout/dataloader auto-tuning config (reference
    incubate/autotune.py:47; dict, json-file path, or None = enable all)."""
    if config is None:
        for section in _config.values():
            section["enable"] = True
        return
    if isinstance(config, str):
        with open(config) as f:
            config = json.load(f)
    if not isinstance(config, dict):
        raise ValueError(
            "The config should be None, a dict or a json file path")
    # validate everything first, THEN commit — a failed call must not
    # leave half-applied global config behind
    staged = []
    for key, val in config.items():
        if key not in _config:
            warnings.warn(f"autotune: unknown section {key!r} ignored "
                          "(valid: kernel/layout/dataloader)", stacklevel=2)
            continue
        if not isinstance(val, dict):
            raise ValueError(f"autotune: section {key!r} must be a dict")
        for k, v in val.items():
            if k == "enable" and not isinstance(v, bool):
                raise ValueError(f"autotune: {key}.enable must be bool")
            if k == "tuning_range" and not isinstance(v, (list, tuple)):
                raise ValueError(
                    f"autotune: {key}.tuning_range must be a list")
            staged.append((key, k, v))
    for key, k, v in staged:
        _config[key][k] = v
