"""paddle.version (reference: the module setup.py write_version_py
generates at build time, python/paddle/version/__init__.py). Here the
fields are authored directly — there is no codegen step — and the
CUDA/XPU backend queries answer honestly for the TPU build (False, as
the reference's CPU build does for cuda())."""

from __future__ import annotations

import os
import subprocess

try:
    from importlib.metadata import version as _pkg_version

    full_version = _pkg_version("paddlepaddle-tpu")
except Exception:
    full_version = "0.4.0"       # source of truth: pyproject.toml
major, minor, patch = (full_version.split(".") + ["0", "0"])[:3]
rc = "0"
nccl_version = "0"
cuda_version = "False"
cudnn_version = "False"
xpu_xre_version = "False"
xpu_xccl_version = "False"
xpu_xhpc_version = "False"
is_tagged = False
with_mkl = "OFF"
cinn_version = "False"
tensorrt_version = "False"
tpu_backend = "jax/XLA/Pallas"

__all__ = ["cuda", "cudnn", "nccl", "show", "xpu", "xpu_xre", "xpu_xccl",
           "xpu_xhpc", "tensorrt", "cuda_archs"]


_commit_cache = None


def _commit():
    """Lazy + cached: resolved from THIS package's checkout (not the
    importer's cwd), only when `commit` is first read."""
    global _commit_cache
    if _commit_cache is None:
        try:
            _commit_cache = subprocess.run(
                ["git", "rev-parse", "HEAD"], capture_output=True,
                text=True, timeout=5,
                cwd=os.path.dirname(os.path.abspath(__file__)),
            ).stdout.strip() or "unknown"
        except Exception:
            _commit_cache = "unknown"
    return _commit_cache


def __getattr__(name):
    if name == "commit":
        return _commit()
    raise AttributeError(name)


def show():
    """Print the version record (reference version.show contract)."""
    if is_tagged:
        print("full_version:", full_version)
        print("major:", major)
        print("minor:", minor)
        print("patch:", patch)
        print("rc:", rc)
    else:
        print("commit:", _commit())
    print("cuda:", cuda_version)
    print("cudnn:", cudnn_version)
    print("nccl:", nccl_version)
    print("xpu_xre:", xpu_xre_version)
    print("xpu_xccl:", xpu_xccl_version)
    print("xpu_xhpc:", xpu_xhpc_version)
    print("cinn:", cinn_version)
    print("tensorrt:", tensorrt_version)
    print("tpu_backend:", tpu_backend)


def cuda():
    return cuda_version


def cudnn():
    return cudnn_version


def nccl():
    return nccl_version


def xpu():
    return xpu_xhpc_version


def xpu_xre():
    return xpu_xre_version


def xpu_xccl():
    return xpu_xccl_version


def xpu_xhpc():
    return xpu_xhpc_version


def tensorrt():
    return tensorrt_version


def cuda_archs():
    return []
