"""In-process metric history: bounded per-series rings over the registry.

Every other surface in the observability family answers "what is the value
*now*" — ``/metrics`` is an instantaneous scrape, ``/healthz`` a live
census.  This module adds *history* without an external Prometheus: a
sampler thread diffs successive :meth:`metrics.Registry.snapshot` outputs
every ``FLAGS_obs_tsdb_interval_s`` and appends one point per series into a
bounded ring.

Series model
------------
* **Counters** are stored as *rates* (delta / dt per sampling interval) —
  the only shape a window aggregate is meaningful over.  A counter reset
  (registry ``clear()``, process restart) yields a negative delta, which is
  dropped rather than recorded as a huge negative rate.
* **Gauges** are stored as sampled values.
* **Histograms** become derived series per label set: ``name:p50`` /
  ``name:p99`` (window quantile estimated from the bucket-count deltas of
  the interval), ``name:rate`` (observations/s) and ``name:mean`` (window
  mean = dsum/dcount).  Intervals with no new observations produce no
  points (a gap, not a zero).

Series ids are ``name{label="value",...}`` with the derived suffix before
the label block (``paddle_serving_ttft_seconds:p99{...}``).

Retention: two tiers per series — a raw ring of ``FLAGS_obs_tsdb_points``
points at the sampling interval, plus a 10x coarser ring of the same
capacity where every 10 raw points collapse to one ``(t, mean, min, max)``
aggregate.  At the defaults (512 points, 2s interval) that is ~17 minutes
raw + ~2.8 hours coarse per series for a fixed byte budget.

Surfaces: the exporter serves ``/query?series=&window=`` (strict JSON) from
the singleton here; :mod:`~.aggregate` publishes :meth:`MetricHistory.
jsonable` under ``obs/tsdb/rank{r}`` TCPStore keys so rank-0
``/fleet/query`` answers across replicas; :mod:`~.alerts` evaluates its
rules against :meth:`MetricHistory.window_agg` on every sampler tick.

Everything is off by default (``FLAGS_obs_tsdb`` / ``PADDLE_OBS_TSDB``);
all sampling work rides the daemon thread, never a serving hot path.
"""

from __future__ import annotations

import json
import threading
import time
from collections import deque
from typing import Callable, Dict, List, Optional, Sequence, Tuple

from .metrics import Registry, _fmt_labels

__all__ = [
    "MetricHistory", "SeriesRing", "enable", "disable", "get", "reset",
    "match_series", "DOWNSAMPLE",
]

#: raw points folded into one coarse point (the "10x coarser" second tier).
DOWNSAMPLE = 10

#: window quantiles derived per histogram label set on every sample.
QUANTILES = (0.5, 0.99)


def _flag(name, default):
    try:
        from ..core import flags as _flags

        v = _flags.flag_value(name)
        return default if v is None else v
    except Exception:
        return default


class SeriesRing:
    """Bounded two-tier point store for ONE series.

    Raw tier: ``(t, value)`` pairs at the sampling interval.  Coarse tier:
    every :data:`DOWNSAMPLE` raw appends collapse into one ``(t, mean, min,
    max)`` aggregate stamped at the last raw point's time.  Both tiers are
    ``deque(maxlen=capacity)`` so memory is fixed at construction.
    """

    __slots__ = ("kind", "raw", "coarse", "_pending")

    def __init__(self, kind: str, capacity: int):
        self.kind = kind
        self.raw: deque = deque(maxlen=max(2, int(capacity)))
        self.coarse: deque = deque(maxlen=max(2, int(capacity)))
        self._pending: List[Tuple[float, float]] = []

    def append(self, t: float, v: float) -> None:
        self.raw.append((t, v))
        self._pending.append((t, v))
        if len(self._pending) >= DOWNSAMPLE:
            vals = [p[1] for p in self._pending]
            self.coarse.append((self._pending[-1][0], sum(vals) / len(vals),
                                min(vals), max(vals)))
            self._pending = []

    def points(self, window_s: Optional[float] = None,
               now: Optional[float] = None) -> Tuple[str, List[Tuple]]:
        """``(tier, points)`` best answering ``window_s`` seconds of
        history: raw while the window fits inside the raw span, else
        coarse (whose points re-emit as ``(t, mean)`` pairs plus their
        min/max for tier-aware aggregation)."""
        if not self.raw:
            return "raw", []
        if now is None:
            now = self.raw[-1][0]
        if window_s is None:
            return "raw", list(self.raw)
        cutoff = now - window_s
        if self.raw[0][0] <= cutoff or not self.coarse:
            return "raw", [p for p in self.raw if p[0] >= cutoff]
        return "coarse", [p for p in self.coarse if p[0] >= cutoff]

    def window_agg(self, window_s: float, agg: str,
                   now: Optional[float] = None) -> Optional[float]:
        """Aggregate over the window; ``None`` when no points fall in it.
        On the coarse tier ``min``/``max`` use the per-point extrema so
        downsampling cannot hide a spike the raw ring has already
        forgotten."""
        tier, pts = self.points(window_s, now)
        if not pts:
            return None
        if agg == "last":
            return float(pts[-1][1])
        if tier == "coarse":
            means = [p[1] for p in pts]
            if agg == "avg":
                return float(sum(means) / len(means))
            if agg == "min":
                return float(min(p[2] for p in pts))
            if agg == "max":
                return float(max(p[3] for p in pts))
            if agg == "sum":
                return float(sum(means))
        else:
            vals = [p[1] for p in pts]
            if agg == "avg":
                return float(sum(vals) / len(vals))
            if agg == "min":
                return float(min(vals))
            if agg == "max":
                return float(max(vals))
            if agg == "sum":
                return float(sum(vals))
        raise ValueError(f"unknown agg {agg!r}")


def match_series(ids: Sequence[str], selector: Optional[str]) -> List[str]:
    """Selector semantics shared by the live store and the fleet merge:
    ``None``/empty -> every series; trailing ``*`` -> id prefix; else exact
    id, falling back to "name part" (id up to ``{``) so ``paddle_x`` finds
    every label variant and ``paddle_x:p99`` every labeled p99 series."""
    ids = sorted(ids)
    if not selector:
        return ids
    if selector.endswith("*"):
        pre = selector[:-1]
        return [s for s in ids if s.startswith(pre)]
    if selector in ids:
        return [selector]
    return [s for s in ids if s.split("{", 1)[0] == selector]


def _window_quantile(dcounts: Dict[float, int], q: float) -> Optional[float]:
    """Quantile estimate from per-window (non-cumulative) bucket deltas:
    walk ascending bounds until the target rank is covered and report that
    bucket's upper bound — same le-semantics as ``Histogram.quantile`` but
    over the window's observations only.  The +Inf bucket reports the
    largest finite bound (the best upper estimate the data carries)."""
    total = sum(dcounts.values())
    if total <= 0:
        return None
    bounds = sorted(dcounts)
    target = q * total
    seen = 0
    last_finite = None
    for b in bounds:
        if b != float("inf"):
            last_finite = b
        seen += dcounts[b]
        if seen >= target:
            return b if b != float("inf") else last_finite
    return last_finite


class MetricHistory:
    """Snapshot-diffing sampler over a :class:`metrics.Registry`.

    ``observe()`` is one sampling pass (tests drive it directly with a
    synthetic clock); ``start()`` runs it on a daemon thread every
    ``interval_s``.  Listeners (the alert engine) run at the end of each
    pass, on the sampler thread.
    """

    def __init__(self, registry: Registry, interval_s: Optional[float] = None,
                 capacity: Optional[int] = None):
        self.registry = registry
        self.interval_s = float(interval_s if interval_s is not None
                                else _flag("obs_tsdb_interval_s", 2.0))
        self.capacity = int(capacity if capacity is not None
                            else _flag("obs_tsdb_points", 512))
        self._series: Dict[str, SeriesRing] = {}
        self._prev: Dict[Tuple[str, tuple], Tuple[float, object]] = {}
        self._listeners: List[Callable] = []
        self._lock = threading.Lock()
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None
        self.samples = 0

    # -- sampling ------------------------------------------------------------
    def _ring(self, sid: str, kind: str) -> SeriesRing:
        r = self._series.get(sid)
        if r is None:
            r = self._series[sid] = SeriesRing(kind, self.capacity)
        return r

    def observe(self, now: Optional[float] = None) -> int:
        """One sampling pass: diff the registry snapshot against the
        previous pass and append one point per live series.  Returns the
        number of points appended."""
        if now is None:
            now = time.time()
        snap = self.registry.snapshot()
        appended = 0
        with self._lock:
            for name, per_key in snap.items():
                metric = self.registry.get(name)
                kind = getattr(metric, "kind", "gauge")
                for key, val in per_key.items():
                    appended += self._observe_one(name, key, kind, val, now)
            self.samples += 1
        for fn in list(self._listeners):
            try:
                fn(self, now)
            except Exception:
                pass
        return appended

    def _observe_one(self, name, key, kind, val, now) -> int:
        labels = _fmt_labels(key)
        pkey = (name, key)
        prev = self._prev.get(pkey)
        self._prev[pkey] = (now, val if kind != "histogram"
                            else {"count": val["count"], "sum": val["sum"],
                                  "buckets": dict(val["buckets"])})
        if kind == "gauge":
            self._ring(f"{name}{labels}", "gauge").append(now, float(val))
            return 1
        if kind == "counter":
            if prev is None:
                return 0
            pt, pv = prev
            dt = now - pt
            dv = float(val) - float(pv)
            if dt <= 0 or dv < 0:   # reset or clock skew: drop the interval
                return 0
            self._ring(f"{name}{labels}", "rate").append(now, dv / dt)
            return 1
        # histogram: window deltas -> rate / mean / quantiles
        if prev is None:
            return 0
        pt, pv = prev
        dt = now - pt
        dcount = val["count"] - pv["count"]
        if dt <= 0 or dcount < 0:
            return 0
        n = 0
        self._ring(f"{name}:rate{labels}", "rate").append(now, dcount / dt)
        n += 1
        if dcount == 0:
            return n   # no new observations: quantiles/mean get a gap
        dsum = val["sum"] - pv["sum"]
        self._ring(f"{name}:mean{labels}", "gauge").append(now, dsum / dcount)
        n += 1
        dbuckets = {b: c - pv["buckets"].get(b, 0)
                    for b, c in val["buckets"].items()}
        for q in QUANTILES:
            est = _window_quantile(dbuckets, q)
            if est is not None:
                sid = f"{name}:p{int(q * 100)}{labels}"
                self._ring(sid, "gauge").append(now, float(est))
                n += 1
        return n

    # -- listeners / thread --------------------------------------------------
    def add_listener(self, fn: Callable) -> None:
        """``fn(history, now)`` after every pass, on the sampler thread."""
        self._listeners.append(fn)

    def remove_listener(self, fn: Callable) -> None:
        try:
            self._listeners.remove(fn)
        except ValueError:
            pass

    def start(self) -> "MetricHistory":
        if self._thread is not None:
            return self
        self._stop.clear()

        def loop():
            while not self._stop.wait(self.interval_s):
                try:
                    self.observe()
                except Exception:
                    pass

        self._thread = threading.Thread(target=loop, name="paddle-tsdb",
                                        daemon=True)
        self._thread.start()
        return self

    def stop(self) -> None:
        self._stop.set()
        t, self._thread = self._thread, None
        if t is not None:
            t.join(timeout=5.0)

    # -- queries -------------------------------------------------------------
    def series_ids(self) -> List[str]:
        with self._lock:
            return sorted(self._series)

    def _match(self, selector: Optional[str]) -> List[str]:
        # caller holds self._lock
        return match_series(self._series.keys(), selector)

    def window_agg(self, selector: str, window_s: float, agg: str,
                   now: Optional[float] = None) -> Dict[str, float]:
        """{series_id: aggregate} over the window for each matching series
        that has points in it — the alert engine's evaluation primitive."""
        out: Dict[str, float] = {}
        with self._lock:
            for sid in self._match(selector):
                v = self._series[sid].window_agg(window_s, agg, now)
                if v is not None:
                    out[sid] = v
        return out

    def query(self, selector: Optional[str] = None,
              window_s: Optional[float] = None,
              max_points: Optional[int] = None,
              now: Optional[float] = None) -> dict:
        """Strict-JSON-able ``/query`` body: matched series with their
        best-tier points for the window."""
        if now is None:
            now = time.time()
        rows = []
        with self._lock:
            for sid in self._match(selector):
                ring = self._series[sid]
                tier, pts = ring.points(window_s, now)
                pts = [[p[0], p[1]] for p in pts]
                if max_points is not None and len(pts) > max_points:
                    pts = pts[-max_points:]
                rows.append({"id": sid, "kind": ring.kind, "tier": tier,
                             "points": pts})
        return {"now": now, "interval_s": self.interval_s,
                "window_s": window_s, "series": rows}

    def jsonable(self, max_points: Optional[int] = None) -> dict:
        """Bounded full dump for the TCPStore fleet plane: the most recent
        ``max_points`` of each tier per series (default
        ``FLAGS_obs_tsdb_publish_points``)."""
        if max_points is None:
            max_points = int(_flag("obs_tsdb_publish_points", 64))
        out: Dict[str, dict] = {}
        with self._lock:
            for sid, ring in self._series.items():
                out[sid] = {
                    "kind": ring.kind,
                    "raw": [list(p) for p in list(ring.raw)[-max_points:]],
                    "coarse": [list(p)
                               for p in list(ring.coarse)[-max_points:]],
                }
        return {"interval_s": self.interval_s, "series": out}

    def clear(self) -> None:
        with self._lock:
            self._series.clear()
            self._prev.clear()
            self.samples = 0


# -- module singleton --------------------------------------------------------
_hist: Optional[MetricHistory] = None
_hist_lock = threading.Lock()


def enable(interval_s: Optional[float] = None,
           capacity: Optional[int] = None,
           registry: Optional[Registry] = None,
           start_thread: bool = True) -> MetricHistory:
    """Arm the history plane (idempotent).  Samples the package registry
    unless an explicit one is given; ``start_thread=False`` leaves the
    sampler to be driven manually (tests)."""
    global _hist
    with _hist_lock:
        if _hist is not None:
            return _hist
        if registry is None:
            from . import get_registry

            registry = get_registry()
        _hist = MetricHistory(registry, interval_s=interval_s,
                              capacity=capacity)
        if start_thread:
            _hist.start()
        return _hist


def disable() -> None:
    global _hist
    with _hist_lock:
        h, _hist = _hist, None
    if h is not None:
        h.stop()


def get() -> Optional[MetricHistory]:
    return _hist


def reset() -> None:
    disable()


def query_body(selector: Optional[str], window_s: Optional[float],
               max_points: Optional[int] = None) -> Tuple[int, str, str]:
    """The ``/query`` exporter route body: strict JSON whether or not the
    plane is armed."""
    h = get()
    if h is None:
        doc = {"enabled": False, "series": []}
        return 200, "application/json", json.dumps(doc)
    doc = h.query(selector, window_s, max_points=max_points)
    doc["enabled"] = True
    return 200, "application/json", json.dumps(doc)
