"""Per-rank HTTP telemetry exporter — the pull half of the fleet plane.

Reference surface: the reference serving stack exposes monitor stats over
an HTTP scrape endpoint per process; Prometheus convention is one exporter
per worker, aggregation downstream. This module serves the process-local
observability state on ``FLAGS_obs_port + rank`` (so every worker of a
multi-process node gets its own port) from a stdlib ``ThreadingHTTPServer``
on a daemon thread — zero dependencies, no interaction with the training
loop beyond reading the registry/recorder:

* ``/metrics``  — Prometheus exposition text (``to_prometheus_text()``);
  on rank 0 of a launched job, :mod:`~.aggregate` swaps this route for the
  fleet-merged view with a ``rank`` label per series;
* ``/healthz``  — JSON readiness: rank/world/pid, which obs subsystems are
  on, plus any registered health providers (a started
  :class:`~..inference.serving.ServingEngine` registers its ``health()``
  here); 503 when any provider reports not-ok;
* ``/vars``     — the full metrics ``snapshot()`` as JSON;
* ``/trace``    — the host span ring buffer as chrome-trace JSON (load in
  Perfetto directly);
* ``/programs`` — the perf plane's program-cost table (XLA FLOPs/bytes,
  measured wall, roofline classification) as JSON; rendered by
  ``obsctl programs``;
* ``/requests`` — recent + in-flight request journeys (reqtrace) with
  SLO-histogram exemplars and the burn-rate block, as strict JSON;
  ``/requests/trace`` serves the same journeys as Perfetto-loadable
  chrome-trace JSON (one track per replica); rendered by
  ``obsctl requests``;
* ``/query``    — metric history from the tsdb plane
  (``?series=<selector>&window=<seconds>``, strict JSON); on rank 0 of a
  launched job :mod:`~.aggregate` adds ``/fleet/query`` with every rank's
  published history; rendered by ``obsctl query`` and ``obsctl top``;
* ``/alerts``   — the alert engine's rule states as strict JSON; a firing
  page-severity rule also flips ``/healthz`` to 503 via its built-in
  ``alerts`` provider block.

Auto-started per worker when ``PADDLE_OBS_EXPORT=1`` (``FLAGS_obs_export``)
— ``distributed.launch --obs_export`` sets that for every rank it spawns.
If the deterministic port is taken, the exporter falls back to an ephemeral
port and says so on stderr rather than dying: telemetry must never take the
worker down.
"""

from __future__ import annotations

import json
import os
import sys
import threading
import time
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Callable, Dict, Optional, Tuple

from ..core import flags as _flags
from .flight import _rank, _world

__all__ = ["TelemetryExporter", "start", "stop", "get", "PROM_CONTENT_TYPE"]

PROM_CONTENT_TYPE = "text/plain; version=0.0.4; charset=utf-8"
_JSON = "application/json"

# route callable: () -> (http_status, content_type, body_str_or_bytes)
Route = Callable[[], Tuple[int, str, object]]
# param route callable: (query_params_dict) -> same tuple
ParamRoute = Callable[[Dict[str, str]], Tuple[int, str, object]]


class _Handler(BaseHTTPRequestHandler):
    server_version = "paddle-obs"

    def do_GET(self):  # noqa: N802 (http.server API)
        exporter = self.server._exporter  # type: ignore[attr-defined]
        raw_path, _, query = self.path.partition("?")
        path = raw_path.rstrip("/") or "/"
        proute = exporter._param_routes.get(path)
        route = exporter._routes.get(path)
        if proute is None and route is None:
            body = json.dumps({"error": f"no route {path}",
                               "routes": exporter.route_names()})
            self._send(404, _JSON, body)
            return
        try:
            if proute is not None:
                from urllib.parse import parse_qsl

                status, ctype, body = proute(dict(parse_qsl(query)))
            else:
                status, ctype, body = route()
        except Exception as e:  # a broken route must not kill the server
            status, ctype = 500, _JSON
            body = json.dumps({"error": f"{type(e).__name__}: {e}"})
        self._send(status, ctype, body)

    def _send(self, status: int, ctype: str, body) -> None:
        data = body if isinstance(body, bytes) else str(body).encode()
        self.send_response(status)
        self.send_header("Content-Type", ctype)
        self.send_header("Content-Length", str(len(data)))
        self.end_headers()
        self.wfile.write(data)

    def log_message(self, *args):  # scrapes must not spam worker stderr
        pass


class TelemetryExporter:
    """One process's telemetry server. ``port=None`` resolves to
    ``FLAGS_obs_port + rank``; ``port=0`` binds ephemeral (tests)."""

    def __init__(self, port: Optional[int] = None, host: Optional[str] = None):
        self.host = host or _flags.flag_value("obs_export_host")
        self._requested_port = port
        self.port: Optional[int] = None
        self._server: Optional[ThreadingHTTPServer] = None
        self._thread: Optional[threading.Thread] = None
        self._started_mono: Optional[float] = None
        self._health_providers: Dict[str, Callable[[], dict]] = {}
        self._routes: Dict[str, Route] = {}
        self._param_routes: Dict[str, ParamRoute] = {}
        self._install_default_routes()

    # -- routes --------------------------------------------------------------
    def register_route(self, path: str, fn: Route) -> None:
        """Add (or replace — the fleet aggregator replaces ``/metrics``) a
        GET route. ``fn`` returns (status, content_type, body)."""
        self._routes[path.rstrip("/") or "/"] = fn

    def register_param_route(self, path: str, fn: ParamRoute) -> None:
        """Like :meth:`register_route` but ``fn`` receives the parsed
        query-string parameters (``/query?series=&window=`` style routes);
        a path registered here shadows any plain route at the same path."""
        self._param_routes[path.rstrip("/") or "/"] = fn

    def route_names(self):
        return sorted(set(self._routes) | set(self._param_routes))

    def register_health(self, name: str, fn: Callable[[], dict],
                        unique: bool = False) -> str:
        """Attach a named health provider; its dict lands under
        ``providers`` in ``/healthz`` and its ``ok`` key gates the 503.
        With ``unique=True`` a taken name gets a ``-2``/``-3`` suffix
        instead of clobbering another provider (two serving engines in one
        process must not overwrite each other). Returns the name used."""
        if unique:
            base, n = name, 2
            while (name in self._health_providers
                   and self._health_providers[name] != fn):
                name = f"{base}-{n}"
                n += 1
        self._health_providers[name] = fn
        return name

    def unregister_health(self, name: str,
                          fn: Optional[Callable[[], dict]] = None) -> None:
        """Remove a provider. Passing ``fn`` makes it a guarded remove:
        the entry is only dropped if it still belongs to that callable."""
        if fn is not None and self._health_providers.get(name) != fn:
            return
        self._health_providers.pop(name, None)

    def _install_default_routes(self) -> None:
        self.register_route("/", self._index)
        self.register_route("/metrics", self._metrics)
        self.register_route("/healthz", self._healthz)
        self.register_route("/vars", self._vars)
        self.register_route("/trace", self._trace)
        self.register_route("/programs", self._programs)
        self.register_route("/requests", self._requests)
        self.register_route("/requests/trace", self._requests_trace)
        self.register_param_route("/query", self._query)
        self.register_route("/alerts", self._alerts)
        self.register_param_route("/profile", self._profile)
        self.register_route("/mem", self._mem)

    def _index(self):
        return 200, _JSON, json.dumps(
            {"routes": self.route_names(), "rank": _rank(),
             "world": _world(), "pid": os.getpid()})

    def _metrics(self):
        from . import to_prometheus_text

        return 200, PROM_CONTENT_TYPE, to_prometheus_text()

    def _vars(self):
        from . import snapshot
        from .metrics import snapshot_to_jsonable

        # allow_nan=False enforces the strict-JSON contract: any non-finite
        # value snapshot_to_jsonable missed fails loudly here, not in a
        # consumer's JSON parser
        return 200, _JSON, json.dumps(snapshot_to_jsonable(snapshot()),
                                      allow_nan=False)

    def _trace(self):
        from . import get_recorder

        return 200, _JSON, json.dumps(get_recorder().to_chrome_trace())

    def _programs(self):
        from . import perf

        body = dict(perf.table_jsonable(), enabled=perf.enabled(),
                    rank=_rank())
        return 200, _JSON, json.dumps(body, allow_nan=False, default=str)

    def _requests(self):
        from . import reqtrace

        body = dict(reqtrace.requests_jsonable(), rank=_rank())
        return 200, _JSON, json.dumps(body, allow_nan=False, default=str)

    def _requests_trace(self):
        from . import reqtrace

        return 200, _JSON, json.dumps(reqtrace.to_chrome_trace(),
                                      allow_nan=False, default=str)

    def _query(self, params: Dict[str, str]):
        from . import tsdb

        try:
            window_s = (float(params["window"])
                        if params.get("window") else None)
            max_points = (int(params["max_points"])
                          if params.get("max_points") else None)
        except ValueError as e:
            return 400, _JSON, json.dumps({"error": f"bad parameter: {e}"})
        return tsdb.query_body(params.get("series") or None, window_s,
                               max_points)

    def _alerts(self):
        from . import alerts

        return alerts.alerts_body()

    def _profile(self, params: Dict[str, str]):
        """Sampling-profiler read side: ``/profile?seconds=&format=
        collapsed|json&top=``; ``?device=<seconds>`` opens an on-demand
        ``jax.profiler`` device-trace window instead and returns its
        output directory."""
        from . import profiler

        prof = profiler.get()
        if prof is None:
            return 503, _JSON, json.dumps(
                {"enabled": False,
                 "error": "profiler not armed (set PADDLE_OBS_PROF=1 or "
                          "call observability.profiler.enable())"})
        try:
            seconds = (float(params["seconds"])
                       if params.get("seconds") else 10.0)
            top = int(params["top"]) if params.get("top") else 30
            device = (float(params["device"])
                      if params.get("device") else None)
        except ValueError as e:
            return 400, _JSON, json.dumps({"error": f"bad parameter: {e}"})
        if device is not None:
            try:
                outdir = prof.device_trace(seconds=device)
            except Exception as e:
                return 409, _JSON, json.dumps({"error": repr(e)})
            return 200, _JSON, json.dumps(
                {"device_trace": outdir, "seconds": device})
        if params.get("format") == "collapsed":
            return (200, "text/plain; charset=utf-8",
                    prof.collapsed(seconds))
        body = dict(prof.jsonable(seconds, top), enabled=True,
                    rank=_rank())
        return 200, _JSON, json.dumps(body, allow_nan=False, default=str)

    def _mem(self):
        """Memory-ledger read side: last bucketed sample + deltas. Takes
        a fresh sample on demand so ``obsctl mem`` works without the
        periodic thread armed."""
        from . import memledger

        try:
            body = dict(memledger.sample_now(), rank=_rank())
        except Exception as e:
            return 503, _JSON, json.dumps({"error": repr(e)})
        return 200, _JSON, json.dumps(body, allow_nan=False, default=str)

    def _healthz(self):
        from . import _metrics_on, _trace_on, _watchdog_on
        from . import flight

        providers = {}
        ok = True
        # built-in provider: the alert engine (when installed) — a firing
        # page-severity rule must flip readiness without any registration
        # ordering between engine install and exporter start
        try:
            from . import alerts as _alerts

            eng = _alerts.get()
            if eng is not None:
                snap = eng.health()
                providers["alerts"] = snap
                ok = ok and bool(snap.get("ok", True))
        except Exception:
            pass
        for name, fn in list(self._health_providers.items()):
            try:
                snap = fn()
                providers[name] = snap
                ok = ok and bool(snap.get("ok", True))
            except Exception as e:
                providers[name] = {"ok": False,
                                   "error": f"{type(e).__name__}: {e}"}
                ok = False
        body = {
            "ok": ok,
            "rank": _rank(),
            "world": _world(),
            "pid": os.getpid(),
            "port": self.port,
            "uptime_s": (None if self._started_mono is None
                         else round(time.monotonic() - self._started_mono, 3)),
            "obs": {"trace": _trace_on, "metrics": _metrics_on,
                    "recompile_watch": _watchdog_on,
                    "blackbox": flight.is_enabled()},
            "providers": providers,
        }
        return (200 if ok else 503), _JSON, json.dumps(body, default=str)

    # -- lifecycle -----------------------------------------------------------
    def resolved_port(self) -> int:
        if self._requested_port is not None:
            return int(self._requested_port)
        return int(_flags.flag_value("obs_port")) + _rank()

    def start(self) -> "TelemetryExporter":
        if self._server is not None:
            return self
        port = self.resolved_port()
        try:
            server = ThreadingHTTPServer((self.host, port), _Handler)
        except OSError as e:
            # deterministic port taken (another worker, a stale process):
            # serve anyway on an ephemeral port and say where
            server = ThreadingHTTPServer((self.host, 0), _Handler)
            sys.stderr.write(
                f"[obs] exporter port {port} unavailable ({e}); "
                f"falling back to {server.server_address[1]}\n")
        server.daemon_threads = True
        server._exporter = self  # type: ignore[attr-defined]
        self._server = server
        self.port = server.server_address[1]
        self._started_mono = time.monotonic()
        self._thread = threading.Thread(
            target=server.serve_forever, daemon=True,
            name=f"obs-exporter:{self.port}")
        self._thread.start()
        return self

    def stop(self) -> None:
        server, self._server = self._server, None
        if server is not None:
            server.shutdown()
            server.server_close()
        if self._thread is not None:
            self._thread.join(timeout=5)
            self._thread = None

    def url(self, path: str = "") -> str:
        return f"http://{self.host}:{self.port}{path}"

    def __enter__(self):
        return self.start()

    def __exit__(self, *exc):
        self.stop()


# -- module singleton (what auto-start and ServingEngine registration use) --

_exporter: Optional[TelemetryExporter] = None


def start(port: Optional[int] = None,
          host: Optional[str] = None) -> TelemetryExporter:
    """Start (or return) the process-wide exporter."""
    global _exporter
    if _exporter is None:
        _exporter = TelemetryExporter(port=port, host=host).start()
    return _exporter


def stop() -> None:
    global _exporter
    if _exporter is not None:
        _exporter.stop()
        _exporter = None


def get() -> Optional[TelemetryExporter]:
    return _exporter
