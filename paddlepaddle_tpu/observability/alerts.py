"""Declarative alert rules over the metric history plane.

The SRE-workbook shape: a rule is an AND of window predicates over
:mod:`tsdb` series — the canonical pair being a *fast* and a *slow* window
on the same burn-rate series (``5m AND 1h``), so a transient spike clears
the fast window before the slow one confirms it, and a slow leak trips the
slow window even when each fast window looks tolerable.  ``for_s`` adds a
hold-down on top: the conditions must hold continuously that long before
the rule transitions pending -> firing.

The engine evaluates on every sampler tick (it registers as a
:class:`~.tsdb.MetricHistory` listener).  Firing is observable everywhere
an operator might already be looking:

* ``paddle_alerts_firing{alert=}`` gauge (1 while firing) and
  ``paddle_alerts_fired_total{alert=}`` counter;
* a ``/healthz`` provider block (page-severity firing => not ok);
* flight-recorder events on every transition, plus exactly ONE automatic
  ``flight.dump("alert-<name>")`` per firing episode with the N slowest
  request journeys attached (joining "alert fired" to "these requests");
* the ``/alerts`` exporter route and ``obsctl alerts`` / ``obsctl top``.

A default ruleset (:func:`default_rules`) covers the failure modes the
serving planes already measure: TTFT/TPOT burn, shed requests, breaker
open, KV page exhaustion, recompile storms and fleet snapshot staleness.
"""

from __future__ import annotations

import json
import threading
import time
from typing import Callable, Dict, List, Optional, Sequence

from . import flight

__all__ = [
    "AlertCondition", "AlertRule", "AlertState", "AlertEngine",
    "default_rules", "install", "uninstall", "get",
]

_OPS: Dict[str, Callable[[float, float], bool]] = {
    ">": lambda v, t: v > t,
    ">=": lambda v, t: v >= t,
    "<": lambda v, t: v < t,
    "<=": lambda v, t: v <= t,
}


class AlertCondition:
    """One window predicate: ``agg(series over window_s) op threshold``.

    A selector matching several label variants holds when ANY variant
    violates (worst-case semantics — one bad replica pages).  A selector
    with no points in the window does not hold: absence of data is absence
    of evidence, never a page.
    """

    __slots__ = ("series", "window_s", "agg", "op", "threshold")

    def __init__(self, series: str, window_s: float, agg: str, op: str,
                 threshold: float):
        if op not in _OPS:
            raise ValueError(f"unknown op {op!r}")
        if agg not in ("avg", "min", "max", "sum", "last"):
            raise ValueError(f"unknown agg {agg!r}")
        self.series = series
        self.window_s = float(window_s)
        self.agg = agg
        self.op = op
        self.threshold = float(threshold)

    def evaluate(self, history, now=None):
        """``(holds, worst_value_or_None, series_id_or_None)``."""
        vals = history.window_agg(self.series, self.window_s, self.agg, now)
        worst = None
        for sid, v in vals.items():
            if _OPS[self.op](v, self.threshold):
                if worst is None or _OPS[self.op](v, worst[0]):
                    worst = (v, sid)
        if worst is not None:
            return True, worst[0], worst[1]
        if vals:
            return False, max(vals.values()), None
        return False, None, None

    def jsonable(self) -> dict:
        return {"series": self.series, "window_s": self.window_s,
                "agg": self.agg, "op": self.op, "threshold": self.threshold}

    def __repr__(self):
        return (f"{self.agg}({self.series}[{self.window_s:g}s]) "
                f"{self.op} {self.threshold:g}")


class AlertRule:
    """AND of conditions + hold-down + severity.  ``severity`` is ``page``
    (flips ``/healthz``, triggers the flight dump) or ``warn``."""

    __slots__ = ("name", "conditions", "for_s", "severity", "description")

    def __init__(self, name: str, conditions: Sequence[AlertCondition],
                 for_s: float = 0.0, severity: str = "page",
                 description: str = ""):
        if severity not in ("page", "warn"):
            raise ValueError(f"severity must be page|warn, got {severity!r}")
        if not conditions:
            raise ValueError("a rule needs at least one condition")
        self.name = name
        self.conditions = list(conditions)
        self.for_s = float(for_s)
        self.severity = severity
        self.description = description

    def jsonable(self) -> dict:
        return {"name": self.name, "severity": self.severity,
                "for_s": self.for_s, "description": self.description,
                "conditions": [c.jsonable() for c in self.conditions]}


class AlertState:
    """Mutable evaluation state for one rule: ``ok`` -> ``pending`` (all
    conditions hold, hold-down running) -> ``firing``."""

    __slots__ = ("rule", "state", "since", "value", "series_id",
                 "fired_total", "last_dump", "last_change")

    def __init__(self, rule: AlertRule):
        self.rule = rule
        self.state = "ok"
        self.since: Optional[float] = None       # start of current hold
        self.value: Optional[float] = None       # worst violating value
        self.series_id: Optional[str] = None
        self.fired_total = 0
        self.last_dump: Optional[str] = None     # dump path of this episode
        self.last_change: Optional[float] = None

    def jsonable(self) -> dict:
        return {
            "name": self.rule.name, "severity": self.rule.severity,
            "state": self.state, "since": self.since, "value": self.value,
            "series": self.series_id, "for_s": self.rule.for_s,
            "fired_total": self.fired_total, "last_change": self.last_change,
            "description": self.rule.description,
            "conditions": [c.jsonable() for c in self.rule.conditions],
        }


def _slowest_journeys(n: int = 3) -> List[dict]:
    """The N slowest completed request journeys, joined through the
    reqtrace exemplar lists (slowest-by-latency trace ids) back to their
    full journey records — what an alert dump attaches so "TTFT burn
    fired" arrives with "and these were the requests"."""
    try:
        from . import reqtrace

        seen: Dict[str, float] = {}
        for ex in (reqtrace.exemplars() or {}).values():
            for row in ex.get("slowest", ()):
                tid = row.get("trace_id")
                if tid is None:
                    continue
                v = float(row.get("value_s") or 0.0)
                if v >= seen.get(tid, -1.0):
                    seen[tid] = v
        out = []
        for tid in sorted(seen, key=seen.get, reverse=True)[:n]:
            j = reqtrace.get(tid)
            if j is not None:
                out.append(j.jsonable())
        return out
    except Exception:
        return []


class AlertEngine:
    """Evaluates rules against a :class:`~.tsdb.MetricHistory` on its
    sampler tick.  Pure with respect to wiring: exporter/health and fleet
    hookup live in ``observability.__init__``."""

    def __init__(self, history, rules: Optional[Sequence[AlertRule]] = None,
                 registry=None, dump_journeys: int = 3):
        if rules is None:
            rules = default_rules()
        self.history = history
        self.states = {r.name: AlertState(r) for r in rules}
        self.dump_journeys = int(dump_journeys)
        self.ticks = 0
        self._lock = threading.Lock()
        if registry is None:
            from . import get_registry

            registry = get_registry()
        self._firing_g = registry.gauge(
            "paddle_alerts_firing",
            "1 while the named alert rule is firing")
        self._fired_c = registry.counter(
            "paddle_alerts_fired_total",
            "alert rule firing transitions (pending -> firing)")
        flight.annotate("alert_slowest_journeys",
                        lambda: _slowest_journeys(self.dump_journeys))

    # -- evaluation ----------------------------------------------------------
    def evaluate(self, history=None, now: Optional[float] = None) -> None:
        """One pass over every rule (the tsdb listener signature)."""
        if now is None:
            now = time.time()
        h = history if history is not None else self.history
        with self._lock:
            for st in self.states.values():
                self._eval_rule(st, h, now)
            self.ticks += 1

    def _eval_rule(self, st: AlertState, h, now: float) -> None:
        holds = True
        worst = None
        for cond in st.rule.conditions:
            ok, val, sid = cond.evaluate(h, now)
            if not ok:
                holds = False
                break
            if worst is None or (val is not None and val > worst[0]):
                worst = (val, sid)
        if holds:
            st.value, st.series_id = worst if worst else (None, None)
            if st.state == "ok":
                st.state = "pending"
                st.since = now
                st.last_change = now
                flight.record("alert", st.rule.name, state="pending",
                              value=st.value, series=st.series_id)
            if st.state == "pending" and now - st.since >= st.rule.for_s:
                self._fire(st, now)
        else:
            if st.state != "ok":
                cleared_from = st.state
                st.state = "ok"
                st.since = None
                st.last_change = now
                self._firing_g.set(0, alert=st.rule.name)
                flight.record("alert", st.rule.name, state="ok",
                              cleared_from=cleared_from)
                st.last_dump = None   # next episode dumps again
            st.value, st.series_id = None, None

    def _fire(self, st: AlertState, now: float) -> None:
        st.state = "firing"
        st.last_change = now
        st.fired_total += 1
        self._firing_g.set(1, alert=st.rule.name)
        self._fired_c.inc(alert=st.rule.name)
        flight.record("alert", st.rule.name, state="firing",
                      severity=st.rule.severity, value=st.value,
                      series=st.series_id)
        if st.rule.severity == "page" and st.last_dump is None:
            # exactly one automatic black-box dump per firing episode,
            # carrying the slowest-journey annotation resolved at dump time
            st.last_dump = flight.dump(f"alert-{st.rule.name}") or "skipped"

    # -- read side -----------------------------------------------------------
    def firing(self, severity: Optional[str] = None) -> List[AlertState]:
        with self._lock:
            return [st for st in self.states.values()
                    if st.state == "firing"
                    and (severity is None or st.rule.severity == severity)]

    def snapshot(self) -> dict:
        with self._lock:
            return {"ticks": self.ticks,
                    "rules": [st.jsonable()
                              for st in sorted(self.states.values(),
                                               key=lambda s: s.rule.name)]}

    def health(self) -> dict:
        """The ``/healthz`` provider block: not-ok while any page-severity
        rule fires."""
        firing = self.firing()
        return {
            "ok": not any(st.rule.severity == "page" for st in firing),
            "firing": [{"name": st.rule.name, "severity": st.rule.severity,
                        "value": st.value, "series": st.series_id,
                        "since": st.since}
                       for st in firing],
            "rules": len(self.states), "ticks": self.ticks,
        }

    def signal(self) -> dict:
        """What the autoscaler consumes instead of re-deriving burn
        thresholds: is a burn rule firing (or any page rule at all)."""
        firing = self.firing()
        burn = [st.rule.name for st in firing
                if "burn" in st.rule.name and st.rule.severity == "page"]
        return {
            "armed": True,
            "burn_firing": burn,
            "page_firing": [st.rule.name for st in firing
                            if st.rule.severity == "page"],
            "warn_firing": [st.rule.name for st in firing
                            if st.rule.severity == "warn"],
        }


def default_rules() -> List[AlertRule]:
    """The shipped ruleset over series the serving planes already emit.
    Burn rules use the fast+slow window pair; thresholds sit at burn==1
    (spending the error budget exactly as it accrues) with the fast window
    catching cliffs and the slow window confirming sustained burn.  Early
    in a process's life both windows clip to the available history, so a
    cold start behaves like a single-window rule until history accrues."""
    from ..core import flags as _flags

    publish = float(_flags.flag_value("obs_publish_interval_s") or 2.0)
    return [
        AlertRule(
            "ttft_burn",
            [AlertCondition("paddle_slo_burn_ttft", 60.0, "avg", ">", 1.0),
             AlertCondition("paddle_slo_burn_ttft", 300.0, "avg", ">", 1.0)],
            for_s=0.0, severity="page",
            description="TTFT SLO error budget burning faster than it "
                        "accrues on both the fast and slow window"),
        AlertRule(
            "tpot_burn",
            [AlertCondition("paddle_slo_burn_tpot", 60.0, "avg", ">", 1.0),
             AlertCondition("paddle_slo_burn_tpot", 300.0, "avg", ">", 1.0)],
            for_s=0.0, severity="page",
            description="TPOT SLO error budget burning faster than it "
                        "accrues on both the fast and slow window"),
        AlertRule(
            "requests_dropped",
            [AlertCondition("paddle_serving_shed_total", 60.0, "max",
                            ">", 0.0)],
            for_s=0.0, severity="page",
            description="requests shed/dropped in the last minute "
                        "(rate of paddle_serving_shed_total > 0)"),
        AlertRule(
            "breaker_open",
            [AlertCondition("paddle_serving_breaker_state", 30.0, "max",
                            ">=", 2.0)],
            for_s=0.0, severity="page",
            description="a serving circuit breaker reached open (state 2)"),
        AlertRule(
            "kv_pages_exhausted",
            [AlertCondition("paddle_serving_kv_pages_free", 60.0, "max",
                            "<=", 0.0)],
            for_s=0.0, severity="warn",
            description="the paged KV pool had zero free pages for a full "
                        "minute — admissions are queuing on preemption"),
        AlertRule(
            "kv_host_tier_full",
            # published by engines with the host prefix tier armed
            # (ROADMAP item 4): sustained near-full occupancy means every
            # further spill discards a cached prefix — the tier has
            # degraded to plain eviction and the budget needs raising
            [AlertCondition("paddle_serving_kv_host_occupancy", 60.0,
                            "avg", ">=", 0.9)],
            for_s=0.0, severity="warn",
            description="the host-RAM prefix tier averaged >= 90% of its "
                        "byte budget over the last minute — spills are "
                        "discarding cached prefixes instead of keeping "
                        "them warm"),
        AlertRule(
            "recompile_storm",
            [AlertCondition("paddle_jit_compiles_total", 60.0, "avg",
                            ">", 0.2)],
            for_s=0.0, severity="warn",
            description="sustained jit recompilation (> 0.2 compiles/s "
                        "averaged over a minute): shape churn is eating "
                        "the TPU"),
        AlertRule(
            "replica_stalled",
            [AlertCondition("paddle_replica_stalls_total", 60.0, "max",
                            ">", 0.0)],
            for_s=0.0, severity="warn",
            description="a stream-progress watchdog tripped in the last "
                        "minute — a replica connection black-holed or a "
                        "replica stopped producing frames"),
        AlertRule(
            "replica_stalled_sustained",
            [AlertCondition("paddle_replica_stalls_total", 60.0, "avg",
                            ">", 0.02),
             AlertCondition("paddle_replica_stalls_total", 300.0, "avg",
                            ">", 0.005)],
            for_s=0.0, severity="page",
            description="stall-detector trips sustained on both the fast "
                        "and slow window (> ~1/min) — a partial partition "
                        "or a gray-failing replica, not a one-off blip"),
        AlertRule(
            "waste_burn",
            # the goodput plane's sliding-window waste share: sustained
            # over-budget waste on both windows catches hedge storms and
            # spec-rejection storms; a brief hedge burst (the fast window
            # alone) is the feature working as designed, not an alert
            [AlertCondition("paddle_goodput_waste_pct", 60.0, "avg",
                            ">", 50.0),
             AlertCondition("paddle_goodput_waste_pct", 300.0, "avg",
                            ">", 50.0)],
            for_s=0.0, severity="warn",
            description="more than half the decoded tokens are wasted "
                        "(hedge losers / spec rejects / retry discards) "
                        "on both the fast and slow window — the fleet is "
                        "burning chips on work nobody receives"),
        AlertRule(
            "hbm_headroom",
            # published by the memory ledger ONLY on backends that report
            # a device memory limit — on CPU the series never exists and
            # the alert engine's absence-of-data rule keeps this silent
            [AlertCondition("paddle_mem_headroom_ratio", 60.0, "avg",
                            "<", 0.05)],
            for_s=60.0, severity="page",
            description="device memory headroom below 5% for a sustained "
                        "minute — the next admission burst or compile "
                        "workspace spike OOMs the chip"),
        AlertRule(
            "fleet_snapshot_stale",
            [AlertCondition("paddle_fleet_snapshot_age_seconds", 60.0,
                            "last", ">", 3.0 * publish)],
            for_s=0.0, severity="warn",
            description="a rank's fleet snapshot is older than 3x the "
                        "publish interval — its merged view is silently "
                        "stale"),
    ]


# -- module singleton --------------------------------------------------------
_engine: Optional[AlertEngine] = None
_engine_lock = threading.Lock()


def install(history=None, rules: Optional[Sequence[AlertRule]] = None,
            registry=None) -> AlertEngine:
    """Create the engine over the armed history plane and subscribe it to
    the sampler tick (idempotent)."""
    global _engine
    with _engine_lock:
        if _engine is not None:
            return _engine
        if history is None:
            from . import tsdb

            history = tsdb.get()
            if history is None:
                raise RuntimeError("alerts.install() needs tsdb enabled")
        _engine = AlertEngine(history, rules=rules, registry=registry)
        history.add_listener(_engine.evaluate)
        return _engine


def uninstall() -> None:
    global _engine
    with _engine_lock:
        eng, _engine = _engine, None
    if eng is not None and eng.history is not None:
        eng.history.remove_listener(eng.evaluate)


def get() -> Optional[AlertEngine]:
    return _engine


def alerts_body() -> tuple:
    """The ``/alerts`` exporter route: strict JSON either way."""
    eng = get()
    if eng is None:
        doc = {"enabled": False, "rules": []}
    else:
        doc = eng.snapshot()
        doc["enabled"] = True
    return 200, "application/json", json.dumps(doc)
