"""Rank-0 fleet aggregation — one merged view of a whole launched job.

Reference surface: the reference fleet stack aggregates per-worker monitor
stats and multi-worker profiler timelines at the controller
(fleet/monitor + profiler merge tooling); Dapper-style trace correlation
needs a shared clock. Here the existing TCPStore/`host_collectives` control
plane carries the telemetry too — no new transport:

* every worker runs a :class:`FleetPublisher` (daemon thread) that
  periodically writes three store keys —
  ``obs/clock/rank{r}``  (a ``(wall, perf_counter)`` anchor pair),
  ``obs/metrics/rank{r}`` (the Prometheus text of its registry), and
  ``obs/trace/rank{r}``   (its chrome-trace ring buffer, when tracing), and
  ``obs/tsdb/rank{r}``    (a bounded dump of its metric-history rings, when
  the :mod:`~.tsdb` plane is armed) —
  plus a final publish at interpreter exit so a cleanly-exiting worker's
  last snapshot survives it;
* rank 0 (:func:`install_fleet_routes`) swaps its exporter's ``/metrics``
  for :func:`merged_fleet_metrics` — every sample from every rank,
  re-labeled ``rank="r"`` via the strict exposition parser — and adds
  ``/fleet/trace`` (:func:`collect_fleet_trace`: per-rank chrome traces
  merged into ONE Perfetto file, one ``pid`` per rank),
  ``/fleet/ranks`` (who has published, how stale) and ``/fleet/query``
  (:func:`collect_fleet_tsdb`: every rank's metric history, keyed by rank
  — the seam that survives the multi-process ``ReplicaClient`` hop
  unchanged, because history rides the store, not process memory).

Clock correlation: each rank's recorder timestamps are ``perf_counter``
microseconds with a process-private epoch. The published ``(wall, perf)``
anchor lets the merger compute per-rank offsets onto the reference rank's
timeline (wall clocks are NTP-disciplined across hosts; the residual error
is far below the DCN latencies being eyeballed). Estimation and transport
both ride the store — no direct worker-to-worker connections.
"""

from __future__ import annotations

import atexit
import json
import sys
import threading
import time
from typing import Dict, Optional, Tuple

from ..core import flags as _flags
from .metrics import (
    Registry,
    _esc,
    format_value,
    parse_prometheus_text,
)

__all__ = [
    "FleetPublisher", "merge_prometheus_texts", "merge_chrome_traces",
    "collect_fleet_metrics", "merged_fleet_metrics", "collect_fleet_trace",
    "collect_fleet_tsdb", "fleet_status", "install_fleet_routes",
    "metrics_key", "clock_key", "trace_key", "tsdb_key",
]


def metrics_key(rank: int) -> str:
    return f"obs/metrics/rank{rank}"


def clock_key(rank: int) -> str:
    return f"obs/clock/rank{rank}"


def trace_key(rank: int) -> str:
    return f"obs/trace/rank{rank}"


def tsdb_key(rank: int) -> str:
    return f"obs/tsdb/rank{rank}"


def prof_key(rank: int) -> str:
    return f"obs/profile/rank{rank}"


def _clock_sample() -> dict:
    return {"wall": time.time(), "perf": time.perf_counter()}


class FleetPublisher:
    """Periodic snapshot publication from one worker into the store.

    ``text_fn``/``trace_fn`` are injectable for tests (and for embedding a
    foreign registry); the defaults read this process's observability
    state. Publishing never raises into the training loop — a dead store
    is logged once and retried next interval."""

    def __init__(self, store, rank: int, interval_s: Optional[float] = None,
                 text_fn=None, trace_fn=None, tsdb_fn=None):
        self.store = store
        self.rank = int(rank)
        self.interval_s = float(
            interval_s if interval_s is not None
            else _flags.flag_value("obs_publish_interval_s"))
        self._text_fn = text_fn
        self._trace_fn = trace_fn
        self._tsdb_fn = tsdb_fn
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None
        self._warned = False
        self._last_trace_sig = None  # skip unchanged-trace republication

    # -- one publication -----------------------------------------------------
    def publish(self) -> None:
        clock = _clock_sample()
        self.store.set(clock_key(self.rank), json.dumps(clock))
        if self._text_fn is not None:
            text = self._text_fn()
        else:
            from . import to_prometheus_text

            text = to_prometheus_text()
        self.store.set(metrics_key(self.rank), json.dumps(
            {"wall": clock["wall"], "rank": self.rank, "prom": text}))
        doc = None
        if self._trace_fn is not None:
            doc = self._trace_fn()
        else:
            # gate on the RUNTIME tracing state (enable(trace=True) and the
            # env flag both set it), not the flag alone — and skip the
            # re-serialize + multi-MB store.set entirely when the ring has
            # not changed since the last publish (each store request holds
            # the client's wire mutex, stalling concurrent collective ops)
            from . import _recorder_if_tracing

            rec = _recorder_if_tracing()
            if rec is not None:
                sig = rec.signature()
                if sig != self._last_trace_sig:
                    self._last_trace_sig = sig
                    doc = rec.to_chrome_trace()
        if doc is not None:
            self.store.set(trace_key(self.rank), json.dumps(
                {"wall": clock["wall"], "trace": doc}))
        hist = None
        if self._tsdb_fn is not None:
            hist = self._tsdb_fn()
        else:
            # publish only when the history plane is armed: the key's
            # absence tells the rank-0 merge "this rank keeps no history",
            # which is different from "stale"
            from . import tsdb as _tsdb

            h = _tsdb.get()
            if h is not None:
                hist = h.jsonable()
        if hist is not None:
            self.store.set(tsdb_key(self.rank), json.dumps(
                {"wall": clock["wall"], "rank": self.rank, "tsdb": hist}))
        # sampling-profiler hot stacks: published only when the profiler
        # is armed — the key's absence tells the rank-0 merge "this rank
        # does not profile", not "stale"
        from . import profiler as _profiler

        prof = _profiler.get()
        if prof is not None:
            self.store.set(prof_key(self.rank), json.dumps(
                {"wall": clock["wall"], "rank": self.rank,
                 "profile": prof.jsonable(seconds=None)}))

    def _publish_safe(self) -> None:
        try:
            self.publish()
            self._warned = False
        except Exception as e:
            if not self._warned:  # say it once, not every interval
                self._warned = True
                sys.stderr.write(
                    f"[obs] fleet publish failed (rank {self.rank}): "
                    f"{e!r}; retrying each interval\n")

    def _loop(self) -> None:
        self._publish_safe()  # first snapshot immediately, not after a wait
        while not self._stop.wait(self.interval_s):
            self._publish_safe()

    # -- lifecycle -----------------------------------------------------------
    def start(self) -> "FleetPublisher":
        if self._thread is None:
            self._stop.clear()  # restartable: stop() leaves the event set
            self._thread = threading.Thread(
                target=self._loop, daemon=True,
                name=f"obs-fleet-publisher:{self.rank}")
            self._thread.start()
            # a worker that exits cleanly between intervals must still leave
            # its final counters behind for the rank-0 merge
            atexit.register(self._publish_safe)
        return self

    def stop(self, final_publish: bool = True) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=5)
            self._thread = None
        # a stopped publisher must stay stopped: without the unregister,
        # stop(final_publish=False) would still publish at interpreter
        # exit, and start/stop cycles would stack exit callbacks
        atexit.unregister(self._publish_safe)
        if final_publish:
            self._publish_safe()


# ---------------------------------------------------------------------------
# metric merge
# ---------------------------------------------------------------------------

def merge_prometheus_texts(texts_by_rank: Dict[int, str],
                           label: str = "rank") -> str:
    """Merge per-rank exposition texts into one, adding ``label="r"`` to
    every sample (existing ``rank`` labels are preserved, not clobbered).
    HELP/TYPE are emitted once per family; a family whose type disagrees
    across ranks raises (that is a bug, not a merge policy question)."""
    merged: Dict[str, dict] = {}
    for rank in sorted(texts_by_rank):
        for name, fam in parse_prometheus_text(texts_by_rank[rank]).items():
            m = merged.setdefault(
                name, {"help": fam["help"], "type": fam["type"], "rows": []})
            if m["type"] != fam["type"]:
                raise ValueError(
                    f"metric {name!r} is {m['type']} on one rank and "
                    f"{fam['type']} on rank {rank}")
            for sample_name, labels, value in fam["samples"]:
                row_labels = dict(labels)
                row_labels.setdefault(label, str(rank))
                m["rows"].append((sample_name, row_labels, value))
    lines = []
    for name, m in merged.items():
        lines.append(f"# HELP {name} {m['help']}")
        lines.append(f"# TYPE {name} {m['type']}")
        for sample_name, labels, value in m["rows"]:
            if labels:
                inner = ",".join(f'{k}="{_esc(str(v))}"'
                                 for k, v in labels.items())
                lines.append(f"{sample_name}{{{inner}}} {format_value(value)}")
            else:
                lines.append(f"{sample_name} {format_value(value)}")
    return "\n".join(lines) + ("\n" if lines else "")


def collect_fleet_metrics(store, world: int,
                          local_rank: Optional[int] = None,
                          local_text_fn=None
                          ) -> Tuple[Dict[int, str], Dict[int, float]]:
    """Pull every rank's published exposition text from the store.
    ``local_rank`` (rank 0 serving the merge) reads its own registry LIVE
    instead of its last published snapshot. Returns ``(texts_by_rank,
    wall_by_rank)``; ranks that have not published yet are absent — the
    merge must not block a scrape on a straggler."""
    texts: Dict[int, str] = {}
    walls: Dict[int, float] = {}
    for r in range(int(world)):
        if local_rank is not None and r == int(local_rank):
            if local_text_fn is not None:
                texts[r] = local_text_fn()
            else:
                from . import to_prometheus_text

                texts[r] = to_prometheus_text()
            walls[r] = time.time()
            continue
        try:
            if not store.check(metrics_key(r)):
                continue
            doc = json.loads(store.get(metrics_key(r)))
        except Exception:
            continue  # a dead rank must not fail the whole scrape
        texts[r] = doc.get("prom", "")
        walls[r] = float(doc.get("wall", 0.0))
    return texts, walls


def merged_fleet_metrics(store, world: int,
                         local_rank: Optional[int] = None,
                         local_text_fn=None) -> str:
    """The fleet ``/metrics`` body: every reporting rank's samples with a
    ``rank`` label, plus ``paddle_fleet_*`` meta-series describing the
    aggregation itself (how many ranks merged, per-rank snapshot age)."""
    texts, walls = collect_fleet_metrics(store, world, local_rank,
                                         local_text_fn)
    now = time.time()
    meta = Registry()
    meta.gauge("paddle_fleet_world_size",
               "world size of the launched job").set(int(world))
    meta.gauge("paddle_fleet_ranks_reporting",
               "ranks whose snapshot was merged into this scrape"
               ).set(len(texts))
    age = meta.gauge("paddle_fleet_snapshot_age_seconds",
                     "age of each merged rank snapshot at scrape time")
    for r, wall in sorted(walls.items()):
        age.set(max(0.0, now - wall), rank=str(r))
    return merge_prometheus_texts(texts) + meta.to_prometheus_text()


# ---------------------------------------------------------------------------
# trace merge
# ---------------------------------------------------------------------------

def merge_chrome_traces(docs_by_rank: Dict[int, dict],
                        clocks_by_rank: Optional[Dict[int, dict]] = None
                        ) -> dict:
    """Merge per-rank chrome-trace docs into one Perfetto-loadable file:
    every event gets ``pid = rank`` (plus ``process_name`` /
    ``process_sort_index`` metadata so Perfetto shows "rank r" tracks in
    order), and — when clock anchors are available — each rank's
    ``perf_counter`` timestamps are shifted onto the lowest rank's
    timeline via the published ``(wall, perf)`` anchors."""
    if not docs_by_rank:
        return {"traceEvents": [], "displayTimeUnit": "ms"}
    clocks = clocks_by_rank or {}
    ref = min(docs_by_rank)
    ref_anchor = None
    if ref in clocks:
        ref_anchor = clocks[ref]["wall"] - clocks[ref]["perf"]
    events = []
    for rank in sorted(docs_by_rank):
        offset_us = 0
        if ref_anchor is not None and rank in clocks:
            anchor = clocks[rank]["wall"] - clocks[rank]["perf"]
            offset_us = int(round((anchor - ref_anchor) * 1e6))
        events.append({"name": "process_name", "ph": "M", "pid": rank,
                       "tid": 0, "args": {"name": f"rank {rank}"}})
        events.append({"name": "process_sort_index", "ph": "M", "pid": rank,
                       "tid": 0, "args": {"sort_index": rank}})
        for ev in docs_by_rank[rank].get("traceEvents", []):
            out = dict(ev)
            out["pid"] = rank
            if "ts" in out:
                out["ts"] = int(out["ts"]) + offset_us
            events.append(out)
    return {"traceEvents": events, "displayTimeUnit": "ms"}


def collect_fleet_trace(store, world: int,
                        local_rank: Optional[int] = None,
                        local_trace_fn=None) -> dict:
    """Pull every rank's published trace + clock anchor and merge."""
    docs: Dict[int, dict] = {}
    clocks: Dict[int, dict] = {}
    for r in range(int(world)):
        try:
            if local_rank is not None and r == int(local_rank):
                if local_trace_fn is not None:
                    docs[r] = local_trace_fn()
                else:
                    from . import get_recorder

                    docs[r] = get_recorder().to_chrome_trace()
                clocks[r] = _clock_sample()
                continue
            if store.check(trace_key(r)):
                docs[r] = json.loads(store.get(trace_key(r)))["trace"]
            if store.check(clock_key(r)):
                clocks[r] = json.loads(store.get(clock_key(r)))
        except Exception:
            continue
    return merge_chrome_traces(docs, clocks)


def _filter_tsdb_doc(doc: dict, selector: Optional[str],
                     window_s: Optional[float], now: float) -> dict:
    """Shape one rank's published tsdb dump like a live ``/query`` answer:
    matched series, best tier for the window (raw while it still covers
    the window's start, else coarse — coarse points re-emit as their
    mean)."""
    from . import tsdb as _tsdb

    series = doc.get("series", {})
    rows = []
    for sid in _tsdb.match_series(series.keys(), selector):
        ent = series[sid]
        raw = ent.get("raw") or []
        coarse = ent.get("coarse") or []
        tier, pts = "raw", raw
        if window_s is not None:
            cutoff = now - float(window_s)
            if raw and raw[0][0] > cutoff and coarse:
                tier, pts = "coarse", coarse
            pts = [p for p in pts if p[0] >= cutoff]
        rows.append({"id": sid, "kind": ent.get("kind", "gauge"),
                     "tier": tier, "points": [[p[0], p[1]] for p in pts]})
    return {"interval_s": doc.get("interval_s"), "series": rows}


def collect_fleet_tsdb(store, world: int, local_rank: Optional[int] = None,
                       selector: Optional[str] = None,
                       window_s: Optional[float] = None) -> dict:
    """The ``/fleet/query`` body: every rank's published metric history,
    keyed by rank. The serving rank answers from its live store; ranks
    without a published ``obs/tsdb/rank{r}`` key (history plane off, or
    not yet published) are absent from ``ranks``."""
    from . import tsdb as _tsdb

    now = time.time()
    ranks: Dict[str, dict] = {}
    for r in range(int(world)):
        if local_rank is not None and r == int(local_rank):
            h = _tsdb.get()
            if h is not None:
                live = h.query(selector, window_s)
                ranks[str(r)] = {"wall": now, "interval_s": live["interval_s"],
                                 "series": live["series"]}
            continue
        try:
            if not store.check(tsdb_key(r)):
                continue
            doc = json.loads(store.get(tsdb_key(r)))
        except Exception:
            continue  # a dead rank must not fail the whole query
        body = _filter_tsdb_doc(doc.get("tsdb", {}), selector, window_s, now)
        ranks[str(r)] = {"wall": doc.get("wall"), **body}
    return {"now": now, "world": int(world), "window_s": window_s,
            "series_selector": selector, "ranks": ranks}


def collect_fleet_profile(store, world: int,
                          local_rank: Optional[int] = None,
                          seconds: Optional[float] = None,
                          top: int = 30) -> dict:
    """The ``/fleet/profile`` body: every profiling rank's hot stacks
    keyed by rank, plus a fleet-wide merge (summed category counts and
    the top folded stacks across ranks — same-shape stacks on different
    ranks add up, which is exactly what a fleet flamegraph wants)."""
    from . import profiler as _profiler

    now = time.time()
    ranks: Dict[str, dict] = {}
    for r in range(int(world)):
        if local_rank is not None and r == int(local_rank):
            prof = _profiler.get()
            if prof is not None:
                ranks[str(r)] = {"wall": now,
                                 **prof.jsonable(seconds, top)}
            continue
        try:
            if not store.check(prof_key(r)):
                continue
            doc = json.loads(store.get(prof_key(r)))
        except Exception:
            continue  # a dead rank must not fail the whole merge
        ranks[str(r)] = {"wall": doc.get("wall"), **doc.get("profile", {})}
    cats: Dict[str, int] = {}
    stacks: Dict[str, int] = {}
    for body in ranks.values():
        for cat, n in (body.get("categories") or {}).items():
            cats[cat] = cats.get(cat, 0) + int(n)
        for row in body.get("top") or []:
            stacks[row["stack"]] = (stacks.get(row["stack"], 0)
                                    + int(row["samples"]))
    total = sum(stacks.values())
    # same ranking rule as SamplingProfiler.hot_stacks: burning stacks
    # first, parked (idle) stacks after all of them regardless of count
    ranked = sorted(stacks.items(),
                    key=lambda kv: (kv[0].startswith("idle;"), -kv[1],
                                    kv[0]))
    merged_top = [{"stack": s, "samples": n,
                   "category": s.split(";", 1)[0],
                   "pct": round(100.0 * n / total, 2) if total else 0.0}
                  for s, n in ranked[:max(top, 0)]]
    return {"now": now, "world": int(world), "query_seconds": seconds,
            "ranks": ranks,
            "merged": {"categories": dict(
                sorted(cats.items(), key=lambda kv: -kv[1])),
                "top": merged_top}}


def fleet_status(store, world: int) -> dict:
    """Who has published, and how stale — the ``/fleet/ranks`` body.
    Reads the few-dozen-byte clock anchor for the age, not the full
    metrics blob (same publication cycle, a fraction of the transfer)."""
    now = time.time()
    ranks = {}
    for r in range(int(world)):
        try:
            published = bool(store.check(metrics_key(r)))
            age = None
            if published and store.check(clock_key(r)):
                age = round(
                    now - json.loads(store.get(clock_key(r)))["wall"], 3)
            ranks[str(r)] = {"published": published, "age_s": age}
        except Exception as e:
            ranks[str(r)] = {"published": False,
                             "error": f"{type(e).__name__}: {e}"}
    return {"world": int(world), "ranks": ranks}


def install_fleet_routes(exporter, store, world: int,
                         local_rank: int = 0) -> None:
    """Turn one rank's exporter into the fleet view: ``/metrics`` becomes
    the rank-labeled merge (the per-rank view stays at
    ``/metrics/local``), ``/fleet/trace`` serves the merged Perfetto file,
    ``/fleet/ranks`` the publication status."""
    from .exporter import PROM_CONTENT_TYPE

    local = exporter._routes.get("/metrics")
    if local is not None:
        exporter.register_route("/metrics/local", local)
    exporter.register_route("/metrics", lambda: (
        200, PROM_CONTENT_TYPE,
        merged_fleet_metrics(store, world, local_rank)))
    exporter.register_route("/fleet/trace", lambda: (
        200, "application/json",
        json.dumps(collect_fleet_trace(store, world, local_rank))))
    exporter.register_route("/fleet/ranks", lambda: (
        200, "application/json", json.dumps(fleet_status(store, world))))

    def _fleet_query(params):
        try:
            window_s = (float(params["window"])
                        if params.get("window") else None)
        except ValueError as e:
            return (400, "application/json",
                    json.dumps({"error": f"bad parameter: {e}"}))
        return (200, "application/json", json.dumps(collect_fleet_tsdb(
            store, world, local_rank, params.get("series") or None,
            window_s)))

    exporter.register_param_route("/fleet/query", _fleet_query)

    def _fleet_profile(params):
        try:
            seconds = (float(params["seconds"])
                       if params.get("seconds") else None)
            top = int(params["top"]) if params.get("top") else 30
        except ValueError as e:
            return (400, "application/json",
                    json.dumps({"error": f"bad parameter: {e}"}))
        return (200, "application/json", json.dumps(collect_fleet_profile(
            store, world, local_rank, seconds, top), default=str))

    exporter.register_param_route("/fleet/profile", _fleet_profile)
