"""Performance attribution plane — measured rooflines, step decomposition.

Three tools on top of the recorder/metrics/exporter pipeline:

* :mod:`~.costs` — program cost registry: exact XLA ``cost_analysis()``
  FLOPs/bytes per compiled program (train step, the decode engine's
  bucketed programs, static ``run_program``), combined with measured
  wall time and the device peak specs (:mod:`~.device`) into measured
  MFU, bandwidth utilization and a compute-vs-bandwidth-bound roofline
  classification. Exported as ``paddle_program_*`` gauges, the
  exporter's ``/programs`` endpoint, and ``obsctl programs``;
* :mod:`~.steptime` — :class:`~.steptime.StepTimeline`: per-step phase
  breakdown (compute / host dispatch / comm / data-wait) diffed from the
  recorder's category aggregates, rendered in ``summary()`` and as
  Perfetto counter tracks;
* request-lifecycle SLO tracing lives in the serving engine itself
  (TTFT/TPOT/queue-wait histograms through the standard serving hook)
  — this package only defines the arming switch they share.

Off by default: arm with ``PADDLE_OBS_PERF=1`` / ``FLAGS_obs_perf`` or
:func:`enable`. When off, instrumented call sites pay one cached-module
attribute read; when on, cost capture happens ONCE per compiled program
(riding the AOT compile the call site was going to do anyway) and wall
observation is a dict update per execution.
"""

from __future__ import annotations

from typing import Optional

from ...core import flags as _flags
from . import costs, device, steptime  # noqa: F401
from .costs import (  # noqa: F401
    CostRegistry,
    capture_jit,
    cost_of_jit,
    cost_of_lowered,
    observe,
    registry,
    table_jsonable,
)
from .steptime import StepTimeline  # noqa: F401

_enabled = False
_timeline: Optional[StepTimeline] = None


def enabled() -> bool:
    return _enabled


def enable() -> None:
    """Arm cost capture + SLO attribution (idempotent). Programs compiled
    BEFORE enabling are not retro-captured — arm before building engines
    / train steps (or set ``PADDLE_OBS_PERF=1`` in the environment)."""
    global _enabled
    _enabled = True
    _flags.set_flags({"obs_perf": True})
    # crash dumps carry the live program-cost table: resolved at dump
    # time (flight supports callable annotations), so the black box of a
    # dying serving host names its programs and their measured rooflines
    try:
        from .. import flight

        flight.annotate("program_costs",
                        lambda: registry().table())
    except Exception:
        pass


def disable() -> None:
    global _enabled
    _enabled = False
    _flags.set_flags({"obs_perf": False})


def reset() -> None:
    """Clear captured costs, observations and the step timeline."""
    registry().clear()
    if _timeline is not None:
        _timeline.clear()


def timeline() -> StepTimeline:
    """The module StepTimeline (created on first use; ``summary()`` renders
    it when it has steps)."""
    global _timeline
    if _timeline is None:
        _timeline = StepTimeline()
    return _timeline


def step(name: str = "step"):
    """Convenience: ``with obs.perf.step("train"): ...`` brackets one step
    on the module timeline."""
    return timeline().step(name)


def publish_gauges() -> None:
    """Mirror the cost table into ``paddle_program_*`` gauges on the
    observability registry (called from ``to_prometheus_text()``)."""
    from .. import get_registry

    costs.publish_gauges(get_registry())


# arm from env (PADDLE_OBS_PERF) at import — same contract as the other
# obs subsystems
if _flags.flag_value("obs_perf"):
    enable()

__all__ = [
    "enabled", "enable", "disable", "reset",
    "capture_jit", "cost_of_jit", "cost_of_lowered", "observe", "registry",
    "table_jsonable", "publish_gauges",
    "timeline", "step", "StepTimeline", "CostRegistry",
    "costs", "device", "steptime",
]
