"""Program cost registry — exact XLA FLOPs/bytes per compiled program,
combined with measured wall time into per-program roofline numbers.

Reference surface: ``paddle.profiler``'s kernel statistics tables (per-
kernel FLOPs and occupancy in the GPU profiler summary). TPU-native
equivalent: XLA's own ``Compiled.cost_analysis()`` — the compiler counts
the FLOPs and HBM bytes of the exact program it emitted, so MFU stops
being an analytic approximation (``bench.py``'s ``6N`` convention, the
ResNet ``3x4.1 GFLOP/image`` guess) and becomes a measurement.

Capture rides the AOT path: :func:`capture_jit` lowers + compiles a
jitted callable at a concrete argument signature, records the cost, and
returns the ``Compiled`` object so the call site can EXECUTE through it —
one compile total, not jit-compile + AOT-compile. Call sites observe wall
time per execution with :func:`CostRegistry.observe`; the registry then
derives, per (program, shape-bucket):

* ``mfu``      — flops / (min_wall * peak_flops): achieved fraction of
  the chip's matmul peak at the program's best observed wall time;
* ``hbm_util`` — bytes / (min_wall * peak_bw): achieved fraction of HBM
  bandwidth;
* ``intensity`` (flops/byte) vs the device ridge point -> ``bound``
  ("compute" or "bandwidth") and ``pct_of_peak`` against the respective
  peak — the roofline classification.

Everything is guarded: a backend without ``cost_analysis`` (or an AOT
quirk) degrades to returning ``None`` and the call site keeps its
original jitted function. Never raises into a hot path.
"""

from __future__ import annotations

import sys
import threading
from typing import Dict, List, Optional, Tuple

from . import device as _device


class ProgramCost:
    """Cost + timing accumulator for one (program, bucket)."""

    __slots__ = ("name", "bucket", "flops", "bytes_accessed", "bytes_out",
                 "calls", "wall_total", "wall_min", "meta")

    def __init__(self, name: str, bucket: str):
        self.name = name
        self.bucket = bucket
        self.flops: Optional[float] = None
        self.bytes_accessed: Optional[float] = None
        self.bytes_out: Optional[float] = None
        self.calls = 0
        self.wall_total = 0.0
        self.wall_min = float("inf")
        self.meta: Dict[str, object] = {}

    def derived(self, specs: dict) -> dict:
        """One row of the /programs table: raw cost + roofline numbers."""
        row = {
            "program": self.name,
            "bucket": self.bucket,
            "flops": self.flops,
            "hbm_bytes": self.bytes_accessed,
            "out_bytes": self.bytes_out,
            "calls": self.calls,
            "wall_s_min": None if self.calls == 0 else self.wall_min,
            "wall_s_avg": (None if self.calls == 0
                           else self.wall_total / self.calls),
        }
        row.update(self.meta)
        f, b = self.flops, self.bytes_accessed
        if f is not None and b and b > 0:
            ai = f / b
            row["intensity_flops_per_byte"] = ai
            row["bound"] = ("compute" if ai >= specs["ridge_flops_per_byte"]
                            else "bandwidth")
        if self.calls and self.wall_min > 0:
            if f is not None:
                row["mfu"] = f / (self.wall_min * specs["peak_flops"])
            if b is not None:
                row["hbm_util"] = b / (self.wall_min
                                       * specs["peak_hbm_bytes_per_s"])
            bound = row.get("bound")
            if bound == "compute" and "mfu" in row:
                row["pct_of_peak"] = row["mfu"]
            elif bound == "bandwidth" and "hbm_util" in row:
                row["pct_of_peak"] = row["hbm_util"]
        return row


def parse_cost_analysis(ca) -> Tuple[Optional[float], Optional[float],
                                     Optional[float]]:
    """(flops, bytes_accessed, output_bytes) from whatever shape the
    backend's ``cost_analysis()`` returns (dict, or list of per-module
    dicts — summed). None fields where the backend doesn't report."""
    if ca is None:
        return None, None, None
    mods = ca if isinstance(ca, (list, tuple)) else [ca]
    flops = byts = out = None
    for d in mods:
        if not isinstance(d, dict):
            continue
        f = d.get("flops")
        b = d.get("bytes accessed")
        o = d.get("bytes accessedout{}")
        if f is not None:
            flops = (flops or 0.0) + float(f)
        if b is not None:
            byts = (byts or 0.0) + float(b)
        if o is not None:
            out = (out or 0.0) + float(o)
    return flops, byts, out


class CostRegistry:
    """Thread-safe store of :class:`ProgramCost` rows keyed by
    (program name, shape bucket)."""

    def __init__(self):
        self._lock = threading.Lock()
        self._programs: Dict[Tuple[str, str], ProgramCost] = {}

    def _get(self, name: str, bucket: str) -> ProgramCost:
        key = (str(name), str(bucket))
        with self._lock:
            pc = self._programs.get(key)
            if pc is None:
                pc = self._programs[key] = ProgramCost(*key)
            return pc

    def record(self, name: str, flops=None, bytes_accessed=None,
               bytes_out=None, bucket: str = "", **meta) -> ProgramCost:
        """Register (or update) a program's compiler-reported cost."""
        pc = self._get(name, bucket)
        if flops is not None:
            pc.flops = float(flops)
        if bytes_accessed is not None:
            pc.bytes_accessed = float(bytes_accessed)
        if bytes_out is not None:
            pc.bytes_out = float(bytes_out)
        if meta:
            pc.meta.update(meta)
        return pc

    def observe(self, name: str, wall_s: float, bucket: str = "") -> None:
        """Fold one measured execution wall time into the program's row
        (creates the row if cost capture hasn't happened / failed)."""
        pc = self._get(name, bucket)
        wall_s = float(wall_s)
        with self._lock:
            pc.calls += 1
            pc.wall_total += wall_s
            if wall_s < pc.wall_min:
                pc.wall_min = wall_s

    def programs(self) -> List[ProgramCost]:
        with self._lock:
            return list(self._programs.values())

    def table(self, specs: Optional[dict] = None) -> List[dict]:
        """Derived rows (roofline numbers included), MFU-descending."""
        if specs is None:
            try:
                specs = _device.specs()
            except Exception:   # no jax backend: raw costs, no roofline
                specs = {"peak_flops": 0.0, "peak_hbm_bytes_per_s": 0.0,
                         "ridge_flops_per_byte": float("inf")}
        rows = [pc.derived(specs) for pc in self.programs()]
        rows.sort(key=lambda r: -(r.get("mfu") or 0.0))
        return rows

    def clear(self) -> None:
        with self._lock:
            self._programs.clear()


_registry = CostRegistry()


def registry() -> CostRegistry:
    return _registry


def observe(name: str, wall_s: float, bucket: str = "") -> None:
    _registry.observe(name, wall_s, bucket=bucket)


def capture_jit(name: str, jit_fn, args: tuple = (), kwargs=None,
                bucket: str = "", **meta):
    """AOT lower + compile ``jit_fn`` at ``args``' signature, record its
    ``cost_analysis()`` under ``(name, bucket)``, and return the
    ``Compiled`` stage so the caller executes through it (one compile
    total; donation declared at ``jax.jit`` time is preserved).

    Returns None on ANY failure — the caller keeps its original jitted
    function and the only trace is a one-line stderr note plus a
    ``paddle_program_capture_failures_total`` counter. Cost capture must
    never be the thing that breaks a train step or a serving engine.
    """
    try:
        compiled = jit_fn.lower(*args, **(kwargs or {})).compile()
    except Exception as e:
        _capture_failed(name, e)
        return None
    try:
        flops, byts, out = parse_cost_analysis(compiled.cost_analysis())
        _registry.record(name, flops=flops, bytes_accessed=byts,
                         bytes_out=out, bucket=bucket,
                         cost_source="compiled", **meta)
    except Exception as e:
        # compiled fine but the cost query failed: still usable for
        # execution; record the row with no cost so /programs names it
        _registry.record(name, bucket=bucket, **meta)
        _capture_failed(name, e)
    return compiled


def cost_of_jit(name: str, jit_fn, args: tuple = (), kwargs=None,
                bucket: str = "", **meta) -> Optional[dict]:
    """Capture + record like :func:`capture_jit` but return the parsed
    cost dict instead of the Compiled (for callers that only want the
    numbers, e.g. a bench recording the analytic-vs-measured delta)."""
    compiled = capture_jit(name, jit_fn, args, kwargs, bucket=bucket, **meta)
    if compiled is None:
        return None
    pc = _registry._get(name, bucket)
    return {"flops": pc.flops, "bytes_accessed": pc.bytes_accessed,
            "bytes_out": pc.bytes_out, "compiled": compiled}


def cost_of_lowered(name: str, jit_fn, args: tuple = (), kwargs=None,
                    bucket: str = "", scale: float = 1.0,
                    record: bool = True, **meta) -> Optional[dict]:
    """Trace + lower ``jit_fn`` (NO backend compile — milliseconds, safe
    to do for a program the caller will never execute) and record the
    cost of the PRE-optimization HLO, scaled by ``scale``.

    Two uses where :func:`capture_jit` is wrong:

    * a program whose executed form wraps the interesting body in a
      ``lax.scan`` — XLA's cost analysis counts a loop body ONCE
      regardless of trip count, so the caller lowers a length-1 variant
      and passes ``scale=chunk`` (recorded in ``meta`` so the row says
      how its flops were derived);
    * a side measurement where an extra backend compile is unaffordable
      (the bench's single-step cost next to its chain timing).

    FLOP counts are identical pre/post optimization for the matmul-
    dominated programs this measures; BYTES from unoptimized HLO
    overcount real HBM traffic (fusion elides intermediates), so rows
    carry ``cost_source="lowered"`` and bandwidth numbers should be read
    as upper bounds. Returns the cost dict or None on failure.
    """
    try:
        lowered = jit_fn.lower(*args, **(kwargs or {}))
        flops, byts, out = parse_cost_analysis(lowered.cost_analysis())
    except Exception as e:
        _capture_failed(name, e)
        return None
    if scale != 1.0:
        flops = None if flops is None else flops * scale
        byts = None if byts is None else byts * scale
        out = None if out is None else out * scale
        meta.setdefault("cost_scale", scale)
    if record:
        _registry.record(name, flops=flops, bytes_accessed=byts,
                         bytes_out=out, bucket=bucket,
                         cost_source="lowered", **meta)
    return {"flops": flops, "bytes_accessed": byts, "bytes_out": out}


def _capture_failed(name: str, e: Exception) -> None:
    try:
        from .. import safe_inc

        safe_inc("paddle_program_capture_failures_total",
                 "program cost captures that failed (AOT compile or "
                 "cost_analysis)", program=name)
        sys.stderr.write(
            f"[obs.perf] cost capture for {name!r} failed: "
            f"{type(e).__name__}: {e}\n")
    except Exception:
        pass


# -- export ------------------------------------------------------------------

def table_jsonable() -> dict:
    """The /programs endpoint body: device specs + derived program rows
    (strict JSON — non-finite values nulled)."""
    import math

    try:
        specs = _device.specs()
    except Exception:
        specs = None

    def scrub(v):
        if isinstance(v, float) and not math.isfinite(v):
            return None
        return v

    rows = [{k: scrub(v) for k, v in r.items()}
            for r in _registry.table(specs)]
    return {"device": specs, "programs": rows}


def publish_gauges(metrics_registry) -> None:
    """Mirror the derived table into ``paddle_program_*`` gauges on the
    given metrics registry — called lazily from ``to_prometheus_text()``
    so every /metrics scrape sees fresh roofline numbers without any
    per-step publication cost."""
    rows = _registry.table()
    if not rows:
        return
    g = {
        "flops": metrics_registry.gauge(
            "paddle_program_flops",
            "XLA cost_analysis FLOPs per execution of the program"),
        "hbm_bytes": metrics_registry.gauge(
            "paddle_program_hbm_bytes",
            "XLA cost_analysis bytes accessed per execution"),
        "calls": metrics_registry.gauge(
            "paddle_program_calls",
            "observed executions folded into the program's timing"),
        "wall_s_min": metrics_registry.gauge(
            "paddle_program_wall_seconds_min",
            "best observed wall time of one execution"),
        "mfu": metrics_registry.gauge(
            "paddle_program_mfu",
            "measured FLOPs / (best wall * device peak FLOP/s)"),
        "hbm_util": metrics_registry.gauge(
            "paddle_program_hbm_util",
            "accessed bytes / (best wall * device peak HBM bandwidth)"),
    }
    bound = metrics_registry.gauge(
        "paddle_program_compute_bound",
        "roofline classification (1 = compute-bound, 0 = bandwidth-bound)")
    for row in rows:
        labels = {"program": row["program"], "bucket": row["bucket"]}
        for key, gauge in g.items():
            v = row.get(key)
            if v is not None:
                gauge.set(float(v), **labels)
        if row.get("bound") is not None:
            bound.set(1.0 if row["bound"] == "compute" else 0.0, **labels)


def render_table(rows: List[dict]) -> str:
    """Human-readable table over derived rows (summary() and obsctl)."""

    def fnum(v, unit=""):
        if v is None:
            return "-"
        for scale, suf in ((1e12, "T"), (1e9, "G"), (1e6, "M"), (1e3, "k")):
            if abs(v) >= scale:
                return f"{v / scale:.2f}{suf}{unit}"
        return f"{v:.3g}{unit}"

    lines = [f"{'Program':<28}{'Bucket':>10}{'Calls':>7}{'FLOPs':>9}"
             f"{'Bytes':>9}{'Wall(ms)':>10}{'MFU':>7}{'BW%':>7}  Bound"]
    for r in rows:
        wall = r.get("wall_s_min")
        mfu = r.get("mfu")
        bw = r.get("hbm_util")
        lines.append(
            f"{r['program'][:28]:<28}{r['bucket'][:10]:>10}"
            f"{r.get('calls', 0):>7}{fnum(r.get('flops')):>9}"
            f"{fnum(r.get('hbm_bytes')):>9}"
            f"{'-' if wall is None else f'{wall * 1e3:.3f}':>10}"
            f"{'-' if mfu is None else f'{mfu:.3f}':>7}"
            f"{'-' if bw is None else f'{bw * 100:.1f}':>7}"
            f"  {r.get('bound', '-')}")
    return "\n".join(lines)
