"""Device peak specs — the denominators of every roofline number.

One table for peak matmul FLOP/s (the MFU denominator ``bench.py`` has
used since round 1, moved here so the cost registry and the bench share
one definition) and one for peak HBM bandwidth (the bandwidth-bound half
of the roofline). Values are the published per-chip peaks for the bf16
MXU path; unknown accelerators fall back to the v4 numbers, CPU to
deliberately tiny figures so CPU smoke runs still produce finite,
obviously-not-a-TPU utilization numbers.
"""

from __future__ import annotations

from typing import Optional

# (device_kind substring, peak bf16 FLOP/s, peak HBM bytes/s)
_TABLE = [
    ("v6", 918e12, 1640e9),   # Trillium
    ("v5p", 459e12, 2765e9),
    ("v5", 197e12, 819e9),    # v5 lite (v5e)
    ("v4", 275e12, 1228e9),
    ("v3", 123e12, 900e9),
    ("v2", 45e12, 700e9),
]
_DEFAULT_ACCEL = (275e12, 1228e9)   # unknown accelerator: assume v4-class
_DEFAULT_CPU = (1e12, 100e9)        # container CPU: keeps ratios finite


def _lookup(device) -> tuple:
    kind = getattr(device, "device_kind", "").lower()
    for key, flops, bw in _TABLE:
        if key in kind:
            return flops, bw
    if getattr(device, "platform", "cpu") in ("tpu", "axon"):
        return _DEFAULT_ACCEL
    return _DEFAULT_CPU


def peak_flops(device=None) -> float:
    """Peak bf16 matmul FLOP/s for ``device`` (default: jax.devices()[0])."""
    return specs(device)["peak_flops"] if device is None \
        else _lookup(device)[0]


def peak_hbm_bytes_per_s(device=None) -> float:
    """Peak HBM bandwidth in bytes/s."""
    return specs(device)["peak_hbm_bytes_per_s"] if device is None \
        else _lookup(device)[1]


_specs: Optional[dict] = None


def specs(device=None) -> dict:
    """Resolved peak-spec dict for the process's default device (cached —
    the registry derives every roofline number from it). Passing a device
    bypasses the cache."""
    global _specs
    if device is not None:
        flops, bw = _lookup(device)
        return {
            "device": str(getattr(device, "device_kind", "")
                          or getattr(device, "platform", "?")),
            "platform": getattr(device, "platform", "?"),
            "peak_flops": flops,
            "peak_hbm_bytes_per_s": bw,
            "ridge_flops_per_byte": flops / bw,
        }
    if _specs is None:
        import jax

        _specs = specs(jax.devices()[0])
    return _specs


def reset_cache() -> None:
    global _specs
    _specs = None
