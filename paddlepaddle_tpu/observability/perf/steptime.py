"""Step-time decomposition — where did this training step's wall time go?

Reference surface: the reference profiler's timeline summary buckets
(``paddle.profiler`` statistic categories: Operator / CudaRuntime /
Communication / Dataloader). TPU-native equivalent over the existing span
recorder: the hot-path hooks already record every eager dispatch
("op"), autograd backward ("autograd"), collective/comm task
("collective"/"comm") and — with this PR — dataloader wait
("dataloader") span into the recorder's per-category aggregates, so a
step bracket only has to DIFF those aggregates across the step to know
how much of the wall went to each phase:

* ``comm``      — collective launches + host-blocking comm tasks;
* ``host``      — eager dispatch + autograd node execution (python/
  dispatch overhead; ~0 when the step is one jitted program);
* ``data_wait`` — time blocked on DataLoader workers;
* ``compute``   — the remainder: device execution + the jit dispatch of
  the fused step. For a jitted step that is (to first order) the chip.

This is the attribution tool for the ResNet MFU gap (ROADMAP item 3): a
step that is 30% ``data_wait`` needs input overlap, one that is 95%
``compute`` but low-MFU needs the cost registry's per-program roofline.

Usage::

    tl = obs.perf.timeline()            # module singleton
    for batch in loader:
        with tl.step("train"):
            loss = train_step(*batch)
            loss.numpy()                # sync: wall must include the chip
    print(obs.summary())                # "Step time" section
    obs.export_chrome_trace(path)       # per-phase counter tracks

The step bracket costs two aggregate snapshots (a dict copy under the
recorder lock) — microseconds against millisecond steps. Phases sum to
the step wall by construction (``compute`` is the floor-at-zero
remainder); if nested spans double-count a category the excess shows as
``compute == 0`` with phases > wall, which the summary flags.
"""

from __future__ import annotations

import threading
import time
from collections import deque
from typing import Dict, Optional

PHASES = ("compute", "host", "comm", "data_wait")

# recorder categories folded into each non-compute phase
_CAT_PHASE = {
    "collective": "comm",
    "comm": "comm",
    "op": "host",
    "autograd": "host",
    "dataloader": "data_wait",
}


class StepRecord:
    __slots__ = ("name", "wall_s", "phases", "t_end")

    def __init__(self, name, wall_s, phases, t_end):
        self.name = name
        self.wall_s = wall_s
        self.phases = phases
        self.t_end = t_end


class _StepCtx:
    __slots__ = ("_tl", "_name", "_t0", "_base")

    def __init__(self, tl: "StepTimeline", name: str):
        self._tl = tl
        self._name = name

    def __enter__(self):
        self._base = self._tl._cat_totals()
        self._t0 = time.perf_counter()
        return self

    def __exit__(self, *exc):
        wall = time.perf_counter() - self._t0
        cur = self._tl._cat_totals()
        base = self._base
        phases = {p: 0.0 for p in PHASES}
        for cat, phase in _CAT_PHASE.items():
            phases[phase] += max(0.0, cur.get(cat, 0.0) - base.get(cat, 0.0))
        attributed = sum(phases.values())
        phases["compute"] = max(0.0, wall - attributed)
        # the comm/host/data spans feeding cat_totals are trace-gated: a
        # step bracketed with tracing OFF reads as 100% compute no matter
        # what it did — record the blind spot so render() can say so
        # instead of silently confirming the wrong conclusion
        try:
            from .. import _trace_on

            traced = _trace_on
        except Exception:
            traced = False
        self._tl._push(self._name, wall, phases, traced=traced)
        return False


class StepTimeline:
    """Per-step phase decomposition over the span recorder's aggregates."""

    def __init__(self, recorder=None, keep: int = 512):
        self._recorder = recorder
        self._lock = threading.Lock()
        self.steps: deque = deque(maxlen=int(keep))
        self.totals: Dict[str, float] = {p: 0.0 for p in PHASES}
        self.wall_total = 0.0
        self.count = 0
        self.untraced = 0    # steps bracketed with tracing off (blind)

    def _rec(self):
        if self._recorder is not None:
            return self._recorder
        from .. import get_recorder

        return get_recorder()

    def _cat_totals(self) -> Dict[str, float]:
        return self._rec().cat_totals()

    def step(self, name: str = "step") -> _StepCtx:
        """Context manager bracketing ONE step. Sync the device inside the
        bracket (e.g. materialize the loss) or ``compute`` only measures
        dispatch."""
        return _StepCtx(self, name)

    def _push(self, name: str, wall: float, phases: Dict[str, float],
              traced: bool = True) -> None:
        rec = StepRecord(name, wall, phases, time.perf_counter())
        with self._lock:
            self.steps.append(rec)
            self.count += 1
            if not traced:
                self.untraced += 1
            self.wall_total += wall
            for p, v in phases.items():
                self.totals[p] += v
        # metrics: cumulative per-phase seconds (off-cost: one is-enabled
        # check inside safe paths; a step is ms-scale, this is ns-scale)
        try:
            from .. import _metrics_if_enabled, _recorder_if_tracing

            reg = _metrics_if_enabled()
            if reg is not None:
                c = reg.counter("paddle_step_phase_seconds_total",
                                "step wall time attributed per phase")
                for p, v in phases.items():
                    c.inc(v, phase=p)
                reg.counter("paddle_steps_total",
                            "steps bracketed by the StepTimeline").inc()
            r = _recorder_if_tracing()
            if r is not None:
                # Perfetto counter track: stacked per-phase ms at step end
                r.counter_track("step_phases_ms", {
                    p: round(v * 1e3, 3) for p, v in phases.items()})
                r.record_complete(name, "step", wall,
                                  {p: round(v * 1e3, 3)
                                   for p, v in phases.items()})
        except Exception:
            pass

    def snapshot(self) -> dict:
        with self._lock:
            return {
                "count": self.count,
                "untraced": self.untraced,
                "wall_total_s": self.wall_total,
                "phase_totals_s": dict(self.totals),
                "last": [{"name": s.name, "wall_s": s.wall_s,
                          "phases": dict(s.phases)}
                         for s in list(self.steps)[-8:]],
            }

    def clear(self) -> None:
        with self._lock:
            self.steps.clear()
            self.totals = {p: 0.0 for p in PHASES}
            self.wall_total = 0.0
            self.count = 0
            self.untraced = 0

    def render(self) -> str:
        """Summary() section body: phase totals + share of step wall."""
        snap = self.snapshot()
        n = snap["count"]
        if n == 0:
            return "(no steps bracketed)"
        wall = snap["wall_total_s"]
        lines = [f"{n} steps, {wall * 1e3:.1f}ms total "
                 f"({wall / n * 1e3:.2f}ms/step)"]
        for p in PHASES:
            v = snap["phase_totals_s"][p]
            pct = v / wall * 100 if wall > 0 else 0.0
            lines.append(f"  {p:<10}{v * 1e3:>10.2f}ms{pct:>7.1f}%")
        attributed = sum(snap["phase_totals_s"].values())
        if attributed > wall * 1.001:
            lines.append("  (phases exceed wall: nested spans double-"
                         "counted a category; compute floored at 0)")
        if snap["untraced"]:
            lines.append(
                f"  WARNING: {snap['untraced']}/{n} steps bracketed with "
                "tracing OFF — comm/host/data_wait spans were not "
                "recorded, so their time reads as 'compute'; enable "
                "obs.enable(trace=True) for real attribution")
        return "\n".join(lines)
