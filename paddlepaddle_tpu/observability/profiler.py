"""Always-on sampling wall-clock profiler — "where were the cycles".

Reference surface: ``paddle.profiler``'s host tracer. That design (enter/
exit hooks on every instrumented region) answers "how long did the things
I annotated take"; production triage needs the inverse — "what was the
process ACTUALLY doing when the 2 a.m. page fired", including the code
nobody annotated. This module is the statistical answer: a daemon thread
samples ``sys._current_frames()`` at ``FLAGS_obs_prof_hz`` (default
50 Hz), folds each thread's stack into a ``category;thread;frames...``
collapsed line, and aggregates counts into per-second buckets kept for
``FLAGS_obs_prof_window_s``. Memory is bounded by distinct stacks per
second, not by runtime; per-sample cost is one stack walk per live
thread (~tens of microseconds), which is what keeps the <5% overhead
gate honest (tools/check_obs_overhead.py gate 7).

Every sampled stack is classified by SEAM — the first frame (scanning
innermost-out) that lands in a known subsystem names the category:

* ``decode``    — decode/spec chunk, first-token collect, retirement
* ``admission`` — admission control, queue pop, batch collect
* ``router``    — dispatch, hedging, failover
* ``wire``      — socket serving / replica client I/O
* ``gc``        — interpreter GC callbacks
* ``idle``      — parked in a lock/queue/sleep wait
* ``other``     — everything else

Read side: ``hot_stacks(seconds, n)`` (top-N table), ``collapsed()``
(flamegraph-ready ``stack count`` lines for inferno/speedscope),
``jsonable()`` (the ``/profile`` and ``/fleet/profile`` payload), plus
an on-demand ``device_trace(seconds)`` window that wraps
``jax.profiler.start_trace/stop_trace`` for the XLA side — the sampler
sees host frames only; device time appears as the host thread parked in
the chunk's sync.

Flight-recorder dumps attach ``hot_stacks`` of the last ~10 s so a
watchdog/breaker/alert dump says where the process was spinning, not
just that it was.
"""

from __future__ import annotations

import os
import sys
import threading
import time
from collections import Counter, deque
from typing import Dict, List, Optional

from ..core import flags as _flags

#: frames deeper than this are truncated (outermost end) — a runaway
#: recursion must not turn every sample into a megabyte of folded text
MAX_DEPTH = 64

# seam classification: (category, function names, filename suffixes).
# Scanned per frame innermost-out; first hit names the stack. ``idle``
# is matched ONLY on the innermost frame — a decode thread blocked in
# a lock deep inside the engine is idle, but an engine frame above a
# helper's wait() must still win as decode.
_SEAMS = (
    ("decode", {"_decode_chunk", "_spec_chunk", "_collect_firsts",
                "_retire", "_run_static_batch", "_decode_attempt",
                "_loop_continuous"},
     ("decode_engine.py", "speculative.py")),
    ("admission", {"_admit", "_check_admission", "_precheck",
                   "_next_request", "_collect_batch", "_requeue_expired_sweep",
                   "_sweep_slots"}, ()),
    ("router", {"_dispatch", "_maybe_hedge", "_cancel_losers",
                "_finish_ok", "_finish_fail", "_pick_replica"},
     ("router.py",)),
    ("wire", set(),
     ("c_api_server.py", "remote_replica.py", "socket.py", "selectors.py",
      "socketserver.py", "ssl.py")),
    ("gc", set(), ("gc.py",)),
)
#: a thread whose INNERMOST frame is one of these waits is parked, not
#: burning — including a server parked in select/accept waiting for a
#: connection (actual wire work — recv_into/sendall mid-RPC — still
#: classifies as ``wire`` through the seam table above)
_IDLE_FUNCS = {"wait", "acquire", "get", "select", "poll", "sleep",
               "accept", "_wait_for_tstate_lock"}
_IDLE_FILES = ("threading.py", "queue.py", "selectors.py", "socket.py")

_basename_cache: Dict[str, str] = {}


def _short(path: str) -> str:
    b = _basename_cache.get(path)
    if b is None:
        b = os.path.basename(path)
        _basename_cache[path] = b
    return b


def classify(frames_innermost_first: List[tuple]) -> str:
    """Category of one sampled stack; ``frames`` are ``(file, func)``
    pairs, innermost first."""
    for depth, (fname, func) in enumerate(frames_innermost_first):
        if depth == 0 and (func in _IDLE_FUNCS
                           and fname.endswith(_IDLE_FILES)):
            return "idle"
        for cat, funcs, files in _SEAMS:
            if func in funcs or (files and fname.endswith(files)):
                return cat
    return "other"


class SamplingProfiler:
    """Bounded folded-stack aggregator over ``sys._current_frames()``.

    ``start_thread=False`` leaves sampling to be driven manually — tests
    call :meth:`sample_once` with a synthetic clock, exactly the tsdb
    sampler's contract."""

    def __init__(self, hz: Optional[float] = None,
                 window_s: Optional[float] = None):
        self.hz = float(hz or _flags.flag_value("obs_prof_hz") or 50.0)
        self.window_s = float(
            window_s or _flags.flag_value("obs_prof_window_s") or 120.0)
        self._lock = threading.Lock()
        # (epoch_second, Counter{folded_stack: samples}) — appended by the
        # sampler, pruned past window_s; readers merge the suffix they need
        self._buckets: deque = deque()
        self.samples = 0            # stack samples recorded (thread-seconds)
        self.ticks = 0              # sampler wakeups
        self._started_at = time.time()
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None
        self._trace_lock = threading.Lock()

    # -- write side ----------------------------------------------------------

    def sample_once(self, now: Optional[float] = None) -> int:
        """Take one sample of every live thread (except the sampler
        itself). Returns the number of stacks recorded."""
        t = time.time() if now is None else now
        sec = int(t)
        own = threading.get_ident()
        try:
            frames = sys._current_frames()
        except Exception:
            return 0
        names = {}
        try:
            for th in threading.enumerate():
                if th.ident is not None:
                    names[th.ident] = th.name
        except Exception:
            pass
        recorded = 0
        folded = []
        for tid, frame in frames.items():
            if tid == own:
                continue
            inner = []
            f = frame
            while f is not None and len(inner) < MAX_DEPTH:
                code = f.f_code
                inner.append((_short(code.co_filename), code.co_name))
                f = f.f_back
            if not inner:
                continue
            cat = classify(inner)
            parts = [f"{fn}:{fun}" for fn, fun in reversed(inner)]
            tname = names.get(tid, f"tid{tid}")
            folded.append(cat + ";" + tname + ";" + ";".join(parts))
            recorded += 1
        del frames
        with self._lock:
            if self._buckets and self._buckets[-1][0] == sec:
                bucket = self._buckets[-1][1]
            else:
                bucket = Counter()
                self._buckets.append((sec, bucket))
                edge = sec - self.window_s
                while self._buckets and self._buckets[0][0] < edge:
                    self._buckets.popleft()
            for line in folded:
                bucket[line] += 1
            self.samples += recorded
            self.ticks += 1
        return recorded

    def _run(self) -> None:
        period = 1.0 / max(self.hz, 0.1)
        next_t = time.monotonic()
        while True:
            next_t += period
            delay = next_t - time.monotonic()
            if delay < -1.0:       # fell behind (GIL stall): don't burst
                next_t = time.monotonic()
                delay = 0.0
            if self._stop.wait(max(delay, 0.0)):
                return
            try:
                self.sample_once()
            except Exception:
                pass    # the profiler must never take the process down

    def start(self) -> "SamplingProfiler":
        if self._thread is None or not self._thread.is_alive():
            self._stop.clear()
            self._thread = threading.Thread(
                target=self._run, daemon=True, name="obs-profiler")
            self._thread.start()
        return self

    def stop(self) -> None:
        self._stop.set()
        t = self._thread
        if t is not None:
            t.join(timeout=2.0)
        self._thread = None

    # -- read side -----------------------------------------------------------

    def _merged(self, seconds: Optional[float],
                now: Optional[float] = None) -> Counter:
        t = time.time() if now is None else now
        edge = None if seconds is None else int(t) - float(seconds)
        out: Counter = Counter()
        with self._lock:
            for sec, bucket in self._buckets:
                if edge is None or sec >= edge:
                    out.update(bucket)
        return out

    def hot_stacks(self, seconds: Optional[float] = 10.0, n: int = 20,
                   now: Optional[float] = None) -> List[dict]:
        """Top-N folded stacks over the trailing window, hottest burning
        stacks first; parked (``idle``) stacks sort after all of them."""
        merged = self._merged(seconds, now)
        total = sum(merged.values())
        # the table answers "what was BURNING": parked (idle) stacks rank
        # after every burning stack no matter their wall-clock count — a
        # wall-clock sampler sees parked threads on every tick, and a
        # triage table led by ten thread-pool waits is useless. The idle
        # share is still first-class in categories()/collapsed().
        ranked = sorted(merged.items(),
                        key=lambda kv: (kv[0].startswith("idle;"), -kv[1],
                                        kv[0]))
        rows = []
        for stack, count in ranked[:max(int(n), 0)]:
            cat, _, rest = stack.partition(";")
            tname, _, frames = rest.partition(";")
            rows.append({
                "category": cat,
                "thread": tname,
                "stack": stack,
                "leaf": frames.rsplit(";", 1)[-1] if frames else "",
                "samples": count,
                "pct": round(100.0 * count / total, 2) if total else 0.0,
            })
        return rows

    def categories(self, seconds: Optional[float] = 10.0,
                   now: Optional[float] = None) -> Dict[str, int]:
        out: Dict[str, int] = {}
        for stack, count in self._merged(seconds, now).items():
            cat = stack.split(";", 1)[0]
            out[cat] = out.get(cat, 0) + count
        return out

    def collapsed(self, seconds: Optional[float] = None,
                  now: Optional[float] = None) -> str:
        """Flamegraph-ready collapsed format: one ``stack count`` line per
        distinct folded stack (feed to inferno / flamegraph.pl /
        speedscope)."""
        merged = self._merged(seconds, now)
        return "\n".join(f"{stack} {count}"
                         for stack, count in sorted(merged.items()))

    def jsonable(self, seconds: Optional[float] = 10.0, n: int = 30,
                 now: Optional[float] = None) -> dict:
        cats = self.categories(seconds, now)
        return {
            "hz": self.hz,
            "window_s": self.window_s,
            "uptime_s": round(time.time() - self._started_at, 1),
            "ticks": self.ticks,
            "samples": self.samples,
            "query_seconds": seconds,
            "categories": dict(sorted(cats.items(),
                                      key=lambda kv: -kv[1])),
            "top": self.hot_stacks(seconds, n, now),
        }

    # -- on-demand device trace ---------------------------------------------

    def device_trace(self, seconds: float = 3.0,
                     outdir: Optional[str] = None) -> str:
        """Capture a ``jax.profiler`` device-trace window (TensorBoard /
        Perfetto-loadable) and return its directory. Serialized: a second
        caller while a window is open gets a RuntimeError instead of
        corrupting the first trace."""
        import tempfile

        import jax

        if not self._trace_lock.acquire(blocking=False):
            raise RuntimeError("a device-trace window is already open")
        try:
            out = outdir or tempfile.mkdtemp(prefix="paddle_devtrace_")
            jax.profiler.start_trace(out)
            try:
                time.sleep(max(float(seconds), 0.0))
            finally:
                jax.profiler.stop_trace()
            return out
        finally:
            self._trace_lock.release()


# -- module singleton --------------------------------------------------------

_profiler: Optional[SamplingProfiler] = None
_prof_lock = threading.Lock()


def enable(hz: Optional[float] = None, window_s: Optional[float] = None,
           start_thread: bool = True) -> SamplingProfiler:
    """Arm (or return) the process profiler. Idempotent; an explicit
    ``hz`` on an already-armed profiler restarts it at the new rate."""
    global _profiler
    with _prof_lock:
        p = _profiler
        if p is not None:
            if hz is not None and float(hz) != p.hz:
                p.stop()
            else:
                if start_thread:
                    p.start()
                return p
        p = SamplingProfiler(hz=hz, window_s=window_s)
        _profiler = p
    if start_thread:
        p.start()
    return p


def disable() -> None:
    global _profiler
    with _prof_lock:
        p, _profiler = _profiler, None
    if p is not None:
        p.stop()


def get() -> Optional[SamplingProfiler]:
    return _profiler


def reset() -> None:
    disable()
