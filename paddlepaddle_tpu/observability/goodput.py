"""Fleet goodput ledger — attribute every decoded token useful vs wasted.

The serving stack now burns work ON PURPOSE: hedged requests decode the
same prompt twice and throw the loser away, speculative decoding drafts
tokens the verifier rejects, retries discard a failed chunk's partial
output, drains and cancels abandon whatever was mid-flight. Aggregate
``tokens/s`` can therefore look healthy while half the chip is producing
tokens nobody receives. This module is the single ledger that splits the
two: every token the engine stamps into ``stats["tokens_out"]`` is
attributed to exactly one kind, so ``goodput_tok_s`` (useful tokens/s)
and ``waste_pct`` become first-class series the alerts, the bench, and
``perf_gate`` consume.

Kinds (``paddle_goodput_tokens_total{kind=}``):

* ``useful`` — delivered to a caller by a retiring slot (post eos/budget
  trim) or a static batch;
* ``overshoot`` — emitted past eos / past budget and trimmed at
  retirement (the k-token spec chunk's tail, the static batch's padding);
* ``hedge_loser`` — decoded by the replica whose hedge twin won;
* ``retry_discard`` — partial output discarded when a decode chunk
  failed and the slot was failed back to the caller;
* ``cancel`` / ``deadline`` — abandoned mid-decode by a client cancel or
  an expired deadline;
* ``drain`` / ``stop`` — abandoned by a graceful drain or engine stop;
* ``spec_rejected`` — DRAFTED by the speculative decoder and rejected by
  the verifier. These tokens never reached ``tokens_out`` (the draft ran,
  the target did not advance past them), so they sit OUTSIDE the
  reconciliation identity below but are real wasted device work.

Accounting invariant (test-pinned): over any interval,

    sum(counts[k] for k in DECODED_KINDS) == engine stats["tokens_out"]

i.e. every decoded token is attributed exactly once. The engine is the
single accounting point for decoded tokens (``_retire`` /
``release_slot``); the serving/router layers only thread the *reason*
through (``GenerationResult.cancel(reason="hedge_loser")``) — a remote
replica's cancel is a socket disconnect with no reason channel, so a
remote hedge loser folds into ``cancel`` on the replica's own ledger.

The ledger is always on (same contract as ``safe_inc``: waste accounting
must be visible without ``obs.enable()``), costs one lock + dict add per
retirement/chunk — never per token — and never raises into the engine.
"""

from __future__ import annotations

import threading
import time
from collections import deque
from typing import Dict, Optional

KINDS = ("useful", "overshoot", "hedge_loser", "retry_discard", "cancel",
         "deadline", "drain", "stop", "spec_rejected")

#: kinds whose tokens were stamped into the engine's ``tokens_out`` —
#: their sum reconciles exactly against the decoded-token total.
DECODED_KINDS = tuple(k for k in KINDS if k != "spec_rejected")

#: everything except ``useful`` — the numerator of ``waste_pct``.
WASTE_KINDS = tuple(k for k in KINDS if k != "useful")


def _emit(kind: str, n: int, waste_pct: Optional[float]) -> None:
    # lazy: goodput is imported by the inference hot paths, which must not
    # drag the whole observability package in at import time
    try:
        from . import safe_inc, safe_set

        safe_inc("paddle_goodput_tokens_total",
                 "decoded/drafted tokens attributed useful vs wasted, "
                 "by kind", n, kind=kind)
        if waste_pct is not None:
            safe_set("paddle_goodput_waste_pct",
                     "wasted share of attributed tokens over the sliding "
                     "window, percent (waste_burn alert input)", waste_pct)
    except Exception:
        pass


class GoodputLedger:
    """Monotonic per-kind token counts plus a sliding-window waste gauge.

    The cumulative counters feed the reconciliation identity and the
    bench's per-run diffs; the window (default 60 s) feeds the
    ``paddle_goodput_waste_pct`` gauge so the ``waste_burn`` alert sees a
    hedge storm NOW instead of diluted into the process's lifetime."""

    def __init__(self, window_s: float = 60.0):
        self.window_s = float(window_s)
        self._lock = threading.Lock()
        self._counts: Dict[str, int] = {k: 0 for k in KINDS}
        self._events: deque = deque()   # (t, is_useful, n) in the window
        # running window sums: account() is on the slot-retirement path,
        # so the window must be O(1) amortized, not a deque scan
        self._win_useful = 0
        self._win_waste = 0

    def account(self, kind: str, n: int = 1,
                now: Optional[float] = None) -> Optional[float]:
        """Attribute ``n`` tokens to ``kind``. Returns the current
        sliding-window waste percentage (None until any tokens land)."""
        if kind not in self._counts:
            raise ValueError(f"unknown goodput kind {kind!r} "
                             f"(expected one of {KINDS})")
        n = int(n)
        if n <= 0:
            return None
        t = time.monotonic() if now is None else now
        useful = kind == "useful"
        with self._lock:
            self._counts[kind] += n
            self._events.append((t, useful, n))
            if useful:
                self._win_useful += n
            else:
                self._win_waste += n
            return self._waste_pct_locked(t)

    def _waste_pct_locked(self, now: float) -> Optional[float]:
        edge = now - self.window_s
        ev = self._events
        while ev and ev[0][0] < edge:
            _, useful, n = ev.popleft()
            if useful:
                self._win_useful -= n
            else:
                self._win_waste -= n
        total = self._win_useful + self._win_waste
        if total <= 0:
            return None
        return 100.0 * self._win_waste / total

    def waste_pct(self, now: Optional[float] = None) -> Optional[float]:
        """Sliding-window waste share in percent (None: no recent data)."""
        t = time.monotonic() if now is None else now
        with self._lock:
            return self._waste_pct_locked(t)

    def snapshot(self) -> Dict[str, object]:
        """Cumulative ledger state — the ``health()["goodput"]`` block and
        the bench's before/after diff basis."""
        with self._lock:
            counts = dict(self._counts)
            window = self._waste_pct_locked(time.monotonic())
        useful = counts["useful"]
        decoded = sum(counts[k] for k in DECODED_KINDS)
        wasted = sum(counts[k] for k in WASTE_KINDS)
        attributed = useful + wasted
        return {
            "kinds": counts,
            "useful_tokens": useful,
            "wasted_tokens": wasted,
            "decoded_tokens": decoded,
            "waste_pct": (round(100.0 * wasted / attributed, 3)
                          if attributed else None),
            "window_waste_pct": (None if window is None
                                 else round(window, 3)),
        }

    def reset(self) -> None:
        with self._lock:
            self._counts = {k: 0 for k in KINDS}
            self._events.clear()
            self._win_useful = self._win_waste = 0


# -- module singleton (always on) -------------------------------------------

_ledger = GoodputLedger()


def get() -> GoodputLedger:
    return _ledger


def account(kind: str, n: int = 1) -> None:
    """Best-effort module-level accounting used by the engine/serving/
    router seams: updates the ledger, bumps the registry counter, and
    refreshes the window gauge. Never raises — waste accounting must not
    be the thing that breaks decode."""
    try:
        waste = _ledger.account(kind, n)
    except Exception:
        return
    if n > 0:
        _emit(kind, int(n), waste)


def snapshot() -> Dict[str, object]:
    return _ledger.snapshot()


def reset() -> None:
    _ledger.reset()
