"""Unified runtime observability — op-level tracing, metrics registry, and a
recompilation watchdog, threaded through every hot path of the framework.

Reference surface: the full ``paddle.profiler`` stack (host tracer + device
tracer + chrome-trace export), ``paddle.monitor``-style stat registries, and
per-collective comm logging. One subsystem here provides all three:

* :class:`~.recorder.Recorder` — zero-dep host span recorder (thread-local
  nesting, ring buffer, chrome-trace JSON export) interleaved with
  ``jax.profiler.TraceAnnotation`` so host spans land in the same
  TensorBoard/Perfetto timeline as XLA device activity;
* :class:`~.metrics.Registry` — counters / gauges / histograms (exponential
  buckets) with ``snapshot()`` and ``to_prometheus_text()``;
* :mod:`~.watchdog` — detects ``jax.jit`` cache misses via
  ``jax.monitoring`` and names the callsite of a recompilation storm;
* instrumentation hooks in dispatch (per-op wall time, AMP casts), autograd
  (node capture/exec), collectives + comm tasks (bytes, latency),
  DataLoader workers (queue depth, wait time) and the serving engine
  (request latency, batch size).

Everything is gated by ``PADDLE_OBS_*`` env vars / ``FLAGS_obs_*`` flags and
defaults OFF: the only cost on a hot path when disabled is one module-global
``is None`` check. Turn it on::

    import paddlepaddle_tpu.observability as obs
    obs.enable()                       # trace + metrics + watchdog
    ... run steps ...
    print(obs.summary())               # per-op/per-collective table
    obs.export_chrome_trace("/tmp/host_trace.json")   # open in Perfetto
    text = obs.to_prometheus_text()    # mount on /metrics

or set ``PADDLE_OBS_TRACE=1 PADDLE_OBS_METRICS=1 PADDLE_OBS_RECOMPILE_WATCH=1``
before import.
"""

from __future__ import annotations

import os
from typing import Optional

from ..core import flags as _flags
from . import flight, goodput, memledger, perf, profiler, reqtrace, watchdog
from .metrics import (  # noqa: F401
    BYTES_BUCKETS,
    LATENCY_BUCKETS,
    Counter,
    Gauge,
    Histogram,
    Registry,
    exponential_buckets,
)
from .recorder import Event, Recorder, trace_region  # noqa: F401

_recorder = Recorder(capacity=_flags.flag_value("obs_buffer_size"))
_registry = Registry()
_trace_on = False
_metrics_on = False
_watchdog_on = False


# -- state accessors (hot-path helpers, also used by recorder/watchdog) ------

def get_recorder() -> Recorder:
    return _recorder


def get_registry() -> Registry:
    return _registry


def _recorder_if_tracing() -> Optional[Recorder]:
    return _recorder if _trace_on else None


def _metrics_if_enabled() -> Optional[Registry]:
    return _registry if _metrics_on else None


def is_enabled() -> bool:
    return _trace_on or _metrics_on or _watchdog_on


def safe_inc(name: str, help_: str = "", n: float = 1, **labels) -> None:
    """Best-effort counter increment for COLD-path fault events (retries,
    restarts, corruption, preemption, watchdog timeouts, load sheds):
    always records — operators must see fault handling even without
    ``enable()`` — and never raises, because fault handling must not fail
    on account of metrics."""
    try:
        _registry.counter(name, help_).inc(n, **labels)
    except Exception:
        pass


def safe_set(name: str, help_: str = "", value: float = 0.0,
             **labels) -> None:
    """Best-effort gauge write, same contract as :func:`safe_inc` — used
    for cold-path state gauges (serving breaker state) that must be
    visible even with metrics off."""
    try:
        _registry.gauge(name, help_).set(value, **labels)
    except Exception:
        pass


class RecordEvent(trace_region):
    """Explicit host annotation: always records (no flags needed) and opens
    a ``jax.profiler.TraceAnnotation``. ``paddle.profiler.RecordEvent`` is a
    thin wrapper over this, so both APIs feed ONE event pipeline."""

    def __init__(self, name: str, cat: str = "region"):
        super().__init__(name, cat, force=True)


# ---------------------------------------------------------------------------
# hot-path hook bodies. Installed into the instrumented modules' nullable
# module globals by enable(); metric objects are resolved once here so the
# per-event work is dict-free.
# ---------------------------------------------------------------------------

def _slo_aligned_buckets(flag_name: str):
    """Latency bucket ladder with the armed SLO threshold inserted as an
    exact edge: burn accounting happens AT the SLO boundary, so the target
    must be a bucket bound — otherwise "violations" counted from the
    nearest exponential edge over/under-state the burn by up to 2x.
    Returns ``None`` (the default ladder) when the SLO flag is unarmed."""
    ms = _flags.flag_value(flag_name)
    if not ms or float(ms) <= 0:
        return None
    edge = float(ms) / 1e3
    from .metrics import LATENCY_BUCKETS

    buckets = list(LATENCY_BUCKETS)
    if edge not in buckets:
        import bisect

        bisect.insort(buckets, edge)
    return buckets


def _make_hooks():
    reg = _registry
    rec = _recorder

    op_calls = reg.counter("paddle_op_calls_total",
                           "eager ops dispatched, by op name")
    op_latency = reg.histogram("paddle_op_seconds",
                               "eager op dispatch wall time, by op name")
    amp_casts = reg.counter("paddle_amp_casts_total",
                            "AMP dtype casts inserted at dispatch, by op")
    node_cap = reg.counter("paddle_autograd_nodes_captured_total",
                           "GradNodes recorded on the tape, by op")
    node_exec = reg.counter("paddle_autograd_nodes_executed_total",
                            "GradNode backwards executed, by op")
    node_exec_lat = reg.histogram("paddle_autograd_node_seconds",
                                  "GradNode backward wall time, by op")
    comm_lat = reg.histogram("paddle_comm_task_seconds",
                             "host-blocking comm/region task latency")
    coll_calls = reg.counter("paddle_collective_calls_total",
                             "eager collective calls, by collective")
    coll_bytes = reg.counter("paddle_collective_bytes_total",
                             "tensor bytes moved by eager collectives")
    coll_lat = reg.histogram("paddle_collective_seconds",
                             "eager collective wall time, by collective")
    io_wait = reg.histogram("paddle_dataloader_wait_seconds",
                            "parent time blocked waiting on worker data")
    io_depth = reg.gauge("paddle_dataloader_queue_depth",
                         "prefetched batches sitting in the data queue")
    io_batches = reg.counter("paddle_dataloader_batches_total",
                             "batches delivered to the training loop")
    srv_requests = reg.counter("paddle_serving_requests_total",
                               "generation requests completed, by outcome")
    srv_lat = reg.histogram("paddle_serving_request_seconds",
                            "submit-to-result generation latency")
    srv_batch = reg.gauge("paddle_serving_batch_size",
                          "active decode slots / batched requests")
    srv_qdepth = reg.gauge("paddle_serving_queue_depth",
                           "generation requests waiting for a decode slot")
    srv_batches = reg.counter("paddle_serving_batches_total",
                              "decode attempts, by outcome (ok/error)")
    # request-lifecycle SLO surface (perf attribution plane): the numbers
    # a serving router load-balances on
    srv_ttft = reg.histogram("paddle_serving_ttft_seconds",
                             "submit-to-first-token latency (TTFT)",
                             buckets=_slo_aligned_buckets("slo_ttft_ms"))
    srv_tpot = reg.histogram("paddle_serving_tpot_seconds",
                             "per-output-token latency after the first "
                             "(TPOT, per-request average)",
                             buckets=_slo_aligned_buckets("slo_tpot_ms"))
    srv_qwait = reg.histogram("paddle_serving_queue_wait_seconds",
                              "submit-to-decode-slot-admission queue wait")
    srv_margin = reg.histogram("paddle_serving_deadline_margin_seconds",
                               "seconds left on the request deadline at "
                               "completion (near-zero = deadlines too tight)")

    def obs_op(name, dur):
        if _metrics_on:
            op_calls.inc(op=name)
            op_latency.observe(dur, op=name)
        if _trace_on:
            rec.record_complete(name, "op", dur)

    def obs_amp(name, n):
        if _metrics_on:
            amp_casts.inc(n, op=name)

    def obs_node(kind, name, dur=None):
        if kind == "capture":
            if _metrics_on:
                node_cap.inc(op=name)
            return
        if _metrics_on:
            node_exec.inc(op=name)
            if dur is not None:
                node_exec_lat.observe(dur, op=name)
        if _trace_on and dur is not None:
            rec.record_complete(name + "_bwd", "autograd", dur)

    def obs_task(name, group, elapsed):
        if _metrics_on:
            comm_lat.observe(elapsed, task=name, group=group or "")
        # "region" tasks are profiler RecordEvents — already recorder spans
        # on the explicit path; re-recording them would double every region
        # in the exported trace
        if _trace_on and group != "region":
            rec.record_complete(name, "comm", elapsed,
                                {"group": group} if group else None)

    def obs_coll(op, nbytes, dur):
        if _metrics_on:
            coll_calls.inc(coll=op)
            if nbytes:
                coll_bytes.inc(nbytes, coll=op)
            coll_lat.observe(dur, coll=op)
        if _trace_on:
            rec.record_complete(op, "collective", dur,
                                {"bytes": nbytes} if nbytes else None)

    def obs_io(event, value):
        if event == "wait":
            if _metrics_on:
                io_wait.observe(value)
            if _trace_on:
                # a "dataloader" span so the StepTimeline can attribute
                # blocked-on-input time as its own step phase
                rec.record_complete("dataloader_wait", "dataloader", value)
            return
        if not _metrics_on:
            return
        if event == "qdepth":
            io_depth.set(value)
        elif event == "batch":
            io_batches.inc(value)

    def obs_srv(event, value):
        if event == "slo":
            obs_slo(value)
            return
        if not _metrics_on:
            return
        if event == "latency":
            srv_lat.observe(value)
            srv_requests.inc(outcome="ok")
        elif event == "error":
            srv_requests.inc(outcome="error")
        elif event == "cancelled":
            srv_requests.inc(outcome="cancelled")
        elif event == "batch_size":
            srv_batch.set(value)
        elif event == "queue_depth":
            srv_qdepth.set(value)
        elif event == "batch":
            srv_batches.inc(outcome=value)

    def obs_slo(d):
        """One completed request's lifecycle numbers (dict from the
        serving engine): SLO histograms + a request span in the trace."""
        if _metrics_on:
            if d.get("ttft") is not None:
                srv_ttft.observe(d["ttft"])
            if d.get("tpot") is not None:
                srv_tpot.observe(d["tpot"])
            if d.get("queue_wait") is not None:
                srv_qwait.observe(d["queue_wait"])
            if d.get("deadline_margin") is not None:
                srv_margin.observe(d["deadline_margin"])
        if _trace_on and d.get("latency") is not None:
            rec.record_complete(
                f"request#{d.get('id', '?')}", "serving.request",
                d["latency"],
                {k: v for k, v in d.items()
                 if k != "latency" and v is not None})

    return {
        "op": obs_op, "amp": obs_amp, "node": obs_node, "task": obs_task,
        "coll": obs_coll, "io": obs_io, "srv": obs_srv,
    }


def _set_hooks(hooks: Optional[dict]) -> None:
    """Install (or clear, with None) the nullable hook globals in every
    instrumented module. Optional modules (serving) are skipped if their
    import fails — observability must never be the thing that breaks."""
    from ..core import autograd as _ag
    from ..core import dispatch as _dp
    from ..distributed import collective as _coll
    from ..distributed import comm_task as _ct
    from ..io import dataloader as _dl

    g = (lambda k: None) if hooks is None else hooks.get
    _dp._obs_op = g("op")
    _dp._obs_amp = g("amp")
    _ag._obs_node = g("node")
    _ct._obs_task = g("task")
    _coll._obs_coll = g("coll")
    _dl._obs_io = g("io")
    try:
        from ..inference import serving as _srv

        _srv._obs_srv = g("srv")
    except Exception:
        pass


# ---------------------------------------------------------------------------
# lifecycle
# ---------------------------------------------------------------------------

def enable(trace: Optional[bool] = None, metrics: Optional[bool] = None,
           watchdog_: Optional[bool] = None) -> None:
    """Turn instrumentation on. ``None`` arguments fall back to the
    ``FLAGS_obs_*`` flags (i.e. the ``PADDLE_OBS_*`` env vars); calling
    ``enable()`` with no arguments and no flags set enables everything —
    "I asked for observability, give me observability"."""
    global _trace_on, _metrics_on, _watchdog_on
    if trace is None and metrics is None and watchdog_ is None \
            and not (_flags.flag_value("obs_trace")
                     or _flags.flag_value("obs_metrics")
                     or _flags.flag_value("obs_recompile_watch")):
        trace = metrics = watchdog_ = True
    _trace_on = _flags.flag_value("obs_trace") if trace is None else bool(trace)
    _metrics_on = (_flags.flag_value("obs_metrics") if metrics is None
                   else bool(metrics))
    _watchdog_on = (_flags.flag_value("obs_recompile_watch")
                    if watchdog_ is None else bool(watchdog_))
    _flags.set_flags({"obs_trace": _trace_on, "obs_metrics": _metrics_on,
                      "obs_recompile_watch": _watchdog_on})
    _recorder.set_capacity(_flags.flag_value("obs_buffer_size"))
    if _trace_on or _metrics_on:
        _set_hooks(_make_hooks())
    else:
        _set_hooks(None)
    if _watchdog_on:
        watchdog.install(_flags.flag_value("obs_recompile_threshold"))
    else:
        watchdog.uninstall()


def disable() -> None:
    """Uninstall every hook; hot paths return to the bare ``is None``
    check. Recorded data is kept — call :func:`reset` to drop it."""
    global _trace_on, _metrics_on, _watchdog_on
    _trace_on = _metrics_on = _watchdog_on = False
    _flags.set_flags({"obs_trace": False, "obs_metrics": False,
                      "obs_recompile_watch": False})
    _set_hooks(None)
    watchdog.uninstall()


def enable_history(interval_s: Optional[float] = None, rules=None,
                   start_thread: bool = True):
    """Arm the metric-history plane (:mod:`~.tsdb`) and its alert engine
    (:mod:`~.alerts`) over the package registry. ``start_thread=False``
    leaves the sampler to be driven manually (tests call
    ``history.observe(now)`` with a synthetic clock). Returns the
    :class:`~.tsdb.MetricHistory`."""
    from . import alerts as _alerts
    from . import tsdb as _tsdb

    h = _tsdb.enable(interval_s=interval_s, start_thread=start_thread)
    _alerts.install(history=h, rules=rules)
    return h


def disable_history() -> None:
    """Stop the history sampler and detach the alert engine."""
    from . import alerts as _alerts
    from . import tsdb as _tsdb

    _alerts.uninstall()
    _tsdb.disable()


def reset() -> None:
    """Clear the ring buffer, all metric values, watchdog state, the
    perf plane (program costs + step timeline), the goodput ledger, and
    tear down the history/alerting, profiler, and memory-ledger planes."""
    _recorder.clear()
    _registry.clear()
    watchdog.reset()
    perf.reset()
    reqtrace.reset()
    goodput.reset()
    try:
        profiler.reset()
    except Exception:
        pass
    try:
        memledger.reset()
    except Exception:
        pass
    try:
        disable_history()
    except Exception:
        pass


# ---------------------------------------------------------------------------
# read-side API
# ---------------------------------------------------------------------------

def snapshot() -> dict:
    return _registry.snapshot()


def to_prometheus_text() -> str:
    # lazy publication: every scrape sees fresh paddle_program_* roofline
    # gauges without the perf plane paying a per-step publish
    try:
        perf.publish_gauges()
    except Exception:
        pass
    return _registry.to_prometheus_text()


def export_chrome_trace(path: str) -> str:
    """Write the host span ring buffer as trace-event JSON (loadable by
    Perfetto / chrome://tracing). Device-side XLA activity comes from
    ``jax.profiler`` traces; host spans opened while such a trace is active
    also appear there via TraceAnnotation."""
    return _recorder.export_chrome_trace(path)


def _fmt_bytes(n: float) -> str:
    for unit in ("B", "KiB", "MiB", "GiB"):
        if n < 1024 or unit == "GiB":
            return f"{n:.1f}{unit}"
        n /= 1024
    return f"{n:.1f}GiB"


def _section(lines, title):
    lines.append("")
    lines.append(title)
    lines.append("-" * len(title))


def summary(top: int = 30) -> str:
    """Human-readable report over everything recorded: per-op dispatch
    counts/timings, autograd node activity, collectives, IO, serving, and
    the recompilation table. Returns (and prints nothing) — callers decide
    where it goes."""
    snap = _registry.snapshot()
    lines = [f"paddlepaddle_tpu observability summary "
             f"(trace={'on' if _trace_on else 'off'}, "
             f"metrics={'on' if _metrics_on else 'off'}, "
             f"watchdog={'on' if _watchdog_on else 'off'})"]
    # rank/world attribution so a summary pasted from a multi-host job says
    # WHICH worker it came from
    try:
        import socket as _socket

        from ..distributed import env as _denv

        lines.append(f"rank {_denv.get_rank()}/{_denv.get_world_size()}  "
                     f"host {_socket.gethostname()}  pid {os.getpid()}")
    except Exception:
        lines.append(f"rank ?/?  pid {os.getpid()}")

    def rows_of(counter_name):
        return sorted(snap.get(counter_name, {}).items(),
                      key=lambda kv: -kv[1])

    op_hist = _registry.get("paddle_op_seconds")
    ops = rows_of("paddle_op_calls_total")
    if ops:
        _section(lines, "Dispatch (eager ops)")
        lines.append(f"{'Op':<32}{'Calls':>8}{'Total(ms)':>12}{'Avg(us)':>10}"
                     f"{'p99(us)':>10}")
        hist_snap = snap.get("paddle_op_seconds", {})
        for key, calls in ops[:top]:
            name = dict(key).get("op", "?")
            h = hist_snap.get(key, {})
            total = h.get("sum", 0.0)
            p99 = op_hist.quantile(0.99, **dict(key)) if op_hist else 0.0
            lines.append(f"{name:<32}{int(calls):>8}{total * 1e3:>12.2f}"
                         f"{total / max(calls, 1) * 1e6:>10.1f}"
                         f"{p99 * 1e6:>10.1f}")
        if len(ops) > top:
            lines.append(f"  ... {len(ops) - top} more ops")

    cap = rows_of("paddle_autograd_nodes_captured_total")
    ex = snap.get("paddle_autograd_nodes_executed_total", {})
    if cap or ex:
        _section(lines, "Autograd (grad nodes)")
        lines.append(f"{'Op':<32}{'Captured':>10}{'Executed':>10}")
        for key, n in cap[:top]:
            name = dict(key).get("op", "?")
            lines.append(f"{name:<32}{int(n):>10}{int(ex.get(key, 0)):>10}")

    colls = rows_of("paddle_collective_calls_total")
    if colls:
        _section(lines, "Collectives (eager)")
        byts = snap.get("paddle_collective_bytes_total", {})
        lat = snap.get("paddle_collective_seconds", {})
        lines.append(f"{'Collective':<24}{'Calls':>8}{'Bytes':>12}"
                     f"{'Avg(us)':>10}")
        for key, calls in colls:
            name = dict(key).get("coll", "?")
            h = lat.get(key, {})
            avg = h.get("sum", 0.0) / max(h.get("count", 1), 1)
            lines.append(f"{name:<24}{int(calls):>8}"
                         f"{_fmt_bytes(byts.get(key, 0)):>12}"
                         f"{avg * 1e6:>10.1f}")

    tasks = snap.get("paddle_comm_task_seconds", {})
    if tasks:
        _section(lines, "Comm/region tasks")
        lines.append(f"{'Task':<32}{'Count':>8}{'Total(ms)':>12}")
        for key, h in sorted(tasks.items(), key=lambda kv: -kv[1]["sum"]):
            name = dict(key).get("task", "?")
            lines.append(f"{name:<32}{h['count']:>8}{h['sum'] * 1e3:>12.2f}")

    io = snap.get("paddle_dataloader_wait_seconds", {})
    if io or snap.get("paddle_dataloader_batches_total"):
        _section(lines, "DataLoader")
        h = io.get((), {})
        batches = snap.get("paddle_dataloader_batches_total", {}).get((), 0)
        depth = snap.get("paddle_dataloader_queue_depth", {}).get((), 0)
        lines.append(f"batches={int(batches)}  queue_depth={depth:g}  "
                     f"wait_total={h.get('sum', 0.0) * 1e3:.1f}ms  "
                     f"waits={h.get('count', 0)}")

    srv = snap.get("paddle_serving_request_seconds", {})
    if srv or snap.get("paddle_serving_requests_total") \
            or snap.get("paddle_serving_shed_total"):
        _section(lines, "Serving")
        h = srv.get((), {})
        reqs = snap.get("paddle_serving_requests_total", {})
        ok = reqs.get((("outcome", "ok"),), 0)
        err = reqs.get((("outcome", "error"),), 0)
        cancelled = reqs.get((("outcome", "cancelled"),), 0)
        bs = snap.get("paddle_serving_batch_size", {}).get((), 0)
        qd = snap.get("paddle_serving_queue_depth", {}).get((), 0)
        avg = h.get("sum", 0.0) / max(h.get("count", 1), 1)
        lines.append(f"requests ok={int(ok)} err={int(err)} "
                     f"cancelled={int(cancelled)}  "
                     f"avg_latency={avg * 1e3:.2f}ms  batch_size={bs:g}  "
                     f"queue_depth={qd:g}")
        sheds = snap.get("paddle_serving_shed_total", {})
        if sheds:
            parts = " ".join(f"{dict(k).get('reason', '?')}={int(v)}"
                             for k, v in sorted(sheds.items()))
            lines.append(f"sheds: {parts}")
        breaker = snap.get("paddle_serving_breaker_state", {}).get((), None)
        if breaker is not None:
            name = {0: "closed", 1: "half_open", 2: "open"}.get(
                int(breaker), "?")
            lines.append(f"breaker: {name}")
        ttft = snap.get("paddle_serving_ttft_seconds", {}).get((), None)
        if ttft and ttft.get("count"):
            h_t = _registry.get("paddle_serving_ttft_seconds")
            tpot = snap.get("paddle_serving_tpot_seconds", {}).get((), {})
            lines.append(
                f"SLO: ttft p50={h_t.quantile(0.5) * 1e3:.1f}ms "
                f"p99={h_t.quantile(0.99) * 1e3:.1f}ms "
                f"({ttft['count']} requests)  tpot_avg="
                f"{tpot.get('sum', 0.0) / max(tpot.get('count', 1), 1) * 1e3:.2f}"
                f"ms/token")

    try:
        cost_rows = perf.registry().table()
    except Exception:
        cost_rows = []
    if cost_rows:
        _section(lines, "Program roofline (XLA cost_analysis x measured "
                        "wall, perf plane)")
        lines.append(perf.costs.render_table(cost_rows[:top]))

    tl = perf._timeline
    if tl is not None and tl.count:
        _section(lines, "Step time decomposition")
        lines.append(tl.render())

    region_stats = _recorder.stats()
    if region_stats and _trace_on:
        _section(lines, f"Host spans (ring buffer, "
                        f"{len(_recorder.events())} events)")
        lines.append(f"{'Span':<40}{'Count':>8}{'Total(ms)':>12}"
                     f"{'Avg(ms)':>10}")
        for name, (cnt, total, _mn, _mx) in sorted(
                region_stats.items(), key=lambda kv: -kv[1][1])[:top]:
            lines.append(f"{name:<40}{cnt:>8}{total * 1e3:>12.3f}"
                         f"{total / max(cnt, 1) * 1e3:>10.3f}")

    counts = watchdog.compile_counts()
    if counts:
        _section(lines, "jit compilations (watchdog)")
        lines.append(watchdog.report())

    if len(lines) == 2:  # only the title + rank header
        lines.append("  (nothing recorded — call observability.enable() "
                     "or set PADDLE_OBS_TRACE/PADDLE_OBS_METRICS)")
    return "\n".join(lines)


# ---------------------------------------------------------------------------
# fleet telemetry plane (exporter / aggregation / black box)
# ---------------------------------------------------------------------------

_fleet_publisher = None  # the autostarted FleetPublisher, so it can be stopped


def start_exporter(port: Optional[int] = None, host: Optional[str] = None):
    """Start (or return) this process's HTTP telemetry exporter — serves
    ``/metrics``, ``/healthz``, ``/vars``, ``/trace`` on
    ``FLAGS_obs_port + rank`` (see :mod:`~.exporter`)."""
    from . import exporter

    return exporter.start(port=port, host=host)


_fleet_stopped = False  # stop_exporter() may race the autostart thread


def stop_exporter() -> None:
    """Stop the exporter AND the autostarted fleet publisher (if any) —
    tearing telemetry down must not leave a thread publishing to the
    store forever. Safe against the autostart thread still dialing the
    store: the flag makes a late-arriving publisher stop itself."""
    global _fleet_publisher, _fleet_stopped
    from . import exporter

    _fleet_stopped = True
    exporter.stop()
    pub, _fleet_publisher = _fleet_publisher, None
    if pub is not None:
        pub.stop(final_publish=False)


def _autostart_fleet() -> None:
    """Under a multi-process launch, publish snapshots into the launcher's
    TCPStore and (on rank 0) serve the merged fleet view. Runs on a daemon
    thread: the store dial must never block (or break) worker import."""
    global _fleet_publisher
    world = flight._world()
    if world <= 1 or _fleet_stopped:
        return
    try:
        from ..distributed.store import create_or_get_global_tcp_store
        from . import aggregate as _aggregate
        from . import exporter as _exporter

        rank = flight._rank()
        # torch-style jobs (RANK/WORLD_SIZE only): pin the PADDLE_* names
        # BEFORE touching the global store, exactly like host_collectives
        # does — otherwise the store factory would see rank 0 / world 1
        # and cache a wrong (self-hosted) store that later poisons the
        # training rendezvous. If the dial FAILS (stale torchrun env
        # pointing at a dead master), unpin: telemetry must not leave the
        # process lying about its rank identity as a side effect.
        pinned = []
        for k, v in (("PADDLE_TRAINER_ID", rank),
                     ("PADDLE_TRAINERS_NUM", world)):
            if k not in os.environ:
                os.environ[k] = str(v)
                pinned.append(k)
        try:
            store = create_or_get_global_tcp_store()
        except BaseException:
            for k in pinned:
                os.environ.pop(k, None)
            raise
        _fleet_publisher = _aggregate.FleetPublisher(store, rank).start()
        if _fleet_stopped:  # stop_exporter() won the race mid-dial
            pub, _fleet_publisher = _fleet_publisher, None
            if pub is not None:  # stop_exporter may have swapped it first
                pub.stop(final_publish=False)
            return
        if rank == 0:
            served = _exporter.get()
            if served is not None:
                _aggregate.install_fleet_routes(served, store, world,
                                                local_rank=0)
    except Exception as e:
        import sys as _sys

        _sys.stderr.write(f"[obs] fleet telemetry autostart failed: {e!r}\n")


# auto-enable from env: PADDLE_OBS_* / FLAGS_obs_* read at define_flag time
if (_flags.flag_value("obs_trace") or _flags.flag_value("obs_metrics")
        or _flags.flag_value("obs_recompile_watch")):
    enable(trace=_flags.flag_value("obs_trace"),
           metrics=_flags.flag_value("obs_metrics"),
           watchdog_=_flags.flag_value("obs_recompile_watch"))

if _flags.flag_value("obs_blackbox"):
    try:
        flight.enable()
    except Exception:
        pass

if _flags.flag_value("obs_reqtrace"):
    try:
        reqtrace.enable()
    except Exception:
        pass

if _flags.flag_value("obs_prof"):
    try:
        profiler.enable()
    except Exception as _e:
        import sys as _sys

        _sys.stderr.write(f"[obs] profiler autostart failed: {_e!r}\n")

if _flags.flag_value("obs_memledger"):
    try:
        memledger.enable()
    except Exception as _e:
        import sys as _sys

        _sys.stderr.write(f"[obs] memledger autostart failed: {_e!r}\n")

if _flags.flag_value("obs_tsdb"):
    try:
        enable_history()
    except Exception as _e:
        import sys as _sys

        _sys.stderr.write(f"[obs] tsdb autostart failed: {_e!r}\n")

if _flags.flag_value("obs_export"):
    try:
        start_exporter()
    except Exception as _e:
        import sys as _sys

        _sys.stderr.write(f"[obs] exporter autostart failed: {_e!r}\n")
    import threading as _threading

    _threading.Thread(target=_autostart_fleet, daemon=True,
                      name="obs-fleet-autostart").start()

__all__ = [
    "Counter", "Gauge", "Histogram", "Registry", "Recorder", "Event",
    "RecordEvent", "trace_region", "exponential_buckets",
    "enable", "disable", "reset", "is_enabled", "safe_inc", "safe_set",
    "get_recorder", "get_registry", "snapshot", "to_prometheus_text",
    "export_chrome_trace", "summary", "watchdog", "flight", "perf",
    "reqtrace", "profiler", "memledger", "goodput",
    "start_exporter", "stop_exporter",
    "enable_history", "disable_history",
]
