"""Crash flight recorder — a black box for every worker process.

Reference surface: production training runs (MegaScale, §5 "diagnosis
tools") keep a bounded in-memory log of recent runtime events per worker and
persist it when something dies, because the telemetry that explains a crash
is exactly the telemetry a crashed process can no longer serve over HTTP.
Dapper-style aside: the recorder keeps structured events, not strings, so
the dump is greppable/joinable across ranks.

The recorder is a bounded ring of structured events fed by the runtime's
existing fault/progress seams:

* step boundaries (``distributed.watchdog.Watchdog.step``),
* eager collective launches (``distributed.collective``),
* retries and retry exhaustion (``resilience.retry``),
* chaos injections (``resilience.chaos``),
* circuit-breaker transitions and load sheds (``inference.serving``),
* preemption signals (``resilience.preemption``),
* jit recompilations (``observability.watchdog``).

Recording costs one module-global read + branch when disabled, and a deque
append when enabled — cheap enough to leave on for a whole job
(``tools/check_obs_overhead.py`` gates the enabled hot path).

On an *unrecoverable* event the buffer is flushed as JSONL — one record per
line, plus all-thread stack traces (``sys._current_frames``), the in-flight
comm-task table, and any open step — to ``FLAGS_obs_blackbox_dir``:

* unhandled exception (``sys.excepthook`` / ``threading.excepthook``),
* step-watchdog timeout (``distributed.watchdog._dump``),
* SIGTERM preemption (``resilience.preemption``),
* serving circuit breaker opening (``inference.serving``),
* an injected chaos kill, right before its ``os._exit``.

Enable with ``PADDLE_OBS_BLACKBOX=1`` (``FLAGS_obs_blackbox``) or
:func:`enable`; read a dump with ``tools/obsctl.py blackbox tail``.
"""

from __future__ import annotations

import itertools
import json
import os
import socket
import sys
import threading
import time
import traceback
from collections import deque
from typing import Optional

from ..core import flags as _flags

__all__ = [
    "FlightRecorder", "enable", "disable", "is_enabled", "get",
    "record", "dump", "annotate", "default_dir",
]

# process-level header annotations (serving quant mode, etc.): kept OUTSIDE
# the recorder so a subsystem can annotate before/without the recorder being
# armed — enabling later still dumps them. Plain dict set; no lock needed
# (atomic under the GIL, dumps snapshot via dict()).
_annotations: dict = {}


def annotate(key: str, value) -> None:
    """Attach a key to every future black-box header (e.g. the serving
    engine's quant mode). Values must be JSON-serializable — or a
    zero-argument callable returning one, resolved at dump time (how the
    perf plane keeps the program-cost table in crash dumps current
    without re-annotating on every observation)."""
    _annotations[str(key)] = value


def _rank() -> int:
    """Launcher env first, torch-style spelling second — the same order
    ``distributed.host_collectives`` uses to decide a job is multi-process.
    Shared by the exporter and the fleet autostart (one definition, not
    three); jax-free so a dump/scrape never forces a backend import."""
    return int(os.environ.get("PADDLE_TRAINER_ID")
               or os.environ.get("RANK") or 0)


def _world() -> int:
    return int(os.environ.get("PADDLE_TRAINERS_NUM")
               or os.environ.get("WORLD_SIZE") or 1)


def default_dir() -> str:
    """``FLAGS_obs_blackbox_dir`` or ``<tmp>/paddle_blackbox``."""
    d = _flags.flag_value("obs_blackbox_dir")
    if d:
        return d
    import tempfile

    return os.path.join(tempfile.gettempdir(), "paddle_blackbox")


class FlightRecorder:
    """Bounded ring of structured runtime events + JSONL crash dumps.

    Thread-safe by construction: the ring is a ``deque`` (atomic append
    under the GIL), the sequence counter is an ``itertools.count``, and
    ``dump()`` only snapshots — it must be callable from a signal handler
    or an excepthook without taking locks that arbitrary frames might
    hold."""

    def __init__(self, directory: Optional[str] = None,
                 capacity: Optional[int] = None):
        self.directory = directory or default_dir()
        cap = (capacity if capacity is not None
               else _flags.flag_value("obs_blackbox_events"))
        self._events: deque = deque(maxlen=max(int(cap), 16))
        self._seq = itertools.count(1)
        self._dump_ordinal = itertools.count(1)
        self._open_steps: dict = {}  # (name) -> event dict of the open step
        self.started_wall = time.time()
        self.started_mono = time.monotonic()

    # -- write side ----------------------------------------------------------
    def record(self, kind: str, name: str = "",
               data: Optional[dict] = None) -> None:
        ev = {
            "seq": next(self._seq),
            "wall": time.time(),
            "mono": time.monotonic(),
            "kind": kind,
            "name": name,
        }
        if data:
            ev["data"] = data
        # track open steps so a dump can name the in-flight step even after
        # the begin event aged out of a busy ring
        if kind == "step" and data is not None:
            phase = data.get("phase")
            if phase == "begin":
                self._open_steps[name] = ev
            elif phase == "end":
                self._open_steps.pop(name, None)
        self._events.append(ev)

    def events(self) -> list:
        return list(self._events)

    # -- dump side -----------------------------------------------------------
    def _stacks(self) -> list:
        names = {t.ident: (t.name, t.daemon) for t in threading.enumerate()}
        out = []
        for tid, frame in sys._current_frames().items():
            name, daemon = names.get(tid, ("?", None))
            out.append({
                "tid": tid, "name": name, "daemon": daemon,
                "frames": [ln.rstrip("\n")
                           for ln in traceback.format_stack(frame)],
            })
        return out

    def dump(self, reason: str, exc_info=None) -> Optional[str]:
        """Flush the ring + stacks + in-flight tables to one JSONL file.
        Never raises (a black box must not add a second failure to the
        first); returns the path, or None if the write failed."""
        try:
            return self._dump(reason, exc_info)
        except Exception:
            try:
                sys.stderr.write(
                    f"[flight] black-box dump for {reason!r} failed:\n"
                    + traceback.format_exc())
            except Exception:
                pass
            return None

    def _dump(self, reason: str, exc_info) -> str:
        os.makedirs(self.directory, exist_ok=True)
        n = next(self._dump_ordinal)
        slug = "".join(c if c.isalnum() or c in "-_" else "_"
                       for c in reason)[:48] or "dump"
        path = os.path.join(
            self.directory,
            f"blackbox-rank{_rank()}-pid{os.getpid()}-{n:02d}-{slug}.jsonl")
        events = list(self._events)  # snapshot before anything else
        open_steps = list(self._open_steps.values())
        lines = [{
            "rec": "header",
            "reason": reason,
            "rank": _rank(),
            "world": _world(),
            "host": socket.gethostname(),
            "pid": os.getpid(),
            "wall": time.time(),
            "uptime_s": round(time.monotonic() - self.started_mono, 3),
            "argv": list(sys.argv),
            "dump_ordinal": n,
            "buffered_events": len(events),
        }]
        if _annotations:
            resolved = {}
            for k, v in dict(_annotations).items():
                if callable(v):
                    try:
                        v = v()
                    except Exception as e:   # a sick annotation must not
                        v = f"<annotation failed: {e!r}>"  # sink the dump
                resolved[k] = v
            lines[0]["annotations"] = resolved
        for ev in events:
            lines.append(dict(ev, rec="event"))
        if exc_info is not None:
            tp, val, tb = exc_info
            lines.append({
                "rec": "exception",
                "type": getattr(tp, "__name__", str(tp)),
                "value": str(val),
                "traceback": [ln.rstrip("\n") for ln in
                              traceback.format_exception(tp, val, tb)],
            })
        for ev in open_steps:
            lines.append({
                "rec": "in_flight_step",
                "name": ev.get("name"),
                "data": ev.get("data"),
                "began_s_before_dump":
                    round(time.monotonic() - ev["mono"], 3),
            })
        try:
            from ..distributed.comm_task import in_flight

            lines.append({
                "rec": "in_flight",
                "tasks": [{"name": t[0], "group": t[1],
                           "elapsed_s": round(t[2], 3), "thread": t[3]}
                          for t in in_flight()],
            })
        except Exception:
            pass
        try:
            # sampling-profiler context: the instantaneous stack snapshot
            # below says where the process IS; the last ~10 s of hot
            # folded stacks say where it has been SPENDING — the
            # difference between "stuck here now" and "spinning here"
            from . import profiler as _profiler

            prof = _profiler.get()
            if prof is not None:
                lines.append({
                    "rec": "hot_stacks",
                    "window_s": 10.0,
                    "hz": prof.hz,
                    "categories": prof.categories(10.0),
                    "stacks": prof.hot_stacks(10.0, 15),
                })
        except Exception:
            pass
        lines.append({"rec": "stacks", "threads": self._stacks()})
        lines.append({"rec": "end", "events": len(events)})
        with open(path, "w") as f:
            for obj in lines:
                f.write(json.dumps(obj, default=str) + "\n")
            f.flush()
            os.fsync(f.fileno())  # the process may _exit right after
        sys.stderr.write(f"[flight] black box written: {path} "
                         f"(reason={reason}, {len(events)} events)\n")
        return path


# ---------------------------------------------------------------------------
# module singleton + crash hooks. `_rec is None` is THE disabled fast path.
# ---------------------------------------------------------------------------

_rec: Optional[FlightRecorder] = None
_prev_excepthook = None
_prev_threading_hook = None


def record(kind: str, name: str = "", **data) -> None:
    """Hot-seam entry point: one global read + branch when disabled."""
    r = _rec
    if r is not None:
        r.record(kind, name, data or None)


def dump(reason: str, exc_info=None) -> Optional[str]:
    """Flush the black box (no-op when disabled)."""
    r = _rec
    if r is None:
        return None
    return r.dump(reason, exc_info)


def _excepthook(tp, val, tb):
    dump("unhandled_exception", (tp, val, tb))
    if _prev_excepthook is not None:
        _prev_excepthook(tp, val, tb)


def _threading_hook(args):
    # a dead helper thread (engine loop, publisher) is a crash too
    dump(f"unhandled_exception_in_thread:{args.thread.name if args.thread else '?'}",
         (args.exc_type, args.exc_value, args.exc_traceback))
    if _prev_threading_hook is not None:
        _prev_threading_hook(args)


def enable(directory: Optional[str] = None, capacity: Optional[int] = None,
           install_hooks: bool = True) -> FlightRecorder:
    """Arm the flight recorder (idempotent — re-enable swaps the config).
    ``install_hooks`` chains ``sys.excepthook``/``threading.excepthook`` so
    an unhandled exception dumps before the interpreter reports it."""
    global _rec, _prev_excepthook, _prev_threading_hook
    _rec = FlightRecorder(directory, capacity)
    if install_hooks:
        if _prev_excepthook is None:
            _prev_excepthook = sys.excepthook
            sys.excepthook = _excepthook
        if _prev_threading_hook is None and hasattr(threading, "excepthook"):
            _prev_threading_hook = threading.excepthook
            threading.excepthook = _threading_hook
    return _rec


def disable() -> None:
    """Disarm and restore the hooks. The recorder (and its events) is
    dropped; dumps already on disk are untouched."""
    global _rec, _prev_excepthook, _prev_threading_hook
    _rec = None
    if _prev_excepthook is not None:
        sys.excepthook = _prev_excepthook
        _prev_excepthook = None
    if _prev_threading_hook is not None:
        threading.excepthook = _prev_threading_hook
        _prev_threading_hook = None


def is_enabled() -> bool:
    return _rec is not None


def get() -> Optional[FlightRecorder]:
    return _rec
